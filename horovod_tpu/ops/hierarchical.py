"""Hierarchical two-level (ICI/DCN) collectives.

TPU-native rebuild of the reference's ``NCCLHierarchicalAllreduce``
(``/root/reference/horovod/common/ops/nccl_operations.cc:286-506``: NCCL
reduce-scatter within the node → cross-node MPI allreduce on the CROSS
communicator → NCCL allgather back) and ``MPIHierarchicalAllgather``
(``/root/reference/horovod/common/ops/mpi_operations.cc``). On TPU the two
levels are the fast intra-slice ICI fabric and the slower inter-slice DCN:

    allreduce(x)  =  psum_scatter(x, ici)  →  psum(piece, dcn)
                                           →  all_gather(piece, ici)

Each chip moves the full vector twice over ICI but only ``1/ici_size`` of
it over DCN — the same traffic shape that makes the reference's
hierarchical path win on >1 node. Enabled with ``HVD_HIERARCHICAL_ALLREDUCE``
/ ``HVD_HIERARCHICAL_ALLGATHER`` (the reference's knobs, parsed at
``operations.cc:525-549``); the 2-D shape defaults to
(processes, chips-per-process) and can be overridden with
``HVD_HIERARCHICAL_ICI_SIZE``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import runtime
from ..utils import envs
from .program_issue import issue_serialized as _issue_serialized
from .reduce_ops import ReduceOp

DCN_AXIS = "hvd_dcn"
ICI_AXIS = "hvd_ici"


def default_ici_size() -> int:
    """Chips per ICI island: ``HVD_HIERARCHICAL_ICI_SIZE`` override, else
    chips-per-process when homogeneous (the analog of the reference's
    local communicator, ``common.h:166-170``), else the world size
    (degenerating to a flat allreduce)."""
    override = envs.get_int(envs.HIERARCHICAL_ICI_SIZE, 0)
    if override:
        return override
    n = runtime.size()
    if runtime.is_homogeneous():
        local = runtime.local_size()
        if local and n % local == 0:
            return local
    return n


def hierarchical_mesh(ici_size: int | None = None) -> Mesh:
    """2-D ``(dcn, ici)`` mesh over the rank-ordered global devices.

    Rank layout is process-major (``runtime._rank_ordered_devices``), so
    reshaping to (n // ici, ici) puts each process's chips in one ICI row
    when ``ici_size`` == chips-per-process.

    Routed through the shared composed-mesh cache
    (``parallel/mesh.py::mesh_for_axes``) — eager hierarchical ops and
    composed traced steps derive their device order from the SAME
    generation-keyed reshape of ``runtime.devices()``, so they cannot
    silently disagree after an elastic re-form."""
    n = runtime.size()
    if ici_size is None:
        ici_size = default_ici_size()
    if ici_size <= 0 or n % ici_size != 0:
        raise ValueError(
            f"hierarchical ici_size {ici_size} must divide world size {n}")
    from ..parallel import mesh as composed
    return composed.mesh_for_axes((DCN_AXIS, ICI_AXIS),
                                  (n // ici_size, ici_size))


# ---------------------------------------------------------------------------
# traced-mode primitives (both axes bound: inside shard_map over a 2-D mesh)
# ---------------------------------------------------------------------------

def hierarchical_allreduce_traced(x, ici_axis, dcn_axis, *,
                                  op: ReduceOp = ReduceOp.AVERAGE,
                                  prescale_factor: float = 1.0,
                                  postscale_factor: float = 1.0):
    """Two-phase allreduce with both mesh axes bound (reference
    ``NCCLHierarchicalAllreduce::Execute``, ``nccl_operations.cc:286-506``).

    Supports SUM/AVERAGE (the reference's hierarchical path is sum-based
    too; MIN/MAX/PRODUCT fall back to the flat op at the call site).
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"hierarchical allreduce supports SUM/AVERAGE, got {op.name}")
    if prescale_factor != 1.0:
        x = x * prescale_factor
    n_ici = lax.psum(1, ici_axis)
    n_total = n_ici * lax.psum(1, dcn_axis)

    orig_dtype = x.dtype
    flat = x.reshape(-1)
    pad = (-flat.size) % n_ici
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # Phase 1: reduce-scatter over the fast ICI axis — each chip ends up
    # owning 1/n_ici of the (locally reduced) vector.
    piece = lax.psum_scatter(flat, ici_axis, scatter_dimension=0, tiled=True)
    # Phase 2: allreduce the small piece over the slow DCN axis.
    piece = lax.psum(piece, dcn_axis)
    # Phase 3: allgather the fully reduced pieces back over ICI.
    out = lax.all_gather(piece, ici_axis, tiled=True)
    out = out[:x.size].reshape(x.shape)
    if op == ReduceOp.AVERAGE:
        out = out / jnp.asarray(n_total, out.dtype)
    if postscale_factor != 1.0:
        out = out * postscale_factor
    return out.astype(orig_dtype)


def hierarchical_allgather_traced(x, ici_axis, dcn_axis):
    """Two-phase allgather: concat within the ICI island, then across DCN
    (reference ``MPIHierarchicalAllgather``). Global rank order is
    dcn-major ici-minor, matching the rank layout of
    :func:`hierarchical_mesh`."""
    within = lax.all_gather(x, ici_axis, tiled=True)
    return lax.all_gather(within, dcn_axis, tiled=True)


# ---------------------------------------------------------------------------
# eager machinery: cached jit(shard_map) over the 2-D mesh
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _eager_hier_allreduce_fn(mesh: Mesh, op: ReduceOp, pre: float, post: float,
                             bundled: bool = True, row0: bool = False):
    """``bundled``: x is the (n, ...) per-rank bundle. Replicated
    (``bundled=False``): x is the raw array every rank contributes
    identically — ``in_specs=P()`` replicates without bundle
    materialization. ``row0``: return the replicated result directly
    (``out_specs=P()``) so dispatch plans need no eager ``[0]`` slice
    (see the flat twins in ops/collectives.py)."""
    dcn_axis, ici_axis = mesh.axis_names

    def inner(x):
        out = hierarchical_allreduce_traced(
            x[0] if bundled else x, ici_axis, dcn_axis, op=op,
            prescale_factor=pre, postscale_factor=post)
        return out[None] if (bundled and not row0) else out

    in_spec = P((dcn_axis, ici_axis)) if bundled else P()
    out_spec = P() if (row0 or not bundled) else in_spec
    return _issue_serialized(jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
        check_vma=False)))


def _hier_grouped_allreduce_smap(mesh: Mesh, op: ReduceOp, pre: float,
                                 post: float, num_bufs: int, bundled: bool):
    """Raw shard-mapped two-level fused reduction (not jitted) — composed
    into the jitted wire program below and into dispatch-plan programs."""
    dcn_axis, ici_axis = mesh.axis_names

    def inner(*xs):
        if bundled:
            return tuple(
                hierarchical_allreduce_traced(
                    x[0], ici_axis, dcn_axis, op=op,
                    prescale_factor=pre, postscale_factor=post)[None]
                for x in xs)
        return tuple(
            hierarchical_allreduce_traced(
                x, ici_axis, dcn_axis, op=op,
                prescale_factor=pre, postscale_factor=post)
            for x in xs)

    spec = P((dcn_axis, ici_axis)) if bundled else P()
    specs = tuple(spec for _ in range(num_bufs))
    return jax.shard_map(inner, mesh=mesh, in_specs=specs, out_specs=specs,
                         check_vma=False)


@functools.lru_cache(maxsize=None)
def _eager_hier_grouped_allreduce_fn(mesh: Mesh, op: ReduceOp, pre: float,
                                     post: float, num_bufs: int,
                                     bundled: bool = True,
                                     donate: tuple = ()):
    return _issue_serialized(jax.jit(
        _hier_grouped_allreduce_smap(mesh, op, pre, post, num_bufs, bundled),
        donate_argnums=tuple(i for i, d in enumerate(donate) if d)))


@functools.lru_cache(maxsize=None)
def _eager_hier_allgather_fn(mesh: Mesh, bundled: bool = True):
    dcn_axis, ici_axis = mesh.axis_names

    def inner(x):  # -> (n*d0, ...) replicated
        return hierarchical_allgather_traced(x[0] if bundled else x,
                                             ici_axis, dcn_axis)

    return _issue_serialized(jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=P((dcn_axis, ici_axis)) if bundled else P(),
        out_specs=P(), check_vma=False)))


def _enabled(knob: str, pset) -> bool:
    """Whether the eager hierarchical path applies: knob set, global set
    (the reference only runs hierarchical on the global communicator), and
    a non-trivial 2-D factorization exists."""
    if not envs.get_bool(knob):
        return False
    if not pset.is_global:
        return False
    ici = default_ici_size()
    return 1 < ici < runtime.size() and runtime.size() % ici == 0


def hierarchical_enabled_for(pset) -> bool:
    return _enabled(envs.HIERARCHICAL_ALLREDUCE, pset)


def hierarchical_allgather_enabled_for(pset) -> bool:
    return _enabled(envs.HIERARCHICAL_ALLGATHER, pset)


def _layout_signature() -> tuple:
    from ..parallel import mesh as composed
    return composed.layout_signature()


def layout_key_for(pset):
    """Axis-layout component of allreduce/grouped-allreduce dispatch-plan
    keys: ``False`` when the hierarchical lane is off for ``pset``
    (exactly the old boolean key), else the active composed-mesh layout
    signature — so a changed ``HVD_MESH_AXES`` carve or ICI size re-keys
    every plan instead of silently replaying a stale axis layout."""
    if not hierarchical_enabled_for(pset):
        return False
    return _layout_signature()


def allgather_layout_key_for(pset):
    """Allgather twin of :func:`layout_key_for`."""
    if not hierarchical_allgather_enabled_for(pset):
        return False
    return _layout_signature()


# ---------------------------------------------------------------------------
# public API (explicit two-level ops; hvd.allreduce also routes here when
# HVD_HIERARCHICAL_ALLREDUCE is set)
# ---------------------------------------------------------------------------

def hierarchical_allreduce(tensor, *, op: ReduceOp = ReduceOp.AVERAGE,
                           prescale_factor: float = 1.0,
                           postscale_factor: float = 1.0,
                           ici_size: int | None = None,
                           ici_axis: str | None = None,
                           dcn_axis: str | None = None,
                           name: str | None = None):
    """Explicit two-level allreduce.

    Traced mode: call inside ``shard_map`` over a 2-D mesh and pass the
    bound ``ici_axis``/``dcn_axis`` names. Eager mode: runs over
    :func:`hierarchical_mesh` (global process set only)."""
    del name
    from .collectives import _as_bundle, _axis_is_bound, _contains_tracer
    from .reduce_ops import handle_average
    ia = ici_axis or ICI_AXIS
    da = dcn_axis or DCN_AXIS
    if _axis_is_bound(ia) and _axis_is_bound(da):
        return hierarchical_allreduce_traced(
            tensor, ia, da, op=op, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
    if _contains_tracer(tensor):
        raise RuntimeError(
            "hierarchical_allreduce() inside jit/pjit requires both mesh "
            "axes bound; run it under jax.shard_map over "
            "hvd.hierarchical_mesh() and pass ici_axis=/dcn_axis=.")
    from ..process_sets import global_process_set
    mesh = hierarchical_mesh(ici_size)
    lowered, post = handle_average(op, runtime.size(), postscale_factor)
    bundle, _ = _as_bundle(tensor, global_process_set)
    fn = _eager_hier_allreduce_fn(mesh, lowered, float(prescale_factor),
                                  float(post))
    return fn(bundle)[0]


def hierarchical_allgather(tensor, *, ici_size: int | None = None,
                           ici_axis: str | None = None,
                           dcn_axis: str | None = None,
                           name: str | None = None):
    """Explicit two-level allgather (concat along dim 0 in global rank
    order). Traced with both axes bound, else eager over
    :func:`hierarchical_mesh`."""
    del name
    from .collectives import _as_bundle, _axis_is_bound, _contains_tracer
    ia = ici_axis or ICI_AXIS
    da = dcn_axis or DCN_AXIS
    if _axis_is_bound(ia) and _axis_is_bound(da):
        return hierarchical_allgather_traced(tensor, ia, da)
    if _contains_tracer(tensor):
        raise RuntimeError(
            "hierarchical_allgather() inside jit/pjit requires both mesh "
            "axes bound; run it under jax.shard_map over "
            "hvd.hierarchical_mesh() and pass ici_axis=/dcn_axis=.")
    from ..process_sets import global_process_set
    mesh = hierarchical_mesh(ici_size)
    bundle, _ = _as_bundle(tensor, global_process_set)
    if bundle.ndim == 1:  # scalars per rank: gather to a vector
        bundle = bundle[:, None]
        return _eager_hier_allgather_fn(mesh)(bundle).reshape(-1)
    return _eager_hier_allgather_fn(mesh)(bundle)
