"""Gradient wire compression.

Rebuild of ``/root/reference/horovod/torch/compression.py`` /
``/root/reference/horovod/tensorflow/compression.py`` (identical 74-line
API): a ``Compressor`` compresses a tensor before the collective and
decompresses after. On TPU the fp16 analog is **bfloat16** (MXU-native,
same 2-byte wire size); fp16 is also provided for exact parity.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: ``compress(tensor) -> (compressed, ctx)``;
    ``decompress(compressed, ctx) -> tensor``."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype = None

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(cls.wire_dtype), ctx
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor


class BF16Compressor(_CastCompressor):
    """bfloat16 wire compression: the TPU-native choice (keeps fp32 range,
    rides the MXU/ICI at half the bytes)."""
    wire_dtype = jnp.bfloat16


class FP16Compressor(_CastCompressor):
    """Exact parity with the reference's fp16 compressor."""
    wire_dtype = jnp.float16


class Compression:
    """Namespace mirroring ``hvd.Compression`` (compression.py:60-74)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
