"""Adasum: scale-invariant gradient combination.

Rebuild of the reference's Adasum
(``/root/reference/horovod/common/ops/adasum/adasum.h:194-342``):
**vector-halving distance-doubling** (VHDD) — at level ``L`` ranks ``r``
and ``r ^ L`` split their current segment in half, exchange the half they
don't keep, and combine

    a' = (1 - a.b / (2 |a|^2)) a + (1 - a.b / (2 |b|^2)) b

where the dot/norms are accumulated over the *distributed* logical vectors
(partial sums reduced over the block of ranks sharing them — the
reference's ``normAndDots`` allreduce over ``reduction_comms``,
``adasum.h:310-330``). A reverse halving-doubling phase gathers the
combined segments back. Each rank moves ``|v|/2 + |v|/4 + ... ≈ |v|`` per
phase — ~2·|v| total, the reference's bandwidth shape — instead of the
``|v|·log n`` of a naive full-vector XOR tree.

TPU-native mapping: the point-to-point exchanges are ``lax.ppermute`` over
the mesh axis with static per-level permutations; the segment sizes halve
at trace time (unrolled python loop → static shapes); per-rank half
selection is a ``dynamic_slice`` with a traced offset. Non-power-of-two
worlds fold the extra ranks into the leading power-of-two block before the
VHDD and broadcast back after (the reference's ``nearest_power_2``
handling, ``adasum.h:215-224``); process-set subsets run the same schedule
over the member rank list. The hierarchical variant (reference
``AdasumGpuAllreduceOp``, ``adasum_gpu_operations.cc``: node-local
reduce-scatter, Adasum across nodes, allgather back) maps to ICI
``psum_scatter`` → DCN VHDD → ICI ``all_gather``.

Accumulation note (SURVEY §7 hard part (d)): dot products and norms are
accumulated in float32 even for bf16/fp16 inputs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..process_sets import ProcessSet, _resolve
from .program_issue import issue_serialized as _issue_serialized


def _coeffs(dot, na, nb):
    """Scale-invariant combine coefficients (adasum.h:248-342), guarding
    zero-norm inputs like the reference."""
    acoeff = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)),
                       1.0)
    bcoeff = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)),
                       1.0)
    return acoeff, bcoeff


def _pairwise_combine(a, b):
    """Whole-vector pairwise combine (both vectors fully local)."""
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    acoeff, bcoeff = _coeffs(jnp.sum(af * bf), jnp.sum(af * af),
                             jnp.sum(bf * bf))
    return (acoeff * af + bcoeff * bf).astype(a.dtype)


def _block_groups(members, block, world):
    """psum groups: ``members`` split into blocks of ``block`` + singleton
    non-members (a partition of the whole axis; unequal group sizes are
    legal for psum)."""
    member_set = set(members)
    groups = [members[i:i + block] for i in range(0, len(members), block)]
    groups.extend([r] for r in range(world) if r not in member_set)
    return groups


def _vhdd(x, axis, members, world, dot_extra_axis=None):
    """Distributed VHDD Adasum of the flat vector ``x`` over the member
    ranks. Every member returns the full combined vector; non-members
    return their input unchanged. ``dot_extra_axis`` additionally reduces
    the coefficient dot/norms over another mesh axis — the hierarchical
    mode's scatter axis, where each logical vector is itself distributed
    (the reference's reduction_comms span those ranks too,
    ``adasum.h:310-330``)."""
    n = len(members)
    p = 1
    while (p << 1) <= n:
        p <<= 1
    extras = n - p  # members[p:] fold into members[:extras]

    idx = lax.axis_index(axis)
    members_arr = jnp.array(members)
    # my position within the member list (garbage for non-members — all
    # their lanes are masked by singleton psum groups / missing perms)
    pos = jnp.sum((members_arr < idx).astype(jnp.int32))

    orig_dtype = x.dtype
    seg = x.astype(jnp.float32)

    # --- fold the non-power-of-two tail (adasum.h nearest_power_2) -------
    if extras:
        perm_in = [(members[p + i], members[i]) for i in range(extras)]
        recv = lax.ppermute(seg, axis, perm_in)  # zeros where no sender
        if dot_extra_axis is None:
            folded = _pairwise_combine(seg, recv)
        else:
            stats = lax.psum(jnp.stack([jnp.sum(seg * recv),
                                        jnp.sum(seg * seg),
                                        jnp.sum(recv * recv)]),
                             dot_extra_axis)
            ac, bc = _coeffs(stats[0], stats[1], stats[2])
            folded = ac * seg + bc * recv
        is_target = pos < extras
        seg = jnp.where(is_target, folded, seg)

    active = members[:p]

    # --- halving (up) phase ----------------------------------------------
    m = seg.shape[0]
    level = 1
    while level < p:
        half = m // 2
        bit = (pos // level) % 2  # (pos & level) != 0, traced-friendly
        my_keep = lax.dynamic_slice(seg, (bit * half,), (half,))
        my_send = lax.dynamic_slice(seg, ((1 - bit) * half,), (half,))
        perm = [(active[i], active[i ^ level]) for i in range(p)]
        recv = lax.ppermute(my_send, axis, perm)
        a_part = jnp.where(bit == 0, my_keep, recv)
        b_part = jnp.where(bit == 0, recv, my_keep)
        groups = _block_groups(active, 2 * level, world)
        # one fused collective for dot/|a|^2/|b|^2 per level (the
        # reference's single normAndDots allreduce, adasum.h:310-330)
        stats = jnp.stack([jnp.sum(a_part * b_part),
                           jnp.sum(a_part * a_part),
                           jnp.sum(b_part * b_part)])
        stats = lax.psum(stats, axis, axis_index_groups=groups)
        if dot_extra_axis is not None:
            stats = lax.psum(stats, dot_extra_axis)
        acoeff, bcoeff = _coeffs(stats[0], stats[1], stats[2])
        seg = acoeff * a_part + bcoeff * b_part
        m = half
        level <<= 1

    # --- doubling (down) phase -------------------------------------------
    level = p >> 1
    while level >= 1:
        bit = (pos // level) % 2
        perm = [(active[i], active[i ^ level]) for i in range(p)]
        recv = lax.ppermute(seg, axis, perm)
        out = jnp.zeros((2 * m,), seg.dtype)
        out = lax.dynamic_update_slice(out, seg, (bit * m,))
        out = lax.dynamic_update_slice(out, recv, ((1 - bit) * m,))
        seg = out
        m *= 2
        level >>= 1

    # --- unfold: send the result back to the folded tail ------------------
    if extras:
        perm_out = [(members[i], members[p + i]) for i in range(extras)]
        recv = lax.ppermute(seg, axis, perm_out)
        is_extra_member = pos >= p
        seg = jnp.where(is_extra_member, recv, seg)

    is_member = jnp.isin(idx, members_arr)
    return jnp.where(is_member, seg, x.astype(jnp.float32)).astype(orig_dtype)


def adasum_reduce(x, axis, groups=None, *, dot_extra_axis=None):
    """Traced-mode Adasum allreduce over mesh axis ``axis`` (any member
    count; ``groups`` = a process-set partition restricts it to the
    member group, non-members pass through unchanged)."""
    world = int(lax.psum(1, axis))
    if groups is None:
        members = list(range(world))
    else:
        members = list(groups[0])
    if len(members) == 1:
        return x
    shape = x.shape
    flat = x.reshape(-1)
    # pad so every halving level splits evenly
    p = 1
    while (p << 1) <= len(members):
        p <<= 1
    pad = (-flat.shape[0]) % p
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    out = _vhdd(flat, axis, members, world, dot_extra_axis=dot_extra_axis)
    return out[:x.size].reshape(shape)


def adasum_hierarchical_traced(x, ici_axis, dcn_axis):
    """Two-level Adasum (reference ``AdasumGpuAllreduceOp``): SUM
    reduce-scatter over the fast ICI axis, scale-invariant Adasum across
    the DCN axis on each piece, allgather back over ICI. Matches the
    reference's semantics where the node-local reduction is a plain sum
    and Adasum applies across nodes (``operations.cc:161-162``)."""
    orig_dtype = x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    n_ici = lax.psum(1, ici_axis)
    pad = (-flat.shape[0]) % n_ici
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    piece = lax.psum_scatter(flat, ici_axis, scatter_dimension=0, tiled=True)
    # coefficient dots reduce over the scatter axis too: each logical
    # vector is distributed across the ICI island
    piece = adasum_reduce(piece, dcn_axis, dot_extra_axis=ici_axis)
    out = lax.all_gather(piece, ici_axis, tiled=True)
    return out[:x.size].reshape(x.shape).astype(orig_dtype)


@functools.lru_cache(maxsize=None)
def _eager_adasum_fn(mesh: Mesh, axis: str):
    def inner(x):  # (1, ...) bundle shard
        return adasum_reduce(x, axis)
    # issue_serialized: eager multi-device collectives must enqueue under
    # the process-wide issue lock (PR-3 deadlock class; ops/program_issue).
    # These two sites predate the lock and were flagged by hvdlint's
    # issue-lock pass.
    return _issue_serialized(jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False)))


@functools.lru_cache(maxsize=None)
def _eager_hier_adasum_fn(mesh: Mesh):
    dcn_axis, ici_axis = mesh.axis_names

    def inner(x):  # (1, ...) bundle shard over the 2-D mesh
        return adasum_hierarchical_traced(x[0], ici_axis, dcn_axis)[None]

    return _issue_serialized(jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=P((dcn_axis, ici_axis)),
        out_specs=P((dcn_axis, ici_axis)), check_vma=False)))


def adasum_allreduce(tensor, *, process_set: ProcessSet | None = None,
                     axis_name=None):
    """Adasum allreduce, eager or traced (reference op selection
    ``operations.cc:161-162``; enqueue with ``ReduceOp.Adasum``). Routes
    through the two-level ICI/DCN schedule when
    ``HVD_HIERARCHICAL_ALLREDUCE`` applies (the reference pairs Adasum
    with its hierarchical GPU op the same way)."""
    from . import hierarchical
    from .collectives import PerRank, _as_bundle, _axis_is_bound, _resolve_axis
    pset = _resolve(process_set)
    axis = _resolve_axis(axis_name)
    if _axis_is_bound(axis):
        return adasum_reduce(tensor, axis, pset.axis_index_groups())
    bundle, _ = _as_bundle(tensor, pset)
    if hierarchical.hierarchical_enabled_for(pset):
        fn = _eager_hier_adasum_fn(hierarchical.hierarchical_mesh())
        return fn(bundle)[0]
    # sub-mesh eager path: the pset mesh spans members only, so inside it
    # the member list is simply 0..size-1
    out = _eager_adasum_fn(pset.mesh(), axis)(bundle)
    return out[0]
