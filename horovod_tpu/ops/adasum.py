"""Adasum: scale-invariant gradient combination.

Rebuild of the reference's Adasum (``/root/reference/horovod/common/ops/adasum/adasum.h:194-342``):
vector-halving distance-doubling (VHDD) recursive reduction where each level
pairs ranks ``r`` and ``r ^ 2^level`` and combines their vectors *a*, *b* as

    a' = (1 - a.b / (2 |a|^2)) a + (1 - a.b / (2 |b|^2)) b

(the ``FusedPairwiseReduceWithComm`` math, ``adasum.h:248-342``), which keeps
the magnitude of the combined update stable when gradients point the same
way (scale invariance) and adds them when orthogonal.

TPU-native mapping: the XOR-partner exchange becomes ``lax.ppermute`` over
the mesh axis; the pairwise combine is a fused elementwise+reduction XLA
program. The combine is symmetric, so both partners compute identical
results locally — after log2(n) levels every rank holds the full Adasum
reduction (no separate allgather leg needed, unlike the MPI p2p version
``adasum_mpi.cc``).

Accumulation note (SURVEY §7 hard part (d)): dot products and norms are
accumulated in float32 even for bf16/fp16 inputs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import runtime
from ..process_sets import ProcessSet, _resolve


def _pairwise_combine(a, b):
    """Scale-invariant pairwise combine (adasum.h:248-342)."""
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.sum(af * bf)
    na = jnp.sum(af * af)
    nb = jnp.sum(bf * bf)
    acoeff = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)), 1.0)
    bcoeff = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)), 1.0)
    out = acoeff * af + bcoeff * bf
    return out.astype(a.dtype)


def adasum_reduce(x, axis, groups=None):
    """Traced-mode Adasum allreduce over mesh axis ``axis`` via a
    ppermute XOR-partner tree. Requires a power-of-two axis size."""
    if groups is not None:
        raise NotImplementedError(
            "Adasum over a process-set subset is not supported yet; "
            "use the eager path (sub-mesh) or the global set.")
    n = lax.axis_size(axis) if hasattr(lax, "axis_size") else None
    if n is None:
        n = lax.psum(1, axis)
    n = int(n)
    if n & (n - 1):
        raise NotImplementedError(
            f"Adasum requires a power-of-two rank count (got {n}); the "
            "reference builds power-of-two reduction comms the same way "
            "(adasum_mpi.cc).")
    level = 1
    while level < n:
        perm = [(r, r ^ level) for r in range(n)]
        partner = lax.ppermute(x, axis, perm)
        x = _pairwise_combine(x, partner)
        level <<= 1
    return x


@functools.lru_cache(maxsize=None)
def _eager_adasum_fn(mesh: Mesh, axis: str):
    def inner(x):  # (1, ...) bundle shard
        return adasum_reduce(x, axis)
    return jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False))


def adasum_allreduce(tensor, *, process_set: ProcessSet | None = None,
                     axis_name=None):
    """Adasum allreduce, eager or traced (reference op selection
    ``operations.cc:161-162``; enqueue with ``ReduceOp.Adasum``)."""
    from .collectives import PerRank, _as_bundle, _axis_is_bound, _resolve_axis
    pset = _resolve(process_set)
    axis = _resolve_axis(axis_name)
    if _axis_is_bound(axis):
        return adasum_reduce(tensor, axis, pset.axis_index_groups())
    n = pset.size()
    if n & (n - 1):
        raise NotImplementedError(
            f"Adasum requires a power-of-two rank count (got {n})")
    bundle, _ = _as_bundle(tensor, pset)
    out = _eager_adasum_fn(pset.mesh(), axis)(bundle)
    return out[0]
