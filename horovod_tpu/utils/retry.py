"""Unified retry/backoff policy for every RPC/KV seam (``HVD_RETRY_*``).

Before this module each seam invented its own failure posture:
``KVClient.put`` raised on the first transient socket error, ``wait``
busy-polled at a fixed interval, the elastic round wait slept a flat
250 ms. This is the one place that posture lives now
(docs/robustness.md): bounded exponential backoff with **deterministic
jitter** and an optional deadline, adopted by KV put/get/wait/gather,
rendezvous publication, and negotiation submission.

Knobs (registered in ``utils/envs.py``, rows in docs/knobs.md):

* ``HVD_RETRY_MAX_ATTEMPTS`` (5) — attempts per :func:`call`;
* ``HVD_RETRY_BACKOFF_MS`` (50) — backoff before the first retry;
* ``HVD_RETRY_MAX_BACKOFF_MS`` (2000) — backoff growth cap (doubling);
* ``HVD_RETRY_JITTER`` (0.25) — backoff is scaled by a deterministic
  factor in ``[1-j, 1+j]`` derived from ``zlib.crc32(what, attempt)``:
  decorrelated across call sites, identical across runs (and free of
  ``random``, which hvdlint's timer-purity pass bans in timer-reachable
  code).

Every retry bumps a per-site counter (surfaced through
``hvd.health_stats()``) and drops a ``RETRY`` instant on the timeline,
so a flapping transport is visible instead of silently absorbed.
"""

from __future__ import annotations

import zlib

from . import envs
from . import invariants as _inv
from . import logging as hvd_logging

# Counter storage lives in the unified metrics registry
# (``horovod_tpu/metrics.py``: ``hvd_retry_retries_total`` /
# ``hvd_retry_giveups_total``, labeled by site, ``always=True`` because
# they back the ``hvd.health_stats()["retries"]`` API). The registry
# lock is a plain leaf lock, so the backoff sleeps / poll pacing remain
# the only retry behavior hvdsched serializes (the sleeps stay on the
# invariants seam's virtual clock).


def _metrics():
    from .. import metrics
    return metrics


def _note(what: str, kind: str) -> None:
    m = _metrics()
    inst = m.RETRY_RETRIES if kind == "retries" else m.RETRY_GIVEUPS
    inst.inc(labels={"site": what})


def stats() -> dict:
    """Per-site ``{"retries": n, "giveups": n}`` counters
    (``hvd.health_stats()["retries"]``) — a view over the metrics
    registry, shape-identical to the pre-registry dict."""
    m = _metrics()
    out: dict[str, dict[str, int]] = {}
    for labelitems, v in m.RETRY_RETRIES.series().items():
        site = dict(labelitems)["site"]
        out.setdefault(site, {"retries": 0, "giveups": 0})["retries"] = int(v)
    for labelitems, v in m.RETRY_GIVEUPS.series().items():
        site = dict(labelitems)["site"]
        out.setdefault(site, {"retries": 0, "giveups": 0})["giveups"] = int(v)
    return out


def reset_stats() -> None:
    m = _metrics()
    m.RETRY_RETRIES.reset()
    m.RETRY_GIVEUPS.reset()


def _jitter_factor(what: str, attempt: int) -> float:
    """Deterministic factor in [1-j, 1+j]: same schedule every run, but
    two sites retrying in lockstep don't thunder in phase."""
    j = envs.get_float(envs.RETRY_JITTER, envs.DEFAULT_RETRY_JITTER)
    if j <= 0.0:
        return 1.0
    h = zlib.crc32(f"{what}:{attempt}".encode()) & 0xFFFFFFFF
    return 1.0 + j * (2.0 * (h / float(1 << 32)) - 1.0)


def backoff_s(what: str, attempt: int) -> float:
    """The sleep before retry ``attempt`` (1-based): jittered
    ``BACKOFF_MS * 2^(attempt-1)`` capped at ``MAX_BACKOFF_MS``."""
    base = envs.get_float(envs.RETRY_BACKOFF_MS,
                          envs.DEFAULT_RETRY_BACKOFF_MS) / 1e3
    cap = envs.get_float(envs.RETRY_MAX_BACKOFF_MS,
                         envs.DEFAULT_RETRY_MAX_BACKOFF_MS) / 1e3
    raw = min(base * (2.0 ** (attempt - 1)), cap)
    return raw * _jitter_factor(what, attempt)


def max_attempts() -> int:
    return max(envs.get_int(envs.RETRY_MAX_ATTEMPTS,
                            envs.DEFAULT_RETRY_MAX_ATTEMPTS), 1)


def _record_retry(what: str, attempt: int, exc: BaseException | None) -> None:
    _note(what, "retries")
    from .. import timeline as _timeline
    _timeline.record_retry(what, attempt)
    hvd_logging.debug("retry %d of %s: %s", attempt, what, exc)


def call(fn, *, what: str, retry_on=None, attempts: int | None = None,
         deadline_s: float | None = None):
    """Run ``fn()`` with bounded exponential backoff.

    ``retry_on`` decides retryability: a predicate ``exc -> bool``, a
    tuple of exception types, or None (any ``Exception``). The last
    failure re-raises unchanged once ``attempts`` (default
    ``HVD_RETRY_MAX_ATTEMPTS``) are exhausted or ``deadline_s`` (a
    budget from the first call, not per attempt) would be exceeded by
    the next backoff."""
    n = attempts if attempts is not None else max_attempts()
    end = None if deadline_s is None else _inv.monotonic() + deadline_s
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except BaseException as exc:
            if callable(retry_on):
                retryable = retry_on(exc)
            elif retry_on is not None:
                retryable = isinstance(exc, retry_on)
            else:
                retryable = isinstance(exc, Exception)
            delay = backoff_s(what, attempt)
            if (not retryable or attempt >= n
                    or (end is not None
                        and _inv.monotonic() + delay > end)):
                if retryable:
                    _note(what, "giveups")
                raise
            _record_retry(what, attempt, exc)
            _inv.sleep(delay)


def poll_intervals(what: str, *, interval_s: float,
                   deadline_s: float | None = None,
                   max_interval_s: float | None = None):
    """Jittered poll pacing for wait loops (KV ``wait``, the elastic
    round wait): yields after sleeping each interval, stops once
    ``deadline_s`` is exhausted (the caller raises its own timeout).
    The interval backs off by 1.5x per yield up to ``max_interval_s``
    (default 8x the base) — a long wait shouldn't keep hammering the
    server at the initial rate."""
    end = None if deadline_s is None else _inv.monotonic() + deadline_s
    cap = max_interval_s if max_interval_s is not None else 8.0 * interval_s
    cur = interval_s
    attempt = 0
    while True:
        attempt += 1
        delay = cur * _jitter_factor(what, attempt)
        if end is not None:
            remaining = end - _inv.monotonic()
            if remaining <= 0:
                return
            delay = min(delay, remaining)
        _inv.sleep(delay)
        yield attempt
        cur = min(cur * 1.5, cap)
