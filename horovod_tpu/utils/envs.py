"""Environment-variable knob surface.

TPU-native rebuild of the reference's env config system (knob list at
``/root/reference/horovod/common/common.h:107-140``, parsed in
``/root/reference/horovod/common/utils/env_parser.cc`` and
``BackgroundThreadLoop`` at ``/root/reference/horovod/common/operations.cc:436-607``).

All knobs use the ``HVD_`` prefix; the reference's ``HOROVOD_`` spellings are
accepted as fallbacks so existing user scripts keep working.
"""

from __future__ import annotations

import os

from ..loopback import context as _lbctx

# --- knob names (HVD_*; HOROVOD_* accepted as fallback) -------------------
FUSION_THRESHOLD = "FUSION_THRESHOLD"  # bytes; reference default 128 MB (operations.cc:491-496)
TRACED_FUSION_THRESHOLD = "TRACED_FUSION_THRESHOLD"  # bytes; 0 (default) = let XLA's combiner fuse traced collectives
CYCLE_TIME = "CYCLE_TIME"  # ms; reference default 1 ms (operations.cc:499-506)
CACHE_CAPACITY = "CACHE_CAPACITY"  # reference default 1024 (global_state.h:89)
TIMELINE = "TIMELINE"  # trace output path (operations.cc:466-488)
TIMELINE_MARK_CYCLES = "TIMELINE_MARK_CYCLES"
AUTOTUNE = "AUTOTUNE"
AUTOTUNE_STRATEGY = "AUTOTUNE_STRATEGY"  # coordinate (default) | bayesian
AUTOTUNE_LOG = "AUTOTUNE_LOG"
AUTOTUNE_WARMUP_SAMPLES = "AUTOTUNE_WARMUP_SAMPLES"
AUTOTUNE_STEPS_PER_SAMPLE = "AUTOTUNE_STEPS_PER_SAMPLE"
AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
LOG_LEVEL = "LOG_LEVEL"
LOG_TIMESTAMP = "LOG_TIMESTAMP"
STALL_CHECK_DISABLE = "STALL_CHECK_DISABLE"
STALL_CHECK_TIME_SECONDS = "STALL_CHECK_TIME_SECONDS"  # reference warns at 60 s (stall_inspector.h:78)
STALL_SHUTDOWN_TIME_SECONDS = "STALL_SHUTDOWN_TIME_SECONDS"
HIERARCHICAL_ALLREDUCE = "HIERARCHICAL_ALLREDUCE"
HIERARCHICAL_ALLGATHER = "HIERARCHICAL_ALLGATHER"
HIERARCHICAL_ICI_SIZE = "HIERARCHICAL_ICI_SIZE"  # chips per ICI island; default local_size
MESH_AXES = "MESH_AXES"  # composed-mesh model-axis carve, e.g. "seq:2" or "expert:4,stage:2" (parallel/mesh.py)
# (the reference's HOROVOD_BATCH_D2D_MEMCOPIES has no knob here by
# design: XLA fuses small copies into the compiled program, so there is
# nothing runtime-batchable to toggle)
ADAPTIVE_CYCLE = "ADAPTIVE_CYCLE"  # event-driven negotiation tick (default on)
PENDING_CYCLE_TIME = "PENDING_CYCLE_TIME"  # ms; cycle floor while work is in flight
FUSION_MAX_PENDING = "FUSION_MAX_PENDING"  # bytes; fusion-cycle backpressure cap (default 4x FUSION_THRESHOLD)
MAX_INFLIGHT_FLUSHES = "MAX_INFLIGHT_FLUSHES"  # pipelined flush executor slots (0/1 = synchronous)
PIPELINE_THRESHOLD = "PIPELINE_THRESHOLD"  # bytes; fused wire buffers past this split into chunks
PIPELINE_CHUNKS = "PIPELINE_CHUNKS"  # chunk count for the large-buffer software pipeline
PIPELINE_PINGPONG = "PIPELINE_PINGPONG"  # auto|1|0: recycle wire buffers across flushes via donation
DYNAMIC_PROCESS_SETS = "DYNAMIC_PROCESS_SETS"
DYNAMIC_ENGINE = "DYNAMIC_ENGINE"  # 0 disables multi-process negotiation
ELASTIC_TIMEOUT = "ELASTIC_TIMEOUT"
ELASTIC_GRACE = "ELASTIC_GRACE"  # s a slot-removed worker gets to exit cleanly (0 = immediate kill)
ELASTIC_WARM = "ELASTIC_WARM"  # auto|1|0: shape-keyed cache survival across elastic re-forms
AUTOSCALE = "AUTOSCALE"  # closed-loop elastic autoscaling policy (0 = scripted/manual churn only)
AUTOSCALE_SLO_MS = "AUTOSCALE_SLO_MS"  # step-time SLO target; 0 = breach/idle rules off (evict-only)
AUTOSCALE_INTERVAL = "AUTOSCALE_INTERVAL"  # s per policy evaluation window
AUTOSCALE_BREACH_WINDOWS = "AUTOSCALE_BREACH_WINDOWS"  # consecutive SLO-breach windows before scale-up
AUTOSCALE_IDLE_WINDOWS = "AUTOSCALE_IDLE_WINDOWS"  # consecutive idle windows before graceful scale-down
AUTOSCALE_EVICT_WINDOWS = "AUTOSCALE_EVICT_WINDOWS"  # consecutive windows blaming one straggler before eviction
AUTOSCALE_COOLDOWN = "AUTOSCALE_COOLDOWN"  # s after any membership decision before the next may fire
AUTOSCALE_MIN = "AUTOSCALE_MIN"  # world floor the policy never shrinks below (default: driver min_np)
AUTOSCALE_MAX = "AUTOSCALE_MAX"  # world ceiling the policy never grows past (default: driver max_np)
AUTOSCALE_GRACE = "AUTOSCALE_GRACE"  # s of slot-lost grace a policy departure (scale-down/evict) gets
AUTOSCALE_IDLE_FACTOR = "AUTOSCALE_IDLE_FACTOR"  # fraction of the SLO below which a window counts as idle
GLOO_TIMEOUT_SECONDS = "GLOO_TIMEOUT_SECONDS"  # KV transport op timeout
SPARSE_AS_DENSE = "SPARSE_AS_DENSE"  # force sparse grads onto dense allreduce
BUCKET_BYTES = "BUCKET_BYTES"  # gradient bucket size for backward-pass overlap (0 = whole-tree)
EAGER_CHAIN = "EAGER_CHAIN"  # auto|1|0: let eager consumer math chain on in-flight collective results
STEP_CAPTURE = "STEP_CAPTURE"  # capture-and-replay of the per-step collective stream (0 = off)
GSPMD_CACHE = "GSPMD_CACHE"  # cached-program fast path for jit/pjit train steps (0 = plain jit per call)
GSPMD_CACHE_DONATE = "GSPMD_CACHE_DONATE"  # auto|1|0: donate param/opt-state buffers into cached GSPMD steps
FLASH_ATTENTION = "FLASH_ATTENTION"  # opt into the Pallas flash kernel
DEBUG_INVARIANTS = "DEBUG_INVARIANTS"  # dev-mode runtime invariant checker
SCHED_CHECK = "SCHED_CHECK"  # cooperative schedule-exploration checker (tools/hvdsched)
SCHED_SEED = "SCHED_SEED"  # base PRNG seed for hvdsched schedule choices
SCHED_SCHEDULES = "SCHED_SCHEDULES"  # schedule budget per hvdsched exploration
SPARK_START_TIMEOUT = "SPARK_START_TIMEOUT"  # spark barrier-task scheduling bound
START_TIMEOUT = "START_TIMEOUT"  # programmatic run() worker startup bound
FAULT_SPEC = "FAULT_SPEC"  # deterministic fault-injection spec (tests/chaos)
HEALTH_INTERVAL = "HEALTH_INTERVAL"  # s between liveness beats (0 = watchdog off)
HEALTH_TIMEOUT = "HEALTH_TIMEOUT"  # s without a peer beat before it is declared dead
RETRY_MAX_ATTEMPTS = "RETRY_MAX_ATTEMPTS"  # attempts per retried RPC/KV call
RETRY_BACKOFF_MS = "RETRY_BACKOFF_MS"  # initial backoff between attempts
RETRY_MAX_BACKOFF_MS = "RETRY_MAX_BACKOFF_MS"  # backoff growth cap
RETRY_JITTER = "RETRY_JITTER"  # +/- fraction of deterministic jitter on backoff
LOOPBACK = "LOOPBACK"  # "1" in loopback rank threads (hvd.loopback.world)
LOOPBACK_TIMEOUT = "LOOPBACK_TIMEOUT"  # s per loopback collective rendezvous (default scales with world)
RESPONSE_CACHE = "RESPONSE_CACHE"  # coordinator ResponseCache: auto = on when hierarchy active, 0 off, 1 on, >1 = capacity
NEGOTIATION_GROUP_SIZE = "NEGOTIATION_GROUP_SIZE"  # ranks per leader group in the hierarchical control plane
HIER_NEGOTIATION = "HIER_NEGOTIATION"  # auto|1|0: two-level leader/member negotiation exchange
METRICS = "METRICS"  # unified metrics registry (0 = hot instruments off)
METRICS_PORT = "METRICS_PORT"  # base port for the per-worker /metrics server
STRAGGLER_THRESHOLD = "STRAGGLER_THRESHOLD"  # s of submit lag naming a rank a straggler
QOS = "QOS"  # multi-tenant QoS collective engine (0 = legacy single-tenant FIFO)
QOS_WINDOW = "QOS_WINDOW"  # arbitration window: parked batches before a pump grants
QOS_QUANTUM = "QOS_QUANTUM"  # DRR quantum bytes credited per weight unit per round
QOS_STARVE_LIMIT = "QOS_STARVE_LIMIT"  # grants between forced oldest-first grants (0 = off)
QOS_DEFAULT_PRIORITY = "QOS_DEFAULT_PRIORITY"  # tier for unconfigured tenants
QOS_DEFAULT_WEIGHT = "QOS_DEFAULT_WEIGHT"  # DRR weight for unconfigured tenants
QOS_PENDING_QUOTA = "QOS_PENDING_QUOTA"  # default per-tenant pending-bytes quota (0 = unlimited)
QOS_SHED_POLICY = "QOS_SHED_POLICY"  # quota policy for unconfigured tenants: block | shed
QOS_CLASSES = "QOS_CLASSES"  # per-tenant class spec string (docs/qos.md grammar)
CONFORMANCE = "CONFORMANCE"  # cross-rank lockstep conformance recorder (0 = off)
CONFORMANCE_DIR = "CONFORMANCE_DIR"  # per-rank trace dump directory (empty = dump on demand only)
CONFORMANCE_RING = "CONFORMANCE_RING"  # full-payload ring capacity per rank recorder
CKPT_DIR = "CKPT_DIR"  # sharded async snapshot directory (empty = state plane off)
CKPT_INTERVAL = "CKPT_INTERVAL"  # commits between background snapshots
CKPT_PEER_RESTORE = "CKPT_PEER_RESTORE"  # re-form state re-sync from survivor shards (0 = rank-0 broadcast)
CKPT_SHARD_QUORUM = "CKPT_SHARD_QUORUM"  # min survivors holding a consistent manifest before peer-restore runs

# rendezvous / launcher env seeded by `hvdrun` (reference:
# HOROVOD_RANK/SIZE/LOCAL_RANK... seeded at gloo_run.py:65-101,201-226)
RANK = "RANK"
SIZE = "SIZE"
LOCAL_RANK = "LOCAL_RANK"
LOCAL_SIZE = "LOCAL_SIZE"
CROSS_RANK = "CROSS_RANK"
CROSS_SIZE = "CROSS_SIZE"
COORDINATOR_ADDR = "COORDINATOR_ADDR"
COORDINATOR_PORT = "COORDINATOR_PORT"
NUM_PROCESSES = "NUM_PROCESSES"
PROCESS_ID = "PROCESS_ID"
KV_ADDR = "KV_ADDR"
KV_PORT = "KV_PORT"
SECRET_KEY = "SECRET_KEY"
HOSTNAME = "HOSTNAME"
ELASTIC = "ELASTIC"  # "1" in workers launched by an elastic driver
ELASTIC_ROUND = "ELASTIC_ROUND"  # round a worker was spawned into (seeded)

_PREFIXES = ("HVD_", "HOROVOD_")

# Runtime knob overrides (autotuner). The reference's ParameterManager
# mutates the live knob values in HorovodGlobalState while env-set knobs
# stay fixed (``operations.cc:490-523``); here overrides sit *under* the
# environment: an env-set knob always wins (it is "fixed"), and consumers
# that read knobs through this module pick up tuned values transparently.
_overrides: dict[str, str] = {}

# Bumped on every override mutation. Consumers that cache derived state
# (the dispatch plan cache keys fusion layouts and hierarchical routing off
# knob values) compare epochs instead of re-reading every knob per call.
_override_epoch = 0


def override_epoch() -> int:
    """Monotonic counter of override mutations (see ``_override_epoch``)."""
    return _override_epoch


def set_override(name: str, value) -> None:
    """Install a runtime override for knob ``name`` (autotuner)."""
    global _override_epoch
    value = str(value)
    if _overrides.get(name) == value:
        return  # no-op re-apply (every autotune sample re-applies the
        # whole state) must not bump the epoch and flush dispatch plans
    _overrides[name] = value
    # epoch, not telemetry: keys dispatch-plan invalidation
    _override_epoch += 1  # hvdlint: disable=metrics-registry


def clear_override(name: str) -> None:
    global _override_epoch
    if _overrides.pop(name, None) is not None:
        _override_epoch += 1  # hvdlint: disable=metrics-registry


def clear_overrides() -> None:
    global _override_epoch
    if _overrides:
        _override_epoch += 1  # hvdlint: disable=metrics-registry
    _overrides.clear()


def _overlay() -> dict | None:
    """The loopback rank context's per-thread env overlay (the launcher
    contract for rank THREADS — ``os.environ`` is shared by every rank
    in one interpreter, so per-rank values live here). None outside a
    loopback context."""
    ctx = _lbctx.current()
    return ctx.env if ctx is not None else None


def is_env_fixed(name: str) -> bool:
    """True when the user pinned this knob via the environment — the
    autotuner must treat it as untunable (reference ``SetAutoTuning`` /
    fixed params, ``operations.cc:490-523``). A loopback overlay entry
    counts: it is that rank's environment."""
    ov = _overlay()
    if ov is not None and any((p + name) in ov for p in _PREFIXES):
        return True
    return any(os.environ.get(p + name) is not None for p in _PREFIXES)


def get(name: str, default: str | None = None) -> str | None:
    """Look up knob ``name``: the loopback rank overlay (when on a rank
    thread), then the environment (HVD_/HOROVOD_ prefixes), then runtime
    overrides, then ``default``."""
    ov = _overlay()
    if ov is not None:
        for prefix in _PREFIXES:  # both spellings, like the environ path
            val = ov.get(prefix + name)
            if val is not None:
                return val
    for prefix in _PREFIXES:
        val = os.environ.get(prefix + name)
        if val is not None:
            return val
    val = _overrides.get(name)
    if val is not None:
        return val
    return default


def require(name: str) -> str:
    """Look up knob ``name`` like :func:`get`, but raise when it is absent
    — for the launcher-seeded worker contract (``HVD_RANK``/``HVD_KV_*``),
    where a missing variable means the process was not started by a
    launcher and continuing would only fail more confusingly later."""
    val = get(name)
    if val is None:
        raise RuntimeError(
            f"required environment variable HVD_{name} is not set (workers "
            "expect the launcher-seeded rendezvous contract; see "
            "docs/knobs.md)")
    return val


def set_env(name: str, value, *, only_if_unset: bool = False) -> None:
    """Seed knob ``name`` into the process environment under the ``HVD_``
    prefix (the launcher/bootstrap side of the contract). Writing through
    the registry keeps the knob inventory centralized; ``only_if_unset``
    preserves an existing HVD_/HOROVOD_ spelling (``setdefault``)."""
    ov = _overlay()
    if ov is not None:
        # On a loopback rank thread the write is rank-local: it must
        # never leak into the interpreter-wide environment the other
        # ranks (and the main thread) read.
        if only_if_unset and (any((p + name) in ov for p in _PREFIXES)
                              or any(os.environ.get(p + name) is not None
                                     for p in _PREFIXES)):
            return
        ov["HVD_" + name] = str(value)
        return
    if only_if_unset and any(
            os.environ.get(p + name) is not None for p in _PREFIXES):
        return
    os.environ["HVD_" + name] = str(value)


def get_bool(name: str, default: bool = False) -> bool:
    val = get(name)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def get_int(name: str, default: int) -> int:
    val = get(name)
    if val is None:
        return default
    try:
        return int(val)
    except ValueError:
        return default


def get_float(name: str, default: float) -> float:
    val = get(name)
    if val is None:
        return default
    try:
        return float(val)
    except ValueError:
        return default


# Defaults mirrored from the reference (operations.cc:491-506, global_state.h:89).
DEFAULT_FUSION_THRESHOLD_BYTES = 128 * 1024 * 1024
DEFAULT_CYCLE_TIME_MS = 1.0
DEFAULT_CACHE_CAPACITY = 1024
DEFAULT_STALL_WARNING_SECONDS = 60.0


def fusion_threshold_bytes() -> int:
    return get_int(FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD_BYTES)


def cycle_time_ms() -> float:
    return get_float(CYCLE_TIME, DEFAULT_CYCLE_TIME_MS)


def cache_capacity() -> int:
    return get_int(CACHE_CAPACITY, DEFAULT_CACHE_CAPACITY)


# Pipelined flush executor defaults. Two in-flight slots are the classic
# double-buffering point: flush k+1's host-side fuse/negotiation overlaps
# flush k's device collective without unbounded device-queue growth. The
# 4 MiB / 4-chunk pipeline splits a large fused wire buffer into chunk
# programs so the collective of chunk i overlaps the fuse/split (and, on
# the CPU mesh, the per-device execution) of its neighbors.
DEFAULT_MAX_INFLIGHT_FLUSHES = 2
DEFAULT_PIPELINE_THRESHOLD_BYTES = 4 * 1024 * 1024
DEFAULT_PIPELINE_CHUNKS = 4


def max_inflight_flushes() -> int:
    return get_int(MAX_INFLIGHT_FLUSHES, DEFAULT_MAX_INFLIGHT_FLUSHES)


def pipeline_enabled() -> bool:
    """The pipelined flush executor is engaged at >= 2 slots; 0/1 keep the
    synchronous (execute-on-the-triggering-thread) behavior byte-for-byte."""
    return max_inflight_flushes() >= 2


def pipeline_threshold_bytes() -> int:
    return get_int(PIPELINE_THRESHOLD, DEFAULT_PIPELINE_THRESHOLD_BYTES)


def pipeline_chunks() -> int:
    return get_int(PIPELINE_CHUNKS, DEFAULT_PIPELINE_CHUNKS)


# Gradient bucketing (optim/_bucketed_allreduce): the backward pass's
# dense gradient pytree is partitioned into size-bounded buckets, each
# issued as its own async grouped allreduce so bucket k's collective is
# in flight while bucket k+1 fuses host-side. 64 MiB matches the
# reference's fusion-buffer sweet spot (half the 128 MB threshold: big
# enough to amortize dispatch, small enough that several buckets pipeline
# through the executor's slots). 0 = whole-tree single grouped call.
DEFAULT_BUCKET_BYTES = 64 * 1024 * 1024


def bucket_bytes() -> int:
    return get_int(BUCKET_BYTES, DEFAULT_BUCKET_BYTES)


def step_capture_enabled() -> bool:
    """Step capture-and-replay (``ops/step_capture.py``): record the
    marked step's rank-deterministic flush stream once, then replay the
    whole step's collective work as ONE cached jitted program. Off by
    default — the eager per-flush path is the reference behavior; the
    capture plan invalidates transparently on any stream divergence.
    Mutually exclusive with the multi-tenant QoS engine: capture assumes
    ONE repeating single-tenant flush stream, while QoS interleaves
    tenants' flushes by admission policy — with ``HVD_QOS=1`` capture
    stays off (the transparent eager path, like any divergence;
    docs/qos.md)."""
    return get_bool(STEP_CAPTURE, False) and not qos_enabled()


def gspmd_cache_enabled() -> bool:
    """GSPMD cached-program fast path (``ops/gspmd_cache.py``): store
    lowered+compiled jit/pjit step executables in the dispatch plan
    cache under a stable step signature, so re-created step closures
    replay instead of retracing. Default on — ``hvd.cached_step`` is an
    explicit opt-in API, so the knob is a kill switch; it also rides
    the cache-wide ``HVD_CACHE_CAPACITY=0`` off switch (cached steps
    are dispatch plans like any other)."""
    return get_bool(GSPMD_CACHE, True) and cache_capacity() > 0


def gspmd_donate_enabled(platform: str) -> bool:
    """Whether cached GSPMD steps donate their parameter/optimizer
    buffers (``donate_argnums`` derived from the step's pytree layout).
    'auto' follows :func:`donation_effective`: on backends where
    donation is a memory no-op the derivation (an extra abstract trace)
    buys nothing."""
    val = (get(GSPMD_CACHE_DONATE, "auto") or "auto").strip().lower()
    if val in ("1", "true", "yes", "on"):
        return True
    if val in ("0", "false", "no", "off"):
        return False
    return donation_effective(platform)


def pipeline_chunking_enabled() -> bool:
    """Large-buffer chunk pipelining rides the pipelined executor: it is
    part of the same overlap mechanism, and disabling the executor
    (MAX_INFLIGHT_FLUSHES<=1) must restore the exact pre-pipeline
    program compositions."""
    return (pipeline_enabled() and pipeline_threshold_bytes() > 0
            and pipeline_chunks() >= 2)


# Failure-domain defaults (docs/robustness.md). The health timeout must sit
# far below the 600 s exchange deadline — a dead peer should surface as a
# PeerFailureError in seconds, not after the full negotiation budget. The
# retry ladder (50 ms * 2^k capped at 2 s, 5 attempts) absorbs single-digit
# seconds of KV/coordinator flap without masking a real outage.
DEFAULT_HEALTH_INTERVAL_S = 2.0
DEFAULT_HEALTH_TIMEOUT_S = 30.0
DEFAULT_RETRY_MAX_ATTEMPTS = 5
DEFAULT_RETRY_BACKOFF_MS = 50.0
DEFAULT_RETRY_MAX_BACKOFF_MS = 2000.0
DEFAULT_RETRY_JITTER = 0.25


def health_interval_s() -> float:
    return get_float(HEALTH_INTERVAL, DEFAULT_HEALTH_INTERVAL_S)


def health_timeout_s() -> float:
    return get_float(HEALTH_TIMEOUT, DEFAULT_HEALTH_TIMEOUT_S)


# Straggler attribution (health.StragglerTracker, docs/metrics.md): a rank
# whose negotiation frame reaches the KV server this many seconds after
# the round's first submitter is counted a straggler for that round. 1 s
# sits far above loopback/LAN submit jitter (single-digit ms) and far
# below the health timeout — sustained straggling warns long before a
# rank looks dead.
DEFAULT_STRAGGLER_THRESHOLD_S = 1.0


def straggler_threshold_s() -> float:
    return get_float(STRAGGLER_THRESHOLD, DEFAULT_STRAGGLER_THRESHOLD_S)


# Multi-tenant QoS defaults (horovod_tpu/qos.py, docs/qos.md). The
# 4-batch arbitration window keeps the gate's deterministic reordering
# span small (latency) while letting strict-priority/DRR ordering bite
# on a backlog; the 64 KiB quantum approximates one small fused flush,
# so weights translate into byte shares at flush granularity; the
# 16-grant starvation valve bounds how long strict priority can hold a
# low-tier batch (deterministic grant-count aging, never wall-clock —
# wall-clock aging would break the rank-deterministic grant order).
DEFAULT_QOS_WINDOW = 4
DEFAULT_QOS_QUANTUM = 64 * 1024
DEFAULT_QOS_STARVE_LIMIT = 16
DEFAULT_QOS_WEIGHT = 1.0


# Conformance recorder defaults (horovod_tpu/conformance.py,
# docs/conformance.md). The 256-event payload ring bounds per-rank
# memory while keeping the recent window a divergence report needs —
# the compact per-event digest chain localizes ANY event; the ring only
# decides whether its full payload is still quotable.
DEFAULT_CONFORMANCE_RING = 256


def conformance_enabled() -> bool:
    """Cross-rank lockstep conformance recorder
    (``horovod_tpu/conformance.py``): off by default — every decision
    point's hook is then one cached module-bool check and an early
    return (the ``utils/faults.py`` fast-path idiom)."""
    return get_bool(CONFORMANCE, False)


def conformance_dir() -> str:
    """``HVD_CONFORMANCE_DIR``: directory for per-rank trace dumps at
    shutdown/abort. Empty (default) = traces stay in memory and are
    only materialized by an explicit ``hvd.conformance_dump()``."""
    return (get(CONFORMANCE_DIR, "") or "").strip()


def conformance_ring() -> int:
    return max(0, get_int(CONFORMANCE_RING, DEFAULT_CONFORMANCE_RING))


# Checkpoint state plane defaults (horovod_tpu/checkpoint.py,
# docs/checkpoint.md). Snapshotting every commit would put a host-side
# pickle+write on every step's critical path shadow; every 10th commit
# keeps the restore point seconds-fresh at commit-per-step cadence while
# the background thread stays comfortably ahead. Peer-restore defaults
# ON unconditionally — it serves from the survivors' LIVE committed
# trees (no snapshot directory required) and the degraded rank-0
# broadcast stays available as the typed fallback, so the fast path is
# safe to prefer. Quorum 1 admits the smallest useful survivor set; jobs
# that fear a lone corrupted survivor raise it.
DEFAULT_CKPT_INTERVAL = 10
DEFAULT_CKPT_SHARD_QUORUM = 1


def ckpt_dir() -> str:
    """``HVD_CKPT_DIR``: root directory for sharded background
    snapshots (``horovod_tpu/checkpoint.py`` state plane). Empty
    (default) = the state plane is off and elastic re-forms re-sync via
    the rank-0 broadcast only."""
    return (get(CKPT_DIR, "") or "").strip()


def ckpt_interval() -> int:
    return max(1, get_int(CKPT_INTERVAL, DEFAULT_CKPT_INTERVAL))


def ckpt_peer_restore_enabled() -> bool:
    """Whether a re-formed world re-syncs model state by pulling shards
    from survivors instead of the rank-0 full-tree broadcast. Only
    meaningful when survivors exist; the degraded broadcast path always
    remains the fallback."""
    return get_bool(CKPT_PEER_RESTORE, True)


def ckpt_shard_quorum() -> int:
    return max(1, get_int(CKPT_SHARD_QUORUM, DEFAULT_CKPT_SHARD_QUORUM))


def qos_enabled() -> bool:
    """Multi-tenant QoS collective engine (``horovod_tpu/qos.py``): off
    by default — ``HVD_QOS=0`` keeps the single-tenant FIFO flush
    pipeline byte-for-byte."""
    return get_bool(QOS, False)


def qos_window() -> int:
    return get_int(QOS_WINDOW, DEFAULT_QOS_WINDOW)


def qos_quantum_bytes() -> int:
    return get_int(QOS_QUANTUM, DEFAULT_QOS_QUANTUM)


def qos_starve_limit() -> int:
    return get_int(QOS_STARVE_LIMIT, DEFAULT_QOS_STARVE_LIMIT)


def mesh_axes() -> str:
    """``HVD_MESH_AXES``: the composed-mesh model-axis carve
    (``parallel/mesh.py``), a comma list of ``name:size`` pairs carved
    out of the ICI island — e.g. ``"seq:2"`` or ``"expert:4,stage:2"``.
    Empty (default) = no model axes: the pure data-parallel
    ``dcn × ici_dp`` layout."""
    return (get(MESH_AXES, "") or "").strip()


# Hierarchical negotiation control plane (horovod_tpu/negotiation/,
# docs/negotiation.md). Group size 8 mirrors the data path's ICI-island
# default (ops/hierarchical.py): one leader per "island" runs the
# cross-leader exchange while members pay O(1) KV ops per round. The
# coordinator ResponseCache defaults to AUTO: on (default capacity)
# whenever the hierarchical control plane is active for the world —
# those are the worlds where steady-state batches already serve with
# zero KV rounds and the cache's divergence-surfacing tradeoff (a
# diverged rank times out instead of every rank seeing the mismatch
# error) is paid for by a typed join-race error + invalidation
# telemetry (docs/troubleshooting.md). Flat small worlds stay off, and
# ``HVD_RESPONSE_CACHE=0`` is a hard off.
DEFAULT_NEGOTIATION_GROUP_SIZE = 8
DEFAULT_RESPONSE_CACHE_CAPACITY = 1024


def negotiation_group_size() -> int:
    return max(1, get_int(NEGOTIATION_GROUP_SIZE,
                          DEFAULT_NEGOTIATION_GROUP_SIZE))


def response_cache_capacity(world_size: int | None = None) -> int:
    """``HVD_RESPONSE_CACHE``: ``auto`` (default) = on at the default
    capacity when hierarchical negotiation is active for ``world_size``
    (else off; ``None`` — callers without a world — reads as off);
    ``0`` = hard off; ``1`` = on at the default capacity; any larger
    value = on with that many entries."""
    raw = (get(RESPONSE_CACHE, "auto") or "auto").strip().lower()
    if raw in ("auto", ""):
        if world_size is not None and hier_negotiation_enabled(world_size):
            return DEFAULT_RESPONSE_CACHE_CAPACITY
        return 0
    try:
        v = int(raw)
    except ValueError:
        v = 0
    if v <= 0:
        return 0
    return DEFAULT_RESPONSE_CACHE_CAPACITY if v == 1 else v


def hier_negotiation_enabled(world_size: int) -> bool:
    """Whether the two-level (leader/member) negotiation exchange runs
    for a service of ``world_size`` members. ``auto`` (default) engages
    it only when the world is larger than one leader group — small
    worlds keep today's flat protocol byte-for-byte."""
    val = (get(HIER_NEGOTIATION, "auto") or "auto").strip().lower()
    if val in ("1", "true", "yes", "on"):
        return world_size > 1
    if val in ("0", "false", "no", "off"):
        return False
    return world_size > negotiation_group_size()


# Closed-loop elastic autoscaling (elastic/policy.py, docs/elastic.md).
# The 2 s evaluation window matches the health-beat default: membership
# decisions ride the same "seconds, not negotiation deadlines" cadence.
# Hysteresis defaults are asymmetric on purpose — growing is cheap and
# reversible (3 breach windows), shrinking throws capacity away (5 idle
# windows), and eviction replaces a live-but-slow worker (3 blame
# windows, the StragglerTracker's own sustain default). The 15 s
# cooldown spans a loopback re-form plus settle time, so one decision's
# own disruption can never read as the next window's signal (the
# oscillation bound tested by the adversarial flapping load).
DEFAULT_AUTOSCALE_INTERVAL_S = 2.0
DEFAULT_AUTOSCALE_BREACH_WINDOWS = 3
DEFAULT_AUTOSCALE_IDLE_WINDOWS = 5
DEFAULT_AUTOSCALE_EVICT_WINDOWS = 3
DEFAULT_AUTOSCALE_COOLDOWN_S = 15.0
DEFAULT_AUTOSCALE_GRACE_S = 30.0
DEFAULT_AUTOSCALE_IDLE_FACTOR = 0.5


def autoscale_enabled() -> bool:
    """Closed-loop autoscaling (``elastic/policy.py``): the driver-side
    policy decides ``add``/``remove``/``evict`` from the metrics-registry
    sensors instead of a script. Off by default — scripted churn and
    manual discovery stay the only membership sources."""
    return get_bool(AUTOSCALE, False)


def autoscale_slo_s() -> float:
    """Step-time SLO target in SECONDS (knob is ms). 0 disables the
    breach/idle rules — the policy then only evicts stragglers."""
    return get_float(AUTOSCALE_SLO_MS, 0.0) / 1e3


def autoscale_interval_s() -> float:
    return get_float(AUTOSCALE_INTERVAL, DEFAULT_AUTOSCALE_INTERVAL_S)


# Elastic warm re-form (docs/elastic.md): plan stores / step plans /
# coordinator response-cache entries are keyed by process-set *shape*
# and survive a world resize instead of being flushed wholesale — a
# resize back to a previously-seen shape (the common preemption-then-
# recovery case) reuses them. 'auto' enables this only on loopback rank
# threads: a process-path re-form tears down the XLA backend
# (clear_backends), so compiled programs cannot outlive the world there.
def elastic_warm_enabled() -> bool:
    val = (get(ELASTIC_WARM, "auto") or "auto").strip().lower()
    if val in ("1", "true", "yes", "on"):
        return True
    if val in ("0", "false", "no", "off"):
        return False
    return _lbctx.current() is not None


def donation_effective(platform: str) -> bool:
    """Whether buffer donation actually recycles memory on this backend.
    The CPU backend ignores donation while still paying per-call
    bookkeeping for it, so donation-dependent optimizations gate on
    this."""
    return platform not in ("cpu",)


def pipeline_pingpong_enabled(platform: str) -> bool:
    """Ping-pong wire-buffer recycling needs real buffer donation; the CPU
    backend ignores donation, turning each recycle output into a copy —
    'auto' therefore enables it off-CPU only."""
    val = (get(PIPELINE_PINGPONG, "auto") or "auto").strip().lower()
    if val in ("1", "true", "yes", "on"):
        return True
    if val in ("0", "false", "no", "off"):
        return False
    return donation_effective(platform)


def eager_chain_enabled(platform: str) -> bool:
    """Whether eager consumer programs may chain on still-in-flight
    collective results (``Handle.result()`` / the optimizer's gradient
    sync returning before device completion). On the XLA CPU backend the
    client runs every per-device execution on one shared thread pool, so
    consumer programs racing an in-flight multi-program collective
    (chunked wire dispatch, pipelined buckets) can occupy the pool while
    blocked on the collective's outputs — starving the rendezvous of its
    remaining participants and deadlocking the process (reproduced by
    ``bench.py --step-bench``). 'auto' therefore chains off-CPU only;
    on CPU results materialize before consumer math sees them."""
    val = (get(EAGER_CHAIN, "auto") or "auto").strip().lower()
    if val in ("1", "true", "yes", "on"):
        return True
    if val in ("0", "false", "no", "off"):
        return False
    return platform not in ("cpu",)
