"""Deterministic, spec-driven fault injection (``HVD_FAULT_SPEC``).

The failure paths grown across PRs 1-4 — pipelined flush executor,
negotiation service, KV transport, elastic rounds — were essentially
untestable because nothing in the tree could *produce* a failure on
demand. This module is the chaos half of the failure domain
(docs/robustness.md): named injection points threaded through the seams
the runtime already owns fire **deterministically** from a seeded spec,
so a chaos test reproduces the exact same fault sequence on every run.

Spec grammar (semicolon-separated rules)::

    HVD_FAULT_SPEC = "site:action[:key=value]..."  [";" more rules]

    kv.put:error:p=0.2:seed=7        # 20% of KV PUTs raise FaultInjected
    svc.exchange:delay=0.5:after=3   # negotiation rounds 4+ sleep 0.5 s
    worker:crash:rank=1:at_step=5    # rank 1 hard-exits at commit #5

* **site** — injection-point name (table in docs/robustness.md); a
  trailing ``*`` prefix-matches (``kv.*`` covers put/get/delete).
  ``policy.eval`` fires inside the autoscale policy's evaluation
  window (docs/elastic.md): an injected error there must degrade to a
  counted ``hold`` decision, never a job failure — the policy's
  failure-semantics contract, tested through exactly this seam.
* **action** — ``error`` (raise :class:`FaultInjected`), ``crash``
  (``os._exit``; code via ``code=N``, default 1), ``delay=<seconds>``
  (sleep, then continue), or — at the ``worker`` site only — a
  **membership action** driving elastic churn (docs/elastic.md):
  ``add`` (``count=K`` fresh hosts join discovery), ``remove`` (the
  firing rank's host leaves discovery; the driver reclaims it
  abruptly), ``preempt`` (SIGTERM-style: the departing rank drains its
  in-flight flushes at the commit boundary, its host leaves discovery,
  and the driver grants it ``grace=S`` seconds to exit cleanly through
  the slot-lost path instead of terminating it mid-collective).
  Membership actions fire through the handler installed by the elastic
  front end (:func:`set_membership_handler`); with no handler they log
  and no-op. They default to ``times=1`` — one scheduled event each.
* **filters** — ``p=<0..1>`` fire probability (deterministic, from
  ``seed=``), ``after=N`` skip the first N matching calls, ``times=N``
  fire at most N times, ``rank=R`` / ``at_step=S`` / ``at_round=R``
  match the caller's context (``rank`` falls back to the
  launcher-seeded ``HVD_RANK``; ``at_step`` counts ``State.commit``
  calls; ``at_round`` matches the elastic round the worker currently
  runs in — ``HVD_ELASTIC_ROUND`` — so schedules can target re-form
  boundaries deterministically).

Determinism: the probability draw is **not** ``random`` — it hashes
``(seed, site, call-index)`` through ``zlib.crc32``, so a fixed seed
yields the identical fire pattern on every run and on every rank (and
the module stays legal in timer-reachable code, where the hvdlint
timer-purity pass bans randomness).

Fast path: with ``HVD_FAULT_SPEC`` unset, :func:`inject` is one module
attribute read and one ``is None`` check (the PR-4 ``invariants.py``
cached-bool idiom) — the hooks cost nothing in production.
"""

from __future__ import annotations

import threading
import time
import zlib

from . import envs


class FaultInjected(RuntimeError):
    """An injected fault fired at ``site`` (never raised in production:
    only a parsed ``HVD_FAULT_SPEC`` can construct one)."""

    def __init__(self, site: str, rule: str):
        super().__init__(
            f"injected fault at {site!r} (HVD_FAULT_SPEC rule {rule!r})")
        self.site = site
        self.rule = rule


class FaultSpecError(ValueError):
    """``HVD_FAULT_SPEC`` could not be parsed."""


_ACTIONS = ("error", "crash", "delay")
# Elastic-churn membership actions (docs/elastic.md): legal only at the
# `worker` site (State.commit — the step boundary), dispatched through
# the handler the elastic front end installs. Scheduled events, so they
# default to firing exactly once.
_MEMBERSHIP_ACTIONS = ("add", "remove", "preempt")


class _Rule:
    __slots__ = ("site", "action", "delay_s", "exit_code", "p", "seed",
                 "after", "times", "rank", "at_step", "at_round", "text",
                 "count", "grace_s", "calls", "fires")

    def __init__(self, text: str):
        self.text = text
        parts = text.split(":")
        if len(parts) < 2:
            raise FaultSpecError(
                f"fault rule {text!r}: expected 'site:action[:key=value]...'")
        self.site = parts[0].strip()
        if not self.site:
            raise FaultSpecError(f"fault rule {text!r}: empty site")
        action = parts[1].strip()
        self.delay_s = 0.0
        if action.startswith("delay="):
            self.action = "delay"
            try:
                self.delay_s = float(action[len("delay="):])
            except ValueError:
                raise FaultSpecError(
                    f"fault rule {text!r}: bad delay value "
                    f"{action[len('delay='):]!r}")
        elif action in ("error", "crash"):
            self.action = action
        elif action in _MEMBERSHIP_ACTIONS:
            if self.site != "worker":
                raise FaultSpecError(
                    f"fault rule {text!r}: membership action {action!r} is "
                    "only legal at the 'worker' site (the commit boundary)")
            self.action = action
        else:
            raise FaultSpecError(
                f"fault rule {text!r}: unknown action {action!r} "
                f"(expected one of {_ACTIONS + _MEMBERSHIP_ACTIONS}, "
                "delay as 'delay=<seconds>')")
        self.exit_code = 1
        self.p = 1.0
        self.seed = 0
        self.after = 0
        self.times: int | None = (
            1 if self.action in _MEMBERSHIP_ACTIONS else None)
        self.rank: int | None = None
        self.at_step: int | None = None
        self.at_round: int | None = None
        self.count = 1          # add: hosts to add
        self.grace_s = 30.0     # preempt: driver-side stale-worker grace
        for param in parts[2:]:
            key, sep, value = param.partition("=")
            key = key.strip()
            if not sep:
                raise FaultSpecError(
                    f"fault rule {text!r}: parameter {param!r} is not "
                    "key=value")
            try:
                if key == "p":
                    self.p = float(value)
                elif key == "seed":
                    self.seed = int(value)
                elif key == "after":
                    self.after = int(value)
                elif key == "times":
                    self.times = int(value)
                elif key == "rank":
                    self.rank = int(value)
                elif key == "at_step":
                    self.at_step = int(value)
                elif key == "at_round":
                    self.at_round = int(value)
                elif key == "code":
                    self.exit_code = int(value)
                elif key == "count":
                    self.count = int(value)
                elif key == "grace":
                    self.grace_s = float(value)
                else:
                    raise FaultSpecError(
                        f"fault rule {text!r}: unknown parameter {key!r}")
            except ValueError as e:
                if isinstance(e, FaultSpecError):
                    raise
                raise FaultSpecError(
                    f"fault rule {text!r}: bad value for {key!r}: {value!r}")
        if not 0.0 <= self.p <= 1.0:
            raise FaultSpecError(
                f"fault rule {text!r}: p={self.p} outside [0, 1]")
        if self.count < 1:
            raise FaultSpecError(
                f"fault rule {text!r}: count={self.count} must be >= 1")
        if self.grace_s < 0:
            raise FaultSpecError(
                f"fault rule {text!r}: grace={self.grace_s} must be >= 0")
        self.calls = 0  # matching calls seen (drives `after` and the draw)
        self.fires = 0

    def matches_site(self, site: str) -> bool:
        if self.site.endswith("*"):
            return site.startswith(self.site[:-1])
        return site == self.site

    def _draw(self, call_index: int) -> float:
        """Deterministic uniform in [0, 1): hash of (seed, site, index).
        Reproducible across runs/ranks for a fixed seed, unlike
        ``random`` (also banned in timer-reachable code)."""
        h = zlib.crc32(f"{self.seed}:{self.site}:{call_index}".encode())
        return (h & 0xFFFFFFFF) / float(1 << 32)

    def should_fire(self, rank: int | None, step: int | None,
                    round_id: int | None = None) -> bool:
        """Advance this rule's call counter for a site match and decide.
        Caller holds the spec lock."""
        if self.rank is not None and (rank is None or rank != self.rank):
            return False
        if self.at_step is not None and (step is None
                                         or step != self.at_step):
            return False
        if self.at_round is not None and (round_id is None
                                          or round_id != self.at_round):
            return False
        self.calls += 1
        if self.calls <= self.after:
            return False
        if self.times is not None and self.fires >= self.times:
            return False
        if self.p < 1.0 and self._draw(self.calls) >= self.p:
            return False
        self.fires += 1
        return True


class _Spec:
    __slots__ = ("rules", "mu", "default_rank", "needs_round")

    def __init__(self, text: str):
        self.rules = [_Rule(part.strip())
                      for part in text.split(";") if part.strip()]
        if not self.rules:
            raise FaultSpecError(
                f"HVD_FAULT_SPEC {text!r} contains no rules")
        # Injection points that don't know their rank (KV client, engine
        # transport) match `rank=` rules against the launcher-seeded rank.
        self.default_rank = envs.get_int(envs.RANK, -1)
        if self.default_rank < 0:
            self.default_rank = None
        # Elastic round context is only read from the env when a rule
        # filters on it (the common non-elastic chaos run skips the read).
        self.needs_round = any(r.at_round is not None for r in self.rules)
        self.mu = threading.Lock()


def parse_spec(text: str) -> list[_Rule]:
    """Parse a spec string into rules (raises :class:`FaultSpecError`);
    exposed for tests and the docs' grammar examples."""
    return _Spec(text).rules


# The cached spec. None == injection off == the production fast path:
# inject() is one attribute read and one `is None` check.
_SPEC: _Spec | None = None


def _load() -> _Spec | None:
    text = envs.get(envs.FAULT_SPEC)
    return _Spec(text) if text else None


_SPEC = _load()


def active() -> bool:
    """Whether any fault rule is installed (cached; see :func:`refresh`)."""
    return _SPEC is not None


def refresh() -> None:
    """Re-read ``HVD_FAULT_SPEC`` (tests toggle it after import). A bad
    spec raises :class:`FaultSpecError` and leaves injection off —
    a typo must fail the chaos run, not silently disable it."""
    global _SPEC
    _SPEC = None
    _SPEC = _load()


def stats() -> dict:
    """Per-rule call/fire counters, keyed by rule text (chaos tests
    assert on these; surfaced through ``hvd.health_stats()``)."""
    spec = _SPEC
    if spec is None:
        return {}
    with spec.mu:
        return {r.text: {"site": r.site, "calls": r.calls, "fires": r.fires}
                for r in spec.rules}


# --------------------------------------------------------------------------
# elastic-churn membership actions (docs/elastic.md): `worker:add/remove/
# preempt` rules fire through a handler the elastic front end installs
# (loopback `elastic_run` wires `discovery.ScriptedChurn`). The handler
# runs on the firing rank's thread at its commit boundary, so it can read
# the rank's env contract (HVD_HOSTNAME) and drain the rank's own queues.
# --------------------------------------------------------------------------

_membership_handler = None


def set_membership_handler(handler) -> None:
    """Install ``handler(action: str, rule)`` for membership actions.
    One handler per process (the elastic driver front end owns churn)."""
    global _membership_handler
    _membership_handler = handler


def clear_membership_handler() -> None:
    global _membership_handler
    _membership_handler = None


def has_membership_rules() -> bool:
    """Whether the installed spec schedules any membership churn — the
    elastic front ends use this to decide whether to wire a handler."""
    spec = _SPEC
    return spec is not None and any(
        r.action in _MEMBERSHIP_ACTIONS for r in spec.rules)


def _crash(code: int) -> None:  # monkeypatched by tests
    from ..loopback import context as _lbctx
    ctx = _lbctx.current()
    if ctx is not None:
        # A loopback rank's "process death": os._exit would take every
        # rank (the whole interpreter) down. Tear the rank down HERE —
        # not only in the rank-thread wrapper — because the crash site
        # may run on a rank-owned helper thread (the negotiation cycle
        # loop, a retrying KV call): RankKilled would unwind just that
        # thread while the watchdog kept beating, and peers would never
        # notice the death. The abrupt stop ceases beats and fails this
        # rank's own waiters with RankKilled, so the main thread unwinds
        # as killed too.
        ctx.dead = True
        exc = _lbctx.RankKilled(code)
        try:
            from ..loopback import engine as _lbengine
            _lbengine._abrupt_stop(ctx, reason=str(exc), exc=exc)
        except Exception as e:
            from . import logging as hvd_logging
            hvd_logging.warning("loopback crash teardown failed: %s", e)
        import threading
        if (ctx.main_thread is not None
                and threading.current_thread() is not ctx.main_thread):
            # helper thread (cycle loop, retry ladder): die silently like
            # a thread of a dead process — the rank's main thread unwinds
            # as RankKilled through its failed waiters (threading swallows
            # SystemExit in non-main threads; RankKilled here would only
            # trip the unhandled-thread-exception hook)
            raise SystemExit(code)
        raise exc
    import os
    os._exit(code)


def _caller_rank(spec: _Spec) -> int | None:
    """Rank context for sites that don't pass one: a loopback rank
    thread's overlay rank (each thread is its own "process"), else the
    spec-load-time launcher rank."""
    from ..loopback import context as _lbctx
    if _lbctx.current() is not None:
        r = envs.get_int(envs.RANK, -1)
        return r if r >= 0 else None
    return spec.default_rank


def inject(site: str, *, rank: int | None = None,
           step: int | None = None) -> None:
    """The injection seam: no-op unless a spec rule matches ``site`` (and
    its rank/step/after/times/p filters) — then sleep, raise, or exit
    per the rule's action. ``rank``/``step`` are optional caller context;
    rank falls back to the launcher-seeded process rank."""
    spec = _SPEC
    if spec is None:
        return
    if rank is None:
        rank = _caller_rank(spec)
    round_id = None
    if spec.needs_round:
        r = envs.get_int(envs.ELASTIC_ROUND, -1)
        round_id = r if r >= 0 else None
    fired = None
    with spec.mu:
        for rule in spec.rules:
            if not rule.matches_site(site):
                continue
            if rule.should_fire(rank, step, round_id):
                fired = rule
                break
    if fired is None:
        return
    from .. import metrics as _metrics
    _metrics.FAULT_FIRES.inc(labels={"site": site})
    if fired.action == "delay":
        time.sleep(fired.delay_s)
        return
    if fired.action == "crash":
        _crash(fired.exit_code)
        return
    if fired.action in _MEMBERSHIP_ACTIONS:
        handler = _membership_handler
        if handler is None:
            from . import logging as hvd_logging
            hvd_logging.warning(
                "membership fault %r fired with no churn handler "
                "installed (elastic front end not wired); ignoring",
                fired.text)
            return
        handler(fired.action, fired)
        return
    raise FaultInjected(site, fired.text)
