"""Rank-aware logging.

Equivalent of the reference's ``LOG(level, rank)`` macros with env-controlled
level and timestamps (``/root/reference/horovod/common/logging.cc:76-95``),
built on Python ``logging``.
"""

from __future__ import annotations

import logging
import sys

from . import envs

_LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(5, "TRACE")

_logger: logging.Logger | None = None


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("horovod_tpu")
        level_name = (envs.get(envs.LOG_LEVEL) or "warning").lower()
        logger.setLevel(_LEVELS.get(level_name, logging.WARNING))
        handler = logging.StreamHandler(sys.stderr)
        if envs.get_bool(envs.LOG_TIMESTAMP, True):
            fmt = "[%(asctime)s] [hvd-tpu] [%(levelname)s] %(message)s"
        else:
            fmt = "[hvd-tpu] [%(levelname)s] %(message)s"
        handler.setFormatter(logging.Formatter(fmt))
        logger.addHandler(handler)
        logger.propagate = False
        _logger = logger
    return _logger


def log(level: str, msg: str, *args) -> None:
    get_logger().log(_LEVELS.get(level, logging.INFO), msg, *args)


def debug(msg: str, *args) -> None:
    get_logger().debug(msg, *args)


def info(msg: str, *args) -> None:
    get_logger().info(msg, *args)


def warning(msg: str, *args) -> None:
    get_logger().warning(msg, *args)


def error(msg: str, *args) -> None:
    get_logger().error(msg, *args)


def exception(msg: str, *args) -> None:
    get_logger().exception(msg, *args)
