"""Dev-mode runtime concurrency invariant checker (``HVD_DEBUG_INVARIANTS=1``).

The runtime's correctness rests on a handful of concurrency invariants
that exist as prose in docs/pipeline.md and docs/fusion_cycle.md: locks
are always taken in a consistent order, executor-private state is only
touched from the executor thread, pending-queue state only mutates under
the queue lock, and a flush execution never re-enters the scheduler's
enqueue path on the same thread. The static suite (``tools/hvdlint``)
checks the *lexical* shape of those invariants; this module checks the
*dynamic* shape — what threads actually did at runtime — and raises
:class:`InvariantViolation` at the first divergence, with enough context
(both acquisition stacks for a lock-order inversion) to debug it.

Everything here is OFF by default: with ``HVD_DEBUG_INVARIANTS`` unset,
:func:`make_lock` / :func:`make_rlock` / :func:`make_condition` return
plain :mod:`threading` primitives and every ``assert_*`` helper returns
immediately, so production pays one cached boolean check per call site.
CI runs the threaded stress suites (``tests/test_pipeline_flush.py``,
``tests/test_fusion_cycle.py``) with the checker on; see
docs/static_analysis.md.

The three checkers:

* **Lock-order witness**: tracked locks record, per thread, the stack of
  held locks. The first time lock ``B`` is acquired while ``A`` is held,
  the edge ``A -> B`` is recorded together with the acquisition stack;
  a later attempt to take ``A`` while holding ``B`` raises with BOTH
  stacks (the recorded one and the current one) before blocking — the
  witness reports the potential deadlock instead of exhibiting it.
* **Thread-affinity assertions**: :func:`assert_thread` (state owned by
  one thread — the flush executor's in-flight window),
  :func:`assert_holding` (state guarded by a lock — the scheduler's
  pending queues, the dispatch-plan cache's LRU map).
* **Re-entrancy guard**: :func:`section` / :func:`assert_outside` detect
  a thread re-entering a code region it is already inside (a flush
  execution calling back into ``enqueue`` would self-deadlock on the
  synchronous path and corrupt flush composition on the pipelined one).

Violations raise by default (``raise_on_violation``) AND are counted;
:func:`report` returns the counters so stress tests can assert "zero
invariant reports" even where an exception would be swallowed by a
daemon loop.

**The hvdsched seam** (``HVD_SCHED_CHECK=1``, docs/schedule_checker.md):
this module is also where the controlled-concurrency model checker
(``tools/hvdsched``) plugs in. Under ``HVD_SCHED_CHECK=1`` the
constructors return *cooperative* primitives driven by hvdsched's
serializing scheduler, and the concurrency core additionally routes
event creation (:func:`make_event`), thread creation
(:func:`spawn_thread`), thread joins (:func:`join_thread`), sleeps
(:func:`sleep`) and monotonic-clock reads (:func:`monotonic`) through
here so the checker can serialize every interleaving point and run time
on a virtual clock. With the knob unset each of those helpers is a thin
alias for the plain :mod:`threading`/:mod:`time` call — the production
code path is unchanged.
"""

from __future__ import annotations

import threading
import time
import traceback

from . import envs

# Guards the witness's own state (edge graph, violation log). A plain,
# untracked lock: it is only ever taken with no tracked lock operation in
# progress on this thread, never exposed, and never nested.
_state_lock = threading.Lock()

# (held_name, acquired_name) -> formatted stack of the first acquisition
# that created the edge; _adjacent is the same graph keyed for traversal
# (transitive-cycle detection).
_edges: dict[tuple[str, str], str] = {}
_adjacent: dict[str, set[str]] = {}

_violations: list[str] = []
_counts: dict[str, int] = {"lock-order": 0, "thread-affinity": 0,
                           "lock-held": 0, "reentrancy": 0}

raise_on_violation = True

_tls = threading.local()

_MAX_VIOLATIONS = 64  # keep report() bounded under a pathological loop


class InvariantViolation(AssertionError):
    """A dev-mode concurrency invariant was broken. Inherits
    AssertionError so test harnesses treat it as a failed check."""


def _env_enabled() -> bool:
    return envs.get_bool(envs.DEBUG_INVARIANTS)


def _env_sched() -> bool:
    return envs.get_bool(envs.SCHED_CHECK)


# HVD_SCHED_CHECK supersedes HVD_DEBUG_INVARIANTS: under the
# cooperative seam the constructors return hvdsched primitives, which
# never register in the witness's held stack — leaving the assert
# helpers armed would make every wired-in assert_holding fire
# spuriously. hvdsched's own detectors cover the same failure class.
_SCHED = _env_sched()
_ENABLED = _env_enabled() and not _SCHED


def enabled() -> bool:
    """Whether the checker is active (cached; see :func:`refresh`)."""
    return _ENABLED


def sched_check() -> bool:
    """Whether the hvdsched cooperative-scheduler seam is active
    (cached; see :func:`refresh`)."""
    return _SCHED


def _sched_mod():
    """The hvdsched primitive module (lazy: only imported when
    ``HVD_SCHED_CHECK=1``, which only makes sense running from a repo
    checkout where ``tools/`` is importable)."""
    try:
        from tools.hvdsched import primitives
    except ImportError as e:  # pragma: no cover - mis-set env only
        raise RuntimeError(
            "HVD_SCHED_CHECK=1 requires the tools/hvdsched package "
            "(run from the repo root with tools/ on sys.path); see "
            "docs/schedule_checker.md") from e
    return primitives


def refresh() -> bool:
    """Re-read ``HVD_DEBUG_INVARIANTS`` / ``HVD_SCHED_CHECK`` (tests
    toggle them after import). Only affects primitives created
    afterwards and the assert helpers. ``HVD_SCHED_CHECK`` supersedes
    the witness (see the cached-flag comment above)."""
    global _ENABLED, _SCHED
    _SCHED = _env_sched()
    _ENABLED = _env_enabled() and not _SCHED
    return _ENABLED


def reset() -> None:
    """Drop recorded edges, violations, and counters (tests)."""
    with _state_lock:
        _edges.clear()
        _adjacent.clear()
        _violations.clear()
        for k in _counts:
            _counts[k] = 0


def report() -> dict:
    """Counters + the recorded violation messages (bounded)."""
    with _state_lock:
        return {"enabled": _ENABLED, "counts": dict(_counts),
                "violations": list(_violations)}


def _violate(kind: str, message: str) -> None:
    with _state_lock:
        _counts[kind] += 1
        if len(_violations) < _MAX_VIOLATIONS:
            _violations.append(f"[{kind}] {message}")
    if raise_on_violation:
        raise InvariantViolation(f"[{kind}] {message}")


# ---------------------------------------------------------------------------
# lock-order witness
# ---------------------------------------------------------------------------

def _held_stack() -> list[str]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def held_locks() -> tuple[str, ...]:
    """Names of tracked locks the current thread holds, outermost first."""
    return tuple(_held_stack())


def _path(frm: str, to: str) -> list[str] | None:
    """A recorded-edge path ``frm -> ... -> to``, or None. Caller holds
    ``_state_lock``."""
    stack = [(frm, [frm])]
    seen = {frm}
    while stack:
        node, path = stack.pop()
        for nxt in _adjacent.get(node, ()):
            if nxt == to:
                return path + [to]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _check_order(name: str) -> None:
    """Record ``held -> name`` edges; raise BEFORE the caller blocks on
    the inner lock (report the potential deadlock instead of exhibiting
    it) when acquiring ``name`` would close a cycle — including a
    transitive one — in the recorded acquisition-order graph."""
    held = _held_stack()
    if not held:
        return
    for h in held:
        if h == name:
            continue  # re-entrant acquisition of the same (R)Lock
        cycle = None
        with _state_lock:
            if (h, name) not in _edges:
                # adding h -> name closes a cycle iff name already
                # reaches h through recorded edges
                cycle = _path(name, h)
                if cycle is None:
                    here = "".join(traceback.format_stack(limit=16)[:-2])
                    _edges[(h, name)] = here
                    _adjacent.setdefault(h, set()).add(name)
                    continue
                prior = _edges[(cycle[0], cycle[1])]
            else:
                continue
        here = "".join(traceback.format_stack(limit=16)[:-2])
        _violate(
            "lock-order",
            f"acquiring {name!r} while holding {h!r}, but the opposite "
            f"order was recorded earlier: {' -> '.join(cycle)}.\n"
            f"--- earlier acquisition ({cycle[0]!r} then {cycle[1]!r}):\n"
            f"{prior}"
            f"--- current acquisition ({h!r} then {name!r}):\n{here}")


class _TrackedLock:
    """A ``threading.Lock`` that feeds the witness. Duck-types the lock
    protocol (acquire/release/context manager/locked) so it drops into
    ``threading.Condition`` too."""

    _reentrant = False

    def __init__(self, name: str):
        self._name = name
        self._inner = self._make_inner()

    @staticmethod
    def _make_inner():
        return threading.Lock()

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held_stack()
        if blocking and not (self._reentrant and self._name in held):
            _check_order(self._name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            held.append(self._name)
        return got

    def release(self) -> None:
        held = _held_stack()
        # remove the innermost occurrence (Condition.wait releases and
        # re-acquires out of strict LIFO order with surrounding locks)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self._name:
                del held[i]
                break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<{type(self).__name__} {self._name!r}>"


class _TrackedRLock(_TrackedLock):
    _reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()


def make_lock(name: str):
    """A mutex for ``name`` — witness-tracked when the checker is on,
    cooperative under ``HVD_SCHED_CHECK=1``, a plain ``threading.Lock``
    otherwise. ``name`` convention: ``module.owner.attr`` (e.g.
    ``fusion_cycle.scheduler.mu``)."""
    if _SCHED:
        return _sched_mod().Lock(name)
    return _TrackedLock(name) if _ENABLED else threading.Lock()


def make_rlock(name: str):
    if _SCHED:
        return _sched_mod().RLock(name)
    return _TrackedRLock(name) if _ENABLED else threading.RLock()


def make_condition(name: str):
    """A ``threading.Condition`` over a tracked mutex. ``wait()`` releases
    and re-acquires through the tracked lock, so held-lock state stays
    correct across waits."""
    if _SCHED:
        m = _sched_mod()
        return m.Condition(m.Lock(name))
    if not _ENABLED:
        return threading.Condition(threading.Lock())
    return threading.Condition(_TrackedLock(name))


def make_event(name: str):
    """A ``threading.Event`` for ``name`` — cooperative under
    ``HVD_SCHED_CHECK=1`` so hvdsched can serialize wait/set/clear
    interleavings and run timed waits on the virtual clock; a plain
    event otherwise (the witness does not track events)."""
    if _SCHED:
        return _sched_mod().Event(name)
    return threading.Event()


def spawn_thread(target, *, name: str, daemon: bool = True,
                 args=(), kwargs=None) -> threading.Thread:
    """Create AND start a thread. Under ``HVD_SCHED_CHECK=1`` a thread
    spawned while an hvdsched model run is active registers with the
    cooperative scheduler (it only runs when scheduled); outside a model
    run — or with the knob unset — this is a plain daemon thread.

    A thread spawned from a loopback rank thread inherits that rank's
    context (``horovod_tpu.loopback.context``): a rank-owned component's
    worker threads — fusion-cycle timer, flush executor, negotiation
    cycle, health watchdog — keep seeing the rank's world, not the
    process-wide one."""
    from ..loopback import context as _lbctx
    target = _lbctx.bind_current(target)
    if _SCHED:
        return _sched_mod().spawn_thread(target, name=name, daemon=daemon,
                                         args=args, kwargs=kwargs or {})
    t = threading.Thread(target=target, name=name, daemon=daemon,
                         args=args, kwargs=kwargs or {})
    t.start()
    return t


def join_thread(thread: threading.Thread | None, timeout=None) -> None:
    """``thread.join(timeout)``, cooperatively when both the joiner and
    the target are hvdsched-managed (a real join on a parked managed
    thread would hang the controlled schedule)."""
    if thread is None:
        return
    if _SCHED:
        _sched_mod().join_thread(thread, timeout)
        return
    thread.join(timeout)


def sleep(seconds: float) -> None:
    """``time.sleep`` routed through the virtual clock under an active
    hvdsched model run (a real sleep would stall the serialized
    schedule without creating any interleaving)."""
    if _SCHED:
        _sched_mod().sleep(seconds)
        return
    time.sleep(seconds)


def monotonic() -> float:
    """``time.monotonic`` from the hvdsched virtual clock under an
    active model run, so deadline arithmetic (cycle pacing, retry
    deadlines, beat aging) is deterministic and schedule-driven."""
    if _SCHED:
        return _sched_mod().monotonic()
    return time.monotonic()


def holding(lock) -> bool:
    """Whether the current thread holds ``lock`` (tracked locks and
    conditions over them only; plain primitives report False)."""
    if isinstance(lock, threading.Condition):
        lock = lock._lock  # the mutex the condition wraps
    name = getattr(lock, "name", None)
    return name is not None and name in _held_stack()


# ---------------------------------------------------------------------------
# assertion helpers (no-ops unless enabled)
# ---------------------------------------------------------------------------

def assert_holding(lock, what: str) -> None:
    """State guarded by ``lock`` is being touched — the current thread
    must hold it. No-op when the checker is off or ``lock`` is a plain
    primitive (created before the checker was enabled)."""
    if not _ENABLED:
        return
    name = getattr(getattr(lock, "_lock", lock), "name", None)
    if name is None:
        return
    if not holding(lock):
        _violate("lock-held",
                 f"{what}: requires lock {name!r}, held: "
                 f"{list(_held_stack())!r} "
                 f"(thread {threading.current_thread().name!r})")


def assert_thread(owner: threading.Thread | None, what: str) -> None:
    """State owned by one thread is being touched — the current thread
    must be ``owner`` (None = owner not running, any thread legal)."""
    if not _ENABLED or owner is None:
        return
    cur = threading.current_thread()
    if cur is not owner:
        _violate("thread-affinity",
                 f"{what}: must run on thread {owner.name!r}, "
                 f"ran on {cur.name!r}")


class section:
    """Re-entrancy guard: ``with section('flush-execute'): ...`` marks the
    region; :func:`assert_outside` raises if the SAME thread is already
    inside it. Always active as a context manager; the bookkeeping is a
    thread-local counter, so the disabled cost is negligible."""

    def __init__(self, name: str):
        self._name = name

    def __enter__(self):
        depths = getattr(_tls, "sections", None)
        if depths is None:
            depths = _tls.sections = {}
        depths[self._name] = depths.get(self._name, 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.sections[self._name] -= 1
        return False


def inside(name: str) -> bool:
    return bool(getattr(_tls, "sections", {}).get(name))


def assert_outside(name: str, what: str) -> None:
    if not _ENABLED:
        return
    if inside(name):
        _violate("reentrancy",
                 f"{what}: re-entered section {name!r} on thread "
                 f"{threading.current_thread().name!r}")
