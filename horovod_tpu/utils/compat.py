"""JAX version compatibility shims.

The codebase targets the modern ``jax.shard_map`` entry point (promoted to
the top-level namespace with the ``check_vma`` keyword). Older jax releases
(<= 0.4.x) only ship ``jax.experimental.shard_map.shard_map`` with the
keyword spelled ``check_rep``. Installing the adapter onto the ``jax``
module keeps every call site — library, tests, examples — on the one
modern spelling instead of scattering try/except imports.

Imported for its side effect from ``horovod_tpu/__init__`` before anything
can touch ``jax.shard_map``.
"""

from __future__ import annotations

import functools

import jax


def _install_shard_map_shim() -> None:
    if hasattr(jax, "shard_map"):
        return  # modern jax: nothing to adapt

    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except ImportError:  # pragma: no cover - no known jax lacks both
        return

    @functools.wraps(_legacy)
    def shard_map(f, mesh=None, in_specs=None, out_specs=None, *,
                  check_vma=None, check_rep=None, **kwargs):
        # check_vma is the modern name for what 0.4.x calls check_rep;
        # accept either, prefer the explicit legacy spelling if given.
        if check_rep is None:
            check_rep = True if check_vma is None else bool(check_vma)
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=check_rep, **kwargs)

    jax.shard_map = shard_map


_install_shard_map_shim()


def _resolve_trace_state_clean():
    """Find ``trace_state_clean`` across jax versions: the public
    ``jax.core`` home first, then ``jax._src.core`` (modern releases have
    been emptying ``jax.core``). Returning None (no probe found) makes
    :func:`trace_state_clean` answer False, which keeps callers on the
    exception-probed legacy path — correct, just slower."""
    fn = getattr(jax.core, "trace_state_clean", None)
    if fn is not None:
        return fn
    try:  # pragma: no cover - exercised only on jax without jax.core's
        from jax._src import core as _src_core
        return getattr(_src_core, "trace_state_clean", None)
    except ImportError:
        return None


_trace_state_clean = _resolve_trace_state_clean()


def trace_state_clean() -> bool:
    """True when no jax trace is in progress — a concrete-value call site
    is definitely in eager mode (the cheap half of mode detection; the
    exception-probed ``lax.axis_index`` stays as the fallback for jax
    builds without the helper)."""
    if _trace_state_clean is None:  # pragma: no cover
        return False
    try:
        return bool(_trace_state_clean())
    except Exception:  # pragma: no cover - defensive
        return False
