from . import envs, logging

__all__ = ["envs", "logging"]
