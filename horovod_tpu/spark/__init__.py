"""Spark integration: run framework jobs as barrier-mode Spark tasks.

TPU-native rebuild of the reference's ``horovod.spark.run()``
(``/root/reference/horovod/spark/runner.py:199-430``: one Spark task per
rank, a driver service for registration/address exchange, results returned
per rank). The rebuild is deliberately thin and Spark-native:

* **Placement** comes from Spark's barrier scheduling
  (``RDD.barrier().mapPartitions``) — all ``num_proc`` tasks start
  together or not at all, the property the reference builds by hand with
  its start-timeout polling loop.
* **Registration / address exchange** uses ``BarrierTaskContext.allGather``
  (every task shares its IP and rank 0 its coordinator port) instead of
  the reference's driver-service RPC registration
  (``spark/driver/driver_service.py``).
* **Rendezvous** reuses the ``hvdrun`` launcher's signed KV server on the
  Spark driver and the same ``HVD_*`` env contract
  (``runner/launch.py:202-343``) — identical to the Ray integration, so a
  job launched from Spark, Ray, or ``hvdrun`` initializes identically.

    import horovod_tpu.spark

    results = horovod_tpu.spark.run(train_fn, args=(cfg,), num_proc=4)

The reference's Petastorm machinery (``horovod/spark/keras``,
``spark/lightning`` adapting Parquet stores to TF/Torch DataLoaders) is a
documented non-goal — it has no analog in the jax input pipeline. The
estimator *role* itself (train from data, Store-backed checkpoints,
resume) IS covered by the lite bridge in
:mod:`horovod_tpu.spark.estimator`: :func:`fit`, :func:`fit_dataframe`,
:func:`save_dataset`. pyspark is imported lazily: the module imports
fine without Spark installed.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from typing import Any, Callable

from ..runner import hosts as hosts_mod
from ..runner.http_kv import KVServer, local_addresses, make_secret
from ..runner.launch import _free_port, worker_env
from ..utils import envs

DEFAULT_START_TIMEOUT_S = 600.0
_REGISTER_SCOPE = "spark/registered"


def _task_body(fn, args, kwargs, secret, kv_addr, kv_port, extra_env):
    """Runs inside every barrier task: exchange placement, seed the
    launcher env contract, run the user function as this rank."""
    from pyspark import BarrierTaskContext

    from ..runner.http_kv import KVClient

    ctx = BarrierTaskContext.get()
    rank = ctx.partitionId()

    import socket
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((kv_addr, int(kv_port)))
            my_ip = s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        my_ip = socket.gethostbyname(socket.gethostname())

    # one allGather round: IPs of every task + rank 0's coordinator port
    # (the reference's task-to-task address registration,
    # spark/runner.py:281-303, collapsed into Spark's own primitive)
    coord_port = _free_port() if rank == 0 else 0
    entries = [json.loads(e) for e in ctx.allGather(
        json.dumps({"rank": rank, "ip": my_ip, "coord_port": coord_port}))]
    entries.sort(key=lambda e: e["rank"])
    ips = [e["ip"] for e in entries]
    slots = hosts_mod.slots_from_ips(ips)

    env = worker_env(slots[rank], coordinator_addr=ips[0],
                     coordinator_port=entries[0]["coord_port"],
                     kv_addr=kv_addr, kv_port=kv_port, secret=secret,
                     extra=extra_env)
    os.environ.update(env)
    # Registration mark: once every rank has reported in, the driver stops
    # counting against start_timeout — the timeout bounds task SCHEDULING
    # only, never the training itself (reference start_timeout semantics,
    # spark/runner.py:210-214).
    KVClient(kv_addr, int(kv_port), secret=secret).put(
        f"{_REGISTER_SCOPE}/{rank}", b"1")
    return [(rank, fn(*args, **(kwargs or {})))]


def run(fn: Callable, args=(), kwargs: dict | None = None,
        num_proc: int | None = None, start_timeout: float | None = None,
        env: dict | None = None, verbose: int = 1) -> list:
    """Run ``fn(*args, **kwargs)`` as ``num_proc`` ranks on Spark executors
    and return the per-rank results, rank-ordered (reference
    ``horovod.spark.run``, ``spark/runner.py:199-430``).

    ``num_proc`` defaults to ``spark.default.parallelism``;
    ``start_timeout`` (or ``HVD_SPARK_START_TIMEOUT``) bounds how long the
    barrier tasks may take to be scheduled and finish, and ``env`` adds
    extra variables to every rank's environment.
    """
    import pyspark

    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError(
            "horovod_tpu.spark.run() needs an active SparkContext — start "
            "a SparkSession first (the reference requires the same, "
            "spark/runner.py:251-254)")
    if num_proc is None:
        num_proc = sc.defaultParallelism
    num_proc = int(num_proc)
    if start_timeout is None:
        start_timeout = envs.get_float(envs.SPARK_START_TIMEOUT,
                                       DEFAULT_START_TIMEOUT_S)

    secret = make_secret()
    kv = KVServer(secret=secret)
    kv_port = kv.start()
    kv_addr = local_addresses()[0]
    extra_env = dict(env or {})

    task = _make_task(fn, tuple(args), kwargs, secret, kv_addr, kv_port,
                      extra_env)
    result_q: queue.Queue = queue.Queue(1)
    group = f"horovod_tpu.spark.run.{os.getpid()}.{id(task):x}"

    def _drive():
        try:
            sc.setJobGroup(group, "horovod_tpu.spark.run",
                           interruptOnCancel=True)
            rdd = sc.parallelize(range(num_proc), num_proc)
            result_q.put(("ok", rdd.barrier().mapPartitions(task).collect()))
        except BaseException as e:  # surfaced on the caller thread
            result_q.put(("error", e))

    thread = threading.Thread(target=_drive, daemon=True,
                              name="hvd-spark-driver")
    thread.start()
    try:
        # Phase 1 — startup, bounded by start_timeout: every task must
        # register through the KV. Phase 2 — training, unbounded: once all
        # ranks are running, the job takes as long as fn takes (the
        # reference's start_timeout covers scheduling only).
        import time as _time
        deadline = _time.monotonic() + start_timeout
        status = payload = None
        while len(kv.keys(_REGISTER_SCOPE)) < num_proc:
            try:
                status, payload = result_q.get(timeout=0.2)
                break  # collect() finished (or failed) before registration
            except queue.Empty:
                pass
            if _time.monotonic() > deadline:
                try:
                    sc.cancelJobGroup(group)
                except Exception:  # hvdlint: disable=silent-except
                    pass  # best-effort cancel; the TimeoutError below is
                    # the real signal
                raise TimeoutError(
                    f"horovod_tpu.spark.run timed out after {start_timeout}s "
                    f"waiting for {num_proc} barrier tasks to start; check "
                    "that the cluster has enough simultaneous slots "
                    "(barrier mode schedules all-or-nothing) or raise "
                    "start_timeout/HVD_SPARK_START_TIMEOUT")
        if status is None:
            status, payload = result_q.get()
        if status == "error":
            raise payload
        pairs = sorted(payload, key=lambda rv: rv[0])
        if [r for r, _ in pairs] != list(range(num_proc)):
            raise RuntimeError(
                f"spark run returned ranks {[r for r, _ in pairs]}, "
                f"expected 0..{num_proc - 1}")
        return [v for _, v in pairs]
    finally:
        # Orderly teardown: cancelJobGroup is best-effort and the daemon
        # _drive thread may still sit in collect(); give the cancellation
        # a moment to unwind before the KV dies, so straggler barrier
        # tasks fail against a cancelled job, not a vanished KV (ADVICE r4).
        thread.join(timeout=10.0)
        kv.stop()


def _make_task(fn, args, kwargs, secret, kv_addr, kv_port, extra_env):
    """Build the mapPartitions closure (kept top-level so everything it
    captures is explicit and cloudpickle-friendly)."""
    def _task(_iterator) -> Any:
        return _task_body(fn, args, kwargs, secret, kv_addr, kv_port,
                          extra_env)
    return _task


from .estimator import fit, fit_dataframe, save_dataset  # noqa: E402
