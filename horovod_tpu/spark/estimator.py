"""Estimator-lite: train a model *from data* on Spark.

Role parity (role, not API) with the reference's Spark estimator layer —
``/root/reference/horovod/spark/keras/estimator.py`` /
``spark/lightning/estimator.py`` backed by a Store
(``spark/common/store.py:1-582``): the user hands data + a model recipe to
the driver and gets trained parameters back, with checkpoints persisted.
The reference materializes DataFrames to Parquet via Petastorm and adapts
them to TF/Torch loaders; that machinery has no jax analog and stays out
of scope (documented in :mod:`horovod_tpu.spark`). The lite bridge keeps
the estimator *role* with the framework's own pieces:

* placement/launch — :func:`horovod_tpu.spark.run` barrier tasks;
* data — :class:`horovod_tpu.data.ShardedArrayLoader` over in-memory
  arrays or an ``.npz`` on storage every executor can read;
* the Store — :class:`horovod_tpu.checkpoint.Checkpointer` (orbax) at
  ``store_path``: per-epoch checkpoints, automatic resume from the
  latest one.

    params = fit((features, labels), init_fn, loss_fn,
                 epochs=3, batch_size=64, num_proc=4,
                 store_path="/shared/run1")

``init_fn(rng, batch) -> params`` builds the model parameters;
``loss_fn(params, batch) -> scalar`` is differentiated. Gradients sync
through :class:`~horovod_tpu.optim.DistributedOptimizer` under ``jit``
(GSPMD inserts the cross-rank reduction for the sharded batch).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Sequence

__all__ = ["fit", "fit_dataframe", "save_dataset"]


def save_dataset(store_path: str, *arrays) -> str:
    """Materialize arrays to ``<store_path>/dataset.npz`` (the Store role
    for inputs: one write on the driver, readable by every executor over
    shared storage). Returns the ``.npz`` path, accepted by :func:`fit`."""
    import numpy as np

    os.makedirs(store_path, exist_ok=True)
    path = os.path.join(store_path, "dataset.npz")
    np.savez(path, **{f"arr_{i}": a for i, a in enumerate(arrays)})
    return path


def _load_data(data) -> tuple:
    import numpy as np

    if isinstance(data, str):
        with np.load(data) as npz:
            return tuple(npz[k] for k in sorted(
                npz.files, key=lambda k: int(k.split("_")[-1])))
    return tuple(np.asarray(a) for a in data)


def _fit_task(data, init_fn, loss_fn, optimizer, epochs, batch_size,
              shuffle, seed, store_path):
    """Runs on every rank (inside a barrier task): shard, train, checkpoint."""
    import jax
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from ..checkpoint import Checkpointer
    from ..data import ShardedArrayLoader

    hvd.init()
    arrays = _load_data(data)
    loader = ShardedArrayLoader(*arrays, batch_size=batch_size,
                                shuffle=shuffle, seed=seed)
    if len(loader) == 0:
        raise ValueError(
            f"batch_size {batch_size} exceeds the dataset "
            f"({len(arrays[0])} rows): zero batches per epoch")

    tx = hvd.DistributedOptimizer(optimizer or optax.adam(1e-3))

    # host-side example batch (same leading dim the loader will yield)
    example = tuple(a[:batch_size] for a in arrays)
    params = init_fn(jax.random.PRNGKey(seed), example)
    opt_state = tx.init(params)

    start_epoch = 0
    ckpt = None
    if store_path:
        ckpt = Checkpointer(os.path.join(store_path, "checkpoints"))
        latest = ckpt.latest_step()
        if latest is not None:  # the Store's resume semantics
            restored = ckpt.restore(
                step=latest, target={"params": params,
                                     "opt_state": opt_state})
            # back to host: restored leaves carry single-device placement,
            # which would clash with the mesh-wide broadcast below
            params = jax.tree.map(np.asarray, restored["params"])
            # optimizer moments resume too — otherwise an interrupted adam
            # run silently restarts with zeroed moments (Store contract)
            opt_state = jax.tree.map(np.asarray, restored["opt_state"])
            start_epoch = latest + 1
    # Rank 0's restore is authoritative for every rank: params/opt_state
    # values AND the resume epoch (a rank whose local store_path is empty
    # must not run extra epochs of collectives nobody else joins).
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = hvd.broadcast_parameters(opt_state, root_rank=0)
    start_epoch = hvd.broadcast_object(start_epoch, root_rank=0)

    @jax.jit
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, new_opt = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt, loss

    last_loss = None
    for epoch in range(start_epoch, epochs):
        loader.set_epoch(epoch)
        loss = None
        for batch in loader:
            params, opt_state, loss = train_step(params, opt_state, batch)
        if loss is not None:
            last_loss = float(jax.block_until_ready(loss))
        if ckpt is not None and hvd.rank() == 0:
            ckpt.save(epoch, {"params": params, "opt_state": opt_state},
                      wait=True)
    if ckpt is not None:
        ckpt.close()
    return {"params": jax.tree.map(np.asarray, params),
            "last_loss": last_loss,
            "epochs_run": max(0, epochs - start_epoch)}


def fit(data, init_fn: Callable, loss_fn: Callable, *,
        optimizer=None, epochs: int = 1, batch_size: int = 32,
        shuffle: bool = True, seed: int = 0, store_path: str | None = None,
        num_proc: int | None = None, start_timeout: float | None = None,
        env: dict | None = None) -> Any:
    """Train on Spark executors and return the trained parameter pytree
    (host numpy leaves). ``data`` is a sequence of arrays sharing a
    leading dimension — e.g. ``(features, labels)``, the shapes
    ``loss_fn`` expects — or the path of an ``.npz`` every executor can
    read (:func:`save_dataset`). With ``store_path`` set, per-epoch
    checkpoints land there and a rerun resumes from the latest."""
    from . import run as spark_run

    results = spark_run(
        _fit_task,
        args=(data, init_fn, loss_fn, optimizer, epochs, batch_size,
              shuffle, seed, store_path),
        num_proc=num_proc, start_timeout=start_timeout, env=env)
    return results[0]["params"]


def fit_dataframe(df, feature_cols: Sequence[str], label_cols: Sequence[str],
                  init_fn: Callable, loss_fn: Callable, *,
                  store_path: str, **fit_kwargs) -> Any:
    """Train from a Spark DataFrame: materialize the selected columns to
    the Store once on the driver (the reference's prepare_data role,
    ``store.py`` + ``util.prepare_data``; here a driver-side collect —
    the lite bridge targets datasets that fit driver memory), then
    :func:`fit` from the materialized ``.npz``. Features with per-row
    vectors (array columns) are stacked to 2-D."""
    import numpy as np

    cols = list(feature_cols) + list(label_cols)
    rows = df.select(*cols).collect()
    features = np.asarray([[row[c] for c in feature_cols] for row in rows],
                          dtype=np.float32)
    labels = np.asarray([[row[c] for c in label_cols] for row in rows])
    if features.ndim == 3:  # array-typed feature columns: one per column
        features = features.reshape(len(rows), -1)
    if labels.shape[-1] == 1:
        labels = labels[:, 0]
    path = save_dataset(store_path, features, labels)
    return fit(path, init_fn, loss_fn, store_path=store_path, **fit_kwargs)
