"""Loader base + async prefetch mixin + a sharded-array loader.

Reference: ``/root/reference/horovod/data/data_loader_base.py:1-165``
(``BaseDataLoader`` interface; ``AsyncDataLoaderMixin`` with a daemon
thread pushing batches into a bounded queue). The rebuild keeps the same
composition pattern::

    class MyAsyncLoader(AsyncDataLoaderMixin, MyLoader):
        pass

and adds :class:`ShardedArrayLoader` — the jax-idiomatic concrete loader
that shards each batch across the mesh's data axis with one
``device_put`` so a jitted SPMD step consumes it directly.
"""

from __future__ import annotations

from queue import Empty, Queue
from threading import Event, Thread


class BaseDataLoader:
    """Iterable of batches (reference ``BaseDataLoader``)."""

    def __len__(self):
        raise NotImplementedError()

    def _iterate(self):
        """Yield raw batches; implemented by concrete loaders."""
        raise NotImplementedError()

    def __iter__(self):
        for batch in self._iterate():
            yield self._process_batch(batch)

    def _process_batch(self, batch):
        """Hook for subclass/trainer batch post-processing."""
        return batch


class AsyncDataLoaderMixin:
    """Prefetch ``_iterate()`` on a daemon thread into a bounded queue
    (reference ``AsyncDataLoaderMixin``; queue size 0 disables async).

    Mix in FIRST: ``class Loader(AsyncDataLoaderMixin, Base)``.
    """

    def __init__(self, *args, async_loader_queue_size: int = 64, **kwargs):
        self.async_loader_queue_size = async_loader_queue_size
        super().__init__(*args, **kwargs)
        self._queue: Queue | None = None
        self._finished: Event | None = None
        self._thread: Thread | None = None

    def close_async_loader(self) -> None:
        """Stop the prefetch thread and drain the queue."""
        if self._thread is None:
            return
        self._finished.set()
        while True:  # unblock a producer waiting on a full queue
            try:
                self._queue.get_nowait()
            except Empty:
                break
        self._thread.join(timeout=30)
        self._thread = None

    def _async_worker(self):
        try:
            for batch in super().__iter__():
                if self._finished.is_set():
                    return
                self._queue.put((batch, None))
        except BaseException as e:  # noqa: BLE001 - re-raised in consumer
            # a producer error must surface in the training loop, not die
            # silently in the daemon thread (a truncated epoch on one rank
            # deadlocks the next collective)
            self._queue.put((None, e))
            return
        self._queue.put((None, None))  # end-of-epoch sentinel

    def __iter__(self):
        if self.async_loader_queue_size <= 0:
            yield from super().__iter__()
            return
        self._finished = Event()
        self._queue = Queue(self.async_loader_queue_size)
        self._thread = Thread(target=self._async_worker, daemon=True,
                              name="hvd-data-prefetch")
        self._thread.start()
        try:
            while True:
                batch, error = self._queue.get()
                if error is not None:
                    raise error
                if batch is None:
                    break
                yield batch
        finally:
            self.close_async_loader()


class ShardedArrayLoader(BaseDataLoader):
    """Batches of host arrays, sharded over the mesh's data axis.

    Each yielded batch is a tuple of jax arrays with
    ``NamedSharding(hvd.mesh(), P(hvd.axis_name()))`` — ready for a
    ``shard_map``/``pjit`` step. The global batch size must divide by the
    world size; the trailing remainder of an epoch is dropped (like the
    reference's distributed samplers pad/drop to keep ranks aligned).
    """

    def __init__(self, *arrays, batch_size: int, shuffle: bool = True,
                 seed: int = 0, drop_remainder: bool = True):
        import numpy as np

        if not arrays:
            raise ValueError("ShardedArrayLoader needs at least one array")
        n = len(arrays[0])
        if any(len(a) != n for a in arrays):
            raise ValueError("all arrays must share the leading dimension")
        self.arrays = [np.asarray(a) for a in arrays]
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def __len__(self):
        n = len(self.arrays[0])
        return n // self.batch_size if self.drop_remainder else \
            -(-n // self.batch_size)

    def _iterate(self):
        import numpy as np

        from .. import runtime
        from jax.sharding import NamedSharding, PartitionSpec as P
        import jax

        n = len(self.arrays[0])
        order = np.arange(n)
        if self.shuffle:
            np.random.default_rng(self.seed + self.epoch).shuffle(order)
        sharding = None
        if runtime.is_initialized():
            sharding = NamedSharding(runtime.mesh(), P(runtime.axis_name()))
            if self.batch_size % runtime.size() != 0:
                raise ValueError(
                    f"batch_size {self.batch_size} must divide by the "
                    f"world size {runtime.size()}")
            if not self.drop_remainder and n % self.batch_size \
                    and (n % self.batch_size) % runtime.size():
                raise ValueError(
                    f"drop_remainder=False with a trailing partial batch of "
                    f"{n % self.batch_size} samples cannot be sharded over "
                    f"{runtime.size()} devices; drop the remainder or pad "
                    "the dataset")
        stop = (n - self.batch_size + 1) if self.drop_remainder else n
        for start in range(0, max(stop, 0), self.batch_size):
            idx = order[start:start + self.batch_size]
            batch = tuple(a[idx] for a in self.arrays)
            if sharding is not None:
                batch = tuple(jax.device_put(b, sharding) for b in batch)
            yield batch
