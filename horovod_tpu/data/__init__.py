"""Data loading utilities.

TPU-native rebuild of ``/root/reference/horovod/data/data_loader_base.py:1-165``:
a minimal loader interface plus an async mixin that prefetches batches on a
background thread so host-side input work overlaps device steps (on TPU
this hides host→HBM transfer and numpy batch assembly behind the MXU).
"""

from .loader import AsyncDataLoaderMixin, BaseDataLoader, ShardedArrayLoader

__all__ = ["BaseDataLoader", "AsyncDataLoaderMixin", "ShardedArrayLoader"]
