"""Process-wide runtime state: device mesh, ranks, process sets.

TPU-native replacement for the reference's ``HorovodGlobalState`` singleton +
init path (``/root/reference/horovod/common/global_state.h:39-126``,
``InitializeHorovodOnce`` at ``/root/reference/horovod/common/operations.cc:811-864``)
and the Python facade ``HorovodBasics``
(``/root/reference/horovod/common/basics.py:48-146,373-468``).

Design inversion (SURVEY.md §7): there is no background negotiation thread.
Under SPMD the program order of collectives is identical on every rank by
construction, so init reduces to (a) optional ``jax.distributed.initialize``
rendezvous, (b) building a rank-ordered global ``jax.sharding.Mesh``, and
(c) registering the global process set. A *rank* is a TPU chip (device), not
a host process: one controller process drives ``local_size`` chips.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .loopback import context as _lbctx
from .utils import envs
from .utils import logging as hvd_logging

# The canonical mesh axis name for the flat data-parallel "rank" axis.
AXIS_NAME = "hvd"


class NotInitializedError(RuntimeError):
    """Raised when the API is used before ``hvd.init()`` (reference raises
    from ``CheckInitialized``, ``operations.cc:904-910``)."""


@dataclasses.dataclass
class _RuntimeState:
    devices: list  # rank-ordered global device list; rank == index
    mesh: Mesh  # 1-D mesh over `devices` with axis AXIS_NAME
    axis_name: str
    process_index: int
    process_count: int
    local_ranks: list  # global ranks owned by this process
    process_set_table: Any  # ProcessSetTable (import cycle avoided)
    # Loopback worlds: rank -> owning (virtual) process. In a real world
    # the mapping comes from each device's process_index; loopback ranks
    # share one interpreter whose fake CPU devices all report process 0,
    # so the world records the virtual mapping explicitly.
    rank_process_map: list | None = None


_state: _RuntimeState | None = None
_lock = threading.Lock()
# Bumped on every successful init(); lets cached per-ProcessSet meshes
# detect a shutdown()/init() cycle and rebuild over fresh device objects.
_generation = 0


def _rank_ordered_devices(devices=None):
    """Global devices ordered so rank = process-major, local-minor.

    Mirrors the reference rank layout where ranks are contiguous per host
    (``gloo_run.py:65-101`` seeds HOROVOD_RANK host-major)."""
    devs = list(devices if devices is not None else jax.devices())
    devs.sort(key=lambda d: (d.process_index, d.id))
    return devs


def init(
    comm: Sequence[int] | None = None,
    process_sets: Sequence[Sequence[int]] | str | None = None,
    *,
    devices=None,
    axis_name: str = AXIS_NAME,
) -> None:
    """Initialize the runtime (reference: ``hvd.init`` → ``horovod_init``,
    ``operations.cc:889-899``).

    Args:
      comm: optional list of global ranks forming the *global* process set
        (reference accepts a rank list at ``basics.py:48-146``). Default: all.
      process_sets: optional list of rank-lists to register as additional
        process sets at init time, or the string ``"dynamic"`` to enable
        dynamic registration (reference gates this on
        ``HOROVOD_DYNAMIC_PROCESS_SETS``, ``operations.cc:606-607``).
      devices: explicit device list (testing hook).
      axis_name: mesh axis name used by every collective.
    """
    from . import conformance as _conformance
    # the lockstep recorder's cached gate re-reads HVD_CONFORMANCE at
    # init so launcher-seeded (or test-set) knobs engage without an
    # import-order dance (docs/conformance.md)
    _conformance.refresh()
    ctx = _lbctx.current()
    if ctx is not None:
        _loopback_init(ctx, axis_name=axis_name, process_sets=process_sets)
        return
    if envs.get_bool(envs.LOOPBACK):
        # Satellite fix (ISSUE 10): a half-configured loopback env — the
        # HVD_LOOPBACK marker without a rank context (e.g. exported
        # manually, or a loopback worker env leaked into a plain
        # process) — must fail HERE with a clear message. Proceeding
        # would treat the leaked HVD_KV_*/HVD_NUM_PROCESSES contract as
        # a real multi-process launch and hang on KV connect.
        raise RuntimeError(
            "HVD_LOOPBACK=1 is set but this thread has no loopback rank "
            "context. Loopback worlds are created with "
            "hvd.loopback.world(n) (or `hvdrun --loopback`); do not "
            "export HVD_LOOPBACK/HVD_KV_* by hand. Unset HVD_LOOPBACK "
            "to run as a normal process.")
    global _state, _generation
    with _lock:
        if _state is not None:
            hvd_logging.debug("init() called twice; ignoring")
            return
        # re-init epoch, not telemetry (keys cache invalidation)
        _generation += 1  # hvdlint: disable=metrics-registry

        _maybe_distributed_init()

        devs = _rank_ordered_devices(devices)
        if comm is not None:
            devs = [devs[r] for r in comm]
        mesh = Mesh(np.array(devs), (axis_name,))

        proc_index = jax.process_index()
        local_ranks = [i for i, d in enumerate(devs) if d.process_index == proc_index]

        from .process_sets import ProcessSetTable  # deferred: avoids cycle

        table = ProcessSetTable()
        _state = _RuntimeState(
            devices=devs,
            mesh=mesh,
            axis_name=axis_name,
            process_index=proc_index,
            process_count=jax.process_count(),
            local_ranks=local_ranks,
            process_set_table=table,
        )
        table.initialize_global(len(devs))

        dynamic = process_sets == "dynamic" or envs.get_bool(envs.DYNAMIC_PROCESS_SETS)
        table.dynamic_enabled = dynamic
        if process_sets and process_sets != "dynamic":
            for ranks in process_sets:
                table.add(list(ranks), force=True)

        hvd_logging.info(
            "initialized: %d chips across %d processes (this=%d, local=%s)",
            len(devs), _state.process_count, proc_index, local_ranks,
        )
    # Outside the lock: timeline autostart builds the native engine.
    from . import timeline as _timeline
    _timeline.maybe_autostart()
    # Per-worker Prometheus exposition when HVD_METRICS_PORT is seeded
    # (hvdrun --metrics-port); idempotent across elastic re-inits.
    from . import metrics as _metrics
    _metrics.maybe_serve()
    # Multi-process jobs start the negotiation service now (the analog of
    # the reference spawning BackgroundThreadLoop inside init,
    # operations.cc:811-864): every process must tick cycles even before
    # its first collective, or peers' exchanges block and stalls go
    # undetected.
    from . import engine_service as _engine_service
    _engine_service.get_service()


def _loopback_init(ctx, *, axis_name: str = AXIS_NAME,
                   process_sets=None) -> None:
    """``init()`` on a loopback rank thread: build this rank's world view
    from its env overlay — no ``jax.distributed``, no cross-process XLA
    program, ever. The negotiation service (real KV wire format) starts
    immediately, exactly like the multi-process init path."""
    if ctx.runtime_state is not None:
        hvd_logging.debug("loopback init() called twice; ignoring")
        return
    missing = [v for v in (envs.NUM_PROCESSES, envs.PROCESS_ID,
                           envs.KV_ADDR, envs.KV_PORT)
               if envs.get(v) is None]
    if missing:
        raise RuntimeError(
            "loopback rank context is half-configured: missing "
            f"HVD_{'/HVD_'.join(missing)}. Loopback worlds seed the full "
            "launcher contract via hvd.loopback.world(n); refusing to "
            "init rather than hang on KV connect (docs/loopback.md).")
    size = int(envs.require(envs.NUM_PROCESSES))
    rank = int(envs.require(envs.PROCESS_ID))
    if not 0 <= rank < size:
        raise RuntimeError(
            f"loopback rank {rank} out of range for world size {size}")
    from .loopback.engine import _check_devices
    _check_devices(size)  # shared check + XLA_FLAGS hint
    devs = _rank_ordered_devices(None)[:size]
    mesh = Mesh(np.array(devs), (axis_name,))
    from .process_sets import ProcessSetTable
    table = ProcessSetTable()
    ctx.generation += 1
    ctx.runtime_state = _RuntimeState(
        devices=devs, mesh=mesh, axis_name=axis_name,
        process_index=rank, process_count=size, local_ranks=[rank],
        process_set_table=table, rank_process_map=list(range(size)))
    table.initialize_global(size)
    # Drop hub occurrence tables from previous world incarnations: an
    # elastic re-form re-seeds the coordinator scope, so the old scopes'
    # slot ids can never recur (loopback/dispatch.prune_stale_scopes).
    from .loopback import dispatch as _lbdispatch
    _lbdispatch.prune_stale_scopes(ctx)
    dynamic = (process_sets == "dynamic"
               or envs.get_bool(envs.DYNAMIC_PROCESS_SETS))
    table.dynamic_enabled = dynamic
    if process_sets and process_sets != "dynamic":
        for ranks in process_sets:
            table.add(list(ranks), force=True)
    hvd_logging.info(
        "loopback initialized: rank %d of %d (world %s)", rank, size,
        envs.get(envs.COORDINATOR_ADDR, "?"))
    # HVD_TIMELINE works in loopback worlds too: the first rank's init
    # starts the one shared writer; every rank's events carry a
    # rank<N>/ lane prefix (the ISSUE-11 attribution fix).
    from . import timeline as _timeline
    _timeline.maybe_autostart()
    from . import engine_service as _engine_service
    _engine_service.get_service()
    # Elastic warm re-form: adopt the shelf entry for this exact shape
    # (world scope, size, rank) as the warm pool — plan builds from here
    # on graft the shelved incarnation's compiled stages when their
    # re-derived negotiation names match (ops/dispatch_cache.py).
    from .ops import dispatch_cache as _dispatch_cache
    warm = _dispatch_cache.restore_for_reform()
    if warm:
        hvd_logging.info(
            "loopback init: %d shelved dispatch plans warm for rank %d "
            "of %d", warm, rank, size)


def _distributed_client_active() -> bool:
    return _distributed_kv_client() is not None


def _maybe_distributed_init() -> None:
    """Bootstrap ``jax.distributed`` from launcher-seeded env, the analog of
    the reference rendezvous (``GlooContext::Initialize`` reading
    ``HOROVOD_GLOO_RENDEZVOUS_ADDR``, ``gloo_context.h:29-42``). Jobs
    launched by ``srun``/``mpirun`` instead of ``hvdrun`` (the reference's
    primary launch modes, ``mpi_run.py``/``lsf.py``) are auto-detected:
    jax's own cluster detection joins the world, and the negotiation KV is
    bootstrapped over jax's distributed key-value store
    (:func:`_maybe_bootstrap_kv`).

    NOTE: must run before anything touches the XLA backend — we avoid any
    jax query here and check env + the distributed client state only.
    """
    addr = envs.get(envs.COORDINATOR_ADDR)
    num_proc = envs.get_int(envs.NUM_PROCESSES, 1)
    if _distributed_client_active():
        _maybe_bootstrap_kv()
        return
    if addr is None or num_proc <= 1:
        _maybe_cluster_autodetect()
        return
    port = envs.get(envs.COORDINATOR_PORT, "9778")
    proc_id = envs.get_int(envs.PROCESS_ID, 0)
    if envs.get_bool(envs.ELASTIC):
        # A peer crash must not fatally poison the coordination service:
        # recoverability keeps the shutdown barrier and error polling from
        # terminating surviving workers, so hvd.elastic can rebuild the
        # world instead (the analog of the reference's elastic
        # AsyncErrorCheck path, ``nccl_operations.cc:126-140``).
        jax.config.update("jax_enable_recoverability", True)
    try:
        jax.distributed.initialize(
            coordinator_address=f"{addr}:{port}",
            num_processes=num_proc,
            process_id=proc_id,
        )
        hvd_logging.info("jax.distributed initialized: process %d/%d via %s:%s",
                         proc_id, num_proc, addr, port)
        _maybe_bootstrap_kv()
    except RuntimeError as e:
        # Either the backend was already initialized by earlier user code
        # (jax.distributed must come first) or the coordinator is
        # unreachable. Degrading silently to single-host would run
        # unsynchronized training, so shout.
        hvd_logging.error(
            "jax.distributed.initialize failed (%s). This process will run "
            "as a single-host world of %d local chips. Call hvd.init() "
            "before any other jax API, or pre-initialize jax.distributed "
            "yourself.", e, len(jax.local_devices()))


# (world-size var, per-process rank var): the rank var is only set inside
# an actual srun/mpirun/jsrun task — an `#SBATCH --ntasks=8` script running
# plain `python` exports SLURM_NTASKS but no SLURM_PROCID, and must NOT
# trigger a blocking multi-process join. JSM_* is IBM JSM, what `jsrun`
# sets on LSF clusters (reference `js_run.py:1-151`).
_CLUSTER_ENV_PAIRS = (("SLURM_NTASKS", "SLURM_PROCID"),
                      ("OMPI_COMM_WORLD_SIZE", "OMPI_COMM_WORLD_RANK"),
                      ("PMI_SIZE", "PMI_RANK"),
                      ("JSM_NAMESPACE_SIZE", "JSM_NAMESPACE_RANK"))


def _cluster_world_hint() -> int:
    """World size advertised by a cluster scheduler's env (srun / mpirun /
    PMI), 1 when none — or when only the batch-level var is present
    without the per-task rank var."""
    for world_var, rank_var in _CLUSTER_ENV_PAIRS:
        val = os.environ.get(world_var)
        if val and os.environ.get(rank_var) is not None:
            try:
                return int(val)
            except ValueError:
                pass
    return 1


def _jsm_init_kwargs() -> dict:
    """Explicit ``jax.distributed.initialize`` kwargs for ``jsrun``-launched
    tasks. jax's built-in cluster detection covers SLURM and Open MPI but
    not IBM JSM, so when only JSM env is present the coordinator is derived
    from the LSF allocation itself: rank 0 lives on the first host of
    ``LSB_DJOB_RANKFILE`` (reference jsrun host source, ``js_run.py``;
    rankfile parsing shared with :mod:`horovod_tpu.runner.lsf`). Returns
    ``{}`` (let jax auto-detect) when JSM env is absent or another
    supported scheduler's rank var is also present."""
    if os.environ.get("JSM_NAMESPACE_RANK") is None:
        return {}
    if (os.environ.get("SLURM_PROCID") is not None
            or os.environ.get("OMPI_COMM_WORLD_RANK") is not None):
        return {}  # jax's own detectors know these; prefer them
    from .runner import lsf as lsf_mod
    first_host = lsf_mod.lsf_host_specs()[0].hostname
    port = envs.get(envs.COORDINATOR_PORT, "9778")
    return dict(
        coordinator_address=f"{first_host}:{port}",
        num_processes=int(os.environ["JSM_NAMESPACE_SIZE"]),
        process_id=int(os.environ["JSM_NAMESPACE_RANK"]),
    )


def _maybe_cluster_autodetect() -> None:
    """`srun python train.py` / `mpirun -np N python train.py` parity:
    when a scheduler advertises a multi-process world and no launcher env
    is present, let jax's built-in cluster detection (SLURM / Open MPI)
    join the world, then bootstrap the negotiation KV."""
    if _cluster_world_hint() <= 1:
        return
    try:
        kwargs = _jsm_init_kwargs()  # jsrun/LSF: jax has no JSM detector
        jax.distributed.initialize(**kwargs)  # jax auto-detects SLURM/OMPI
        hvd_logging.info(
            "jax.distributed auto-initialized from cluster env: "
            "process %d/%d", jax.process_index(), jax.process_count())
    except Exception as e:
        hvd_logging.error(
            "cluster env advertises a multi-process world but "
            "jax.distributed auto-detection failed (%s); running "
            "single-process. Launch with hvdrun, or pre-initialize "
            "jax.distributed yourself.", e)
        return
    _maybe_bootstrap_kv()


_bootstrap_kv_server = None  # keep-alive for the process-0 KV server
_bootstrap_seeded_env = False  # whether WE seeded HVD_KV_* (vs a launcher)
_KV_BOOTSTRAP_KEY = "hvd/kv_bootstrap/{}"  # per-generation: re-init safe


def _distributed_kv_client():
    """jax's distributed key-value client (None when unavailable)."""
    try:
        from jax._src import distributed as _dist
        return _dist.global_state.client
    except Exception:  # pragma: no cover - private API moved
        return None


def _kv_advertise_address() -> str:
    """The address peers should dial for the bootstrap KV server: the NIC
    that routes to the jax.distributed coordinator (UDP-connect trick, no
    packet leaves the host), because on multi-NIC hosts the first entry of
    ``local_addresses()`` may be unroutable from peers and negotiation
    would silently hang (ADVICE r4). Falls back to ``local_addresses()[0]``
    when no coordinator is known."""
    import socket

    coord = None
    try:
        from jax._src import distributed as _dist
        coord = _dist.global_state.coordinator_address
    except Exception:  # pragma: no cover  # hvdlint: disable=silent-except
        pass  # private API probe: absence falls through to the env knob
    if not coord:
        addr = envs.get(envs.COORDINATOR_ADDR)
        if addr:
            coord = f"{addr}:{envs.get(envs.COORDINATOR_PORT, '9778')}"
    if coord:
        host, _, port = coord.rpartition(":")
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect((host or coord, int(port) if port.isdigit() else 9778))
                return s.getsockname()[0]
            finally:
                s.close()
        except OSError:
            pass
    from .runner.http_kv import local_addresses
    return local_addresses()[0]


def _maybe_bootstrap_kv() -> None:
    """Stand up the negotiation/rendezvous KV for worlds NOT launched by
    ``hvdrun`` (srun/mpirun/user-initialized jax.distributed): process 0
    starts a :class:`KVServer` and publishes ``addr:port:secret`` through
    jax's distributed KV store; everyone seeds the usual ``HVD_KV_*`` env
    so the dynamic engine and elastic plumbing work identically to a
    launcher job. The exchange key carries the init generation, so an
    init/shutdown/init cycle publishes fresh coordinates instead of
    colliding with (or reusing) the previous world's."""
    global _bootstrap_kv_server, _bootstrap_seeded_env
    if envs.get(envs.KV_ADDR):
        return  # launcher already provided one
    client = _distributed_kv_client()
    if client is None or jax.process_count() <= 1:
        return  # nothing to negotiate in a single-process world
    key = _KV_BOOTSTRAP_KEY.format(_generation)
    try:
        if jax.process_index() == 0:
            from .runner.http_kv import KVServer, make_secret
            secret = make_secret()
            server = KVServer(secret=secret)
            port = server.start()
            _bootstrap_kv_server = server
            payload = f"{_kv_advertise_address()}:{port}:{secret}"
            client.key_value_set(key, payload)
        else:
            payload = client.blocking_key_value_get(key, 60_000)
        addr, port, secret = payload.split(":", 2)
        envs.set_env(envs.KV_ADDR, addr)
        envs.set_env(envs.KV_PORT, port)
        envs.set_env(envs.SECRET_KEY, secret)
        _bootstrap_seeded_env = True
        hvd_logging.info("negotiation KV bootstrapped at %s:%s", addr, port)
    except Exception as e:
        hvd_logging.warning(
            "could not bootstrap the negotiation KV over jax's distributed "
            "store (%s); multi-process eager collectives will run without "
            "negotiation (mismatches hang instead of erroring)", e)


def shutdown() -> None:
    """Tear down the runtime (reference ``horovod_shutdown``,
    ``operations.cc:926-942``). Also stops the negotiation service — it is
    bound to this world's size/rank/KV prefix and must be rebuilt by the
    next init()."""
    ctx = _lbctx.current()
    if ctx is not None:
        _loopback_shutdown(ctx)
        return
    global _state, _bootstrap_kv_server, _bootstrap_seeded_env
    from . import autotune as _autotune
    from . import conformance as _conformance
    from . import engine_service as _engine_service
    from .ops import dispatch_cache as _dispatch_cache
    from .ops import fusion_cycle as _fusion_cycle
    # Queued async collectives land BEFORE teardown (every submitted op
    # eventually executes — the reference drains its tensor queue in
    # ShutDownHorovod the same way); the cycle timer stops with the world.
    if _state is not None:
        try:
            _fusion_cycle.drain()
        except Exception:
            hvd_logging.exception("fusion-cycle drain failed at shutdown")
    _engine_service.reset_service()
    _autotune.reset()
    # Plans hold compiled programs over this world's meshes; none survive
    # a shutdown (the generation epoch also guards re-init races).
    _dispatch_cache.invalidate("runtime shutdown")
    # Conformance trace out LAST — the teardown above records events
    # too (service stop, plan shelving); the recorder then resets so a
    # later init() starts a fresh trace incarnation.
    _conformance.maybe_dump("shutdown")
    _conformance.reset()
    if _bootstrap_kv_server is not None:
        try:
            _bootstrap_kv_server.stop()
        except Exception as e:
            hvd_logging.debug("bootstrap KV server stop failed: %s", e)
        _bootstrap_kv_server = None
    if _bootstrap_seeded_env:
        # the seeded coordinates point at the server just stopped; a later
        # init() must bootstrap afresh, not trust stale env
        for var in ("HVD_KV_ADDR", "HVD_KV_PORT", "HVD_SECRET_KEY"):
            os.environ.pop(var, None)
        _bootstrap_seeded_env = False
    with _lock:
        _state = None


def _loopback_shutdown(ctx) -> None:
    """``shutdown()`` on a loopback rank thread: drain this rank's
    queued async work, stop its negotiation services, drop its dispatch
    plans — the per-rank mirror of the process-wide teardown. Shared
    process state (autotune, timeline, the OTHER ranks' worlds) is
    untouched."""
    if ctx.runtime_state is None:
        return
    from . import conformance as _conformance
    from . import engine_service as _engine_service
    from .ops import dispatch_cache as _dispatch_cache
    from .ops import fusion_cycle as _fusion_cycle
    try:
        _fusion_cycle.drain()
    except Exception:
        hvd_logging.exception(
            "loopback fusion-cycle drain failed at shutdown")
    # Elastic warm re-form (docs/elastic.md): park this rank's restorable
    # plans on the shape-keyed shelf BEFORE the service reset invalidates
    # the store — a later re-form back to this shape grafts their
    # compiled stages instead of re-tracing. No-op under HVD_ELASTIC_WARM=0.
    shelved = _dispatch_cache.shelve_for_reform()
    if shelved:
        hvd_logging.debug("loopback shutdown: shelved %d dispatch plans",
                          shelved)
    _engine_service.reset_service()
    _dispatch_cache.invalidate("loopback runtime shutdown")
    sched, ctx.scheduler = ctx.scheduler, None
    if sched is not None:
        sched.stop()
    # Per-rank conformance trace out LAST — the teardown above records
    # events too (plan shelving, service stop); reset so an elastic
    # re-init in the SAME context starts a fresh trace (the generation
    # in the file name keeps incarnations apart).
    _conformance.maybe_dump("shutdown")
    _conformance.reset()
    # NOTE: ctx.notification_manager deliberately survives — an elastic
    # re-init calls this mid-run and the manager's listeners must carry
    # into the next round (real elastic parity); the worker wrapper and
    # _abrupt_stop shut it down when the rank truly ends.
    ctx.runtime_state = None


def _current_state() -> _RuntimeState | None:
    ctx = _lbctx.current()
    if ctx is not None:
        return ctx.runtime_state
    return _state


def is_initialized() -> bool:
    return _current_state() is not None


def generation() -> int:
    """Monotonic init() counter (see ProcessSet.mesh cache). Loopback
    rank threads count their own context's init()s."""
    ctx = _lbctx.current()
    if ctx is not None:
        return ctx.generation
    return _generation


def _get() -> _RuntimeState:
    st = _current_state()
    if st is None:
        raise NotInitializedError(
            "horovod_tpu has not been initialized; call hvd.init() first.")
    return st


# --- rank/size queries (reference C API: operations.cc:944-1030) ----------

def size() -> int:
    """Total number of chips (== Horovod world size when 1 GPU per process)."""
    return len(_get().devices)


def local_size() -> int:
    """Chips driven by this controller process."""
    return len(_get().local_ranks)


def rank() -> int:
    """Representative global rank of this process: its first local chip.

    Under SPMD one process drives many chips; inside traced code use
    :func:`axis_rank` for the per-chip rank.
    """
    st = _get()
    return st.local_ranks[0] if st.local_ranks else 0


def local_rank() -> int:
    # The representative rank (first local chip) is by definition local
    # index 0 within this process.
    _get()
    return 0


def cross_rank() -> int:
    """Host index (reference cross-communicator rank, ``common.h:166-170``)."""
    return _get().process_index


def cross_size() -> int:
    return _get().process_count


def process_rank() -> int:
    return _get().process_index


def process_count() -> int:
    return _get().process_count


def is_homogeneous() -> bool:
    """True when every process drives the same number of chips
    (reference ``horovod_is_homogeneous``, ``operations.cc:1013-1017``)."""
    st = _get()
    counts = {}
    if st.rank_process_map is not None:
        for p in st.rank_process_map:
            counts[p] = counts.get(p, 0) + 1
    else:
        for d in st.devices:
            counts[d.process_index] = counts.get(d.process_index, 0) + 1
    return len(set(counts.values())) <= 1


def mesh() -> Mesh:
    """The global 1-D rank mesh."""
    return _get().mesh


def axis_name() -> str:
    return _get().axis_name


def devices() -> list:
    return list(_get().devices)


def process_set_table():
    return _get().process_set_table


def local_ranks() -> list:
    return list(_get().local_ranks)


def process_of_rank(global_rank: int) -> int:
    """Index of the process owning chip ``global_rank`` (devices are
    rank-ordered process-major; loopback worlds carry the virtual
    mapping explicitly — their fake devices all report process 0)."""
    st = _get()
    if st.rank_process_map is not None:
        return st.rank_process_map[global_rank]
    return st.devices[global_rank].process_index


# ---------------------------------------------------------------------------
# capability queries (reference basics.py:273-371) — migration shims so
# `if hvd.nccl_built(): ...` style feature probes run unmodified. The
# rebuild has exactly one collective backend: XLA over ICI/DCN.
# ---------------------------------------------------------------------------

def xla_built() -> bool:
    """True: XLA collectives are the (only) backend of the rebuild."""
    return True


def xla_enabled() -> bool:
    return True


def tpu_built() -> bool:
    """Whether a TPU backend is live (or configured) in this process.

    Safe to call before :func:`init`, like the reference's ``*_built()``
    probes: before the runtime is up this answers from configuration only
    — touching ``jax.default_backend()`` here would initialize the XLA
    client and break the later ``jax.distributed.initialize`` (see
    ``_maybe_distributed_init``)."""
    import jax

    if is_initialized():
        try:
            return jax.default_backend() == "tpu"
        except Exception:
            return False
    platforms = (os.environ.get("JAX_PLATFORMS")
                 or getattr(jax.config, "jax_platforms", None) or "")
    return "tpu" in str(platforms).lower()


def mpi_threads_supported() -> bool:
    """Reference ``hvd.mpi_threads_supported()``. The rebuild has no MPI;
    the analogous guarantee — collectives may be driven from multiple
    Python threads — holds (the engine service thread does exactly that),
    so answer True like a threads-enabled MPI build would."""
    return True


def mpi_enabled() -> bool:
    """False: no MPI backend — XLA collectives replace it (SURVEY §5.8)."""
    return False


def mpi_built() -> bool:
    return False


def gloo_enabled() -> bool:
    """False: the launcher's HTTP-KV rendezvous plays gloo's role."""
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    """False: ICI/DCN collectives are emitted by XLA, not NCCL."""
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False
