"""Timeline: Chrome-trace recording of eager collectives.

TPU-native rebuild of the reference Timeline (``timeline.cc:1-678``, writer
thread + per-tensor lanes; runtime start/stop via ``horovod_start_timeline``
at ``operations.cc:1032-1064``). The writer lives in the native engine
(``native/timeline.cc``); this module owns the process-wide instance, the
``HVD_TIMELINE`` auto-start (seeded by ``hvdrun --timeline-filename``), and
the recording hooks the eager collectives call.

Traced-mode collectives compile into the XLA program, where a wall-clock
writer cannot see them — use ``jax.profiler`` traces for those; eager ops
additionally get a ``jax.profiler.TraceAnnotation`` range so both timelines
line up (the NVTX analog, ``nvtx_op_range.cc``).
"""

from __future__ import annotations

import threading

from .loopback import context as _lbctx
from .utils import envs
from .utils import logging as hvd_logging

# Rank suffix appended per process so concurrent multi-process jobs don't
# clobber one file (the reference writes coordinator-only; symmetric
# processes each write their own view).
_lock = threading.Lock()
_engine = None  # NativeEngine owning the active timeline writer
_active = False
_atexit_registered = False

NEGOTIATE = "NEGOTIATE"
QUEUE_ENQUEUE = "QUEUE_ENQUEUE"
CYCLE_FLUSH = "CYCLE_FLUSH"
PIPELINE_LANE = "pipeline"
INFLIGHT_DEPTH = "INFLIGHT_DEPTH"
HEALTH_LANE = "health"
RETRY = "RETRY"
PHASE_BEGIN = 0
PHASE_END = 1
PHASE_INSTANT = 2


def _get_engine():
    global _engine
    if _engine is None:
        from .dynamic import NativeEngine
        _engine = NativeEngine(world_size=1, rank=0)
    return _engine


_mark_cycles = False


def start_timeline(file_path: str, mark_cycles: bool = False) -> None:
    """Start recording eager collectives to ``file_path`` (Chrome trace
    JSON; open in ``chrome://tracing`` / Perfetto). Reference
    ``hvd.start_timeline`` → ``horovod_start_timeline``
    (``operations.cc:1032-1064``). With ``mark_cycles`` (or
    ``HVD_TIMELINE_MARK_CYCLES``) every negotiation cycle of the dynamic
    service drops an instant marker (``operations.cc:485-488``)."""
    global _active, _atexit_registered, _mark_cycles
    with _lock:
        _get_engine().timeline_start(file_path)
        _active = True
        _mark_cycles = bool(mark_cycles) or envs.get_bool(
            envs.TIMELINE_MARK_CYCLES)
        if not _atexit_registered:
            import atexit
            atexit.register(stop_timeline)  # flushes on interpreter exit
            _atexit_registered = True


def mark_cycle() -> None:
    """Instant 'CYCLE' marker, called by the dynamic service's loop when
    cycle marking is on (HOROVOD_TIMELINE_MARK_CYCLES analog)."""
    if _active and _mark_cycles:
        record("negotiation", "CYCLE", PHASE_INSTANT)


def stop_timeline() -> None:
    """Flush and close the timeline (reference ``hvd.stop_timeline``)."""
    global _active
    with _lock:
        if _engine is not None:
            _engine.timeline_stop()
        _active = False


def timeline_active() -> bool:
    return _active


def maybe_autostart() -> None:
    """Start the timeline when ``HVD_TIMELINE`` is seeded (by
    ``hvdrun --timeline-filename`` or the user). Called from
    ``hvd.init()``. ``DYNAMIC`` defers to an explicit
    :func:`start_timeline` call, like the reference
    (``operations.cc:466-488``)."""
    path = envs.get(envs.TIMELINE)
    if not path or path.upper() == "DYNAMIC" or _active:
        return
    from . import runtime
    if _lbctx.current() is not None:
        # Loopback rank threads share ONE process and so one writer:
        # the first rank's init starts the single file and every rank's
        # events land in it with a ``rank<N>/`` lane prefix (see
        # :func:`record`) — a per-rank ``.<rank>`` suffix here would
        # just mislabel the shared file with whichever rank won init.
        pass
    elif runtime.process_count() > 1:
        path = f"{path}.{runtime.process_rank()}"
    try:
        start_timeline(path)
    except Exception as e:  # IO error / native engine unavailable: a
        # missing timeline must never break init
        hvd_logging.error("cannot start timeline at %s: %s", path, e)


def record_dispatch(tensor: str, hit: bool) -> None:
    """Instant plan-cache marker on the op's lane (``PLAN_HIT`` /
    ``PLAN_MISS``) so steady-state dispatch behavior is visible next to
    the NEGOTIATE/op ranges. Cheap no-op guard on the hot path; full
    counters live in ``hvd.dispatch_cache_stats()``."""
    if _active:
        record(tensor, "PLAN_HIT" if hit else "PLAN_MISS", PHASE_INSTANT)


def record_queue_enqueue(tensor: str) -> None:
    """Instant ``QUEUE_ENQUEUE`` marker on the tensor's lane when an
    async submission lands in a fusion-cycle pending queue (the analog of
    the reference timeline's QUEUE state, ``timeline.cc`` negotiation
    phases) — the gap to the next CYCLE_FLUSH shows queueing latency."""
    if _active:
        record(tensor, QUEUE_ENQUEUE, PHASE_INSTANT)


def record_cycle_flush(trigger: str) -> None:
    """Instant ``CYCLE_FLUSH`` marker on the ``fusion_cycle`` lane, one
    per flush, labeled with the trigger (threshold/cycle/synchronize/...)
    so coalescing behavior is visible next to the op ranges."""
    if _active:
        record("fusion_cycle", f"{CYCLE_FLUSH}.{trigger}", PHASE_INSTANT)


def record_inflight_depth(depth: int) -> None:
    """Instant ``INFLIGHT_DEPTH.<n>`` marker on the ``pipeline`` lane when
    the flush executor admits a batch: ``n`` is how many earlier flushes
    are still in flight on device at dispatch time (sampled BEFORE eager
    retirement — docs/pipeline.md "Overlap semantics"), so achieved
    overlap (and bubbles — long stretches at depth 0) read straight off
    the trace."""
    if _active:
        record(PIPELINE_LANE, f"{INFLIGHT_DEPTH}.{int(depth)}",
               PHASE_INSTANT)


QOS_LANE = "qos"


def record_qos(event: str, tenant: str) -> None:
    """Instant ``QOS_<event>.<tenant>`` marker on the ``qos`` lane for
    admission-gate transitions (``PARK``/``GRANT``/``FORCE``/``SHED``/
    ``BLOCK``) so a tenant's admission waits — and any shed or
    quota-blocked submissions — are attributable next to the flush and
    pipeline lanes (docs/qos.md)."""
    if _active:
        record(QOS_LANE, f"QOS_{event}.{tenant}", PHASE_INSTANT)


CAPTURE_LANE = "step_capture"


def record_capture(event: str) -> None:
    """Instant ``CAPTURE_<event>`` marker on the ``step_capture`` lane for
    capture lifecycle transitions (``RECORD``/``SEAL``/``REPLAY``/
    ``REPLAY_DONE``/``FALLBACK``) so a replayed step — and any transparent
    fallback to eager — is attributable next to the op ranges
    (docs/step_capture.md)."""
    if _active:
        record(CAPTURE_LANE, f"CAPTURE_{event}", PHASE_INSTANT)


def record_retry(what: str, attempt: int) -> None:
    """Instant ``RETRY.<site>.<n>`` marker on the ``health`` lane when a
    retried RPC/KV call backs off (``utils/retry.py``) — a flapping
    transport shows as a burst of RETRY instants instead of silently
    stretching the neighboring op ranges."""
    if _active:
        record(HEALTH_LANE, f"{RETRY}.{what}.{int(attempt)}", PHASE_INSTANT)


def record_health_event(event: str) -> None:
    """Instant marker on the ``health`` lane for failure-domain state
    changes (``PEER_DEAD.<rank>``, ``POISON``, ``STRAGGLER.<rank>``) so
    a coordinated abort — or a sustained straggler — is attributable on
    the trace."""
    if _active:
        record(HEALTH_LANE, event, PHASE_INSTANT)


def pipeline_stage(stage: str) -> "op_range":
    """Span on the ``pipeline`` lane around one stage of a chunked flush
    (``PIPELINE_FUSE`` / ``PIPELINE_DISPATCH`` / ``PIPELINE_SPLIT``) —
    the software-pipeline twin of the per-op ranges. The spans cover the
    *host-side dispatch* of each stage (device execution is asynchronous);
    overlap shows as DISPATCH spans packed back-to-back while earlier
    chunks' collectives are still in flight. ``PIPELINE_SLOT_WAIT`` spans
    mark executor admission blocking on device completion (the window is
    full) — their total is ``fusion_stats()["pipeline"]["device_wait_ms"]``."""
    return op_range(PIPELINE_LANE, f"PIPELINE_{stage}")


def record(tensor: str, activity: str, phase: int) -> None:
    """Record one event when the timeline is active (cheap no-op guard on
    the hot path). Loopback rank threads share ONE process — and so one
    writer and one file — so the lane is prefixed with the thread's rank
    from the :class:`~horovod_tpu.loopback.context.RankContext`: every
    rank's events stay attributable in the single merged trace (the
    multi-process path gets the same attribution from
    ``maybe_autostart``'s per-process ``<path>.<rank>`` files)."""
    if not _active:
        return
    eng = _engine
    if eng is not None:
        label = _lbctx.current_rank_label()
        if label:
            tensor = f"{label}/{tensor}"
        eng.timeline_record(tensor, activity, phase)


def merge_timelines(inputs, output: str) -> int:
    """Merge per-process timeline files into one Chrome trace, one pid per
    process (the reference writes a single coordinator-side file,
    ``timeline.cc``; the symmetric rebuild writes per-process files and
    merges after the run). Input order assigns pids; files named
    ``<base>.<rank>`` (the ``maybe_autostart`` convention) are labeled with
    their rank. Returns the number of events written.

    Also usable as a CLI: ``python -m horovod_tpu.timeline merged.json
    trace.0 trace.1 ...``.
    """
    import json
    import os
    import re

    events = []
    for i, path in enumerate(inputs):
        m = re.search(r"\.(\d+)$", os.path.basename(path))
        pid = int(m.group(1)) if m else i
        text = open(path).read().strip()
        # the writer appends events incrementally; tolerate a missing
        # closing bracket / trailing comma (Chrome's own loader does)
        text = text.rstrip(",\n ")
        if not text.endswith("]"):
            text += "]"
        for ev in json.loads(text):
            ev["pid"] = pid
            events.append(ev)
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"rank {pid}"}})
    events.sort(key=lambda e: e.get("ts", 0))
    with open(output, "w") as f:
        json.dump(events, f)
    return len(events)


# jax.profiler.TraceAnnotation, resolved ONCE: op_range.__enter__ sits on
# every eager collective's hot path, and the previous per-call
# ``import jax.profiler`` under a blanket ``except Exception`` paid the
# sys.modules lookup + attribute walk (and re-paid the full failed-import
# machinery forever on hosts without the profiler) once per op. None with
# ``_ann_failed`` set = resolution failed and stays failed; the timeline
# half of op_range keeps working either way.
_ann_cls = None
_ann_failed = False


def _annotation_cls():
    global _ann_cls, _ann_failed
    if _ann_cls is None and not _ann_failed:
        try:
            from jax.profiler import TraceAnnotation
            _ann_cls = TraceAnnotation
        except Exception:  # profiler unavailable: cache the failure
            _ann_failed = True
    return _ann_cls


class op_range:
    """Context manager tracing one eager collective: begin/end records in
    the Chrome timeline plus a ``jax.profiler.TraceAnnotation`` range so
    the op also shows in XLA profiler traces (NVTX analog)."""

    __slots__ = ("tensor", "activity", "_ann")

    def __init__(self, tensor: str, activity: str):
        self.tensor = tensor
        self.activity = activity
        self._ann = None

    def __enter__(self):
        if _active:
            record(self.tensor, self.activity, PHASE_BEGIN)
            cls = _annotation_cls()
            if cls is not None:
                try:
                    self._ann = cls(
                        f"hvd.{self.activity}.{self.tensor}")
                    self._ann.__enter__()
                except Exception:  # a broken annotation must not break
                    self._ann = None  # the collective or the timeline
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        if _active:
            record(self.tensor, self.activity, PHASE_END)
        return False


if __name__ == "__main__":  # pragma: no cover - thin CLI
    import sys
    if len(sys.argv) < 3:
        print("usage: python -m horovod_tpu.timeline OUT.json IN.0 [IN.1 ...]",
              file=sys.stderr)
        raise SystemExit(2)
    n = merge_timelines(sys.argv[2:], sys.argv[1])
    print(f"merged {len(sys.argv) - 2} timelines ({n} events) -> {sys.argv[1]}")
