"""Timeline: Chrome-trace recording of eager collectives.

TPU-native rebuild of the reference Timeline (``timeline.cc:1-678``, writer
thread + per-tensor lanes; runtime start/stop via ``horovod_start_timeline``
at ``operations.cc:1032-1064``). The writer lives in the native engine
(``native/timeline.cc``); this module owns the process-wide instance, the
``HVD_TIMELINE`` auto-start (seeded by ``hvdrun --timeline-filename``), and
the recording hooks the eager collectives call.

Traced-mode collectives compile into the XLA program, where a wall-clock
writer cannot see them — use ``jax.profiler`` traces for those; eager ops
additionally get a ``jax.profiler.TraceAnnotation`` range so both timelines
line up (the NVTX analog, ``nvtx_op_range.cc``).
"""

from __future__ import annotations

import threading

from .utils import envs
from .utils import logging as hvd_logging

# Rank suffix appended per process so concurrent multi-process jobs don't
# clobber one file (the reference writes coordinator-only; symmetric
# processes each write their own view).
_lock = threading.Lock()
_engine = None  # NativeEngine owning the active timeline writer
_active = False
_atexit_registered = False

NEGOTIATE = "NEGOTIATE"
PHASE_BEGIN = 0
PHASE_END = 1
PHASE_INSTANT = 2


def _get_engine():
    global _engine
    if _engine is None:
        from .dynamic import NativeEngine
        _engine = NativeEngine(world_size=1, rank=0)
    return _engine


def start_timeline(file_path: str, mark_cycles: bool = False) -> None:
    """Start recording eager collectives to ``file_path`` (Chrome trace
    JSON; open in ``chrome://tracing`` / Perfetto). Reference
    ``hvd.start_timeline`` → ``horovod_start_timeline``
    (``operations.cc:1032-1064``)."""
    global _active, _atexit_registered
    del mark_cycles  # cycle marks need the dynamic service; accepted for parity
    with _lock:
        _get_engine().timeline_start(file_path)
        _active = True
        if not _atexit_registered:
            import atexit
            atexit.register(stop_timeline)  # flushes on interpreter exit
            _atexit_registered = True


def stop_timeline() -> None:
    """Flush and close the timeline (reference ``hvd.stop_timeline``)."""
    global _active
    with _lock:
        if _engine is not None:
            _engine.timeline_stop()
        _active = False


def timeline_active() -> bool:
    return _active


def maybe_autostart() -> None:
    """Start the timeline when ``HVD_TIMELINE`` is seeded (by
    ``hvdrun --timeline-filename`` or the user). Called from
    ``hvd.init()``. ``DYNAMIC`` defers to an explicit
    :func:`start_timeline` call, like the reference
    (``operations.cc:466-488``)."""
    path = envs.get(envs.TIMELINE)
    if not path or path.upper() == "DYNAMIC" or _active:
        return
    from . import runtime
    if runtime.process_count() > 1:
        path = f"{path}.{runtime.process_rank()}"
    try:
        start_timeline(path)
    except Exception as e:  # IO error / native engine unavailable: a
        # missing timeline must never break init
        hvd_logging.error("cannot start timeline at %s: %s", path, e)


def record(tensor: str, activity: str, phase: int) -> None:
    """Record one event when the timeline is active (cheap no-op guard on
    the hot path)."""
    if not _active:
        return
    eng = _engine
    if eng is not None:
        eng.timeline_record(tensor, activity, phase)


class op_range:
    """Context manager tracing one eager collective: begin/end records in
    the Chrome timeline plus a ``jax.profiler.TraceAnnotation`` range so
    the op also shows in XLA profiler traces (NVTX analog)."""

    __slots__ = ("tensor", "activity", "_ann")

    def __init__(self, tensor: str, activity: str):
        self.tensor = tensor
        self.activity = activity
        self._ann = None

    def __enter__(self):
        if _active:
            record(self.tensor, self.activity, PHASE_BEGIN)
            try:
                import jax.profiler
                self._ann = jax.profiler.TraceAnnotation(
                    f"hvd.{self.activity}.{self.tensor}")
                self._ann.__enter__()
            except Exception:  # profiler unavailable: timeline still works
                self._ann = None
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        if _active:
            record(self.tensor, self.activity, PHASE_END)
        return False
