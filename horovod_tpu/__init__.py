"""horovod_tpu: a TPU-native distributed training framework with Horovod's
capabilities (reference surveyed in SURVEY.md), built on jax/XLA.

Five-line usage, mirroring the reference README (``/root/reference/README.rst``):

    import horovod_tpu as hvd
    hvd.init()
    tx = hvd.DistributedOptimizer(optax.sgd(0.01 * hvd.size()))
    params = hvd.broadcast_parameters(params, root_rank=0)
    # train under jax.jit / shard_map over hvd.mesh()

Hot-path inversion (SURVEY.md §7): the reference injects a C++ background
runtime between the framework and NCCL/MPI; here the XLA compiler schedules
collectives natively over the ICI/DCN mesh. A native (C++) dynamic engine —
negotiation, response cache, fusion planning, stall inspection, Chrome-trace
timeline — is built on demand from ``native/`` and bound via ctypes
(:mod:`horovod_tpu.dynamic`); the eager collectives record into its
timeline (``hvd.start_timeline``).
"""

from .utils import compat as _compat  # installs the jax.shard_map shim
from . import runtime as _runtime
from .runtime import (
    AXIS_NAME,
    NotInitializedError,
    axis_name,
    ccl_built,
    cross_rank,
    cross_size,
    cuda_built,
    ddl_built,
    devices,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_ranks,
    local_size,
    mesh,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    process_count,
    process_rank,
    rank,
    rocm_built,
    shutdown,
    size,
    tpu_built,
    xla_built,
    xla_enabled,
)
from .ops import (
    Adasum,
    Average,
    Compression,
    Handle,
    Max,
    Min,
    PerRank,
    Product,
    ReduceOp,
    SparseRows,
    Sum,
    adasum_allreduce,
    allgather,
    allgather_async,
    allgather_object,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    broadcast_object,
    cached_step,
    dispatch_cache_stats,
    fusion_flush,
    fusion_stats,
    gspmd_cache_stats,
    grouped_allreduce,
    grouped_allreduce_async,
    grouped_broadcast,
    grouped_broadcast_async,
    hierarchical_allgather,
    hierarchical_allreduce,
    hierarchical_mesh,
    join,
    per_rank,
    poll,
    reducescatter,
    rows_from_dense,
    rows_to_dense,
    sparse_allreduce,
    sparse_allreduce_async,
    sparse_allreduce_to_dense,
    step_marker,
    synchronize,
)
from .process_sets import (
    ProcessSet,
    add_process_set,
    global_process_set,
    remove_process_set,
)
from .optim import (
    DistributedOptimizer,
    allreduce_gradients_transform,
    grad,
    value_and_grad,
)
from .functions import (
    broadcast_optimizer_state,
    broadcast_parameters,
    broadcast_variables,
)
from .exceptions import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
    PeerFailureError,
    QosAdmissionError,
)
from . import qos
from .qos import QosClass, qos_stats, set_qos
from .health import health_stats
from .engine_service import response_cache_stats
from . import metrics
from .metrics import metrics_dump
from . import conformance
from .conformance import conformance_dump, conformance_stats
from .timeline import start_timeline, stop_timeline
from . import autotune
from . import callbacks
from . import checkpoint
from . import data
from . import elastic
from . import loopback
from . import parallel
from .parallel.mesh import (
    MeshLayout,
    MeshLayoutError,
    composed_mesh,
    mesh_layout,
    sync_gradients,
)
from .callbacks import average_metrics, metric_average
from .version import __version__


def __getattr__(name):
    # lazy: pulls in flax model definitions only when actually used, so
    # plain `import horovod_tpu` (launcher, runner utilities) stays light
    if name == "SyncBatchNorm":
        from .models.sync_batch_norm import SyncBatchNorm
        return SyncBatchNorm
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

# Torch-parity aliases (reference exposes in-place variants; jax arrays are
# immutable so they alias the pure versions).
allreduce_ = allreduce
broadcast_ = broadcast

__all__ = [
    "AXIS_NAME", "NotInitializedError", "axis_name", "cross_rank",
    "cross_size", "devices", "init", "is_homogeneous", "is_initialized",
    "local_rank", "local_ranks", "local_size", "mesh", "process_count",
    "process_rank", "rank", "shutdown", "size",
    "ccl_built", "cuda_built", "ddl_built", "gloo_built", "gloo_enabled",
    "mpi_built", "mpi_enabled", "mpi_threads_supported", "nccl_built",
    "rocm_built", "tpu_built", "xla_built", "xla_enabled",
    "Adasum", "Average", "Compression", "Handle", "Max", "Min", "PerRank",
    "Product", "ReduceOp", "Sum", "adasum_allreduce", "allgather",
    "allgather_async", "allgather_object", "allreduce", "allreduce_",
    "allreduce_async", "alltoall", "alltoall_async", "barrier", "broadcast",
    "broadcast_", "broadcast_async", "broadcast_object",
    "cached_step", "dispatch_cache_stats", "fusion_flush", "fusion_stats",
    "gspmd_cache_stats", "step_marker",
    "grouped_allreduce", "grouped_allreduce_async", "grouped_broadcast",
    "grouped_broadcast_async",
    "hierarchical_allgather", "hierarchical_allreduce", "hierarchical_mesh",
    "MeshLayout", "MeshLayoutError", "composed_mesh", "mesh_layout",
    "sync_gradients",
    "join", "per_rank", "poll", "reducescatter", "synchronize",
    "SparseRows", "rows_from_dense", "rows_to_dense", "sparse_allreduce", "sparse_allreduce_async",
    "sparse_allreduce_to_dense",
    "ProcessSet", "add_process_set", "global_process_set", "remove_process_set",
    "DistributedOptimizer", "allreduce_gradients_transform", "grad",
    "value_and_grad", "broadcast_optimizer_state", "broadcast_parameters",
    "broadcast_variables", "HorovodInternalError", "HostsUpdatedInterrupt",
    "PeerFailureError", "QosAdmissionError", "QosClass", "qos",
    "qos_stats", "set_qos", "health_stats", "response_cache_stats",
    "metrics", "metrics_dump",
    "conformance", "conformance_dump", "conformance_stats",
    "start_timeline", "stop_timeline", "autotune", "callbacks",
    "checkpoint", "data", "elastic", "loopback", "parallel",
    "average_metrics",
    "metric_average", "SyncBatchNorm", "__version__",
]
