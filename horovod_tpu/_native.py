"""ctypes binding for the native dynamic engine (native/engine.cc).

Loads ``horovod_tpu/lib/libhvd_core.so``, compiling it from ``native/`` on
demand when missing or stale (single g++ invocation, zero third-party
dependencies — the reference needs CMake + flatbuffers + boost for the same
components, ``/root/reference/horovod/CMakeLists.txt``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.join(os.path.dirname(_PKG_DIR), "native")
_LIB_DIR = os.path.join(_PKG_DIR, "lib")
_LIB_PATH = os.path.join(_LIB_DIR, "libhvd_core.so")

_SOURCES = ("engine.cc", "timeline.cc")
_HEADERS = ("hvd_core.h", "message.h", "wire.h", "timeline.h")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


class NativeBuildError(RuntimeError):
    """The engine sources could not be compiled (no g++, compile error)."""


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    so_mtime = os.path.getmtime(_LIB_PATH)
    for f in _SOURCES + _HEADERS:
        src = os.path.join(_NATIVE_DIR, f)
        if os.path.exists(src) and os.path.getmtime(src) > so_mtime:
            return True
    return False


def _build() -> None:
    srcs = [os.path.join(_NATIVE_DIR, s) for s in _SOURCES]
    missing = [s for s in srcs if not os.path.exists(s)]
    if missing:
        raise NativeBuildError(f"engine sources not found: {missing}")
    os.makedirs(_LIB_DIR, exist_ok=True)
    cxx = os.environ.get("CXX", "g++")
    tmp = _LIB_PATH + f".tmp.{os.getpid()}"
    cmd = [cxx, "-O2", "-fPIC", "-std=c++17", "-pthread", "-shared",
           *srcs, "-o", tmp]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeBuildError(f"failed to run {cxx}: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native engine compile failed (rc={proc.returncode}):\n"
            f"{proc.stdout[-4000:]}")
    os.replace(tmp, _LIB_PATH)  # atomic: concurrent builders can't corrupt


def _declare(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.hvd_engine_create.restype = ctypes.c_void_p
    lib.hvd_engine_create.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_double, ctypes.c_double]
    lib.hvd_engine_destroy.argtypes = [ctypes.c_void_p]
    lib.hvd_engine_enqueue.restype = ctypes.c_int32
    lib.hvd_engine_enqueue.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32, ctypes.c_int32, ctypes.c_double, ctypes.c_double,
        ctypes.c_int32]
    for name in ("hvd_engine_pop_requests", "hvd_engine_compute_responses",
                 "hvd_engine_cache_bits", "hvd_engine_stall_report"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int32
        fn.argtypes = [ctypes.c_void_p, ctypes.POINTER(u8p),
                       ctypes.POINTER(ctypes.c_size_t)]
    lib.hvd_engine_ingest.restype = ctypes.c_int32
    lib.hvd_engine_ingest.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, u8p, ctypes.c_size_t]
    lib.hvd_engine_commit_cache_bits.restype = ctypes.c_int32
    lib.hvd_engine_commit_cache_bits.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_size_t]
    lib.hvd_engine_register_group.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
    lib.hvd_engine_abandon.restype = ctypes.c_int32
    lib.hvd_engine_abandon.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.hvd_engine_pending_count.restype = ctypes.c_int32
    lib.hvd_engine_pending_count.argtypes = [ctypes.c_void_p]
    lib.hvd_engine_cache_size.restype = ctypes.c_int32
    lib.hvd_engine_cache_size.argtypes = [ctypes.c_void_p]
    # coordinator ResponseCache gates (absent from pre-r13 builds; the
    # wrappers in dynamic.py degrade to "never serve locally" without them)
    if hasattr(lib, "hvd_engine_cache_has"):
        lib.hvd_engine_cache_has.restype = ctypes.c_int32
        lib.hvd_engine_cache_has.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    if hasattr(lib, "hvd_engine_join_pending"):
        lib.hvd_engine_join_pending.restype = ctypes.c_int32
        lib.hvd_engine_join_pending.argtypes = [ctypes.c_void_p]
    lib.hvd_timeline_start.restype = ctypes.c_int32
    lib.hvd_timeline_start.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.hvd_timeline_stop.argtypes = [ctypes.c_void_p]
    lib.hvd_timeline_record.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32,
        ctypes.c_int64]
    lib.hvd_core_version.restype = ctypes.c_char_p


def build_native(force: bool = False) -> str:
    """Build the native library from ``native/`` sources, returning the
    library path. ``force=True`` rebuilds unconditionally — used by the CI
    gate so a stale or foreign-arch binary can never be what ships."""
    if force or _needs_build():
        _build()
    return _LIB_PATH


def load() -> ctypes.CDLL:
    """Load (building if needed) the native engine library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _needs_build():
            _build()
        lib = ctypes.CDLL(_LIB_PATH)
        _declare(lib)
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native engine can be loaded (or built)."""
    try:
        load()
        return True
    except (NativeBuildError, OSError):
        return False


def version() -> str:
    return load().hvd_core_version().decode()
