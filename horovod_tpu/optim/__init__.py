"""Distributed optimizer wrappers.

TPU-native rebuild of the reference's optimizer surface:

* ``DistributedOptimizer`` — the optax analog of
  ``/root/reference/horovod/torch/optimizer.py:131-343`` (per-param hook →
  allreduce → step) and ``/root/reference/horovod/tensorflow/__init__.py:443-630``.
  Here the allreduce is an ``optax.GradientTransformation`` stage, so under
  ``jit`` XLA fuses/overlaps the gradient collectives with the update math —
  the compiler plays the role of Horovod's fusion buffer + background cycle.
* ``backward_passes_per_step`` — local gradient aggregation, the analog of
  ``LocalGradientAggregationHelper``
  (``/root/reference/horovod/tensorflow/gradient_aggregation*.py``), via
  ``optax.MultiSteps``.
* ``value_and_grad``/``grad`` — the ``DistributedGradientTape`` analog
  (``/root/reference/horovod/tensorflow/__init__.py:770-851``): wraps
  ``jax.value_and_grad`` and allreduces the gradient pytree.
"""

from __future__ import annotations

from typing import Any

import jax
import optax

from ..ops import collectives
from ..ops.compression import Compression, Compressor
from ..ops.reduce_ops import ReduceOp
from ..process_sets import ProcessSet


def _allreduce_tree(tree, *, op, process_set, compression, prescale_factor,
                    postscale_factor, axis_name):
    """Allreduce every leaf of a gradient pytree with dtype-fused wire
    buffers (eager) or per-leaf psum (traced; XLA fuses)."""
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    compressed, ctxs = [], []
    for leaf in leaves:
        c, ctx = compression.compress(leaf)
        compressed.append(c)
        ctxs.append(ctx)
    reduced = collectives.grouped_allreduce(
        compressed, op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        axis_name=axis_name)
    out = [compression.decompress(r, ctx) for r, ctx in zip(reduced, ctxs)]
    return jax.tree.unflatten(treedef, out)


def allreduce_gradients_transform(
        *, op: ReduceOp = ReduceOp.AVERAGE,
        process_set: ProcessSet | None = None,
        compression: type[Compressor] = Compression.none,
        prescale_factor: float = 1.0, postscale_factor: float = 1.0,
        axis_name=None) -> optax.GradientTransformation:
    """An optax stage that allreduces incoming gradients."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        synced = _allreduce_tree(
            updates, op=op, process_set=process_set, compression=compression,
            prescale_factor=prescale_factor, postscale_factor=postscale_factor,
            axis_name=axis_name)
        return synced, state

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(
        optimizer: optax.GradientTransformation,
        *, op: ReduceOp = ReduceOp.AVERAGE,
        process_set: ProcessSet | None = None,
        compression: type[Compressor] = Compression.none,
        prescale_factor: float = 1.0, postscale_factor: float = 1.0,
        backward_passes_per_step: int = 1,
        axis_name=None) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates see globally-reduced gradients
    (reference ``hvd.DistributedOptimizer``).

    With ``backward_passes_per_step > 1`` gradients accumulate locally
    (running mean, matching ``average_aggregated_gradients=True``) and the
    allreduce + inner update run every k-th step.
    """
    distributed = optax.chain(
        allreduce_gradients_transform(
            op=op, process_set=process_set, compression=compression,
            prescale_factor=prescale_factor, postscale_factor=postscale_factor,
            axis_name=axis_name),
        optimizer,
    )
    if backward_passes_per_step > 1:
        return optax.MultiSteps(
            distributed, every_k_schedule=backward_passes_per_step)
    return distributed


def value_and_grad(fun, argnums=0, has_aux: bool = False,
                   *, op: ReduceOp = ReduceOp.AVERAGE,
                   process_set: ProcessSet | None = None,
                   compression: type[Compressor] = Compression.none,
                   axis_name=None):
    """``jax.value_and_grad`` whose gradients are allreduced — the
    ``DistributedGradientTape`` analog. The loss value is *not* reduced
    (matches the reference, which only reduces gradients)."""
    vg = jax.value_and_grad(fun, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        value, grads = vg(*args, **kwargs)
        grads = _allreduce_tree(
            grads, op=op, process_set=process_set, compression=compression,
            prescale_factor=1.0, postscale_factor=1.0, axis_name=axis_name)
        return value, grads

    return wrapped


def grad(fun, argnums=0, has_aux: bool = False, **kwargs):
    """``jax.grad`` with allreduced gradients. With ``has_aux=True``
    returns ``(grads, aux)``, matching the jax.grad contract."""
    vg = value_and_grad(fun, argnums=argnums, has_aux=has_aux, **kwargs)

    def wrapped(*args, **kw):
        value, grads = vg(*args, **kw)
        if has_aux:
            _, aux = value
            return grads, aux
        return grads

    return wrapped
