"""Distributed optimizer wrappers.

TPU-native rebuild of the reference's optimizer surface:

* ``DistributedOptimizer`` — the optax analog of
  ``/root/reference/horovod/torch/optimizer.py:131-343`` (per-param hook →
  allreduce → step) and ``/root/reference/horovod/tensorflow/__init__.py:443-630``.
  Here the allreduce is an ``optax.GradientTransformation`` stage, so under
  ``jit`` XLA fuses/overlaps the gradient collectives with the update math —
  the compiler plays the role of Horovod's fusion buffer + background cycle.
  In EAGER mode the stage buckets the gradient pytree by
  ``HVD_BUCKET_BYTES`` (default 64 MiB, the reference fusion-buffer scale)
  and issues each bucket as its own flushed async grouped allreduce so
  bucket k's collective hides under bucket k+1's host-side fuse and the
  update math — the reference's backward-pass comm/compute overlap
  (PAPER.md §L2), rebuilt on the pipelined flush executor.
* ``backward_passes_per_step`` — local gradient aggregation, the analog of
  ``LocalGradientAggregationHelper``
  (``/root/reference/horovod/tensorflow/gradient_aggregation*.py``), via
  ``optax.MultiSteps``.
* ``value_and_grad``/``grad`` — the ``DistributedGradientTape`` analog
  (``/root/reference/horovod/tensorflow/__init__.py:770-851``): wraps
  ``jax.value_and_grad`` and allreduces the gradient pytree.
"""

from __future__ import annotations

import re
from typing import Any

import jax
import jax.numpy as jnp
import optax

from ..ops import collectives
from ..ops import sparse as sparse_ops
from ..ops import step_capture
from ..ops.compression import Compression, Compressor
from ..ops.reduce_ops import ReduceOp
from ..process_sets import ProcessSet
from ..utils import envs


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        if key is None:
            key = getattr(p, "name", str(p))
        parts.append(str(key))
    return "/".join(parts)


def _sparse_rows_for(path_str: str, sparse_gradient_paths, sparse_max_rows):
    """max_rows for a sparse-routed leaf, or None for the dense path."""
    if not sparse_gradient_paths:
        return None
    for pat in sparse_gradient_paths:
        if re.search(pat, path_str):
            if isinstance(sparse_max_rows, dict):
                for k, v in sparse_max_rows.items():
                    if re.search(k, path_str):
                        return int(v)
                raise ValueError(
                    f"sparse gradient leaf {path_str!r} matched "
                    f"{pat!r} but sparse_max_rows has no entry for it")
            return int(sparse_max_rows)
    return None


def _leaf_nbytes(leaf) -> int:
    """Per-rank payload bytes of one gradient leaf (PerRank bundles drop
    the rank axis) — the accounting the bucket layout partitions on.
    Derives from static shape/dtype only, so every rank computes the
    identical layout for the same gradient tree."""
    if isinstance(leaf, collectives.PerRank):
        arr = leaf.array
        rows = max(int(arr.shape[0]), 1)
        return max(int(arr.nbytes) // rows, 1)
    nbytes = getattr(leaf, "nbytes", None)
    if nbytes is not None:
        return max(int(nbytes), 1)
    return int(jnp.dtype(jnp.result_type(leaf)).itemsize)


def _bucket_layout(sizes, cap: int) -> list[list[int]]:
    """Partition leaf indices into contiguous buckets of at most ``cap``
    bytes each, walking the flattened gradient tree in REVERSE traversal
    order — the backward pass produces the last layers' gradients first,
    so reverse-order buckets approximate gradient production order (the
    reference fusion buffer fills the same way). The layout is a pure
    function of the leaf sizes, so every rank issues the identical
    bucket stream in the identical order (the PR-2/3 rank-deterministic
    composition contract). A single leaf larger than ``cap`` forms its
    own bucket; indices stay reverse-traversal-ordered within and across
    buckets."""
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i in reversed(range(len(sizes))):
        if cur and cur_bytes + sizes[i] > cap:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += sizes[i]
    if cur:
        buckets.append(cur)
    return buckets


def _bucketed_allreduce(leaves, *, op, process_set, compression,
                        prescale_factor, postscale_factor, axis_name):
    """Sync the dense gradient leaves with backward-pass comm/compute
    overlap (``HVD_BUCKET_BYTES``, default 64 MiB): partition into
    size-bounded reverse-traversal buckets, issue each bucket as its own
    ``grouped_allreduce_async`` and flush it immediately — bucket k's
    collective is then in flight on device while bucket k+1 fuses
    host-side and, downstream, the optax update math chains on completed
    buckets (results are collected without a device block; data
    dependencies order execution). Numerics are identical to the
    whole-tree grouped call: the reduction is elementwise per leaf, and
    fusion only changes wire packaging.

    Falls back to the single whole-tree grouped dispatch when bucketing
    is off (``HVD_BUCKET_BYTES=0``), the tree fits one bucket, or the
    leaves are tracers (traced mode: XLA's combiner/scheduler already
    overlaps per-leaf collectives with backward compute).

    Where ``envs.eager_chain_enabled`` says consumer math must not chain
    on in-flight results (XLA CPU: its shared per-device thread pool
    lets the optax update programs starve an in-flight chunked
    collective's rendezvous — a reproduced hard deadlock), results are
    materialized before they return; overlap BETWEEN buckets is
    untouched (all buckets are submitted before the first collection
    blocks, and the flush executor pipelines them regardless)."""
    tracers = any(collectives._contains_tracer(l) for l in leaves)

    def sync(ts):
        out = collectives.grouped_allreduce(
            ts, op=op, process_set=process_set,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, axis_name=axis_name,
            compression=compression)
        if not tracers and not envs.eager_chain_enabled(
                jax.devices()[0].platform):
            jax.block_until_ready(collectives._result_arrays(out))
        return out

    cap = envs.bucket_bytes()
    if cap <= 0 or len(leaves) < 2 or tracers:
        return sync(leaves)
    buckets = _bucket_layout([_leaf_nbytes(l) for l in leaves], cap)
    if len(buckets) < 2:
        return sync(leaves)
    # Step capture boundary (HVD_STEP_CAPTURE; ops/step_capture.py):
    # the bucket stream below is submit-then-collect — every bucket is
    # submitted and flushed before the first result is observed — which
    # is exactly the shape capture can record once and replay as ONE
    # whole-step program on later steps. The region is a no-op with the
    # knob off or when a user `hvd.step_marker()` region already spans
    # the step.
    with step_capture.auto_region():
        handles = []
        for idxs in buckets:
            h = collectives.grouped_allreduce_async(
                [leaves[i] for i in idxs], op=op, process_set=process_set,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor, axis_name=axis_name,
                compression=compression)
            # dispatch NOW (the "bucket" flush trigger): without this the
            # bucket would sit queued until a threshold/cycle/synchronize
            # trigger and nothing would overlap
            h.flush()
            handles.append((idxs, h))
        out = [None] * len(leaves)
        for idxs, h in handles:
            for i, r in zip(idxs, h.result()):
                out[i] = r
    return out


def _mesh_spec_sync(tree, mesh_spec, *, op, compression, prescale_factor,
                    postscale_factor):
    """Composed-mesh two-level gradient sync (``parallel/mesh.py``):
    when the spec's data axes are BOUND (the step runs inside
    ``shard_map`` over the composed mesh), every leaf reduces
    intra-slice over ``ici_dp`` (psum_scatter) then cross-slice over
    ``dcn`` (psum) with the standard pre/post scale split — model axes
    (seq/expert/stage) are never touched, and ``ReduceOp.ADASUM`` rides
    the ``dcn`` axis through the pairwise tree. Returns ``None`` when
    the axes are not bound (an eager call): the caller falls through to
    the bucketed eager path, keeping the PR-6 bucket pipelining and the
    PR-8 step capture exactly as for plain DP."""
    from ..parallel import mesh as composed
    dcn_axis, ici_axis = composed.resolve_data_axes(mesh_spec)
    if not (collectives._axis_is_bound(dcn_axis)
            and collectives._axis_is_bound(ici_axis)):
        return None
    from ..ops import adasum as adasum_ops
    from ..ops import hierarchical

    def sync_leaf(leaf):
        c, ctx = compression.compress(leaf)
        if op == ReduceOp.ADASUM:
            if prescale_factor != 1.0 or postscale_factor != 1.0:
                raise ValueError("Adasum is scale-invariant; pre/post "
                                 "scale factors do not apply")
            synced = adasum_ops.adasum_hierarchical_traced(
                c, ici_axis, dcn_axis)
        else:
            synced = hierarchical.hierarchical_allreduce_traced(
                c, ici_axis, dcn_axis, op=op,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
        return compression.decompress(synced, ctx)

    return jax.tree.map(sync_leaf, tree)


def _allreduce_tree(tree, *, op, process_set, compression, prescale_factor,
                    postscale_factor, axis_name,
                    sparse_gradient_paths=None, sparse_max_rows=None,
                    mesh_spec=None):
    """Allreduce every leaf of a gradient pytree with dtype-fused wire
    buffers (eager) or per-leaf psum (traced; XLA fuses). Leaves whose key
    path matches ``sparse_gradient_paths`` take the indexed-rows allgather
    path instead (wire traffic ∝ touched rows — the reference's
    IndexedSlices handling inside DistributedOptimizer).

    ``mesh_spec`` (a ``parallel.mesh.MeshLayout`` or a
    ``(dcn_axis, ici_dp_axis)`` name pair) routes BOUND-axis trees
    through the composed-mesh two-level sync — every leaf dense (the
    sparse allgather path is eager machinery); eager trees fall through
    to the bucketed path unchanged."""
    if mesh_spec is not None:
        synced = _mesh_spec_sync(
            tree, mesh_spec, op=op, compression=compression,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
        if synced is not None:
            return synced
    path_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    if not path_leaves:
        return tree
    out: list = [None] * len(path_leaves)
    dense_idx, dense_leaves = [], []
    for i, (path, leaf) in enumerate(path_leaves):
        max_rows = _sparse_rows_for(_path_str(path), sparse_gradient_paths,
                                    sparse_max_rows)
        if max_rows is not None and getattr(leaf, "ndim", 0) == 2:
            axis = collectives._resolve_axis(axis_name)
            if (collectives._contains_tracer(leaf)
                    and not collectives._axis_is_bound(axis)):
                # Plain jit/pjit (GSPMD): the partitioner already globally
                # averaged the gradient — sync is the identity here exactly
                # as on the dense path (_gspmd_passthrough_check).
                collectives._gspmd_passthrough_check(op, "sparse_allreduce")
                scale = prescale_factor * postscale_factor
                out[i] = leaf if scale == 1.0 else leaf * scale
            else:
                # sparse leaves honor the same scaling/compression contract
                # as the dense leaves in the tree (compression casts the
                # wire dtype; scales bracket the reduction)
                scaled = leaf if prescale_factor == 1.0 \
                    else leaf * prescale_factor
                c, ctx = compression.compress(scaled)
                synced = sparse_ops.sparse_allreduce_to_dense(
                    c, max_rows, op=op, process_set=process_set,
                    axis_name=axis_name)
                synced = compression.decompress(synced, ctx)
                out[i] = synced if postscale_factor == 1.0 \
                    else synced * postscale_factor
        else:
            dense_idx.append(i)
            dense_leaves.append(leaf)
    if dense_leaves:
        # Wire compression is routed INTO the grouped dispatch: the fusion
        # buffers are keyed by wire dtype (mixed-source-dtype grads share
        # one compressed buffer) and results are decompressed after the
        # split — no per-leaf compress/decompress op storm around the call.
        # Eager trees larger than HVD_BUCKET_BYTES dispatch as a stream of
        # per-bucket async grouped allreduces so communication overlaps
        # the remaining host-side work (see _bucketed_allreduce).
        reduced = _bucketed_allreduce(
            dense_leaves, op=op, process_set=process_set,
            prescale_factor=prescale_factor, postscale_factor=postscale_factor,
            axis_name=axis_name, compression=compression)
        for i, r in zip(dense_idx, reduced):
            out[i] = r
    return jax.tree.unflatten(treedef, out)


def allreduce_gradients_transform(
        *, op: ReduceOp = ReduceOp.AVERAGE,
        process_set: ProcessSet | None = None,
        compression: type[Compressor] = Compression.none,
        prescale_factor: float = 1.0, postscale_factor: float = 1.0,
        sparse_gradient_paths=None, sparse_max_rows=None,
        axis_name=None, mesh_spec=None) -> optax.GradientTransformation:
    """An optax stage that allreduces incoming gradients."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        synced = _allreduce_tree(
            updates, op=op, process_set=process_set, compression=compression,
            prescale_factor=prescale_factor, postscale_factor=postscale_factor,
            sparse_gradient_paths=sparse_gradient_paths,
            sparse_max_rows=sparse_max_rows,
            axis_name=axis_name, mesh_spec=mesh_spec)
        return synced, state

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(
        optimizer: optax.GradientTransformation,
        *, op: ReduceOp = ReduceOp.AVERAGE,
        process_set: ProcessSet | None = None,
        compression: type[Compressor] = Compression.none,
        prescale_factor: float = 1.0, postscale_factor: float = 1.0,
        backward_passes_per_step: int = 1,
        sparse_gradient_paths=None, sparse_max_rows=None,
        axis_name=None, mesh_spec=None) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates see globally-reduced gradients
    (reference ``hvd.DistributedOptimizer``).

    ``mesh_spec`` opts the sync into the composed-mesh contract
    (``parallel/mesh.py``, docs/mesh.md): pass the step's
    ``MeshLayout`` (or an explicit ``(dcn_axis, ici_dp_axis)`` pair)
    and a BOUND-axis step (``shard_map`` over ``hvd.composed_mesh()``)
    reduces its gradients two-level over the DATA axes only —
    intra-slice ``psum_scatter`` over ``ici_dp``, cross-slice ``psum``
    over ``dcn`` — leaving sequence/expert/stage model axes sharded.
    Eager steps with the same ``mesh_spec`` fall through to the
    bucketed pipeline below unchanged.

    With ``backward_passes_per_step > 1`` gradients accumulate locally
    (running mean, matching ``average_aggregated_gradients=True``) and the
    allreduce + inner update run every k-th step.

    Eager gradient trees larger than ``HVD_BUCKET_BYTES`` (default
    64 MiB; ``0`` disables) sync as a stream of per-bucket async grouped
    allreduces in stable reverse-traversal order — each bucket's
    collective is in flight while the next bucket fuses, and results are
    collected without a device block (where ``HVD_EAGER_CHAIN`` allows;
    auto = off on the XLA CPU backend, where consumer programs racing an
    in-flight collective deadlock its rendezvous) so the wrapped
    optimizer's update math chains on completed buckets. Numerics are
    identical to the
    whole-tree call; bucket composition is a pure function of the leaf
    shapes, so multi-process jobs stay rank-deterministic. Traced
    (jit/shard_map) updates are untouched: XLA already schedules the
    collectives against the backward compute.

    ``sparse_gradient_paths`` is a list of regexes matched against each
    gradient leaf's ``/``-joined key path (e.g. ``["embedding"]``); matching
    2-D leaves sync via the indexed-rows allgather path with per-step wire
    traffic ∝ ``sparse_max_rows`` (an int, or a dict of path-regex → int)
    instead of the full table — the reference's IndexedSlices handling
    (``tensorflow/__init__.py:95-112``). ``HVD_SPARSE_AS_DENSE`` falls back
    to dense allreduce.
    """
    distributed = optax.chain(
        allreduce_gradients_transform(
            op=op, process_set=process_set, compression=compression,
            prescale_factor=prescale_factor, postscale_factor=postscale_factor,
            sparse_gradient_paths=sparse_gradient_paths,
            sparse_max_rows=sparse_max_rows,
            axis_name=axis_name, mesh_spec=mesh_spec),
        optimizer,
    )
    if backward_passes_per_step > 1:
        return optax.MultiSteps(
            distributed, every_k_schedule=backward_passes_per_step)
    return distributed


def value_and_grad(fun, argnums=0, has_aux: bool = False,
                   *, op: ReduceOp = ReduceOp.AVERAGE,
                   process_set: ProcessSet | None = None,
                   compression: type[Compressor] = Compression.none,
                   axis_name=None, mesh_spec=None):
    """``jax.value_and_grad`` whose gradients are allreduced — the
    ``DistributedGradientTape`` analog. The loss value is *not* reduced
    (matches the reference, which only reduces gradients).
    ``mesh_spec`` routes bound-axis gradients through the composed-mesh
    two-level data sync (see :func:`DistributedOptimizer`)."""
    vg = jax.value_and_grad(fun, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        value, grads = vg(*args, **kwargs)
        grads = _allreduce_tree(
            grads, op=op, process_set=process_set, compression=compression,
            prescale_factor=1.0, postscale_factor=1.0, axis_name=axis_name,
            mesh_spec=mesh_spec)
        return value, grads

    return wrapped


def grad(fun, argnums=0, has_aux: bool = False, **kwargs):
    """``jax.grad`` with allreduced gradients. With ``has_aux=True``
    returns ``(grads, aux)``, matching the jax.grad contract."""
    vg = value_and_grad(fun, argnums=argnums, has_aux=has_aux, **kwargs)

    def wrapped(*args, **kw):
        value, grads = vg(*args, **kw)
        if has_aux:
            _, aux = value
            return grads, aux
        return grads

    return wrapped
