"""Gaussian-process Bayesian optimization for the autotuner.

numpy twin of the reference's GP/BO pair
(``/root/reference/horovod/common/optim/gaussian_process.cc`` and
``bayesian_optimization.cc:1-194``, themselves a C++ adaptation of the
Krasser GP tutorial): an RBF-kernel GP posterior over observed
(config, score) samples and an expected-improvement (EI) acquisition
proposing the next configuration to try. Two deliberate departures from
the reference's mechanics (same role, simpler machinery, no new deps):

* kernel hyperparameters come from a small log-marginal-likelihood grid
  instead of L-BFGS gradient ascent;
* EI is maximized over a dense random candidate set within bounds
  instead of L-BFGS with random restarts — with 2–3 tuned knobs a few
  hundred candidates cover the box better than gradient polish.
"""

from __future__ import annotations

import math

import numpy as np

_SQRT2 = math.sqrt(2.0)


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + np.vectorize(math.erf)(z / _SQRT2))


def _norm_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)


class GaussianProcessRegressor:
    """RBF-kernel GP with observation noise ``alpha`` (the reference's
    ``GaussianProcessRegressor(alpha)``); inputs are expected normalized
    to comparable scales by the caller."""

    def __init__(self, alpha: float = 1e-10):
        self.alpha = float(alpha)
        self._X = None
        self._y = None
        self._L = None
        self._w = None
        self.length_scale = 1.0
        self.sigma_f = 1.0

    def _kernel(self, A, B, length_scale, sigma_f):
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return sigma_f ** 2 * np.exp(-0.5 * d2 / length_scale ** 2)

    def _log_marginal(self, X, y, length_scale, sigma_f):
        K = self._kernel(X, X, length_scale, sigma_f)
        K[np.diag_indices_from(K)] += self.alpha
        try:
            L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            return -np.inf
        w = np.linalg.solve(L.T, np.linalg.solve(L, y))
        return float(-0.5 * y @ w - np.log(np.diag(L)).sum()
                     - 0.5 * len(y) * math.log(2 * math.pi))

    def fit(self, X: np.ndarray, y: np.ndarray) -> None:
        X = np.atleast_2d(np.asarray(X, float))
        y = np.asarray(y, float).ravel()
        # hyperparameters by log-marginal-likelihood grid (the reference
        # runs L-BFGS on the same objective)
        best, best_lml = (1.0, 1.0), -np.inf
        for ls in (0.2, 0.5, 1.0, 2.0):
            for sf in (0.5, 1.0, 2.0):
                lml = self._log_marginal(X, y, ls, sf)
                if lml > best_lml:
                    best, best_lml = (ls, sf), lml
        self.length_scale, self.sigma_f = best
        K = self._kernel(X, X, *best)
        K[np.diag_indices_from(K)] += self.alpha
        self._L = np.linalg.cholesky(K)
        self._w = np.linalg.solve(self._L.T, np.linalg.solve(self._L, y))
        self._X, self._y = X, y

    def predict(self, Xs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at ``Xs``."""
        Xs = np.atleast_2d(np.asarray(Xs, float))
        Ks = self._kernel(Xs, self._X, self.length_scale, self.sigma_f)
        mu = Ks @ self._w
        v = np.linalg.solve(self._L, Ks.T)
        var = (self.sigma_f ** 2 - (v ** 2).sum(0)).clip(min=0.0)
        return mu, np.sqrt(var)


class BayesianOptimization:
    """Propose-the-next-config loop (reference ``BayesianOptimization``):
    ``add_sample`` observations, ``next_sample`` the EI argmax."""

    def __init__(self, bounds, alpha: float, xi: float = 0.01,
                 seed: int = 0, n_candidates: int = 512):
        self.bounds = [(float(lo), float(hi)) for lo, hi in bounds]
        self.xi = float(xi)
        self.gpr = GaussianProcessRegressor(alpha)
        self._rng = np.random.default_rng(seed)
        self.n_candidates = n_candidates
        self._X: list[np.ndarray] = []
        self._y: list[float] = []

    def add_sample(self, x, y: float) -> None:
        self._X.append(np.asarray(x, float))
        self._y.append(float(y))

    def clear(self) -> None:
        self._X.clear()
        self._y.clear()

    def _unit(self, X):
        lo = np.array([b[0] for b in self.bounds])
        hi = np.array([b[1] for b in self.bounds])
        return (np.atleast_2d(X) - lo) / np.where(hi > lo, hi - lo, 1.0)

    def next_sample(self, candidates=None) -> tuple[np.ndarray, float]:
        """(proposed x, max expected improvement). ``candidates`` narrows
        the proposal set to given points (e.g. a discrete knob grid —
        continuous proposals rounded to a coarse grid collapse back onto
        the incumbent and never explore); default is uniform-random in
        bounds. With <2 samples the proposal is random (nothing to model
        yet)."""
        if candidates is not None:
            cands = np.atleast_2d(np.asarray(candidates, float))
        else:
            lo = np.array([b[0] for b in self.bounds])
            hi = np.array([b[1] for b in self.bounds])
            cands = self._rng.uniform(lo, hi,
                                      size=(self.n_candidates, len(lo)))
        if len(self._y) < 2:
            return cands[self._rng.integers(len(cands))], float("inf")
        y = np.asarray(self._y)
        mu_y, sd_y = y.mean(), y.std()
        if len(y) >= 3 and sd_y > 0:  # reference NextSample normalization
            y = (y - mu_y) / sd_y
        self.gpr.fit(self._unit(np.vstack(self._X)), y)
        mu, sigma = self.gpr.predict(self._unit(cands))
        mu_best = self.gpr.predict(self._unit(np.vstack(self._X)))[0].max()
        imp = mu - mu_best - self.xi
        with np.errstate(divide="ignore", invalid="ignore"):
            z = np.where(sigma > 0, imp / sigma, 0.0)
        ei = np.where(sigma > 0, imp * _norm_cdf(z) + sigma * _norm_pdf(z),
                      0.0)
        i = int(np.argmax(ei))
        return cands[i], float(ei[i])
