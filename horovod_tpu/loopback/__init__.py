"""Loopback multi-rank world: the full world>1 stack in ONE interpreter.

``hvd.loopback.world(n)`` boots *n* ranks as threads inside the current
process: each rank gets its own runtime context (rank/size/process-set
table, its own negotiation ``DynamicService`` + ``FusionScheduler`` +
health watchdog), all ranks share one in-process HTTP KV server and the
real ``KVTransport``/``engine_service`` negotiation wire format, and
collective *execution* is emulated on the virtual-device CPU mesh by a
loopback dispatch backend (:mod:`horovod_tpu.loopback.dispatch`) that
rendezvouses the ranks' bundles and computes the reduction through the
very same compiled single-controller programs — numerics identical to
the world=1 path by construction. jax-0.4's "Multiprocess computations
aren't implemented on the CPU backend" never triggers because no
cross-process XLA program is ever built.

See docs/loopback.md for the architecture, what is emulated vs real,
and the fidelity limits vs a true multi-process world.

This ``__init__`` stays import-light on purpose: ``loopback.context``
is imported from low-level modules (``utils/envs.py``,
``utils/invariants.py``, ``runtime.py``) during package init, so the
heavy pieces (world, dispatch) load lazily on first attribute access.
"""

from __future__ import annotations

from . import context  # stdlib-only; safe during package init
from .context import RankContext, RankKilled, current

__all__ = [
    "LoopbackWorld", "RankContext", "RankKilled", "current",
    "elastic_run", "world",
]


def __getattr__(name):
    if name in ("world", "LoopbackWorld", "elastic_run"):
        from . import engine as _engine
        return getattr(_engine, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
