"""Loopback dispatch backend: emulated collective execution.

The execution half of the loopback world (negotiation is the *real*
``engine_service`` protocol over the real HTTP KV — nothing there is
emulated). Every eager collective in a multi-rank job funnels, post-
negotiation, through a handful of bundle-execution choke points in
``ops/collectives.py`` (``_execute_allreduce_bundle``,
``_execute_grouped_bundles``, the allgather/broadcast/alltoall/
reducescatter eager bodies, and the joined-rank zero reconstruction).
Under a loopback context those choke points call :func:`channel`:
each rank contributes *its own row* of the ``(n, ...)`` bundle, the hub
rendezvouses the rows under the globally-agreed negotiation tensor name
(unique while in flight; per-name occurrence counters disambiguate
steady-state reuse), and the completing rank runs the caller-supplied
compute — the unmodified single-controller program over the
reconstructed true bundle on the shared virtual-device mesh. Every rank
returns the identical result object, so numerics match the world=1 path
bit-for-bit.

Why rows instead of programs: a raw (local) tensor enters the bundle
path as ``broadcast_to(local, (n, ...))`` — every row equals the local
value — while a user-built ``PerRank`` bundle already carries the true
rows. Taking row ``pset position`` is correct for both, and a joined
rank's zero bundle contributes a zero row, which is exactly the
reference JoinOp semantics.
"""

from __future__ import annotations

from collections import OrderedDict

from . import context as _ctx
from ..utils import envs

DEFAULT_LOOPBACK_TIMEOUT_S = 120.0

# Per-scope cap on the occurrence table (see ``_next_occurrence``):
# auto-named collectives never recur, so a long world=64 run would grow
# one dead entry per collective per rank without eviction.
_XSEQ_CAP = 2048


def _timeout_s() -> float:
    """Deadline for one loopback rendezvous. The default scales with
    world size (ISSUE 13 loopback-scale audit): at world=64 the 2-core
    CI box runs 64 rank threads over 64 virtual devices, so a
    first-call compile + the world's worth of contending collectives
    legitimately takes several small-world timeouts. An explicit
    ``HVD_LOOPBACK_TIMEOUT`` is honored as-is."""
    explicit = envs.get(envs.LOOPBACK_TIMEOUT)
    if explicit is not None:
        try:
            return float(explicit)
        except ValueError:
            pass
    from .. import runtime
    n = runtime.process_count() if runtime.is_initialized() else 1
    return DEFAULT_LOOPBACK_TIMEOUT_S * max(1.0, n / 16.0)


def _next_occurrence(ctx, scope, name) -> int:
    """The per-``(scope, name)`` occurrence counter disambiguating
    steady-state name reuse, stored per scope in insertion order with an
    LRU cap. Eviction is deterministic across the scope's member ranks:
    each rank touches the scope's names in the globally-agreed
    negotiation order, so every member evicts the same name at the same
    per-scope usage index — an evicted name that recurs restarts at
    occurrence 0 on every rank simultaneously."""
    table = ctx.xseq.get(scope)
    if table is None:
        table = ctx.xseq[scope] = OrderedDict()
    occurrence = table.get(name, 0)
    table[name] = occurrence + 1
    table.move_to_end(name)
    while len(table) > _XSEQ_CAP:
        table.popitem(last=False)
    return occurrence


def prune_stale_scopes(ctx) -> None:
    """Drop occurrence tables from previous world incarnations (elastic
    re-forms re-seed the coordinator scope): their slot ids can never
    recur, so keeping them is a per-round leak. Called from the loopback
    ``runtime.init`` branch."""
    addr = envs.get(envs.COORDINATOR_ADDR, "local")
    port = envs.get(envs.COORDINATOR_PORT, "0")
    for scope in list(ctx.xseq):
        live = (scope[:2] == (addr, port)
                or scope[:3] == ("obj", addr, port))
        if not live:
            del ctx.xseq[scope]


def active() -> bool:
    """Whether the calling thread runs inside an initialized loopback
    rank (plan builders pick loopback execute closures here; plans are
    per-context, so the choice can never leak across worlds)."""
    ctx = _ctx.current()
    return ctx is not None and ctx.runtime_state is not None


class Channel:
    """One rank's handle on one collective execution's rendezvous: the
    slot identity (scope + name + occurrence), this rank's position in
    the process set, and the failure probe that turns a watchdog-
    detected peer death into a prompt error on parked waiters."""

    __slots__ = ("hub", "slot_id", "pos", "count", "_failure_check")

    def __init__(self, hub, slot_id, pos, count, failure_check):
        self.hub = hub
        self.slot_id = slot_id
        self.pos = pos
        self.count = count
        self._failure_check = failure_check

    def compute(self, payload, fn):
        """Exchange ``payload`` (this rank's row/rows) and return
        ``fn(ordered_payloads)`` computed once by the completing rank."""
        return self.hub.exchange_compute(
            self.slot_id, self.pos, self.count, payload, fn,
            timeout=_timeout_s(), failure_check=self._failure_check)

    def gather(self, payload) -> list:
        """Exchange ``payload`` and return every rank's, in set order."""
        return self.hub.exchange(
            self.slot_id, self.pos, self.count, payload,
            timeout=_timeout_s(), failure_check=self._failure_check)

    def transfer(self, payload):
        """Pairwise hand-off (:meth:`LoopbackHub.transfer`): both sides
        return the owner's (position 0) payload."""
        return self.hub.transfer(
            self.slot_id, self.pos, payload,
            timeout=_timeout_s(), failure_check=self._failure_check)


def _failure_probe(ctx, pset):
    """Failure check evaluated while parked on a slot: the rank's own
    death (fault-injected kill) or its negotiation service's coordinated
    abort (health watchdog: peer death / poison)."""
    from .. import engine_service

    def check():
        if ctx.dead:
            return _ctx.RankKilled()
        svc = ctx.services.get(engine_service._set_key(pset))
        if svc is not None and svc._failure:
            return svc._failure_error()
        return None

    return check


def channel(pset, name) -> Channel | None:
    """The loopback channel for one collective execution over ``pset``
    keyed by negotiation tensor ``name`` — or None when execution should
    take the normal path (no loopback context, world not up, a
    single-member set, or no name to pair on)."""
    ctx = _ctx.current()
    if ctx is None or ctx.runtime_state is None or ctx.world is None:
        return None
    ranks = tuple(pset.ranks)
    if len(ranks) <= 1 or not name:
        return None
    from .. import engine_service, runtime
    pos = pset.rank(runtime.rank())
    if pos < 0:
        return None
    ctx.check_alive()
    scope = (envs.get(envs.COORDINATOR_ADDR, "local"),
             envs.get(envs.COORDINATOR_PORT, "0"),
             engine_service._set_key(pset), ranks)
    occurrence = _next_occurrence(ctx, scope, str(name))
    slot_id = scope + (str(name), occurrence)
    return Channel(ctx.world.hub, slot_id, pos, len(ranks),
                   _failure_probe(ctx, pset))


# ---------------------------------------------------------------------------
# process-level object collectives (broadcast_object / allgather_object):
# the loopback stand-in for jax's multihost_utils, which needs a real
# multi-process backend. Calls are rank-deterministic program points
# (elastic state sync / host-update checks), paired by a per-scope call
# counter.
# ---------------------------------------------------------------------------

def object_channel() -> Channel | None:
    ctx = _ctx.current()
    if ctx is None or ctx.runtime_state is None or ctx.world is None:
        return None
    from .. import runtime
    n = runtime.process_count()
    if n <= 1:
        return None
    ctx.check_alive()
    scope = ("obj", envs.get(envs.COORDINATOR_ADDR, "local"),
             envs.get(envs.COORDINATOR_PORT, "0"))
    occurrence = _next_occurrence(ctx, scope, "")
    slot_id = scope + (occurrence,)
    from ..process_sets import global_process_set
    return Channel(ctx.world.hub, slot_id, runtime.process_rank(), n,
                   _failure_probe(ctx, global_process_set))


def peer_channel(tag: tuple, role: int) -> Channel | None:
    """Pairwise channel for one checkpoint shard hand-off (``hub.
    transfer``), or None when the KV fallback must carry it (no loopback
    world). Unlike collective slots, the pair's identity is fully
    carried by ``tag`` — the restore protocol derives one globally
    unique tag per (step, owner, puller, range, attempt) from the
    manifest-agree round, so no occurrence counter is needed (and none
    would be safe: only two of the world's ranks ever touch the slot)."""
    ctx = _ctx.current()
    if ctx is None or ctx.runtime_state is None or ctx.world is None:
        return None
    ctx.check_alive()
    scope = ("ckpt", envs.get(envs.COORDINATOR_ADDR, "local"),
             envs.get(envs.COORDINATOR_PORT, "0"))
    slot_id = scope + tuple(tag)
    from ..process_sets import global_process_set
    return Channel(ctx.world.hub, slot_id, role, 2,
                   _failure_probe(ctx, global_process_set))
