"""In-process rendezvous hub: where the N loopback ranks' bundles meet.

One :class:`LoopbackHub` per loopback world. Every emulated collective
execution is one *slot*: each participating rank posts its contribution
under a slot id that is identical on every rank — the globally-agreed
negotiation tensor name plus a per-name occurrence counter (names are
unique while in flight, and every rank uses a name's k-th occurrence in
the same order, both guaranteed by the negotiation protocol) — and
blocks until all participants have posted. The rank whose post completes
the set (the *leader*) computes the result **once**, outside the hub
lock, by running the very same compiled single-controller program the
world=1 path uses over the reconstructed ``(n, ...)`` bundle; every
participant then returns the identical result object. Numerics are
therefore identical to the world=1 path by construction, not by
re-implementation.

**Sharding (ISSUE 13).** The slot registry is partitioned into
``_N_SHARDS`` independent ``(condition, slots)`` shards keyed by a hash
of the slot id: at world=64 every rank thread otherwise serializes on
one hub lock per collective, and — worse — every slot completion
``notify_all``s every parked waiter of every *other* slot, an O(world²)
thundering herd per step. Unrelated collectives now rendezvous on
unrelated conditions; a slot's waiters share a shard with only ~1/16th
of the world.

Failure semantics: waits poll a caller-provided ``failure_check`` (the
rank's negotiation-service failure state, fed by the health watchdog)
so a peer death surfaces as :class:`~horovod_tpu.exceptions.
PeerFailureError` within the watchdog budget instead of the full
exchange deadline; :meth:`fail_all` poisons every pending slot (on
every shard) at world teardown. Slots are reference-counted and deleted
once every participant consumed the result.

All blocking goes through the ``utils/invariants.py`` constructor seam,
so the whole rendezvous is explorable and replayable under
``HVD_SCHED_CHECK=1`` (tools/hvdsched — the ``loopback-exchange``
model) and witness-checked under ``HVD_DEBUG_INVARIANTS=1``.
"""

from __future__ import annotations

import zlib

from ..utils import invariants as _inv

# Wait-slice while parked on a slot: short enough that a failure_check
# hit (watchdog-detected peer death) surfaces promptly, long enough not
# to spin. Virtualized under HVD_SCHED_CHECK.
_WAIT_SLICE_S = 0.2

# Shard count: enough that 64 rank threads rarely collide on a shard
# lock, few enough that a fail_all sweep is cheap.
_N_SHARDS = 16


class ExchangeTimeout(RuntimeError):
    """A loopback exchange did not complete within its deadline — the
    in-process analog of the negotiation exchange timeout (some rank
    never issued the matching collective)."""


class _Slot:
    __slots__ = ("values", "count", "computing", "done", "result",
                 "error", "consumed")

    def __init__(self, count: int):
        self.values: dict[int, object] = {}
        self.count = count
        self.computing = False
        self.done = False
        self.result = None
        self.error: BaseException | None = None
        self.consumed = 0


class _Shard:
    __slots__ = ("cv", "slots")

    def __init__(self, cv):
        self.cv = cv
        self.slots: dict[tuple, _Slot] = {}


class LoopbackHub:
    def __init__(self, name: str = "loopback"):
        self._shards = [
            _Shard(_inv.make_condition(f"{name}.hub.cv{i}"))
            for i in range(_N_SHARDS)]
        self._failure: BaseException | None = None

    def _shard(self, slot_id: tuple) -> _Shard:
        h = zlib.crc32(repr(slot_id).encode())
        return self._shards[h % _N_SHARDS]

    # -- lifecycle ---------------------------------------------------------

    def fail_all(self, exc: BaseException) -> None:
        """Poison every pending (and future) slot: world teardown or an
        unrecoverable rank failure. Parked waiters raise ``exc``; they
        hold direct slot references, so the registry can drop the slots
        immediately (payload tensors must not outlive the failure)."""
        self._failure = exc  # visible to every shard's next check
        for shard in self._shards:
            with shard.cv:
                for slot in shard.slots.values():
                    if not slot.done:
                        slot.error = exc
                        slot.done = True
                shard.slots.clear()
                shard.cv.notify_all()

    # -- the rendezvous primitive ------------------------------------------

    def exchange_compute(self, slot_id: tuple, pos: int, count: int,
                         payload, compute, *, timeout: float,
                         failure_check=None):
        """Post ``payload`` as participant ``pos`` of ``count`` under
        ``slot_id``; when all participants posted, the completing rank
        runs ``compute([payload_0, ..., payload_{count-1}])`` once and
        every participant returns its result. ``compute`` runs with no
        hub lock held (it issues compiled mesh programs)."""
        deadline = _inv.monotonic() + timeout
        shard = self._shard(slot_id)
        lead = False
        with shard.cv:
            self._raise_poisoned()
            slot = shard.slots.get(slot_id)
            if slot is None:
                slot = _Slot(count)
                shard.slots[slot_id] = slot
            if pos in slot.values or slot.count != count:
                raise RuntimeError(
                    f"loopback exchange {slot_id!r}: duplicate or "
                    f"mismatched participation (pos {pos}, count {count} "
                    f"vs {slot.count}) — collective streams diverged "
                    "across ranks")
            slot.values[pos] = payload
            if len(slot.values) == count:
                slot.computing = True
                lead = True
                ordered = [slot.values[p] for p in sorted(slot.values)]
            shard.cv.notify_all()
        if lead:
            result = None
            error = None
            try:
                result = compute(ordered)
            except BaseException as e:
                error = e
            with shard.cv:
                slot.result = result
                slot.error = error
                slot.done = True
                shard.cv.notify_all()
            return self._consume(shard, slot_id, slot)
        with shard.cv:
            while not slot.done:
                exc = failure_check() if failure_check is not None else None
                if exc is not None:
                    # the slot may still complete for the other waiters;
                    # this participant gives up with the failure it saw
                    self._abandon_locked(shard, slot_id, slot)
                    raise exc
                remaining = deadline - _inv.monotonic()
                if remaining <= 0 and not slot.computing:
                    self._abandon_locked(shard, slot_id, slot)
                    # timeout applies to MISSING participants only: once
                    # every rank posted and the leader is computing (a
                    # first-call compile can be slow under load), the
                    # collective WILL complete or error — keep waiting
                    missing = sorted(set(range(count)) - set(slot.values))
                    raise ExchangeTimeout(
                        f"loopback exchange {slot_id!r} timed out after "
                        f"{timeout:g}s waiting for participants {missing} "
                        "(a rank never issued the matching collective, "
                        "or died before the watchdog noticed)")
                shard.cv.wait(_WAIT_SLICE_S if remaining <= 0
                              else min(remaining, _WAIT_SLICE_S))
        return self._consume(shard, slot_id, slot)

    def exchange(self, slot_id: tuple, pos: int, count: int, payload, *,
                 timeout: float, failure_check=None) -> list:
        """Plain allgather: every participant returns the ordered list of
        all payloads (no leader computation)."""
        return self.exchange_compute(slot_id, pos, count, payload,
                                     lambda vals: list(vals),
                                     timeout=timeout,
                                     failure_check=failure_check)

    def transfer(self, slot_id: tuple, role: int, payload, *,
                 timeout: float, failure_check=None):
        """Pairwise hand-off (checkpoint shard pull, docs/checkpoint.md):
        the owner (role 0) posts its payload, the puller (role 1) posts
        a placeholder, and both return the owner's payload. Riding
        ``exchange_compute`` keeps the failure semantics of every other
        rendezvous: a dead peer surfaces through ``failure_check``
        within the watchdog budget, and teardown ``fail_all`` poisons a
        half-met transfer instead of stranding its payload."""
        return self.exchange_compute(slot_id, role, 2, payload,
                                     lambda vals: vals[0],
                                     timeout=timeout,
                                     failure_check=failure_check)

    # -- internals ---------------------------------------------------------

    def _raise_poisoned(self) -> None:
        if self._failure is not None:
            raise self._failure

    def _abandon_locked(self, shard: _Shard, slot_id: tuple,
                        slot: _Slot) -> None:
        """A waiter gives up (peer death / timeout): count it as consumed
        and drop the slot once every KNOWN poster has given up — a dead
        rank never posts, so waiting for ``count`` consumptions would pin
        the posted payload tensors for the world's lifetime. A live-but-
        slow participant arriving later recreates the slot, times out
        against the already-failed world, and cleans up the same way."""
        slot.consumed += 1
        threshold = slot.count if slot.done else len(slot.values)
        if slot.consumed >= threshold:
            shard.slots.pop(slot_id, None)

    def _consume(self, shard: _Shard, slot_id: tuple, slot: _Slot):
        with shard.cv:
            slot.consumed += 1
            if slot.consumed >= slot.count:
                shard.slots.pop(slot_id, None)
            error, result = slot.error, slot.result
        if error is not None:
            raise error
        return result

    def pending(self) -> int:
        total = 0
        for shard in self._shards:
            with shard.cv:
                total += len(shard.slots)
        return total
