"""Loopback world engine: N ranks as threads in one interpreter.

``world(n)`` (exported as ``hvd.loopback.world``) boots *n* rank threads,
each bound to its own :class:`~horovod_tpu.loopback.context.RankContext`
carrying the launcher env contract (``HVD_RANK``/``HVD_KV_*``/...) as a
per-thread overlay. ``runtime.init()`` on a rank thread takes its
loopback branch: a per-rank runtime state (rank/size/process-set table)
over the shared virtual-device CPU mesh, a per-rank negotiation
``DynamicService`` speaking the real ``KVTransport`` wire format against
this world's in-process HTTP KV server, a per-rank ``FusionScheduler``,
and a per-rank health watchdog. Collective execution rendezvouses
through the world's :class:`~horovod_tpu.loopback.hub.LoopbackHub`
(see ``loopback/dispatch.py``).

The elastic path (:func:`elastic_run`, ``hvdrun --loopback --min-np``)
reuses the REAL elastic driver, registry, rendezvous and discovery —
only ``create_worker_fn`` changes: workers are rank threads instead of
processes, with ``wait()/poll()/terminate()`` handles the driver
supervises exactly like subprocesses. A fault-injected ``crash`` on a
rank thread raises :class:`~horovod_tpu.loopback.context.RankKilled`,
the rank's services stop beating (abrupt teardown — the in-process
analog of a process death), survivors' watchdogs detect the silence,
and the driver blacklists + re-forms the round.
"""

from __future__ import annotations

import itertools
import sys
import threading
import traceback

from . import context as _ctx
from .hub import LoopbackHub
from ..utils import envs
from ..utils import invariants as _inv
from ..utils import logging as hvd_logging

_world_ids = itertools.count(1)


class WorldTimeout(RuntimeError):
    """A loopback rank thread did not finish within the run deadline."""


class Outcome:
    """Per-rank result of one loopback run: the body's return value, the
    exception that ended it (if any), and the process-exit-code analog
    the elastic driver supervises (0 ok, 66 slot-lost, crash code)."""

    __slots__ = ("rank", "result", "error", "exit_code")

    def __init__(self, rank: int):
        self.rank = rank
        self.result = None
        self.error: BaseException | None = None
        self.exit_code: int | None = None

    def __repr__(self):
        return (f"Outcome(rank={self.rank}, exit_code={self.exit_code}, "
                f"error={self.error!r})")


class RankThread:
    """Worker handle with the subprocess supervision surface the elastic
    driver expects (``wait``/``poll``/``terminate``)."""

    def __init__(self, world, ctx: _ctx.RankContext, thread: threading.Thread,
                 outcome: Outcome):
        self.world = world
        self.ctx = ctx
        self.thread = thread
        self.outcome = outcome

    def poll(self):
        if self.thread.is_alive():
            return None
        return self.outcome.exit_code if self.outcome.exit_code is not None \
            else 1

    def wait(self):
        self.thread.join()
        return self.poll()

    def terminate(self):
        """Driver-side kill of a stale/straggling worker: mark the rank
        dead and fail its in-flight negotiation waits so the thread
        unwinds promptly (it cannot be force-killed like a process)."""
        if not self.thread.is_alive():
            return
        _abrupt_stop(self.ctx, reason="worker terminated by driver")


def _abrupt_stop(ctx: _ctx.RankContext, reason: str,
                 exc: BaseException | None = None) -> None:
    """The in-process analog of a worker process dying: stop the rank's
    liveness beats and negotiation cycles WITHOUT a graceful drain, so
    peers observe exactly what a real death looks like (silence on the
    health channel), while the dying rank's own waiters unblock instead
    of leaking parked threads. ``exc`` (the crash path passes
    ``RankKilled``) becomes the error those waiters raise, so the rank's
    main thread unwinds as killed even when the crash site was a helper
    thread."""
    ctx.dead = True
    sched = ctx.scheduler
    if sched is not None:
        try:
            sched.abort(reason)
            sched.stop()
        except Exception:
            hvd_logging.exception("loopback: scheduler teardown failed")
    # Snapshot + clear under the service lock: the rank's own main
    # thread may be inside engine_service.reset_service()'s locked
    # iteration over this same table (a preempted rank's clean exit
    # racing the driver's terminate), and an unlocked clear() here blows
    # that iteration up with "dictionary changed size during iteration".
    from .. import engine_service as _es
    with _es._service_lock:
        svcs = list(ctx.services.values())
        ctx.services.clear()
    for svc in svcs:
        try:
            wd = svc.health_watchdog()
            if wd is not None:
                wd.stop(join=False)  # beats cease; no poison published
            svc._shutdown.set()
            svc._tick.set()
            svc._fail_all(reason, exc)
        except Exception:
            hvd_logging.exception("loopback: service teardown failed")
    nm, ctx.notification_manager = ctx.notification_manager, None
    if nm is not None:
        try:
            nm.shutdown()  # stop the per-rank elastic notify poller
        except Exception:
            hvd_logging.exception(
                "loopback: notification teardown failed")
    # Abort-path conformance dump (docs/conformance.md): the dying
    # rank's decision trace is exactly what a post-mortem hvdtrace diff
    # against the survivors needs. maybe_dump never raises; ctx routes
    # the lookup since the supervisor calls this off-thread.
    from .. import conformance as _conformance
    _conformance.maybe_dump("abort", ctx=ctx)


def _worker(world, ctx: _ctx.RankContext, fn, out: Outcome,
            auto_init: bool) -> None:
    from .. import runtime
    killed = False
    ctx.main_thread = threading.current_thread()
    with _ctx.activate(ctx):
        try:
            if auto_init:
                runtime.init()
            out.result = fn()
            out.exit_code = 0
        except SystemExit as e:
            # sys.exit on a rank thread (elastic slot-lost self-exit):
            # record the code like a process exit would carry it
            code = e.code
            out.exit_code = code if isinstance(code, int) else \
                (0 if code is None else 1)
        except _ctx.RankKilled as e:
            out.error = e
            out.exit_code = e.code
            killed = True
        except BaseException as e:
            out.error = e
            out.exit_code = 1
        finally:
            try:
                if killed or ctx.dead:
                    _abrupt_stop(ctx, reason="loopback rank killed")
                else:
                    runtime.shutdown()
                    nm, ctx.notification_manager = \
                        ctx.notification_manager, None
                    if nm is not None:
                        nm.shutdown()  # per-rank elastic notify poller
            except BaseException:
                hvd_logging.exception(
                    "loopback rank %s teardown failed", ctx.name)


class LoopbackWorld:
    """One loopback world: the shared rendezvous hub, the (owned or
    external) KV server, and the rank-thread spawner."""

    def __init__(self, size: int | None = None, *, extra_env=None,
                 kv_addr: str | None = None, kv_port: int | None = None,
                 secret: str | None = None, name: str | None = None):
        from .. import _native
        if not _native.available():
            raise RuntimeError(
                "loopback world needs the native negotiation engine "
                "(horovod_tpu._native); build it first")
        self.size = size
        self.name = name or f"lbw{next(_world_ids)}"
        self.hub = LoopbackHub(self.name)
        self._round = 0
        self._extra_env = dict(extra_env or {})
        self._kv_server = None
        if kv_addr is None:
            from ..runner.http_kv import KVServer, make_secret
            self._secret = make_secret()
            self._kv_server = KVServer(secret=self._secret)
            self._kv_port = self._kv_server.start()
            self._kv_addr = "127.0.0.1"
        else:
            self._kv_addr = kv_addr
            self._kv_port = int(kv_port or 0)
            self._secret = secret
        self._handles: list[RankThread] = []

    @property
    def kv_endpoint(self) -> tuple:
        """``(addr, port)`` of the world's KV server. Its ``/metrics``
        route serves every rank's registry store rank-labeled
        (docs/metrics.md) — the tier-1 scrape surface for world>1."""
        return self._kv_addr, self._kv_port

    # -- env contract ------------------------------------------------------

    def rank_env(self, rank: int, size: int, *, extra=None) -> dict:
        """The launcher-seeded worker env contract, as a per-thread
        overlay (``runner/launch.worker_env`` analog for rank threads)."""
        env = {
            "HVD_LOOPBACK": "1",
            "HVD_RANK": str(rank),
            "HVD_SIZE": str(size),
            "HVD_LOCAL_RANK": "0",
            "HVD_LOCAL_SIZE": "1",
            "HVD_CROSS_RANK": str(rank),
            "HVD_CROSS_SIZE": str(size),
            "HVD_PROCESS_ID": str(rank),
            "HVD_NUM_PROCESSES": str(size),
            "HVD_COORDINATOR_ADDR": self.name,
            "HVD_COORDINATOR_PORT": str(self._round),
            "HVD_KV_ADDR": self._kv_addr,
            "HVD_KV_PORT": str(self._kv_port),
            "HVD_HOSTNAME": f"{self.name}-host{rank}",
        }
        if self._secret is not None:
            env["HVD_SECRET_KEY"] = self._secret
        env.update(self._extra_env)
        env.update(extra or {})
        return env

    # -- spawning ----------------------------------------------------------

    def spawn(self, fn, env: dict, *, auto_init: bool = False,
              name: str | None = None) -> RankThread:
        # prune finished handles: a long elastic run re-forms many
        # rounds, and pinning every dead rank's context/result for the
        # world's lifetime is a leak proportional to rounds x world
        self._handles = [h for h in self._handles if h.thread.is_alive()]
        rank = int(env.get("HVD_RANK", -1))
        ctx = _ctx.RankContext(self, rank, env=env,
                               name=name or f"{self.name}-rank{rank}")
        out = Outcome(rank)
        thread = threading.Thread(
            target=_worker, args=(self, ctx, fn, out, auto_init),
            daemon=True, name=ctx.name)
        handle = RankThread(self, ctx, thread, out)
        self._handles.append(handle)
        thread.start()
        return handle

    def run(self, fn, *, timeout="auto",
            allow_failures: bool = False, extra_env=None) -> list[Outcome]:
        """Run ``fn()`` on every rank of a fresh static round (each rank
        auto-``init()``s its loopback runtime first; ``fn`` may call
        ``hvd.init()`` again harmlessly). Returns per-rank
        :class:`Outcome`\\ s; unless ``allow_failures``, the first rank
        error re-raises. ``timeout=None`` supervises without a deadline
        (the launcher path — a training job runs as long as it runs);
        the ``"auto"`` default scales the 300 s small-world deadline
        with world size — 64 rank threads time-slicing a 2-core CI box
        legitimately need several small-world budgets (ISSUE 13
        loopback-scale audit)."""
        n = self.size
        if not n or n < 1:
            raise ValueError("LoopbackWorld.run needs a world size")
        if timeout == "auto":
            timeout = 300.0 * max(1.0, n / 16.0)
        _check_devices(n)
        self._round += 1
        handles = [self.spawn(fn, self.rank_env(r, n, extra=extra_env),
                              auto_init=True) for r in range(n)]
        if timeout is None:
            for h in handles:
                h.thread.join()
        else:
            deadline = _inv.monotonic() + timeout
            for h in handles:
                h.thread.join(max(deadline - _inv.monotonic(), 0.1))
        stuck = [h for h in handles if h.thread.is_alive()]
        if stuck:
            dump = _thread_stacks({h.thread.ident: h.ctx.name
                                   for h in stuck})
            self.hub.fail_all(WorldTimeout("loopback world timed out"))
            for h in stuck:
                _abrupt_stop(h.ctx, reason="loopback run timeout")
            for h in stuck:
                h.thread.join(5.0)
            raise WorldTimeout(
                f"loopback ranks {[h.ctx.name for h in stuck]} did not "
                f"finish within {timeout:g}s; stacks:\n{dump}")
        outs = [h.outcome for h in handles]
        if not allow_failures:
            for o in outs:
                if o.error is not None:
                    raise o.error
        return outs

    def shutdown(self) -> None:
        self.hub.fail_all(RuntimeError("loopback world shut down"))
        for h in self._handles:
            if h.thread.is_alive():
                _abrupt_stop(h.ctx, reason="loopback world shut down")
        for h in self._handles:
            h.thread.join(5.0)
        if self._kv_server is not None:
            self._kv_server.stop()
            self._kv_server = None


def _seed_xla_device_flags(n: int) -> None:
    """Force >= ``n`` virtual CPU devices. XLA reads ``XLA_FLAGS`` at
    BACKEND INITIALIZATION (the first ``jax.devices()`` call), not at
    jax import — the launcher imports jax transitively, so seeding here
    still works as long as no backend is live yet."""
    import os
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip())
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _check_devices(n: int) -> None:
    import jax
    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(
            f"loopback world of {n} needs {n} XLA devices but only "
            f"{len(devs)} exist; set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n} (or more) "
            "BEFORE the first jax import")


def _thread_stacks(idents: dict) -> str:
    frames = sys._current_frames()
    chunks = []
    for ident, name in idents.items():
        frame = frames.get(ident)
        if frame is not None:
            chunks.append(f"--- {name}\n"
                          + "".join(traceback.format_stack(frame)))
    return "\n".join(chunks)


class world:
    """``with hvd.loopback.world(n) as w: w.run(body)`` — the loopback
    twin of ``hvdrun -np n``. Also usable as a plain constructor-and-
    shutdown pair in fixtures."""

    def __init__(self, size: int, **kwargs):
        self._world = LoopbackWorld(size, **kwargs)

    def __enter__(self) -> LoopbackWorld:
        return self._world

    def __exit__(self, *exc):
        self._world.shutdown()
        return False


# ---------------------------------------------------------------------------
# elastic: the real driver over rank threads
# ---------------------------------------------------------------------------

def elastic_run(fn, *, np: int, min_np: int | None = None,
                max_np: int | None = None, discovery=None,
                extra_env=None, timeout: float | None = None,
                reset_limit: int | None = None,
                churn_events: list | None = None,
                autoscale_box: dict | None = None):
    """Run an elastic loopback job: the REAL ``ElasticDriver`` + registry
    + rendezvous + discovery, with workers as loopback rank threads.
    ``fn`` is the worker body (the full "script": it calls ``hvd.init()``
    and typically ``hvd.elastic.run``). Returns ``(results, succeeded)``
    mirroring ``elastic/launch.run_elastic``'s decision inputs.
    ``churn_events`` (optional list) receives the ScriptedChurn event log
    — (monotonic seconds, action, host) per fired membership rule — when
    ``HVD_FAULT_SPEC`` schedules churn (the elastic bench reads it).
    ``autoscale_box`` (optional dict) receives the closed-loop policy's
    decision log under ``"decisions"`` when ``HVD_AUTOSCALE=1``
    (docs/elastic.md "Autoscaler"; the autoscale bench reads it)."""
    from ..elastic.bootstrap import make_elastic_infra
    from ..runner.launch import _free_port
    from ..utils import faults as _faults

    base_env = dict(extra_env or {})
    # Scripted churn (docs/elastic.md): `worker:add/remove/preempt` rules
    # in HVD_FAULT_SPEC drive the discovery set through a ScriptedChurn
    # handler, so spot/preemptible membership change is a seeded,
    # replayable schedule. Requires a mutable discovery (FixedHosts).
    from ..elastic.discovery import install_scripted_churn
    churn = install_scripted_churn(discovery, events=churn_events,
                                   warn=True)
    if timeout is None and envs.get(envs.ELASTIC_TIMEOUT) is None:
        # elastic round/start deadlines scale with world size like the
        # static run deadline (ISSUE 13 loopback-scale audit); an
        # explicit HVD_ELASTIC_TIMEOUT or timeout= is honored as-is.
        # The scaled value is ALSO seeded into the worker overlays:
        # each worker's rendezvous reads HVD_ELASTIC_TIMEOUT itself,
        # and an unscaled worker would give up at 600 s while the
        # driver is still within its scaled budget.
        timeout = 600.0 * max(1.0, (max_np or np) / 16.0)
        base_env.setdefault("HVD_ELASTIC_TIMEOUT", str(int(timeout)))

    infra = make_elastic_infra(
        discovery, min_np or np, max_np, timeout=timeout,
        reset_limit=reset_limit,
        # Loopback "hosts" are labels, not machines: a free local port
        # stands in for the per-host coordinator endpoint probe (the
        # coordinator address is only a service-prefix discriminator
        # here — no jax.distributed world is ever built).
        remote_port_probe=lambda host: _free_port())
    w = LoopbackWorld(kv_addr=infra.kv_addr, kv_port=infra.kv_port,
                      secret=infra.secret)
    driver = infra.driver

    def create_worker_fn(slot_info, spec_round: int):
        spec = infra.round_spec(spec_round)
        env = elastic_worker_env(slot_info, spec, infra.kv_addr,
                                 infra.kv_port, infra.secret, spec_round,
                                 extra=base_env)
        return w.spawn(
            fn, env, auto_init=False,
            name=f"{w.name}-{slot_info.hostname}[{slot_info.local_rank}]")

    if churn is not None:
        churn.attach_driver(driver)
    # Closed-loop autoscaling (docs/elastic.md): with HVD_AUTOSCALE=1
    # the driver-side policy reads per-rank sensor blobs off this
    # world's KV and mutates the SAME discovery seam scripted churn
    # uses. HVD_AUTOSCALE must also reach the worker overlays so the
    # per-rank commit observers arm.
    from ..elastic import policy as _policy_mod
    autoscaler = _policy_mod.maybe_start(
        driver, discovery, infra.kv, min_np=min_np or np, max_np=max_np,
        env=base_env)
    try:
        _check_devices(max_np or np)
        driver.start(np, create_worker_fn)
        driver.join()
        results = driver.get_results()
        succeeded = driver.succeeded
    finally:
        if autoscaler is not None:
            autoscaler.stop()
            if autoscale_box is not None:
                autoscale_box["decisions"] = [
                    d.as_dict() for d in autoscaler.decisions]
                autoscale_box["stats"] = autoscaler.policy_stats()
        if churn is not None:
            _faults.clear_membership_handler()
        infra.stop()
        w.shutdown()
    return results, succeeded


def elastic_worker_env(slot_info, spec: dict, kv_addr: str, kv_port: int,
                       secret: str, spec_round: int, extra=None) -> dict:
    """The elastic worker env contract as a rank-thread overlay — the
    loopback twin of ``runner/launch.worker_env`` +
    ``ElasticInfra.worker_extra_env``."""
    env = {
        "HVD_LOOPBACK": "1",
        "HVD_RANK": str(slot_info.rank),
        "HVD_SIZE": str(slot_info.size),
        "HVD_LOCAL_RANK": str(slot_info.local_rank),
        "HVD_LOCAL_SIZE": str(slot_info.local_size),
        "HVD_CROSS_RANK": str(slot_info.cross_rank),
        "HVD_CROSS_SIZE": str(slot_info.cross_size),
        "HVD_PROCESS_ID": str(slot_info.rank),
        "HVD_NUM_PROCESSES": str(slot_info.size),
        "HVD_COORDINATOR_ADDR": str(spec["coord_addr"]),
        "HVD_COORDINATOR_PORT": str(spec["coord_port"]),
        "HVD_KV_ADDR": kv_addr,
        "HVD_KV_PORT": str(kv_port),
        "HVD_SECRET_KEY": secret,
        "HVD_HOSTNAME": slot_info.hostname,
        "HVD_ELASTIC": "1",
        "HVD_ELASTIC_ROUND": str(spec_round),
    }
    env.update(extra or {})
    return env


# ---------------------------------------------------------------------------
# hvdrun --loopback: run a worker SCRIPT on every rank thread
# ---------------------------------------------------------------------------

def script_body(command: list[str]):
    """``(body, argv)`` for a training command: the rank-thread body
    executing the script (or ``python -m module``) via runpy, and the
    ``sys.argv`` the scripts should see. ``sys.argv`` is process-global,
    so the caller sets it once; module imports are shared across ranks —
    scripts must tolerate that (see docs/loopback.md, fidelity limits)."""
    if not command:
        raise ValueError("loopback launch: empty command")
    import re
    rest = list(command)
    base = rest[0].rsplit("/", 1)[-1]
    # interpreter detection matches python/pythonN[.M] exactly — a
    # directly-executable script that merely STARTS with "python"
    # (python_tool.py) is the training script, not an interpreter
    if re.fullmatch(r"python\d*(\.\d+)?", base) or rest[0] == sys.executable:
        rest = rest[1:]
        if not rest:
            raise ValueError(
                "loopback launch: expected a script after the interpreter")
    if rest[0] == "-m":
        if len(rest) < 2:
            raise ValueError(
                "loopback launch: expected a module after -m")
        module, argv = rest[1], rest[1:]

        def body():
            import runpy
            runpy.run_module(module, run_name="__main__", alter_sys=False)
    else:
        path, argv = rest[0], rest

        def body():
            import runpy
            runpy.run_path(path, run_name="__main__")

    return body, argv


def run_command(args, command: list[str]) -> int:
    """The ``hvdrun --loopback`` static path: one interpreter, ``np``
    rank threads each executing the command's script."""
    np_ = args.np or 1
    _seed_xla_device_flags(np_)
    body, argv = script_body(command)
    sys.argv = argv
    w = LoopbackWorld(np_)
    try:
        # no run deadline: the launcher supervises a training job like
        # the process path's unbounded p.wait() (--start-timeout bounds
        # job START in the process launcher, never total runtime)
        outs = w.run(body, timeout=None, allow_failures=True)
    finally:
        w.shutdown()
    for o in outs:
        if o.error is not None:
            print(f"hvdrun --loopback: rank {o.rank} failed:",
                  file=sys.stderr)
            traceback.print_exception(type(o.error), o.error,
                                      o.error.__traceback__)
    bad = {o.rank: o.exit_code for o in outs if (o.exit_code or 0) != 0}
    if bad:
        print(f"hvdrun --loopback: worker failure, exit codes by rank: "
              f"{bad}", file=sys.stderr)
        return next(iter(bad.values()), 1)
    return 0
