"""Per-rank loopback context: the thread-local seam every runtime module
consults before falling back to its process-wide state.

A :class:`RankContext` is the loopback analog of "one worker process":
it carries the rank's environment overlay (the launcher env contract —
``HVD_RANK``/``HVD_KV_*``/... — without touching ``os.environ``, which
all ranks share), its runtime state (built by ``runtime.init()``'s
loopback branch), its negotiation-service table, its fusion scheduler,
its dispatch-plan store, and its auto-name counters. The modules that
own the corresponding process-wide singletons check
:func:`current` first, so code running on a rank thread — or any thread
*spawned from* one through ``utils.invariants.spawn_thread`` — sees the
rank's world instead of the process's.

Deliberately stdlib-only: this module is imported from
``utils/envs.py`` and ``utils/invariants.py`` during package init, so
it must not pull in jax or any sibling runtime module.
"""

from __future__ import annotations

import threading

_tls = threading.local()


class RankKilled(BaseException):
    """A fault-injected ``crash`` on a loopback rank thread: the
    in-process stand-in for ``os._exit`` (which would take the whole
    interpreter — i.e. every rank — down). BaseException so user-level
    ``except Exception`` blocks in the training body cannot swallow a
    simulated process death."""

    def __init__(self, code: int = 1):
        super().__init__(f"loopback rank killed (exit code {code})")
        self.code = code


class RankContext:
    """One loopback rank's world view. Created by
    :class:`~horovod_tpu.loopback.world.LoopbackWorld`; populated by the
    loopback branches of ``runtime.init()`` / ``engine_service`` /
    ``fusion_cycle`` / ``dispatch_cache`` as the rank runs."""

    __slots__ = (
        "world", "rank", "name", "env", "dead", "main_thread",
        # runtime.py loopback state
        "runtime_state", "generation",
        # engine_service.py per-rank service table
        "services", "service_unavailable",
        # ops/fusion_cycle.py per-rank scheduler
        "scheduler",
        # ops/dispatch_cache.py per-rank plan store + elastic warm pool
        "plans", "plan_epoch", "warm_plans",
        # ops/collectives.py per-rank auto-name counters
        "auto_counters",
        # loopback/dispatch.py per-rank exchange occurrence counters
        "xseq",
        # elastic worker-side singletons (per rank, not per process)
        "notification_manager", "worker_rendezvous",
        # metrics.py keeps per-rank value stores in a WeakKeyDictionary
        # keyed by this context, so a dead world's samples are collected
        # with it
        "__weakref__",
    )

    def __init__(self, world, rank: int, env: dict | None = None,
                 name: str = ""):
        self.world = world
        self.rank = rank
        self.name = name or f"loopback-rank-{rank}"
        self.env: dict[str, str] = dict(env or {})
        self.dead = False
        self.main_thread = None  # the rank's body thread (engine._worker)
        self.runtime_state = None
        self.generation = 0
        self.services: dict = {}
        self.service_unavailable = False
        self.scheduler = None
        self.plans = None  # OrderedDict, created lazily by dispatch_cache
        self.plan_epoch = None
        self.warm_plans = None  # elastic warm re-form pool (same module)
        self.auto_counters: dict = {}
        self.xseq: dict = {}
        self.notification_manager = None
        self.worker_rendezvous = None

    def check_alive(self) -> None:
        if self.dead:
            raise RankKilled()

    def __repr__(self):
        return f"<RankContext {self.name} rank={self.rank} dead={self.dead}>"


def current() -> RankContext | None:
    """The loopback context bound to the calling thread, or None (the
    normal process-wide world)."""
    return getattr(_tls, "ctx", None)


def current_rank_label() -> str:
    """``"rankN"`` for the calling loopback rank thread, ``""`` on the
    process-wide world — THE shared derivation of the per-rank display
    label. The timeline's loopback lane prefix and the conformance
    recorder's trace labels both read it from here instead of keeping
    their own copies of the ``current().rank`` dance."""
    ctx = current()
    return f"rank{ctx.rank}" if ctx is not None else ""


class activate:
    """Bind ``ctx`` to the current thread for the with-block (re-entrant:
    the previous binding is restored on exit)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: RankContext | None):
        self._ctx = ctx
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


def bind_current(fn):
    """Wrap ``fn`` so it runs under the *spawning* thread's context —
    the propagation rule for every thread created through
    ``utils.invariants.spawn_thread`` (scheduler timer, flush executor,
    negotiation cycle, watchdog): a component owned by a rank keeps
    seeing that rank's world from its own threads. No-op wrapper when
    the spawning thread has no context."""
    ctx = current()
    if ctx is None:
        return fn

    def run(*args, **kwargs):
        with activate(ctx):
            try:
                return fn(*args, **kwargs)
            except SystemExit:
                # silent thread exit: the loopback crash teardown ends a
                # rank-owned helper thread this way (a thread of a dead
                # process just stops — no unhandled-exception hook)
                return None

    run.__name__ = getattr(fn, "__name__", "bound")
    return run
