"""Parallelism schedules beyond the reference's data-parallel scope.

The reference implements data parallelism only (SURVEY.md §2.3); its
``alltoall`` primitive (``operations.cc:1642``) and Adasum's neighbor
exchanges are the building blocks long-context schedules need. This
package makes the schedules themselves first-class for TPU:

* :func:`ring_attention` — blockwise causal attention with KV blocks
  rotating over the mesh axis (``lax.ppermute`` ring, online-softmax
  accumulation): sequence length scales with the number of chips while
  attention memory stays at one block per chip.
* :func:`ulysses_attention` (+ the :func:`seq_to_heads`/:func:`heads_to_seq`
  all-to-all switches) — DeepSpeed-Ulysses-style sequence parallelism:
  resharding from sequence-parallel to head-parallel and back with two
  ``lax.all_to_all``\\ s, running exact full-sequence attention locally.
* :func:`moe_alltoall` (+ :func:`route_top_k`, :func:`load_balance_loss`)
  — expert parallelism: capacity-bounded top-k MoE dispatch/combine over
  one alltoall each way, one expert group per chip.
* :func:`pipeline_apply` — GPipe-style pipeline parallelism: one stage's
  params per chip, microbatches flowing around a ``ppermute`` ring inside
  one ``lax.scan`` (no host scheduler), optional stage rematerialization.
* :mod:`~horovod_tpu.parallel.mesh` — the composed-mesh layer that puts
  all of the above on ONE hierarchical device mesh (``dcn × ici_dp`` data
  axes + optional model axes carved from the ICI island) with the
  engine's gradient collectives reduced two-level over the data axes
  only (docs/mesh.md).
"""

from .mesh import (
    DATA_AXES,
    DCN_AXIS,
    ICI_DP_AXIS,
    MeshLayout,
    MeshLayoutError,
    composed_mesh,
    default_layout,
    layout,
    layout_signature,
    mesh_for_axes,
    mesh_layout,
    parse_axes,
    sync_gradients,
)
from .moe import load_balance_loss, moe_alltoall, route_top_k
from .pipeline import (
    microbatch,
    pipeline_apply,
    stack_stage_params,
    unstack_stage,
)
from .sequence import (
    heads_to_seq,
    ring_attention,
    seq_to_heads,
    ulysses_attention,
)

__all__ = ["ring_attention", "ulysses_attention", "seq_to_heads",
           "heads_to_seq", "pipeline_apply", "microbatch",
           "stack_stage_params", "unstack_stage",
           "moe_alltoall", "route_top_k",
           "load_balance_loss",
           "DATA_AXES", "DCN_AXIS", "ICI_DP_AXIS",
           "MeshLayout", "MeshLayoutError", "composed_mesh",
           "default_layout", "layout", "layout_signature",
           "mesh_for_axes", "mesh_layout", "parse_axes",
           "sync_gradients"]
