"""Pipeline parallelism: a GPipe-style microbatch schedule over a mesh axis.

The reference framework is pure data-parallel — pipeline parallelism has
no analog there (SURVEY §2 maps tp/sp/ep; pp is beyond-parity). On TPU
the natural formulation is SPMD: every rank holds ONE stage's parameters,
microbatches flow around a ``ppermute`` ring, and the whole schedule is a
``lax.scan`` the compiler can pipeline — no per-stage processes, no
host-side scheduler (contrast torch's GPipe/PipeDream runtimes).

    out = pipeline_apply(stage_fn, stage_params, x, "pp",
                         n_microbatches=8)

``stage_fn(params, x) -> y`` is the per-stage computation with ``y``
shaped like ``x`` (the transformer-block invariant: d_model in, d_model
out); rank r applies it as stage r. The returned global output (every
microbatch, last stage's values) is broadcast to all pipeline ranks with
one ``psum``, so a loss computed after it is identical everywhere and
gradients flow back through the schedule's AD transpose (``ppermute``
reverses direction, the scan transposes into the reverse sweep).

Memory: the scan saves one activation per tick per stage by default —
O((n_micro + n_stages) · microbatch). ``remat=True`` wraps the stage in
``jax.checkpoint`` so only stage BOUNDARIES persist and the backward
recomputes block internals, the standard trade for deep stages.

Composition: the pp axis is one axis of the device mesh; data parallelism
(dp) shards the batch over another axis outside this function, and tensor
parallelism (tp) would shard ``stage_fn``'s internals over yet another —
see ``__graft_entry__.dryrun_multichip`` for a dp x pp training step and
``examples/pipeline_train.py`` for a full pipelined LM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _broadcast_from_last(done, axis):
    """Replicate the last pipeline rank's values to every rank (one
    psum of a masked buffer). Custom VJP because the raw psum's transpose
    SUMS the cotangents of the n identical replicas — a loss computed
    from the replicated output on every rank (the normal shard_map
    pattern with ``check_vma=False``) would see axis-size-times-too-large
    gradients; averaging the replica cotangents restores the one-loss
    semantics exactly."""
    n = lax.psum(1, axis)
    my = lax.axis_index(axis)
    return lax.psum(jnp.where(my == n - 1, done, jnp.zeros_like(done)),
                    axis)


def _broadcast_from_last_fwd(done, axis):
    return _broadcast_from_last(done, axis), None


def _broadcast_from_last_bwd(axis, _res, ct):
    n = lax.psum(1, axis)
    my = lax.axis_index(axis)
    return (jnp.where(my == n - 1, lax.pmean(ct, axis),
                      jnp.zeros_like(ct)),)


_broadcast_from_last.defvjp(_broadcast_from_last_fwd,
                            _broadcast_from_last_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _replicated_input(x, axis):
    """Identity on a pp-replicated input whose VJP replicates the
    cotangent too: the raw schedule's transpose lands d(loss)/dx on pp
    rank 0 only (only rank 0 feeds the ring), which would silently shrink
    (after a pmean) or desync (without one) gradients of any shared
    layers upstream of the pipeline. psum-ing the rank-0-only cotangent
    hands every pp rank the identical full dx, so upstream replicated
    params get replica-consistent gradients with no collective needed."""
    return x


def _replicated_input_fwd(x, axis):
    return x, None


def _replicated_input_bwd(axis, _res, ct):
    return (lax.psum(ct, axis),)


_replicated_input.defvjp(_replicated_input_fwd, _replicated_input_bwd)


def microbatch(x, n_microbatches: int):
    """(B, ...) -> (n_micro, B/n_micro, ...); validates divisibility."""
    if x.shape[0] % n_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} must divide into n_microbatches="
            f"{n_microbatches}")
    return x.reshape(n_microbatches, x.shape[0] // n_microbatches,
                     *x.shape[1:])


def pipeline_apply(stage_fn, params, x, axis, *, n_microbatches: int,
                   remat: bool = False):
    """Run the GPipe schedule inside ``shard_map`` with ``axis`` bound.

    Args:
      stage_fn: ``(params, x_microbatch) -> y_microbatch``, same shape;
        the output is cast back to ``x.dtype`` (stages may compute in
        higher precision internally).
      params: THIS rank's stage parameters (stage r on rank r).
      x: the full (global-batch, ...) input block, identical on every
        pipeline rank (shard it over a separate dp axis for data
        parallelism).
      axis: bound mesh axis name; its size is the number of stages.
      n_microbatches: pipeline depth of the schedule; the bubble fraction
        is (stages-1)/(n_micro + stages - 1), so use n_micro >= stages.
      remat: rematerialize stage internals in the backward.

    Returns the (global-batch, ...) output of the LAST stage, broadcast
    to every pipeline rank (one ``psum``).

    Gradient conventions (both replica-consistent, no user collectives
    needed over the pp axis): d(loss)/d(stage params) carries exactly-once
    one-loss semantics (see :func:`_broadcast_from_last`), and
    d(loss)/dx is the identical full input cotangent on EVERY pp rank
    (see :func:`_replicated_input`) — shared layers upstream of the
    pipeline train correctly whether or not their grads are pmean'd
    over pp.
    """
    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    x = _replicated_input(x, axis)  # replica-consistent d(loss)/dx
    micro = microbatch(x, n_microbatches)
    mb_shape = micro.shape[1:]
    total = n_microbatches + n - 1  # fill + drain ticks
    pad = jnp.zeros((n - 1,) + mb_shape, x.dtype)
    stream = jnp.concatenate([micro, pad], axis=0)  # rank 0's feed

    # one hop toward the next stage; the last stage's send wraps to rank 0
    # where it is ignored (rank 0 feeds from the stream)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(buf, feed):
        stage_in = jnp.where(my == 0, feed, buf)
        # cast back to the stream dtype: a stage computing in higher
        # precision (f32 params on bf16 activations) would otherwise
        # break the scan carry with an opaque dtype-mismatch error
        out = fn(params, stage_in).astype(x.dtype)
        return lax.ppermute(out, axis, perm), out

    buf0 = jnp.zeros(mb_shape, x.dtype)
    _, outs = lax.scan(tick, buf0, stream)  # outs: (total, mb, ...)

    # microbatch m leaves the last stage at tick m + n - 1
    done = outs[n - 1:].reshape((x.shape[0],) + mb_shape[1:])
    # broadcast the last stage's outputs to every pipeline rank so the
    # loss (and its gradient source) is identical everywhere
    return _broadcast_from_last(done, axis)


def stack_stage_params(per_stage_params):
    """Host-side helper: a list of per-stage pytrees -> one pytree with a
    leading stage dim, ready to shard with ``P('pp')`` so shard_map hands
    rank r stage r's slice (squeeze the leading 1 inside with
    :func:`unstack_stage`)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def unstack_stage(stacked):
    """Inside shard_map: drop the leading per-rank stage dim of 1."""
    return jax.tree.map(lambda x: jnp.squeeze(x, 0), stacked)
