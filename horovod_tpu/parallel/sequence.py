"""Sequence/context parallel attention schedules.

Long-context training shards the *sequence* dimension over chips; the two
standard schedules are both built from the framework's collective
primitives (the reference exposes the primitives but no schedule,
SURVEY.md §5.7):

* **Ring attention** (Liu et al. 2023): keep Q resident, rotate K/V
  blocks around a ``ppermute`` ring, accumulate with the online-softmax
  (flash-attention) recurrence. Per-step the ring moves one KV block over
  ICI while the MXU works on the previous one; attention *logits* never
  materialize (O(block²) working set instead of O(seq²)). Training
  memory is O(block) too: the backward is a **re-rotating recompute VJP**
  (``_ring_core``'s custom_vjp) — the forward saves only this chip's home
  Q/K/V blocks plus (out, lse); the backward restarts the ring from the
  home blocks and rotates dK/dV accumulators around with them, so no
  per-step K/V residuals ever accumulate. Causal runs also skip the
  attention math for blocks that are entirely in the future of the local
  Q block (a ``lax.cond``), recovering the ~2x FLOP overhead a naive
  causal ring wastes on fully-masked blocks.
* **Ulysses** (Jacobs et al. 2023): two ``all_to_all``\\ s reshard
  (seq-sharded, heads-full) → (seq-full, heads-sharded), run exact local
  attention over the full sequence, and reshard back. Cheaper collectives
  for moderate sequence lengths; requires ``num_heads %% axis_size == 0``.

Everything here runs inside ``jax.shard_map`` with the sequence axis
bound; tensors use the (batch, seq, heads, head_dim) layout of
:mod:`horovod_tpu.models.transformer`. Both paths are differentiable
(``ppermute``/``all_to_all`` have transposes), so they drop into training
steps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax
                 # rows finite (all-masked blocks produce 0 contributions)


def _ring_fwd_loop(qf, kf, vf, axis, causal, use_pallas, interpret):
    """Run the forward ring, returning normalized output and log-sum-exp.

    ``qf`` pre-scaled, (bh, sq, d); ``kf``/``vf`` (bh, sk, d) home blocks.
    Causal steps whose KV block lies entirely in the future of the local Q
    block skip the attention math through a ``lax.cond`` (the ppermute
    still runs so the ring stays aligned).
    """
    from ..ops import flash

    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    bh, sq, d = qf.shape
    sk = kf.shape[1]
    m = jnp.full((bh, sq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bh, sq, 1), jnp.float32)
    acc = jnp.zeros((bh, sq, d), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: send to next rank
    k_cur, v_cur = kf, vf
    for step in range(n):
        kv_idx = (my - step) % n  # block held at this step
        qpos0 = (my * sq).astype(jnp.int32)
        kpos0 = (kv_idx * sk).astype(jnp.int32)

        def attend(carry, _k=k_cur, _v=v_cur, _qp=qpos0, _kp=kpos0):
            m, l, acc = carry
            if use_pallas or interpret:
                return flash.block_attend(qf, _k, _v, _qp, _kp, causal,
                                          interpret, m, l, acc)
            return flash._attend_jnp(qf, _k, _v, _qp, _kp, causal,
                                     m, l, acc)

        if causal:
            # block entirely in the future of every local query row:
            # contributes nothing — skip its FLOPs at runtime
            fully_future = kpos0 > qpos0 + (sq - 1)
            m, l, acc = lax.cond(fully_future, lambda c: c, attend,
                                 (m, l, acc))
        else:
            m, l, acc = attend((m, l, acc))
        if step != n - 1:
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)
    l_safe = jnp.maximum(l, 1e-30)
    return acc / l_safe, m + jnp.log(l_safe)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_core(qf, kf, vf, axis, causal, use_pallas, interpret):
    """Differentiable ring-attention core with O(block) training memory.

    Returns ``(out, lse)`` where ``out`` is the normalized attention
    output (float32) and ``lse`` the per-row log-sum-exp. The custom VJP
    saves ONLY the home blocks + (out, lse) — never the rotated per-step
    K/V blocks (which a plain ``jax.vjp`` through the loop would pin,
    making per-chip K/V activation memory O(sequence),
    the round-3 gap)."""
    return _ring_fwd_loop(qf, kf, vf, axis, causal, use_pallas, interpret)


def _ring_core_fwd(qf, kf, vf, axis, causal, use_pallas, interpret):
    out, lse = _ring_fwd_loop(qf, kf, vf, axis, causal, use_pallas,
                              interpret)
    # O(block) residuals: home Q/K/V + out + lse. Nothing per-step.
    return (out, lse), (qf, kf, vf, out, lse)


def _ring_core_bwd(axis, causal, use_pallas, interpret, res, cts):
    """Re-rotating backward: restart the ring from the home K/V blocks and
    carry dK/dV accumulators around with them. Uses the flash backward
    identities on the normalized softmax (p = exp(s - lse)):
    dV += pᵀ·dO, dS = p ∘ (dO·Vᵀ − D), dQ += dS·K, dK += dSᵀ·Q with
    D = rowsum(dO ∘ O). After n rotations each block's accumulator is back
    on its home rank, so the returned cotangents line up with the inputs.
    """
    qf, kf, vf, out, lse = res
    dout, _dlse = cts  # lse is a diagnostic output; its cotangent is zero
    dout = dout.astype(jnp.float32)
    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    bh, sq, d = qf.shape
    sk = kf.shape[1]
    D = jnp.sum(dout * out, axis=-1, keepdims=True)  # (bh, sq, 1)

    dq = jnp.zeros((bh, sq, d), jnp.float32)
    dk_acc = jnp.zeros((bh, sk, d), jnp.float32)
    dv_acc = jnp.zeros((bh, sk, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = kf, vf
    for step in range(n):
        kv_idx = (my - step) % n
        qpos0 = (my * sq).astype(jnp.int32)
        kpos0 = (kv_idx * sk).astype(jnp.int32)

        def block_grads(carry, _k=k_cur, _v=v_cur, _qp=qpos0, _kp=kpos0):
            from ..ops import flash

            dq, dk_a, dv_a = carry
            if use_pallas or interpret:
                # pallas backward: logits recomputed per tile in VMEM,
                # never materialized at O(sq*sk) in HBM
                dq_blk, dk_blk, dv_blk = flash.flash_block_grads(
                    qf, _k, _v, lse, dout, D, _qp, _kp, causal,
                    interpret=interpret)
            else:
                dq_blk, dk_blk, dv_blk = flash.jnp_block_grads(
                    qf, _k, _v, lse, dout, D, _qp, _kp, causal)
            return dq + dq_blk, dk_a + dk_blk, dv_a + dv_blk

        if causal:
            fully_future = kpos0 > qpos0 + (sq - 1)
            dq, dk_acc, dv_acc = lax.cond(
                fully_future, lambda c: c, block_grads, (dq, dk_acc, dv_acc))
        else:
            dq, dk_acc, dv_acc = block_grads((dq, dk_acc, dv_acc))

        # dK/dV travel WITH their block; the extra nth rotation (vs the
        # forward's n-1) returns every accumulator to its home rank.
        dk_acc = lax.ppermute(dk_acc, axis, perm)
        dv_acc = lax.ppermute(dv_acc, axis, perm)
        if step != n - 1:
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)
    return (dq.astype(qf.dtype), dk_acc.astype(kf.dtype),
            dv_acc.astype(vf.dtype))


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention(q, k, v, axis, *, causal: bool = True,
                   use_pallas: bool | None = None,
                   interpret: bool = False):
    """Blockwise ring attention over mesh axis ``axis``.

    Inside ``shard_map`` with the sequence dimension sharded over
    ``axis``: ``q``/``k``/``v`` are this chip's (batch, seq_block, heads,
    head_dim) blocks. K/V rotate around the ring; after ``axis_size``
    steps every Q block has attended to the full sequence. Returns this
    chip's output block (same shape as ``q``).

    The per-step block update runs through the Pallas flash kernel
    (:mod:`horovod_tpu.ops.flash`) on TPU — logits never touch HBM — and
    through the jnp formulation elsewhere. ``use_pallas`` forces the
    choice; ``interpret`` runs the kernel in interpreter mode (CPU tests).
    Differentiating through this saves O(block) residuals (re-rotating
    recompute backward, :func:`_ring_core_bwd`), so per-chip training
    memory stays flat as the ring grows.
    """
    from ..ops import flash

    if use_pallas is None:
        use_pallas = flash.supported()
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / (d ** 0.5)

    # kernel layout: one (batch x head) program per row
    qf = (q * scale).transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    out, _lse = _ring_core(qf, kf, vf, axis, causal, bool(use_pallas),
                           bool(interpret))
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3).astype(v.dtype)


def seq_to_heads(x, axis):
    """All-to-all reshard (batch, seq/n, heads, d) → (batch, seq,
    heads/n, d): trade sequence sharding for head sharding (the Ulysses
    forward switch)."""
    n = lax.psum(1, axis)
    if x.shape[2] % n:
        raise ValueError(
            f"num_heads {x.shape[2]} must divide by the sequence-parallel "
            f"axis size {n} for the Ulysses all-to-all")
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def heads_to_seq(x, axis):
    """Inverse of :func:`seq_to_heads`: (batch, seq, heads/n, d) →
    (batch, seq/n, heads, d)."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def _local_flash_fwd_loop(qf, kf, vf, causal, use_pallas, interpret,
                          kv_chunk: int = 1024):
    """Full local attention in flash form over (bh, s, d) rows, returning
    ``(out, lse)``."""
    from ..ops import flash

    bh, s, d = qf.shape
    m = jnp.full((bh, s, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bh, s, 1), jnp.float32)
    acc = jnp.zeros((bh, s, d), jnp.float32)
    zero = jnp.asarray(0, jnp.int32)
    if use_pallas or interpret:
        m, l, acc = flash.block_attend(qf, kf, vf, zero, zero, causal,
                                       interpret, m, l, acc)
    else:
        chunk = min(kv_chunk, s)
        if s % chunk:
            chunk = s
        for off in range(0, s, chunk):
            m, l, acc = flash._attend_jnp(
                qf, kf[:, off:off + chunk], vf[:, off:off + chunk],
                zero, jnp.asarray(off, jnp.int32), causal, m, l, acc)
    l_safe = jnp.maximum(l, 1e-30)
    return acc / l_safe, m + jnp.log(l_safe)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _local_flash_core(qf, kf, vf, causal, use_pallas, interpret, kv_chunk):
    """Differentiable full local attention with flash-style memory: like
    :func:`_ring_core`, the custom VJP saves only (qf, kf, vf, out, lse)
    and the backward runs the Pallas block-gradient kernels (or the
    KV-chunked jnp identities), so the O(s²) logits never persist for
    the backward."""
    return _local_flash_fwd_loop(qf, kf, vf, causal, use_pallas, interpret,
                                 kv_chunk)


def _local_flash_core_fwd(qf, kf, vf, causal, use_pallas, interpret,
                          kv_chunk):
    out, lse = _local_flash_fwd_loop(qf, kf, vf, causal, use_pallas,
                                     interpret, kv_chunk)
    return (out, lse), (qf, kf, vf, out, lse)


def _local_flash_core_bwd(causal, use_pallas, interpret, kv_chunk, res,
                          cts):
    from ..ops import flash

    qf, kf, vf, out, lse = res
    dout, _dlse = cts
    dout = dout.astype(jnp.float32)
    D = jnp.sum(dout * out, axis=-1, keepdims=True)
    zero = jnp.asarray(0, jnp.int32)
    if use_pallas or interpret:
        dq, dk, dv = flash.flash_block_grads(qf, kf, vf, lse, dout, D,
                                             zero, zero, causal,
                                             interpret=interpret)
    else:
        # same KV chunking as the forward: peak logits O(s * kv_chunk)
        dq, dk, dv = flash.jnp_block_grads(qf, kf, vf, lse, dout, D,
                                           zero, zero, causal,
                                           kv_chunk=kv_chunk)
    return (dq.astype(qf.dtype), dk.astype(kf.dtype), dv.astype(vf.dtype))


_local_flash_core.defvjp(_local_flash_core_fwd, _local_flash_core_bwd)


def _local_flash(q, k, v, causal, use_pallas, interpret,
                 kv_chunk: int = 1024):
    """Exact local attention in flash form: (b, s, h, d) in/out, logits
    never materialized at O(s²) in forward OR backward — the Pallas
    kernels tile both; the jnp fallback loops ``kv_chunk``-sized KV slabs
    in both directions (peak logits O(s·kv_chunk))."""
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    qf = (q * scale).transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    out, _lse = _local_flash_core(qf, kf, vf, causal, bool(use_pallas),
                                  bool(interpret), int(kv_chunk))
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(v.dtype)


def ulysses_attention(q, k, v, axis, *, causal: bool = True,
                      use_pallas: bool | None = None,
                      interpret: bool = False):
    """Ulysses sequence parallelism: reshard to head-parallel with one
    all-to-all per tensor, run exact full-sequence attention on the local
    head group (in flash form — no O(seq²) logits in HBM), reshard the
    output back to sequence-parallel."""
    from ..ops import flash

    if use_pallas is None:
        use_pallas = flash.supported()
    q = seq_to_heads(q, axis)
    k = seq_to_heads(k, axis)
    v = seq_to_heads(v, axis)
    out = _local_flash(q, k, v, causal, use_pallas, interpret)
    return heads_to_seq(out, axis)
