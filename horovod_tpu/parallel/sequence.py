"""Sequence/context parallel attention schedules.

Long-context training shards the *sequence* dimension over chips; the two
standard schedules are both built from the framework's collective
primitives (the reference exposes the primitives but no schedule,
SURVEY.md §5.7):

* **Ring attention** (Liu et al. 2023): keep Q resident, rotate K/V
  blocks around a ``ppermute`` ring, accumulate with the online-softmax
  (flash-attention) recurrence. Per-step the ring moves one KV block over
  ICI while the MXU works on the previous one; attention *logits* never
  materialize (O(block²) working set instead of O(seq²)). Note on
  training memory: the current backward saves each step's rotated K/V
  block as residuals, so K/V activation memory is O(sequence) per chip —
  the same as vanilla attention's K/V (the quadratic logits saving still
  holds); a re-rotating backward that keeps it at O(block) is future
  work.
* **Ulysses** (Jacobs et al. 2023): two ``all_to_all``\\ s reshard
  (seq-sharded, heads-full) → (seq-full, heads-sharded), run exact local
  attention over the full sequence, and reshard back. Cheaper collectives
  for moderate sequence lengths; requires ``num_heads %% axis_size == 0``.

Everything here runs inside ``jax.shard_map`` with the sequence axis
bound; tensors use the (batch, seq, heads, head_dim) layout of
:mod:`horovod_tpu.models.transformer`. Both paths are differentiable
(``ppermute``/``all_to_all`` have transposes), so they drop into training
steps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax
                 # rows finite (all-masked blocks produce 0 contributions)


def ring_attention(q, k, v, axis, *, causal: bool = True,
                   use_pallas: bool | None = None,
                   interpret: bool = False):
    """Blockwise ring attention over mesh axis ``axis``.

    Inside ``shard_map`` with the sequence dimension sharded over
    ``axis``: ``q``/``k``/``v`` are this chip's (batch, seq_block, heads,
    head_dim) blocks. K/V rotate around the ring; after ``axis_size``
    steps every Q block has attended to the full sequence. Returns this
    chip's output block (same shape as ``q``).

    The per-step block update runs through the Pallas flash kernel
    (:mod:`horovod_tpu.ops.flash`) on TPU — logits never touch HBM — and
    through the jnp formulation elsewhere. ``use_pallas`` forces the
    choice; ``interpret`` runs the kernel in interpreter mode (CPU tests).
    """
    from ..ops import flash

    if use_pallas is None:
        use_pallas = flash.supported()
    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / (d ** 0.5)

    # kernel layout: one (batch x head) program per row
    qf = (q * scale).transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    m = jnp.full((b * h, sq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b * h, sq, 1), jnp.float32)
    acc = jnp.zeros((b * h, sq, d), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: send to next rank
    for step in range(n):
        kv_idx = (my - step) % n  # block held at this step
        qpos0 = (my * sq).astype(jnp.int32)
        kpos0 = (kv_idx * sk).astype(jnp.int32)
        if use_pallas or interpret:
            m, l, acc = flash.block_attend(qf, kf, vf, qpos0, kpos0,
                                           causal, interpret, m, l, acc)
        else:
            m, l, acc = flash._attend_jnp(qf, kf, vf, qpos0, kpos0,
                                          causal, m, l, acc)
        if step != n - 1:
            kf = lax.ppermute(kf, axis, perm)
            vf = lax.ppermute(vf, axis, perm)
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3).astype(v.dtype)


def seq_to_heads(x, axis):
    """All-to-all reshard (batch, seq/n, heads, d) → (batch, seq,
    heads/n, d): trade sequence sharding for head sharding (the Ulysses
    forward switch)."""
    n = lax.psum(1, axis)
    if x.shape[2] % n:
        raise ValueError(
            f"num_heads {x.shape[2]} must divide by the sequence-parallel "
            f"axis size {n} for the Ulysses all-to-all")
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def heads_to_seq(x, axis):
    """Inverse of :func:`seq_to_heads`: (batch, seq, heads/n, d) →
    (batch, seq/n, heads, d)."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def _local_flash(q, k, v, causal, use_pallas, interpret,
                 kv_chunk: int = 1024):
    """Exact local attention in flash form: (b, s, h, d) in/out, logits
    never materialized at O(s²) — the Pallas kernel tiles KV internally;
    the jnp fallback loops KV chunks with the same online-softmax
    update."""
    from ..ops import flash

    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    qf = (q * scale).transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    m = jnp.full((b * h, s, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b * h, s, 1), jnp.float32)
    acc = jnp.zeros((b * h, s, d), jnp.float32)
    zero = jnp.asarray(0, jnp.int32)
    if use_pallas or interpret:
        m, l, acc = flash.block_attend(qf, kf, vf, zero, zero, causal,
                                       interpret, m, l, acc)
    else:
        chunk = min(kv_chunk, s)
        if s % chunk:
            chunk = s
        for off in range(0, s, chunk):
            m, l, acc = flash._attend_jnp(
                qf, kf[:, off:off + chunk], vf[:, off:off + chunk],
                zero, jnp.asarray(off, jnp.int32), causal, m, l, acc)
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(v.dtype)


def ulysses_attention(q, k, v, axis, *, causal: bool = True,
                      use_pallas: bool | None = None,
                      interpret: bool = False):
    """Ulysses sequence parallelism: reshard to head-parallel with one
    all-to-all per tensor, run exact full-sequence attention on the local
    head group (in flash form — no O(seq²) logits in HBM), reshard the
    output back to sequence-parallel."""
    from ..ops import flash

    if use_pallas is None:
        use_pallas = flash.supported()
    q = seq_to_heads(q, axis)
    k = seq_to_heads(k, axis)
    v = seq_to_heads(v, axis)
    out = _local_flash(q, k, v, causal, use_pallas, interpret)
    return heads_to_seq(out, axis)
