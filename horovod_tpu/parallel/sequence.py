"""Sequence/context parallel attention schedules.

Long-context training shards the *sequence* dimension over chips; the two
standard schedules are both built from the framework's collective
primitives (the reference exposes the primitives but no schedule,
SURVEY.md §5.7):

* **Ring attention** (Liu et al. 2023): keep Q resident, rotate K/V
  blocks around a ``ppermute`` ring, accumulate with the online-softmax
  (flash-attention) recurrence. Per-step the ring moves one KV block over
  ICI while the MXU works on the previous one; attention *logits* never
  materialize (O(block²) working set instead of O(seq²)). Training
  memory is O(block) too: the backward is a **re-rotating recompute VJP**
  (``_ring_core``'s custom_vjp) — the forward saves only this chip's home
  Q/K/V blocks plus (out, lse); the backward restarts the ring from the
  home blocks and rotates dK/dV accumulators around with them, so no
  per-step K/V residuals ever accumulate. Causal runs also skip the
  attention math for blocks that are entirely in the future of the local
  Q block (a ``lax.cond``), recovering the ~2x FLOP overhead a naive
  causal ring wastes on fully-masked blocks.
* **Ulysses** (Jacobs et al. 2023): two ``all_to_all``\\ s reshard
  (seq-sharded, heads-full) → (seq-full, heads-sharded), run exact local
  attention over the full sequence, and reshard back. Cheaper collectives
  for moderate sequence lengths; requires ``num_heads %% axis_size == 0``.

Everything here runs inside ``jax.shard_map`` with the sequence axis
bound; tensors use the (batch, seq, heads, head_dim) layout of
:mod:`horovod_tpu.models.transformer`. Both paths are differentiable
(``ppermute``/``all_to_all`` have transposes), so they drop into training
steps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax
                 # rows finite (all-masked blocks produce 0 contributions)


def _ring_fwd_loop(qf, kf, vf, axis, causal, use_pallas, interpret):
    """Run the forward ring, returning normalized output and log-sum-exp.

    ``qf`` pre-scaled, (bh, sq, d); ``kf``/``vf`` (bh, sk, d) home blocks.
    Causal steps whose KV block lies entirely in the future of the local Q
    block skip the attention math through a ``lax.cond`` (the ppermute
    still runs so the ring stays aligned).
    """
    from ..ops import flash

    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    bh, sq, d = qf.shape
    sk = kf.shape[1]
    m = jnp.full((bh, sq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bh, sq, 1), jnp.float32)
    acc = jnp.zeros((bh, sq, d), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: send to next rank
    k_cur, v_cur = kf, vf
    for step in range(n):
        kv_idx = (my - step) % n  # block held at this step
        qpos0 = (my * sq).astype(jnp.int32)
        kpos0 = (kv_idx * sk).astype(jnp.int32)

        def attend(carry, _k=k_cur, _v=v_cur, _qp=qpos0, _kp=kpos0):
            m, l, acc = carry
            if use_pallas or interpret:
                return flash.block_attend(qf, _k, _v, _qp, _kp, causal,
                                          interpret, m, l, acc)
            return flash._attend_jnp(qf, _k, _v, _qp, _kp, causal,
                                     m, l, acc)

        if causal:
            # block entirely in the future of every local query row:
            # contributes nothing — skip its FLOPs at runtime
            fully_future = kpos0 > qpos0 + (sq - 1)
            m, l, acc = lax.cond(fully_future, lambda c: c, attend,
                                 (m, l, acc))
        else:
            m, l, acc = attend((m, l, acc))
        if step != n - 1:
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)
    l_safe = jnp.maximum(l, 1e-30)
    return acc / l_safe, m + jnp.log(l_safe)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_core(qf, kf, vf, axis, causal, use_pallas, interpret):
    """Differentiable ring-attention core with O(block) training memory.

    Returns ``(out, lse)`` where ``out`` is the normalized attention
    output (float32) and ``lse`` the per-row log-sum-exp. The custom VJP
    saves ONLY the home blocks + (out, lse) — never the rotated per-step
    K/V blocks (which a plain ``jax.vjp`` through the loop would pin,
    making per-chip K/V activation memory O(sequence),
    the round-3 gap)."""
    return _ring_fwd_loop(qf, kf, vf, axis, causal, use_pallas, interpret)


def _ring_core_fwd(qf, kf, vf, axis, causal, use_pallas, interpret):
    out, lse = _ring_fwd_loop(qf, kf, vf, axis, causal, use_pallas,
                              interpret)
    # O(block) residuals: home Q/K/V + out + lse. Nothing per-step.
    return (out, lse), (qf, kf, vf, out, lse)


def _ring_core_bwd(axis, causal, use_pallas, interpret, res, cts):
    """Re-rotating backward: restart the ring from the home K/V blocks and
    carry dK/dV accumulators around with them. Uses the flash backward
    identities on the normalized softmax (p = exp(s - lse)):
    dV += pᵀ·dO, dS = p ∘ (dO·Vᵀ − D), dQ += dS·K, dK += dSᵀ·Q with
    D = rowsum(dO ∘ O). After n rotations each block's accumulator is back
    on its home rank, so the returned cotangents line up with the inputs.
    """
    qf, kf, vf, out, lse = res
    dout, _dlse = cts  # lse is a diagnostic output; its cotangent is zero
    dout = dout.astype(jnp.float32)
    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    bh, sq, d = qf.shape
    sk = kf.shape[1]
    D = jnp.sum(dout * out, axis=-1, keepdims=True)  # (bh, sq, 1)

    dq = jnp.zeros((bh, sq, d), jnp.float32)
    dk_acc = jnp.zeros((bh, sk, d), jnp.float32)
    dv_acc = jnp.zeros((bh, sk, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = kf, vf
    for step in range(n):
        kv_idx = (my - step) % n
        qpos0 = (my * sq).astype(jnp.int32)
        kpos0 = (kv_idx * sk).astype(jnp.int32)

        def block_grads(carry, _k=k_cur, _v=v_cur, _qp=qpos0, _kp=kpos0):
            from ..ops import flash

            dq, dk_a, dv_a = carry
            if use_pallas or interpret:
                # pallas backward: logits recomputed per tile in VMEM,
                # never materialized at O(sq*sk) in HBM
                dq_blk, dk_blk, dv_blk = flash.flash_block_grads(
                    qf, _k, _v, lse, dout, D, _qp, _kp, causal,
                    interpret=interpret)
            else:
                dq_blk, dk_blk, dv_blk = flash.jnp_block_grads(
                    qf, _k, _v, lse, dout, D, _qp, _kp, causal)
            return dq + dq_blk, dk_a + dk_blk, dv_a + dv_blk

        if causal:
            fully_future = kpos0 > qpos0 + (sq - 1)
            dq, dk_acc, dv_acc = lax.cond(
                fully_future, lambda c: c, block_grads, (dq, dk_acc, dv_acc))
        else:
            dq, dk_acc, dv_acc = block_grads((dq, dk_acc, dv_acc))

        # dK/dV travel WITH their block; the extra nth rotation (vs the
        # forward's n-1) returns every accumulator to its home rank.
        dk_acc = lax.ppermute(dk_acc, axis, perm)
        dv_acc = lax.ppermute(dv_acc, axis, perm)
        if step != n - 1:
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)
    return (dq.astype(qf.dtype), dk_acc.astype(kf.dtype),
            dv_acc.astype(vf.dtype))


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


# --------------------------------------------------------------------------
# zigzag schedule: causal load balance.
#
# With contiguous blocks, the fully_future skip halves causal FLOPs but
# not wall-clock: at ring step s only ranks r >= s have work, yet every
# step still waits on a full block attend somewhere (rank n-1 works at
# EVERY step). The zigzag assignment (Liu et al.'s ring + the zigzag
# chunking used by zigzag ring/striped attention) splits the sequence
# into 2n chunks and hands rank r chunks (r, 2n-1-r); at every step every
# rank then does ~2 of its 4 (q-chunk, kv-chunk) sub-blocks — the causal
# 2x shows up in latency, not just energy.
# --------------------------------------------------------------------------


def _zig_rank_of(chunk: int, n: int) -> int:
    """Which rank owns global chunk id ``chunk`` in zigzag layout."""
    return chunk if chunk < n else 2 * n - 1 - chunk


def zigzag_shard(x, axis):
    """Convert a contiguous shard_map sequence block (dim 1) to the zigzag
    layout: rank r's (low, high) halves become global chunks (r, 2n-1-r).
    Two half-block ppermutes; inverse is :func:`zigzag_unshard`."""
    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    c = x.shape[1] // 2
    # rank r holds contiguous chunks (2r, 2r+1); route each to its owner
    perm_even = [(r, _zig_rank_of(2 * r, n)) for r in range(n)]
    perm_odd = [(r, _zig_rank_of(2 * r + 1, n)) for r in range(n)]
    recv_even = lax.ppermute(x[:, :c], axis, perm_even)   # even chunk ids
    recv_odd = lax.ppermute(x[:, c:], axis, perm_odd)     # odd chunk ids
    # my low chunk id is `my` (parity of `my` says which ppermute brought
    # it); my high chunk id 2n-1-my has the opposite parity
    even_is_low = (my % 2 == 0)
    low = jnp.where(even_is_low, recv_even, recv_odd)
    high = jnp.where(even_is_low, recv_odd, recv_even)
    return jnp.concatenate([low, high], axis=1)


def zigzag_unshard(x, axis):
    """Inverse of :func:`zigzag_shard`."""
    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    c = x.shape[1] // 2
    low, high = x[:, :c], x[:, c:]
    # my even-id chunk is `my` (low) when my is even, else 2n-1-my (high)
    even_is_low = (my % 2 == 0)
    payload_even = jnp.where(even_is_low, low, high)
    payload_odd = jnp.where(even_is_low, high, low)
    perm_even = [(_zig_rank_of(2 * r, n), r) for r in range(n)]
    perm_odd = [(_zig_rank_of(2 * r + 1, n), r) for r in range(n)]
    first = lax.ppermute(payload_even, axis, perm_even)   # chunk 2r
    second = lax.ppermute(payload_odd, axis, perm_odd)    # chunk 2r+1
    return jnp.concatenate([first, second], axis=1)


def _zig_halves(block, c):
    return block[:, :c], block[:, c:]


def _zig_positions(qi, ki, my, kv_rank, n, c):
    """Global token offsets of this rank's q-half ``qi`` and the arriving
    block's kv-half ``ki`` (chunk ids: low = rank, high = 2n-1-rank);
    ``qi``/``ki`` are Python ints, ``my``/``kv_rank`` traced scalars."""
    q_chunk = my if qi == 0 else 2 * n - 1 - my
    kv_chunk = kv_rank if ki == 0 else 2 * n - 1 - kv_rank
    return ((q_chunk * c).astype(jnp.int32),
            (kv_chunk * c).astype(jnp.int32))


def _zig_attend_step(qf, k_cur, v_cur, carries, my, kv_rank, n, use_pallas,
                     interpret):
    """One zigzag ring step: 4 (q-half, kv-half) causal sub-attends, each
    skipped entirely when the kv chunk is in the q chunk's future."""
    from ..ops import flash

    c = qf.shape[1] // 2
    q_halves = _zig_halves(qf, c)
    k_halves = _zig_halves(k_cur, c)
    v_halves = _zig_halves(v_cur, c)
    out = list(carries)
    for qi in range(2):
        for ki in range(2):
            m, l, acc = out[qi]
            qh, kh, vh = q_halves[qi], k_halves[ki], v_halves[ki]
            qpos0, kpos0 = _zig_positions(qi, ki, my, kv_rank, n, c)

            def attend(carry, _k=kh, _v=vh, _qp=qpos0, _kp=kpos0, _q=qh):
                m, l, acc = carry
                if use_pallas or interpret:
                    return flash.block_attend(_q, _k, _v, _qp, _kp, True,
                                              interpret, m, l, acc)
                return flash._attend_jnp(_q, _k, _v, _qp, _kp, True,
                                         m, l, acc)

            fully_future = kpos0 > qpos0 + (c - 1)
            out[qi] = lax.cond(fully_future, lambda cr: cr, attend,
                               (m, l, acc))
    return out


def _zigzag_fwd_loop(qf, kf, vf, axis, use_pallas, interpret):
    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    bh, sq, d = qf.shape
    c = sq // 2

    carries = [(jnp.full((bh, c, 1), NEG_INF, jnp.float32),
                jnp.zeros((bh, c, 1), jnp.float32),
                jnp.zeros((bh, c, d), jnp.float32)) for _ in range(2)]
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = kf, vf
    for step in range(n):
        kv_rank = (my - step) % n
        carries = _zig_attend_step(qf, k_cur, v_cur, carries, my, kv_rank,
                                   n, use_pallas, interpret)
        if step != n - 1:
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)
    outs, lses = [], []
    for m, l, acc in carries:
        l_safe = jnp.maximum(l, 1e-30)
        outs.append(acc / l_safe)
        lses.append(m + jnp.log(l_safe))
    return (jnp.concatenate(outs, axis=1), jnp.concatenate(lses, axis=1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _zigzag_core(qf, kf, vf, axis, use_pallas, interpret):
    """Differentiable zigzag ring core (causal only), O(block) residuals
    like :func:`_ring_core`."""
    return _zigzag_fwd_loop(qf, kf, vf, axis, use_pallas, interpret)


def _zigzag_core_fwd(qf, kf, vf, axis, use_pallas, interpret):
    out, lse = _zigzag_fwd_loop(qf, kf, vf, axis, use_pallas, interpret)
    return (out, lse), (qf, kf, vf, out, lse)


def _zigzag_core_bwd(axis, use_pallas, interpret, res, cts):
    """Re-rotating recompute backward over zigzag sub-blocks: dK/dV
    accumulators rotate with their blocks, dQ halves accumulate locally
    (mirrors :func:`_ring_core_bwd`)."""
    from ..ops import flash

    qf, kf, vf, out, lse = res
    dout, _dlse = cts
    dout = dout.astype(jnp.float32)
    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    bh, sq, d = qf.shape
    c = sq // 2
    D = jnp.sum(dout * out, axis=-1, keepdims=True)

    dq = jnp.zeros((bh, sq, d), jnp.float32)
    dk_acc = jnp.zeros((bh, sq, d), jnp.float32)
    dv_acc = jnp.zeros((bh, sq, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = kf, vf
    for step in range(n):
        kv_rank = (my - step) % n
        for qi in range(2):
            for ki in range(2):
                qs = slice(qi * c, (qi + 1) * c)
                ks = slice(ki * c, (ki + 1) * c)
                qpos0, kpos0 = _zig_positions(qi, ki, my, kv_rank, n, c)

                def grads(carry, _qs=qs, _ks=ks, _qp=qpos0, _kp=kpos0,
                          _k=k_cur, _v=v_cur):
                    dq, dk_a, dv_a = carry
                    fn = (flash.flash_block_grads
                          if (use_pallas or interpret)
                          else flash.jnp_block_grads)
                    kwargs = ({"interpret": interpret}
                              if (use_pallas or interpret) else {})
                    dq_b, dk_b, dv_b = fn(
                        qf[:, _qs], _k[:, _ks], _v[:, _ks], lse[:, _qs],
                        dout[:, _qs], D[:, _qs], _qp, _kp, True, **kwargs)
                    return (dq.at[:, _qs].add(dq_b),
                            dk_a.at[:, _ks].add(dk_b),
                            dv_a.at[:, _ks].add(dv_b))

                fully_future = kpos0 > qpos0 + (c - 1)
                dq, dk_acc, dv_acc = lax.cond(
                    fully_future, lambda cr: cr, grads, (dq, dk_acc, dv_acc))
        # dK/dV travel WITH their block; the extra nth rotation returns
        # every accumulator home
        dk_acc = lax.ppermute(dk_acc, axis, perm)
        dv_acc = lax.ppermute(dv_acc, axis, perm)
        if step != n - 1:
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)
    return (dq.astype(qf.dtype), dk_acc.astype(kf.dtype),
            dv_acc.astype(vf.dtype))


_zigzag_core.defvjp(_zigzag_core_fwd, _zigzag_core_bwd)


def ring_attention(q, k, v, axis, *, causal: bool = True,
                   use_pallas: bool | None = None,
                   interpret: bool = False,
                   schedule: str = "contiguous"):
    """Blockwise ring attention over mesh axis ``axis``.

    Inside ``shard_map`` with the sequence dimension sharded over
    ``axis``: ``q``/``k``/``v`` are this chip's (batch, seq_block, heads,
    head_dim) blocks. K/V rotate around the ring; after ``axis_size``
    steps every Q block has attended to the full sequence. Returns this
    chip's output block (same shape as ``q``).

    The per-step block update runs through the Pallas flash kernel
    (:mod:`horovod_tpu.ops.flash`) on TPU — logits never touch HBM — and
    through the jnp formulation elsewhere. ``use_pallas`` forces the
    choice; ``interpret`` runs the kernel in interpreter mode (CPU tests).
    Differentiating through this saves O(block) residuals (re-rotating
    recompute backward, :func:`_ring_core_bwd`), so per-chip training
    memory stays flat as the ring grows.

    ``schedule="zigzag"`` (causal only, even per-chip block length)
    rebalances causal work: the contiguous layout's fully-future skip
    halves FLOPs but not wall-clock (the last rank works at every step);
    zigzag hands each rank chunks (r, 2n-1-r) so every step does ~half a
    block everywhere and the 2x lands in latency. Inputs/outputs keep the
    contiguous layout — conversion costs eight half-block ppermutes per
    call (two each for q/k/v in, two for the output back), amortized
    over the n ring steps.
    """
    from ..ops import flash

    if use_pallas is None:
        use_pallas = flash.supported()
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / (d ** 0.5)

    # kernel layout: one (batch x head) program per row
    qf = (q * scale).transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    if schedule == "zigzag":
        if not causal:
            raise ValueError("schedule='zigzag' is a causal load-balance; "
                             "use the contiguous schedule for non-causal")
        if sq != sk or sq % 2:
            raise ValueError(
                f"zigzag needs equal, even per-chip q/kv block lengths; "
                f"got sq={sq}, sk={sk}")
        qf = zigzag_shard(qf, axis)
        kf = zigzag_shard(kf, axis)
        vf = zigzag_shard(vf, axis)
        out, _lse = _zigzag_core(qf, kf, vf, axis, bool(use_pallas),
                                 bool(interpret))
        out = zigzag_unshard(out, axis)
    elif schedule == "contiguous":
        out, _lse = _ring_core(qf, kf, vf, axis, causal, bool(use_pallas),
                               bool(interpret))
    else:
        raise ValueError(f"unknown ring schedule {schedule!r}; valid: "
                         "'contiguous', 'zigzag'")
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3).astype(v.dtype)


def seq_to_heads(x, axis):
    """All-to-all reshard (batch, seq/n, heads, d) → (batch, seq,
    heads/n, d): trade sequence sharding for head sharding (the Ulysses
    forward switch)."""
    n = lax.psum(1, axis)
    if x.shape[2] % n:
        raise ValueError(
            f"num_heads {x.shape[2]} must divide by the sequence-parallel "
            f"axis size {n} for the Ulysses all-to-all")
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def heads_to_seq(x, axis):
    """Inverse of :func:`seq_to_heads`: (batch, seq, heads/n, d) →
    (batch, seq/n, heads, d)."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def _local_flash_fwd_loop(qf, kf, vf, causal, use_pallas, interpret,
                          kv_chunk: int = 1024):
    """Full local attention in flash form over (bh, s, d) rows, returning
    ``(out, lse)``."""
    from ..ops import flash

    bh, s, d = qf.shape
    m = jnp.full((bh, s, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bh, s, 1), jnp.float32)
    acc = jnp.zeros((bh, s, d), jnp.float32)
    zero = jnp.asarray(0, jnp.int32)
    if use_pallas or interpret:
        m, l, acc = flash.block_attend(qf, kf, vf, zero, zero, causal,
                                       interpret, m, l, acc)
    else:
        chunk = min(kv_chunk, s)
        if s % chunk:
            chunk = s
        for off in range(0, s, chunk):
            m, l, acc = flash._attend_jnp(
                qf, kf[:, off:off + chunk], vf[:, off:off + chunk],
                zero, jnp.asarray(off, jnp.int32), causal, m, l, acc)
    l_safe = jnp.maximum(l, 1e-30)
    return acc / l_safe, m + jnp.log(l_safe)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _local_flash_core(qf, kf, vf, causal, use_pallas, interpret, kv_chunk):
    """Differentiable full local attention with flash-style memory: like
    :func:`_ring_core`, the custom VJP saves only (qf, kf, vf, out, lse)
    and the backward runs the Pallas block-gradient kernels (or the
    KV-chunked jnp identities), so the O(s²) logits never persist for
    the backward."""
    return _local_flash_fwd_loop(qf, kf, vf, causal, use_pallas, interpret,
                                 kv_chunk)


def _local_flash_core_fwd(qf, kf, vf, causal, use_pallas, interpret,
                          kv_chunk):
    out, lse = _local_flash_fwd_loop(qf, kf, vf, causal, use_pallas,
                                     interpret, kv_chunk)
    return (out, lse), (qf, kf, vf, out, lse)


def _local_flash_core_bwd(causal, use_pallas, interpret, kv_chunk, res,
                          cts):
    from ..ops import flash

    qf, kf, vf, out, lse = res
    dout, _dlse = cts
    dout = dout.astype(jnp.float32)
    D = jnp.sum(dout * out, axis=-1, keepdims=True)
    zero = jnp.asarray(0, jnp.int32)
    if use_pallas or interpret:
        dq, dk, dv = flash.flash_block_grads(qf, kf, vf, lse, dout, D,
                                             zero, zero, causal,
                                             interpret=interpret)
    else:
        # same KV chunking as the forward: peak logits O(s * kv_chunk)
        dq, dk, dv = flash.jnp_block_grads(qf, kf, vf, lse, dout, D,
                                           zero, zero, causal,
                                           kv_chunk=kv_chunk)
    return (dq.astype(qf.dtype), dk.astype(kf.dtype), dv.astype(vf.dtype))


_local_flash_core.defvjp(_local_flash_core_fwd, _local_flash_core_bwd)


def _local_flash(q, k, v, causal, use_pallas, interpret,
                 kv_chunk: int = 1024):
    """Exact local attention in flash form: (b, s, h, d) in/out, logits
    never materialized at O(s²) in forward OR backward — the Pallas
    kernels tile both; the jnp fallback loops ``kv_chunk``-sized KV slabs
    in both directions (peak logits O(s·kv_chunk))."""
    b, s, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    qf = (q * scale).transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    out, _lse = _local_flash_core(qf, kf, vf, causal, bool(use_pallas),
                                  bool(interpret), int(kv_chunk))
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(v.dtype)


def ulysses_attention(q, k, v, axis, *, causal: bool = True,
                      use_pallas: bool | None = None,
                      interpret: bool = False):
    """Ulysses sequence parallelism: reshard to head-parallel with one
    all-to-all per tensor, run exact full-sequence attention on the local
    head group (in flash form — no O(seq²) logits in HBM), reshard the
    output back to sequence-parallel."""
    from ..ops import flash

    if use_pallas is None:
        use_pallas = flash.supported()
    q = seq_to_heads(q, axis)
    k = seq_to_heads(k, axis)
    v = seq_to_heads(v, axis)
    out = _local_flash(q, k, v, causal, use_pallas, interpret)
    return heads_to_seq(out, axis)
