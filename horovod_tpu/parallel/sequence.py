"""Sequence/context parallel attention schedules.

Long-context training shards the *sequence* dimension over chips; the two
standard schedules are both built from the framework's collective
primitives (the reference exposes the primitives but no schedule,
SURVEY.md §5.7):

* **Ring attention** (Liu et al. 2023): keep Q resident, rotate K/V
  blocks around a ``ppermute`` ring, accumulate with the online-softmax
  (flash-attention) recurrence. Per-step the ring moves one KV block over
  ICI while the MXU works on the previous one — communication overlaps
  compute and peak memory is one block.
* **Ulysses** (Jacobs et al. 2023): two ``all_to_all``\\ s reshard
  (seq-sharded, heads-full) → (seq-full, heads-sharded), run exact local
  attention over the full sequence, and reshard back. Cheaper collectives
  for moderate sequence lengths; requires ``num_heads %% axis_size == 0``.

Everything here runs inside ``jax.shard_map`` with the sequence axis
bound; tensors use the (batch, seq, heads, head_dim) layout of
:mod:`horovod_tpu.models.transformer`. Both paths are differentiable
(``ppermute``/``all_to_all`` have transposes), so they drop into training
steps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax
                 # rows finite (all-masked blocks produce 0 contributions)


def _block_attend(q, k, v, qpos, kpos, causal, m, l, o):
    """One blockwise online-softmax update (the flash-attention
    recurrence). q: (b, sq, h, d); k/v: (b, sk, h, d); positions are
    global token indices for masking. m/l/o are the running max,
    normalizer, and weighted accumulator."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    if causal:
        mask = qpos[:, None] >= kpos[None, :]  # (sq, sk)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv.astype(jnp.float32)
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis, *, causal: bool = True):
    """Blockwise ring attention over mesh axis ``axis``.

    Inside ``shard_map`` with the sequence dimension sharded over
    ``axis``: ``q``/``k``/``v`` are this chip's (batch, seq_block, heads,
    head_dim) blocks. K/V rotate around the ring; after ``axis_size``
    steps every Q block has attended to the full sequence. Returns this
    chip's output block (same shape as ``q``).
    """
    n = int(lax.psum(1, axis))
    my = lax.axis_index(axis)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    q = (q * scale).astype(q.dtype)

    qpos = my * sq + jnp.arange(sq)
    m = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    o = jnp.zeros((b, sq, h, d), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: send to next rank
    for step in range(n):
        kv_idx = (my - step) % n  # block held at this step
        kpos = kv_idx * sk + jnp.arange(sk)
        m, l, o = _block_attend(q, k, v, qpos, kpos, causal, m, l, o)
        if step != n - 1:
            k = lax.ppermute(k, axis, perm)
            v = lax.ppermute(v, axis, perm)
    l = jnp.maximum(l, 1e-30)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(v.dtype)


def seq_to_heads(x, axis):
    """All-to-all reshard (batch, seq/n, heads, d) → (batch, seq,
    heads/n, d): trade sequence sharding for head sharding (the Ulysses
    forward switch)."""
    n = lax.psum(1, axis)
    if x.shape[2] % n:
        raise ValueError(
            f"num_heads {x.shape[2]} must divide by the sequence-parallel "
            f"axis size {n} for the Ulysses all-to-all")
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def heads_to_seq(x, axis):
    """Inverse of :func:`seq_to_heads`: (batch, seq, heads/n, d) →
    (batch, seq/n, heads, d)."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, axis, *, causal: bool = True):
    """Ulysses sequence parallelism: reshard to head-parallel with one
    all-to-all per tensor, run exact full-sequence attention on the local
    head group, reshard the output back to sequence-parallel."""
    q = seq_to_heads(q, axis)
    k = seq_to_heads(k, axis)
    v = seq_to_heads(v, axis)

    s, d = q.shape[1], q.shape[3]
    scale = 1.0 / (d ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k).astype(jnp.float32)
    if causal:
        pos = jnp.arange(s)
        logits = jnp.where((pos[:, None] >= pos[None, :])[None, None],
                           logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return heads_to_seq(out, axis)
