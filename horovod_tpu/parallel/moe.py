"""Expert parallelism: Mixture-of-Experts dispatch/combine over alltoall.

The reference stops at the ``alltoall`` primitive (``operations.cc:1642``)
— SURVEY.md §2.3 marks expert parallelism "primitive only". This module
makes the MoE schedule itself first-class: top-k routing, a
capacity-bounded dispatch (Switch/GShard style — static shapes, overflow
tokens dropped), one shape-preserving ``lax.all_to_all`` to move each
token to its expert's chip, the expert computation on local tokens, the
inverse exchange, and the gate-weighted combine. One expert group lives
on each chip of the mesh axis; everything runs inside ``jax.shard_map``
and differentiates end-to-end (router gradients flow through the gate
weighting, the standard trick).

    def expert_fn(tokens):           # (N, d) on this chip's expert
        return nn.relu(tokens @ w_in) @ w_out

    y, aux = moe_alltoall(x, router_logits, expert_fn, axis)
    loss = task_loss(y) + 0.01 * aux  # Switch load-balance auxiliary
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def route_top_k(router_logits, k: int = 1):
    """Top-k routing: returns ``(expert_idx, gates)`` of shape
    (tokens, k). For k=1 the gate is the RAW top softmax probability
    (Switch Transformer convention) — renormalizing would make it
    identically 1 and sever the router's task-loss gradient; for k>1
    the k gates are renormalized to a convex blend (GShard convention),
    through which router gradients still flow."""
    probs = jax.nn.softmax(router_logits, axis=-1)
    gates, expert_idx = lax.top_k(probs, k)
    if k > 1:
        gates = gates / jnp.maximum(jnp.sum(gates, -1, keepdims=True),
                                    1e-9)
    return expert_idx, gates


def load_balance_loss(router_logits, expert_idx) -> jax.Array:
    """Switch Transformer auxiliary loss (eq. 4): n_expert times the dot
    of (fraction of tokens routed to e, mean router probability of e) —
    minimized by a uniform assignment."""
    n_expert = router_logits.shape[-1]
    probs = jax.nn.softmax(router_logits, axis=-1)
    onehot = jax.nn.one_hot(expert_idx[..., 0], n_expert,
                            dtype=probs.dtype)  # primary expert
    frac_tokens = jnp.mean(onehot, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    return n_expert * jnp.sum(frac_tokens * frac_probs)


def moe_alltoall(x, router_logits, expert_fn: Callable, axis, *,
                 k: int = 1, capacity: int | None = None,
                 capacity_factor: float = 1.25):
    """Route this chip's tokens through the mesh's experts and back.

    Inside ``shard_map`` with one expert (group) per chip of ``axis``:
    ``x`` (tokens, d) and ``router_logits`` (tokens, n_expert) are this
    chip's shard; ``expert_fn`` maps (N, d) -> (N, d_out) using THIS
    chip's expert parameters. Returns ``(y, aux)`` where ``y``
    (tokens, d_out) is the gate-weighted combine of each token's k expert
    outputs (dropped overflow tokens contribute zero, as in
    Switch/GShard) and ``aux`` the load-balance loss.

    ``capacity`` bounds tokens per (source chip, expert) pair; default
    ``ceil(capacity_factor * k * tokens / n_expert)``, floored at 4 so
    tiny shards keep a usable bucket.
    """
    tokens, d = x.shape
    n_expert = int(lax.psum(1, axis))
    if router_logits.shape != (tokens, n_expert):
        raise ValueError(
            f"router_logits shape {router_logits.shape} != "
            f"({tokens}, axis size {n_expert})")
    if capacity is None:
        capacity = max(math.ceil(capacity_factor * k * tokens / n_expert),
                       4)

    expert_idx, gates = route_top_k(router_logits, k)

    # flatten the (token, pick) pairs and slot each into its expert's
    # capacity bucket in routing-priority order (pick 0 first)
    flat_expert = expert_idx.T.reshape(-1)          # (k*tokens,) pick-major
    flat_token = jnp.tile(jnp.arange(tokens), k)
    flat_gate = gates.T.reshape(-1)
    onehot = jax.nn.one_hot(flat_expert, n_expert, dtype=jnp.int32)
    pos = jnp.sum((jnp.cumsum(onehot, axis=0) - 1) * onehot, axis=-1)
    keep = pos < capacity
    pos = jnp.minimum(pos, capacity - 1)

    dispatch = jnp.zeros((n_expert, capacity, d), x.dtype)
    dispatch = dispatch.at[flat_expert, pos].add(
        jnp.where(keep[:, None], x[flat_token], 0))

    # exchange: row s of this chip's buffer is now the bucket chip s
    # addressed to this chip's expert
    recv = lax.all_to_all(dispatch, axis, split_axis=0, concat_axis=0,
                          tiled=True)               # (n_src, capacity, d)
    out = expert_fn(recv.reshape(n_expert * capacity, d))
    d_out = out.shape[-1]
    out = out.reshape(n_expert, capacity, d_out)

    # inverse exchange: each chip's buckets come home, expert-major again
    back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                          tiled=True)               # (n_expert, cap, d_out)

    picked = back[flat_expert, pos] * \
        jnp.where(keep, flat_gate, 0)[:, None]      # (k*tokens, d_out)
    y = jnp.sum(picked.reshape(k, tokens, d_out), axis=0)
    return y, load_balance_loss(router_logits, expert_idx)
