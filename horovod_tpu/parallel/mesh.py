"""Composed-parallelism mesh layer: ONE hierarchical device mesh.

The reference Horovod composes nothing — it is data-parallel only, and
its headline perf features (hierarchical allreduce,
``nccl_operations.cc:286-506``; Adasum's pairwise tree) are wired
straight into that single-axis world. Here every parallelism schedule in
this package (``ring_attention``/``ulysses_attention`` over a sequence
axis, ``moe_alltoall`` over an expert axis, ``pipeline_apply`` over a
stage axis) and the engine's gradient collectives share ONE
``jax.sharding.Mesh``, split by role:

* **data axes** — ``dcn`` (cross-slice) × ``ici_dp`` (intra-slice
  data-parallel). Gradient sync reduces ONLY over these, two-level:
  ``psum_scatter`` over ``ici_dp`` then ``psum`` over ``dcn`` then
  ``all_gather`` back (:func:`~horovod_tpu.ops.hierarchical.
  hierarchical_allreduce_traced` generalized from its private 2-D mesh
  to sub-axes of the shared mesh). Adasum's pairwise tree rides the
  ``dcn`` axis (:func:`~horovod_tpu.ops.adasum.
  adasum_hierarchical_traced`).
* **model axes** — optional ``model``/``seq``/``expert``/``stage`` axes
  carved out of the ICI dimension. The schedules run their collectives
  over these; the gradient sync never touches them.

Device order is THE contract: every mesh this module hands out reshapes
the same rank-ordered (process-major) ``runtime.devices()`` list, cached
per runtime generation — so the eager hierarchical ops
(``ops/hierarchical.py`` routes its 2-D mesh through
:func:`mesh_for_axes`) and composed traced steps can never silently
disagree on device placement after an elastic re-form.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .. import runtime
from ..utils import envs

# Canonical axis names. The two data axes are fixed; model axes default
# to the canonical role names below but any non-colliding identifier is
# accepted (a second tensor-parallel axis, say).
DCN_AXIS = "dcn"
ICI_DP_AXIS = "ici_dp"
DATA_AXES = (DCN_AXIS, ICI_DP_AXIS)
MODEL_AXIS_ROLES = ("model", "seq", "expert", "stage")


class MeshLayoutError(ValueError):
    """A composed-mesh layout cannot be realized on this world.

    Raised when the axis-size product does not match the device count,
    when the model-axis carve does not divide the ICI island, or when an
    ``HVD_MESH_AXES`` spec string is malformed. Typed (rather than a
    bare ``ValueError`` from ``numpy.reshape``) so composed train-step
    wrappers and the bench harness can distinguish a layout mistake from
    a numerics bug."""


@dataclasses.dataclass(frozen=True)
class MeshLayout:
    """One composed-mesh axis layout: ``dcn × ici_dp × model axes``.

    ``model_axes`` is an ordered tuple of ``(name, size)`` pairs carved
    from the ICI dimension (they index faster than ``ici_dp``, keeping
    each model group inside one ICI island — model collectives stay on
    the fast fabric, only the ``dcn`` hop crosses slices)."""

    dcn: int
    ici_dp: int
    model_axes: tuple = ()

    def __post_init__(self):
        model = tuple((str(n), int(s)) for n, s in self.model_axes)
        object.__setattr__(self, "model_axes", model)
        if self.dcn < 1 or self.ici_dp < 1:
            raise MeshLayoutError(
                f"data axis sizes must be >= 1, got dcn={self.dcn} "
                f"ici_dp={self.ici_dp}")
        names = [n for n, _ in model]
        for n, s in model:
            if s < 1:
                raise MeshLayoutError(f"model axis {n!r} size {s} < 1")
            if not n.isidentifier():
                raise MeshLayoutError(f"model axis name {n!r} is not an "
                                      "identifier")
        if len(set(names)) != len(names) or set(names) & set(DATA_AXES):
            raise MeshLayoutError(
                f"model axis names {names} must be unique and must not "
                f"collide with the data axes {DATA_AXES}")

    # -- shape ------------------------------------------------------------
    @property
    def axis_names(self) -> tuple:
        return DATA_AXES + tuple(n for n, _ in self.model_axes)

    @property
    def shape(self) -> tuple:
        return (self.dcn, self.ici_dp) + tuple(s for _, s in self.model_axes)

    @property
    def size(self) -> int:
        return int(math.prod(self.shape))

    @property
    def data_axes(self) -> tuple:
        """Axes the gradient sync reduces over (and nothing else does)."""
        return DATA_AXES

    @property
    def model_axis_names(self) -> tuple:
        return tuple(n for n, _ in self.model_axes)

    def axis_size(self, name: str) -> int:
        try:
            return dict(zip(self.axis_names, self.shape))[name]
        except KeyError:
            raise MeshLayoutError(
                f"axis {name!r} not in layout {self.axis_names}") from None

    def key(self) -> tuple:
        """Hashable identity for dispatch-plan / capture keys."""
        return (self.dcn, self.ici_dp) + self.model_axes

    # -- sharding helpers -------------------------------------------------
    def batch_spec(self, *trailing) -> P:
        """PartitionSpec for a batch-led array: dim 0 over BOTH data
        axes (dcn-major, matching global rank order), trailing dims as
        given (axis names or None)."""
        return P(DATA_AXES, *trailing)

    def replicated_spec(self) -> P:
        return P()


def parse_axes(spec: str) -> tuple:
    """Parse an ``HVD_MESH_AXES``-style model-axis spec: a comma list of
    ``name:size`` pairs, e.g. ``"seq:2"`` or ``"expert:4,stage:2"``.
    Empty/whitespace = no model axes (pure data-parallel layout)."""
    spec = (spec or "").strip()
    if not spec:
        return ()
    axes = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, colon, size = part.partition(":")
        try:
            axes.append((name.strip(), int(size.strip())))
        except ValueError:
            raise MeshLayoutError(
                f"bad HVD_MESH_AXES entry {part!r}; expected name:size "
                f"(e.g. 'seq:2,expert:4')") from None
        if not colon or not name.strip():
            raise MeshLayoutError(
                f"bad HVD_MESH_AXES entry {part!r}; expected name:size")
    return tuple(axes)


def layout(model_axes=(), *, ici_size: int | None = None,
           world: int | None = None) -> MeshLayout:
    """Derive a :class:`MeshLayout` for ``world`` devices: the ICI
    island size comes from ``HVD_HIERARCHICAL_ICI_SIZE`` / topology
    (``ops.hierarchical.default_ici_size``), ``dcn`` is the island
    count, and the model axes are carved out of the island —
    ``ici_dp = island / prod(model sizes)``."""
    from ..ops import hierarchical
    n = runtime.size() if world is None else int(world)
    island = int(ici_size) if ici_size else hierarchical.default_ici_size()
    if island <= 0 or n % island != 0:
        raise MeshLayoutError(
            f"ici island size {island} must divide world size {n}")
    model = tuple((str(a), int(s)) for a, s in model_axes)
    carve = math.prod(s for _, s in model) if model else 1
    if carve <= 0 or island % carve != 0:
        raise MeshLayoutError(
            f"model axes {model} carve {carve} devices but the ICI "
            f"island has {island}; the product of model-axis sizes must "
            f"divide the island")
    return MeshLayout(dcn=n // island, ici_dp=island // carve,
                      model_axes=model)


# Top-level package alias (`hvd.mesh_layout`): `layout` is too generic a
# name next to `hvd.mesh()` (the 1-D rank mesh).
def mesh_layout(model_axes=(), *, ici_size: int | None = None,
                world: int | None = None) -> MeshLayout:
    return layout(model_axes, ici_size=ici_size, world=world)


def default_layout(*, world: int | None = None) -> MeshLayout:
    """The layout the ``HVD_MESH_AXES`` knob describes for this world
    (no model axes when unset — the engine's plain hierarchical-DP
    shape)."""
    return layout(parse_axes(envs.mesh_axes()), world=world)


def layout_signature() -> tuple:
    """Stable hashable identity of the ACTIVE layout for dispatch-plan /
    step-capture keys. Never raises: an unrealizable ``HVD_MESH_AXES``
    spec degrades to the raw spec string (the key still changes whenever
    the knob does, which is all a cache key must guarantee)."""
    n = runtime.size()
    try:
        return (n,) + default_layout(world=n).key()
    except MeshLayoutError:
        return (n, "unrealizable", envs.mesh_axes())


# (axis_names, shape, runtime generation) -> Mesh. ONE cache for every
# consumer — ops/hierarchical.py's 2-D eager mesh and the composed
# meshes here resolve through the same rank-ordered device list, so
# their device order cannot diverge. Stale generations are evicted (a
# mesh from before shutdown()/init() holds dead device objects).
_mesh_cache: dict = {}


def mesh_for_axes(axis_names, shape) -> Mesh:
    """THE mesh constructor: reshape the rank-ordered global devices to
    ``shape`` with ``axis_names``. Cached per runtime generation; raises
    :class:`MeshLayoutError` when the axis product != device count."""
    axis_names = tuple(axis_names)
    shape = tuple(int(s) for s in shape)
    devs = runtime.devices()
    if math.prod(shape) != len(devs):
        raise MeshLayoutError(
            f"mesh axes {dict(zip(axis_names, shape))} multiply to "
            f"{math.prod(shape)} devices but the world has {len(devs)}")
    key = (axis_names, shape, runtime.generation())
    mesh = _mesh_cache.get(key)
    if mesh is None:
        gen = runtime.generation()
        for k in [k for k in _mesh_cache if k[2] != gen]:
            del _mesh_cache[k]
        mesh = Mesh(np.array(devs).reshape(shape), axis_names)
        _mesh_cache[key] = mesh
    return mesh


def composed_mesh(lay: MeshLayout | None = None) -> Mesh:
    """The shared composed mesh for ``lay`` (default:
    :func:`default_layout`). Axis order is dcn-major then ici_dp then
    model axes — reshaping the process-major rank order this way keeps
    each ICI island (and every model group within it) contiguous in
    rank space, the same rank↔device contract as
    :func:`~horovod_tpu.ops.hierarchical.hierarchical_mesh`."""
    if lay is None:
        lay = default_layout()
    return mesh_for_axes(lay.axis_names, lay.shape)


def resolve_data_axes(mesh_spec) -> tuple:
    """Normalize a ``mesh_spec`` (a :class:`MeshLayout`, or an explicit
    ``(dcn_axis, ici_axis)`` name pair) to bound data-axis names."""
    if isinstance(mesh_spec, MeshLayout):
        return mesh_spec.data_axes
    if (isinstance(mesh_spec, (tuple, list)) and len(mesh_spec) == 2
            and all(isinstance(a, str) for a in mesh_spec)):
        return tuple(mesh_spec)
    raise MeshLayoutError(
        f"mesh_spec must be a MeshLayout or a (dcn_axis, ici_axis) name "
        f"pair, got {mesh_spec!r}")


def sync_gradients(tree, lay: MeshLayout | None = None, *,
                   op=None, prescale_factor: float = 1.0,
                   postscale_factor: float = 1.0):
    """Two-level data-axis gradient sync for composed traced steps:
    every leaf is reduced intra-slice over ``ici_dp`` (psum_scatter)
    then cross-slice over ``dcn`` (psum), with the pre/post scale split
    of the eager hierarchical path; model axes are untouched, so each
    model group keeps its own shard of sequence/expert/stage state.
    ``ReduceOp.ADASUM`` routes the cross-slice step through Adasum's
    pairwise tree on the ``dcn`` axis instead. Call inside ``shard_map``
    over :func:`composed_mesh` with both data axes bound."""
    from ..ops import adasum as _adasum
    from ..ops import hierarchical as _hier
    from ..ops.reduce_ops import ReduceOp
    if op is None:
        op = ReduceOp.AVERAGE
    dcn_axis, ici_axis = DATA_AXES if lay is None else lay.data_axes
    if op == ReduceOp.ADASUM:
        if prescale_factor != 1.0 or postscale_factor != 1.0:
            raise ValueError("Adasum is scale-invariant; pre/post scale "
                             "factors do not apply")
        return jax.tree.map(
            lambda x: _adasum.adasum_hierarchical_traced(
                x, ici_axis, dcn_axis), tree)
    return jax.tree.map(
        lambda x: _hier.hierarchical_allreduce_traced(
            x, ici_axis, dcn_axis, op=op, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor), tree)


__all__ = [
    "DCN_AXIS", "ICI_DP_AXIS", "DATA_AXES", "MODEL_AXIS_ROLES",
    "MeshLayout", "MeshLayoutError", "parse_axes", "layout",
    "mesh_layout", "default_layout", "layout_signature", "mesh_for_axes",
    "composed_mesh", "resolve_data_axes", "sync_gradients",
]
