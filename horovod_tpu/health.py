"""Health watchdog: liveness beats, poison records, coordinated abort.

The failure-detection half of the failure domain (docs/robustness.md).
The reference's stall inspector (``stall_inspector.h:71-86``) only
*notices* a missing peer after a collective is already waiting on it;
a dead rank here additionally left the survivors blocked for the full
600 s ``HVD_ELASTIC_TIMEOUT`` exchange deadline. The watchdog closes
that gap with two signals over the launcher KV channel the runtime
already owns:

* **beats** — every rank PUTs a monotonically increasing counter under
  ``<prefix>/beat/<rank>`` each ``HVD_HEALTH_INTERVAL`` seconds. Peers
  track *when the counter last changed on their own monotonic clock*,
  so clock skew between hosts cannot fake a death. No change for
  ``HVD_HEALTH_TIMEOUT`` seconds declares the peer dead.
* **poison** — a rank whose negotiation loop caught a local error PUTs
  ``<prefix>/poison/<rank>`` with the reason. Its process (and its
  beats) may well still be alive — poison is the fast path for "alive
  but broken", detected on the next monitor tick instead of after the
  beat timeout.

On either signal the owner's ``on_failure(rank, reason)`` callback runs
exactly once; the engine service uses it to fail every in-flight ticket
with :class:`~horovod_tpu.exceptions.PeerFailureError` (naming the dead
rank and the tensors it owed), drive the fusion executor's ``abort()``
so no pipelined waiter hangs, and — in elastic workers — publish a
peer-failure record the driver converts into a registry failure, so
``ElasticDriver.resume()`` re-forms the round instead of wedging.

``hvd.health_stats()`` aggregates the watchdog state with the retry and
fault-injection counters.
"""

from __future__ import annotations

import json
import threading
import time

from . import metrics as _metrics
from . import timeline as _timeline
from .exceptions import PeerFailureError
from .utils import envs
from .utils import faults as _faults
from .utils import invariants as _inv
from .utils import logging as hvd_logging
from .utils import retry as _retry

# Driver-side conversion channel: an elastic worker that detected a peer
# death publishes {"dead_rank": r, "reason": ...} here; the launcher KV
# observer (elastic/bootstrap.py) hands it to
# ElasticDriver.record_peer_failure, which blacklists the dead rank's
# host and resumes — without waiting for the dead process to be reaped.
PEER_FAILURE_KEY_PREFIX = "health/peerfail/"


def peer_failure_key(reporter_rank: int) -> str:
    return f"{PEER_FAILURE_KEY_PREFIX}{reporter_rank}"


def parse_peer_failure(key: str, payload: bytes):
    """``(dead_rank, reason, round_id)`` if ``key`` records a peer
    failure, else None (malformed records are ignored — the process-exit
    path still catches the failure). ``round_id`` is the elastic round
    the REPORTER was in (-1 for legacy records): global ranks renumber
    every round, so the driver must resolve the rank against the
    reporter's round, not whatever round is newest — a stale report
    about a just-replaced rank must never blacklist its innocent
    successor (docs/elastic.md)."""
    if not key.startswith(PEER_FAILURE_KEY_PREFIX):
        return None
    try:
        body = json.loads(payload.decode())
        return (int(body["dead_rank"]), str(body.get("reason", "")),
                int(body.get("round", -1)))
    except (ValueError, KeyError, UnicodeDecodeError):
        return None


def enabled() -> bool:
    """The watchdog runs whenever beats are on (``HVD_HEALTH_INTERVAL``
    > 0; set 0 to disable)."""
    return envs.health_interval_s() > 0.0


def watchdog_budget_s() -> float:
    """Upper bound on how long a peer death can go undeclared: one beat
    interval of publish skew plus the no-beat timeout. Blocking
    protocols that promise to "fail over within the watchdog budget"
    (the checkpoint peer-restore shard pulls, docs/checkpoint.md) size
    their wait deadlines from this instead of re-deriving the knobs."""
    return envs.health_interval_s() + envs.health_timeout_s()


class HealthWatchdog:
    """One rank's view of its peers' liveness over a shared KV store.

    ``kv`` needs ``put(key, bytes)`` / ``get(key) -> bytes|None``;
    both the worker-side :class:`~horovod_tpu.runner.http_kv.KVClient`
    and the server-side store satisfy it. A single daemon thread both
    publishes this rank's beat and monitors the peers — beat and check
    cadence are the same knob, so a beat can never be starved by its
    own monitor."""

    def __init__(self, kv, world_size: int, rank: int, prefix: str,
                 on_failure, interval_s: float | None = None,
                 timeout_s: float | None = None, global_ranks=None,
                 layout=None):
        self.kv = kv
        self.world_size = world_size
        self.rank = rank
        self.prefix = prefix.rstrip("/")
        self.on_failure = on_failure
        # Hierarchical beat channel (docs/negotiation.md): with a
        # GroupLayout, beats publish under per-group scopes and each
        # group's leader aggregates its members' counters into ONE
        # ``agg/<gid>`` blob per tick — a monitor then reads its own
        # group's raw beats plus the O(world/G) aggregates instead of
        # O(world) keys. A dead LEADER freezes its whole group's
        # counters from a remote monitor's view; the leader carries the
        # group's smallest rank, so sorted silence detection names the
        # leader first — exactly the failure the aggregation introduced.
        self.layout = layout
        self._gid = layout.group_of(rank) if layout is not None else 0
        self._leads = layout.is_leader(rank) if layout is not None else False
        self.interval_s = (interval_s if interval_s is not None
                           else envs.health_interval_s())
        self.timeout_s = (timeout_s if timeout_s is not None
                          else envs.health_timeout_s())
        # Beat keys and internal tracking use transport-LOCAL indices
        # (consistent across the members of a per-process-set service);
        # everything outward-facing — on_failure, error messages, the
        # driver-side peer-failure report — speaks GLOBAL process ranks
        # via this map, else a subset service would name (and blacklist)
        # the wrong process.
        self.global_ranks = (list(global_ranks) if global_ranks is not None
                             else list(range(world_size)))
        self._beat = 0
        self._beats_sent = 0
        self._beat_errors = 0
        # peer local rank -> (last counter value, monotonic time it
        # advanced). changed_at None = never beaten: silence detection
        # only arms after a peer's FIRST beat — service creation is lazy
        # (first collective), so ranks legitimately start minutes apart
        # and a startup clock would false-positive a healthy job. A rank
        # that dies before ever beating is still covered by the stall
        # inspector / exchange deadline, exactly as before this PR.
        self._seen: dict[int, tuple[int | None, float | None]] = {}
        # Local ranks that announced a GRACEFUL departure (an elastic
        # slot-lost exit publishes a `left/<rank>` marker): their beats
        # legitimately cease, so silence detection skips them — without
        # the marker, a preempted worker's clean exit raced the
        # survivors' re-rendezvous and read as a death (docs/elastic.md).
        self._left: set[int] = set()
        self._failed: tuple[int, str] | None = None
        # Through the invariants constructors so both the lock-order
        # witness (HVD_DEBUG_INVARIANTS) and the hvdsched cooperative
        # scheduler (HVD_SCHED_CHECK) cover the watchdog's failure
        # domain alongside the fusion scheduler it aborts into.
        self._mu = _inv.make_lock("health.watchdog.mu")
        self._stop = _inv.make_event("health.watchdog.stop")
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        with self._mu:
            for r in range(self.world_size):
                if r != self.rank:
                    self._seen[r] = (None, None)
        self._thread = _inv.spawn_thread(
            self._loop, name=f"hvd-health-{self.rank}")
        _register(self)

    def stop(self, join: bool = True) -> None:
        """Stop beating. ``join=False`` is the loopback crash path: the
        dying rank must cease beats NOW without waiting out a beat in
        flight — the in-process analog of a process death."""
        self._stop.set()
        t = self._thread
        if join and t is not None and t is not threading.current_thread():
            _inv.join_thread(t, timeout=5)
        self._thread = None
        _unregister(self)

    # -- protocol ----------------------------------------------------------

    def _beat_key(self, rank: int) -> str:
        if self.layout is not None:
            return f"{self.prefix}/b{self.layout.group_of(rank)}/{rank}"
        return f"{self.prefix}/beat/{rank}"

    def _poison_key(self, rank: int) -> str:
        return f"{self.prefix}/poison/{rank}"

    def poison(self, reason: str) -> None:
        """Publish an explicit poison record for THIS rank (it caught a
        local error peers cannot see): every peer's watchdog fails fast
        on its next tick instead of waiting out the beat timeout."""
        try:
            self.kv.put(self._poison_key(self.rank), reason.encode())
        except Exception as e:
            hvd_logging.warning("health: poison publish failed: %s", e)

    def mark_leaving(self) -> None:
        """Announce a GRACEFUL departure (elastic slot-lost exit): this
        rank's beats are about to cease on purpose. Peers' silence
        detection skips marked ranks — a preempted worker's clean exit
        must never read as a death to a survivor that hasn't
        re-rendezvoused yet."""
        try:
            self.kv.put(f"{self.prefix}/left/{self.rank}", b"1")
        except Exception as e:
            hvd_logging.warning("health: leave marker publish failed: %s",
                                e)

    def _check_left(self) -> None:
        """Fold newly-announced graceful departures into ``_left`` (one
        key listing per tick, the `_check_poison` pattern)."""
        try:
            names = self.kv.keys(f"{self.prefix}/left")
        except Exception:
            return  # KV flap: skip this tick's update
        marker = f"{self.prefix}/left/"
        for key in names:
            try:
                self._left.add(int(key[len(marker):]))
            except ValueError:
                continue

    def report_peer_failure(self, dead_rank: int, reason: str) -> None:
        """Elastic conversion: record the death on the launcher KV so the
        driver blacklists the dead host without waiting for process
        reaping (no-op outside elastic workers)."""
        if not envs.get_bool(envs.ELASTIC):
            return
        try:
            self.kv.put(peer_failure_key(self.rank), json.dumps(
                {"dead_rank": dead_rank, "reason": reason,
                 # the reporter's round: ranks renumber per round, so the
                 # driver resolves dead_rank against THIS round's table
                 "round": envs.get_int(envs.ELASTIC_ROUND, -1)}).encode())
        except Exception as e:
            hvd_logging.warning(
                "health: peer-failure publish failed: %s", e)

    # -- monitor loop ------------------------------------------------------

    def _loop(self) -> None:
        decided = False
        while not self._stop.is_set():
            self._publish_beat()
            if not decided:
                dead = self._check_peers()
                if dead is not None:
                    local_rank, reason = dead
                    rank = self.global_ranks[local_rank]  # outward-facing
                    with self._mu:
                        already = self._failed is not None
                        if not already:
                            self._failed = (rank, reason)
                    if not already:
                        _metrics.HEALTH_PEER_FAILURES.inc(
                            labels={"rank": rank})
                        hvd_logging.error(
                            "health watchdog: peer rank %d failed: %s",
                            rank, reason)
                        if local_rank not in self._left:
                            # graceful leavers are never reported to
                            # the elastic driver: no blacklist, no
                            # misattributed re-form
                            self.report_peer_failure(rank, reason)
                        try:
                            self.on_failure(rank, reason)
                        except Exception:
                            hvd_logging.exception(
                                "health on_failure callback failed")
                    # One failure DECISION per watchdog lifetime — but
                    # keep BEATING until stop(): the old `return` also
                    # silenced this rank's beats, so the first peer to
                    # detect a death looked freshly dead to every peer
                    # that hadn't decided yet — a cascade of
                    # misattributed deaths (observed under scripted
                    # churn: a survivor's report blacklisted a LIVE
                    # host and derailed the whole schedule). Only real
                    # teardown may cease beats.
                    decided = True
            self._stop.wait(self.interval_s)

    def _publish_beat(self) -> None:
        self._beat += 1
        try:
            # One bounded retry ladder per beat: a transient KV flap must
            # not look like OUR death to the peers.
            _retry.call(
                lambda: self.kv.put(self._beat_key(self.rank),
                                    str(self._beat).encode()),
                what="health.beat")
            self._beats_sent += 1
            _metrics.HEALTH_BEATS.inc()
        except Exception as e:
            self._beat_errors += 1
            _metrics.HEALTH_BEAT_ERRORS.inc()
            hvd_logging.warning("health: beat publish failed: %s", e)

    def _fetch_beats(self) -> dict[int, int] | None:
        """All beat counters keyed by local rank — ONE server-side gather
        per tick when the KV supports it (our own beat satisfies the
        count, so it never blocks), instead of one GET per peer per tick:
        O(world) fleet-wide monitor load, not O(world^2). None on a
        transport failure (the caller must not age peers on OUR error).
        In-memory KVs (tests, the driver-side server) fall back to
        direct gets — no HTTP involved there."""
        if self.layout is not None:
            return self._fetch_beats_hier()
        prefix = f"{self.prefix}/beat"
        gather = getattr(self.kv, "gather", None)
        try:
            if gather is not None:
                got = gather(prefix, 1, timeout=max(self.interval_s, 0.2))
            else:
                got = {}
                for r in list(self._seen):
                    raw = self.kv.get(self._beat_key(r))
                    if raw is not None:
                        got[self._beat_key(r)] = raw
        except TimeoutError:
            return {}  # no beats under the prefix at all yet
        except Exception:
            return None
        out: dict[int, int] = {}
        for key, raw in got.items():
            try:
                out[int(key.rsplit("/", 1)[1])] = int(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
        return out

    def _scope_counters(self, scope: str) -> dict[int, int] | None:
        """``{rank: counter}`` for every beat key under ``scope``; {} on
        no keys yet, None on a transport failure (never age on OUR
        error)."""
        gather = getattr(self.kv, "gather", None)
        try:
            if gather is not None:
                # Short server wait: our own beat satisfies the count
                # for our group scope, so this returns immediately; the
                # short timeout only bounds the startup window before
                # any key exists. A BLOCKING wait here would stretch the
                # monitor tick past interval_s and delay our own next
                # beat — peers would read the slow monitor as a death.
                got = gather(scope, 1, timeout=0.05)
            else:
                got = {}
                for r in list(self._seen) + [self.rank]:
                    key = self._beat_key(r)
                    if key.startswith(scope + "/"):
                        raw = self.kv.get(key)
                        if raw is not None:
                            got[key] = raw
        except TimeoutError:
            return {}
        except Exception:
            return None
        out: dict[int, int] = {}
        for key, raw in got.items():
            try:
                out[int(key.rsplit("/", 1)[1])] = int(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
        return out

    def _fetch_beats_hier(self) -> dict[int, int] | None:
        """Leader-aggregated beat fetch: own group's raw beats +
        every group's ``agg/<gid>`` blob; a leader also REPUBLISHES its
        group's aggregate from the raw beats it just read, so the
        aggregate advances exactly while the leader lives."""
        mine = self._scope_counters(f"{self.prefix}/b{self._gid}")
        if mine is None:
            return None
        if self._leads:
            try:
                self.kv.put(f"{self.prefix}/agg/{self._gid}",
                            json.dumps({str(r): c
                                        for r, c in sorted(mine.items())}
                                       ).encode())
            except Exception as e:
                hvd_logging.warning(
                    "health: beat aggregate publish failed: %s", e)
        out = dict(mine)
        gather = getattr(self.kv, "gather", None)
        try:
            if gather is not None:
                # non-blocking read of whatever aggregates exist: before
                # the first leader publishes there is nothing to wait
                # for, and blocking here would starve our own beats
                aggs = gather(f"{self.prefix}/agg", 1, timeout=0.05)
            else:
                aggs = {}
                for g in range(self.layout.n_groups):
                    raw = self.kv.get(f"{self.prefix}/agg/{g}")
                    if raw is not None:
                        aggs[f"{self.prefix}/agg/{g}"] = raw
        except TimeoutError:
            aggs = {}  # no leader has aggregated yet: startup grace
        except Exception:
            return None
        for key, blob in aggs.items():
            try:
                gid = int(key.rsplit("/", 1)[1])
                counters = json.loads(blob.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            if gid == self._gid:
                continue  # own group: the raw beats are fresher
            for r, c in counters.items():
                try:
                    out.setdefault(int(r), int(c))
                except (TypeError, ValueError):
                    continue
        return out

    def _check_poison(self):
        """(local rank, reason) for the first poisoned peer, else None.
        One key listing per tick; the reason payload is fetched only for
        an actual hit."""
        try:
            names = self.kv.keys(f"{self.prefix}/poison")
        except Exception:
            return None  # KV flap: the beat timeout still guards
        marker = f"{self.prefix}/poison/"
        for key in sorted(names):
            try:
                r = int(key[len(marker):])
            except ValueError:
                continue
            if r == self.rank or r not in self._seen:
                continue
            try:
                reason = (self.kv.get(key) or b"").decode(errors="replace")
            except Exception:
                reason = "(reason unavailable)"
            return r, f"poison record: {reason}"
        return None

    def _check_peers(self):
        """Return ``(local rank, reason)`` for the first dead peer."""
        now = _inv.monotonic()
        self._check_left()
        dead = self._check_poison()
        if dead is not None:
            return dead
        beats = self._fetch_beats()
        if beats is None:
            return None
        for r in sorted(self._seen):
            value = beats.get(r)
            with self._mu:
                last_value, changed_at = self._seen[r]
                if value is not None and value != last_value:
                    self._seen[r] = (value, now)
                    continue
                if changed_at is None:
                    continue  # never beaten: startup grace (see __init__)
                silent_s = now - changed_at
            if silent_s > self.timeout_s:
                if r in self._left:
                    # Announced graceful departure: NOT a death — the
                    # decision still fails this service's in-flight
                    # waiters fast (work owed by a departed rank can
                    # never complete), but the loop suppresses the
                    # driver-side peer-failure report, so a leaver is
                    # never blacklisted and a slow survivor cannot
                    # misattribute a re-form teardown as a crash.
                    return r, (f"left the world (graceful departure; "
                               f"beats ceased {silent_s:.1f}s ago) — "
                               "its pending work cannot complete")
                return r, (f"no liveness beat for {silent_s:.1f}s "
                           f"(HVD_HEALTH_TIMEOUT={self.timeout_s:g}s)")
        return None

    # -- introspection -----------------------------------------------------

    def peer_left(self, global_rank: int) -> bool:
        """Whether ``global_rank`` announced a GRACEFUL departure (the
        ``left/<rank>`` marker) before this watchdog's failure decision.
        The engine service consults this to type its failure: owed work
        is failed fast either way, but a departure is not a *broken*
        world — shape-keyed warm state (whose coherence the successor's
        digest round re-proves) may still shelve (docs/elastic.md; the
        world>4 churn runs surfaced exactly this: a slow survivor
        crossing the silence timeout on an already-departed peer vetoed
        the shelve and cascaded into a cold re-form for everyone)."""
        with self._mu:
            left = set(self._left)
        return any(self.global_ranks[lr] == global_rank
                   for lr in left if lr < len(self.global_ranks))

    def last_seen(self) -> dict[int, float | None]:
        """Seconds since each peer's beat counter last advanced, keyed by
        GLOBAL rank; None for a peer never seen beating."""
        now = _inv.monotonic()
        with self._mu:
            return {self.global_ranks[r]:
                    (None if changed_at is None else now - changed_at)
                    for r, (_v, changed_at) in sorted(self._seen.items())}

    def describe_peers(self) -> str:
        """Human-readable liveness summary for error messages (the
        exchange-timeout satellite: name the ranks last seen)."""
        seen = self.last_seen()
        if not seen:
            return "no peers tracked"
        return ", ".join(
            (f"rank {r}: beat {s:.1f}s ago" if s is not None
             else f"rank {r}: no beat observed yet")
            for r, s in seen.items())

    def stats(self) -> dict:
        with self._mu:
            failed = self._failed
        return {
            "rank": self.global_ranks[self.rank],
            "world_size": self.world_size,
            "member_ranks": list(self.global_ranks),
            "interval_s": self.interval_s,
            "timeout_s": self.timeout_s,
            "beats_sent": self._beats_sent,
            "beat_errors": self._beat_errors,
            "peers_last_seen_s": self.last_seen(),
            "failed_peer": (None if failed is None
                            else {"rank": failed[0], "reason": failed[1]}),
        }


def make_peer_failure_error(dead_rank: int, reason: str,
                            owed_tensors=()) -> PeerFailureError:
    """The coordinated-abort error every waiter surfaces."""
    return PeerFailureError(dead_rank, reason, owed_tensors)


class StragglerTracker:
    """Per-negotiation-round straggler attribution — the *slow* half of
    the failure spectrum the watchdog's *dead* half doesn't cover (the
    reference stall inspector names ranks that never submitted; this
    names ranks that submit **late**, docs/metrics.md).

    Each busy negotiation round the KV transport reports every member's
    submit lag (server-receipt clock, skew-free). A round whose last
    submitter lags past ``HVD_STRAGGLER_THRESHOLD`` seconds:

    * bumps ``hvd_straggler_rounds_total{rank=<global rank>}`` in the
      metrics registry (the label names the straggler, so survivors'
      series aggregate per culprit);
    * drops a ``STRAGGLER.<rank>`` instant on the timeline's ``health``
      lane;
    * after ``sustain_rounds`` *consecutive* rounds blaming the same
      rank, logs a rate-limited warning naming the global rank, its lag,
      and the tensors this rank is still owed — the stall-check analog.

    ``observe`` runs on the service's cycle thread only; ``stats`` may
    be read from anywhere (tests assert the warning through it)."""

    def __init__(self, my_rank: int, global_ranks, *,
                 threshold_s: float | None = None,
                 sustain_rounds: int = 3,
                 warn_interval_s: float = 30.0):
        self.rank = my_rank  # transport-local index of this member
        self.global_ranks = list(global_ranks)
        self.threshold_s = (threshold_s if threshold_s is not None
                            else envs.straggler_threshold_s())
        self.sustain_rounds = max(int(sustain_rounds), 1)
        self.warn_interval_s = warn_interval_s
        self._mu = _inv.make_lock("health.straggler.mu")
        self._streak_rank: int | None = None  # local index
        self._streak = 0
        self._last_warn_at: dict[int, float] = {}  # global rank -> t
        self._rounds: dict[int, int] = {}  # global rank -> count
        self._warnings = 0
        self._last_warning: str | None = None

    def observe(self, lags: dict, owed_tensors=()) -> None:
        """One busy round's per-member submit lags (local rank ->
        seconds behind the round's first submitter)."""
        if not lags:
            return
        worst = max(sorted(lags), key=lambda r: lags[r])
        lag = lags[worst]
        if worst == self.rank or lag < self.threshold_s:
            # own lag is unobservable honestly (our put gates our
            # gather), and an under-threshold round breaks any streak
            with self._mu:
                self._streak_rank = None
                self._streak = 0
            return
        gr = self.global_ranks[worst]
        _metrics.STRAGGLER_ROUNDS.inc(labels={"rank": gr})
        _timeline.record_health_event(f"STRAGGLER.{gr}")
        now = _inv.monotonic()
        warn = None
        with self._mu:
            self._rounds[gr] = self._rounds.get(gr, 0) + 1
            if self._streak_rank == worst:
                self._streak += 1
            else:
                self._streak_rank = worst
                self._streak = 1
            if (self._streak >= self.sustain_rounds
                    and now - self._last_warn_at.get(gr, float("-inf"))
                    >= self.warn_interval_s):
                self._last_warn_at[gr] = now
                warn = (
                    f"negotiation straggler: global rank {gr} was last "
                    f"to submit for {self._streak} consecutive rounds, "
                    f"{lag:.3f}s behind the first submitter "
                    f"(HVD_STRAGGLER_THRESHOLD={self.threshold_s:g}s); "
                    f"tensors owed to this rank: "
                    f"{sorted(owed_tensors)}")
                self._warnings += 1
                self._last_warning = warn
        if warn is not None:
            hvd_logging.warning("%s", warn)

    def stats(self) -> dict:
        with self._mu:
            return {
                "threshold_s": self.threshold_s,
                "straggler_rounds": dict(sorted(self._rounds.items())),
                "current_streak": (
                    None if self._streak_rank is None
                    else {"rank": self.global_ranks[self._streak_rank],
                          "rounds": self._streak}),
                "warnings": self._warnings,
                "last_warning": self._last_warning,
            }


def straggler_blames() -> dict[int, int]:
    """Cumulative straggler rounds THIS rank's trackers have charged to
    each global rank, read off the metrics registry (the calling
    thread's world store, so a loopback rank reports only its own
    observations). The autoscale policy's eviction sensor
    (docs/elastic.md): per-rank observers publish deltas of this view
    and the driver-side policy aggregates the blames across reporters —
    the seam between "rank N is slow" (StragglerTracker) and "replace
    rank N" (AutoscalePolicy)."""
    out: dict[int, int] = {}
    for labelitems, v in _metrics.STRAGGLER_ROUNDS.series().items():
        try:
            out[int(dict(labelitems).get("rank"))] = int(v)
        except (TypeError, ValueError):
            continue
    return out


# -- process-wide registry + the hvd.health_stats() surface -----------------

_registry_mu = _inv.make_lock("health.registry.mu")
_watchdogs: list[HealthWatchdog] = []


def _register(w: HealthWatchdog) -> None:
    with _registry_mu:
        if w not in _watchdogs:
            _watchdogs.append(w)


def _unregister(w: HealthWatchdog) -> None:
    with _registry_mu:
        if w in _watchdogs:
            _watchdogs.remove(w)


def health_stats() -> dict:
    """Failure-domain counters (exported as ``hvd.health_stats()``):
    per-site retry/giveup counts, fault-injection rule counters, and
    every active watchdog's liveness view."""
    with _registry_mu:
        dogs = list(_watchdogs)
    return {
        "retries": _retry.stats(),
        "faults": _faults.stats(),
        "watchdogs": [w.stats() for w in dogs],
    }
