"""Framework exceptions.

TPU-native rebuild of ``/root/reference/horovod/common/exceptions.py``: the
two exception types that drive the elastic protocol (``run_fn`` catches both,
``/root/reference/horovod/common/elastic.py:151-174``).
"""

from __future__ import annotations


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective routine fails mid-flight.

    In elastic mode this triggers state restore + re-initialization instead
    of aborting the job (reference semantics: NCCL async errors are turned
    into this type via ``AsyncErrorCheck``, ``nccl_operations.cc:126-140``).
    On TPU the analogous sources are ``jax.distributed`` runtime errors
    (peer death, heartbeat loss, coordinator barrier failure); use
    :func:`wrap_internal_errors` to translate them.
    """


class PeerFailureError(HorovodInternalError):
    """A peer rank was declared dead by the health watchdog — it stopped
    publishing liveness beats for ``HVD_HEALTH_TIMEOUT`` seconds, or it
    wrote an explicit poison record after catching a local error.

    Raised on every surviving rank's in-flight negotiation waits (and on
    queued fusion-cycle handles at ``synchronize()``) well before the
    600 s exchange deadline would expire, naming the dead rank and the
    tensors it still owed. Subclasses :class:`HorovodInternalError` so
    elastic mode (``hvd.elastic.run``) treats it as recoverable: restore
    committed state, re-rendezvous without the dead host, resume.
    """

    def __init__(self, rank: int, reason: str, owed_tensors=()):
        self.rank = rank
        self.reason = reason
        self.owed_tensors = tuple(owed_tensors)
        owed = (f"; undelivered tensors: {list(self.owed_tensors)}"
                if self.owed_tensors else "")
        super().__init__(
            f"peer rank {rank} failed: {reason}{owed}")


class ResponseCacheJoinError(HorovodInternalError):
    """The coordinator ResponseCache served a batch locally while a
    peer's JOIN was racing the join latch (``HVD_RESPONSE_CACHE``;
    docs/negotiation.md "Joins"): the locally-served collectives were
    never scheduled through a real round, so the joining rank can never
    contribute its zero executions and the work would otherwise hang
    until the full exchange deadline. The coordinator detects the race
    on the cycle that first observes the JOIN and fails fast with this
    typed error naming the joining rank.

    Subclasses :class:`HorovodInternalError`: the world is healthy but
    this service's serving decisions diverged — elastic mode restores
    committed state and re-forms, and non-elastic callers get a precise
    error in seconds instead of a deadline timeout.
    """

    def __init__(self, joining_rank: int, served_batches: int):
        self.joining_rank = joining_rank
        self.served_batches = served_batches
        who = (f"rank {joining_rank}" if joining_rank >= 0
               else "an unidentified rank")
        super().__init__(
            f"coordinator ResponseCache served {served_batches} batch(es) "
            f"locally while {who}'s JOIN was in flight (pre-join-latch "
            "window); the served collectives cannot pair with the joined "
            "rank — re-negotiate (elastic mode re-forms automatically). "
            "Keep HVD_RESPONSE_CACHE off for join-terminated workloads "
            "(docs/negotiation.md).")


class QosAdmissionError(RuntimeError):
    """An async collective submission was shed at enqueue by its
    tenant's QoS admission control (``hvd.set_qos(...,
    policy="shed")`` / ``HVD_QOS_*``; docs/qos.md): the tenant's
    unacknowledged pending bytes would exceed its quota.

    Raised from the submission's handle (``synchronize()`` /
    ``result()``) — a shed handle always raises, it never returns data.
    Deliberately NOT a :class:`HorovodInternalError`: shedding is flow
    control on a healthy engine, not a peer/communication failure, so
    elastic mode must not respond by re-forming the world. Serving
    drivers catch it and retry/downgrade the request.
    """

    def __init__(self, tenant: str, nbytes: int, pending: int, quota: int):
        self.tenant = tenant
        self.nbytes = int(nbytes)
        self.pending = int(pending)
        self.quota = int(quota)
        super().__init__(
            f"tenant {tenant!r}: submission of {nbytes} B shed by QoS "
            f"admission control ({pending} B already pending, quota "
            f"{quota} B)")


class HostsUpdatedInterrupt(RuntimeError):
    """Internal interrupt raised when the set of available hosts changed.

    ``skip_sync`` is True when hosts were only *removed*: the surviving
    workers still hold identical state, so the post-reset ``state.sync()``
    can be skipped. Any addition forces a sync so the new workers receive
    rank 0's state (reference raises with
    ``all_update == HostUpdateResult.removed``).
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__("hosts updated")
        self.skip_sync = skip_sync


# Error-message fragments from the jax/XLA distributed runtime that indicate
# a *membership/communication* failure (recoverable by re-initializing the
# world) rather than a user bug. Applied only to exception types raised by
# the jax/jaxlib/grpc runtime itself — a user's HTTP 503 ("service
# unavailable") must surface as the real traceback, not be swallowed into
# an elastic retry loop.
_TRANSIENT_DISTRIBUTED_MARKERS = (
    "distributed",
    "heartbeat",
    "coordination service",
    "preemption",
    "deadline exceeded",
    "unavailable",
    "connection reset",
    "connection closed",
    "connection refused",
    "socket closed",
    "broken pipe",
    "barrier",
    # XLA CPU/TPU collective-runtime failures when a peer dies mid-op
    "gloo",
    "all-reduce failed",
    "all-gather failed",
    "collective",
    "peer",
)

# For exceptions of builtin type (e.g. the ValueError XLA raises when a
# gloo collective loses a peer, or a RuntimeError from jax.distributed) the
# type's module tells us nothing, so only multi-word phrases specific to
# the coordination/collective runtime qualify — single words like
# "unavailable" or "peer" would swallow ordinary user errors.
_STRICT_DISTRIBUTED_MARKERS = (
    "coordination service",
    "jax.distributed",
    "distributed runtime",
    "preemption sync",
    "connection closed by peer",
    "connection reset by peer",
    "all-reduce failed",
    "all-gather failed",
    "all-to-all failed",
    "collective-permute failed",
    "gloo broadcast failed",
    "gloo reduce failed",
    "gloo barrier failed",
)


def _is_runtime_module(mod: str) -> bool:
    # Exactly jax/jaxlib and their submodules — NOT jaxtyping/jaxopt (user
    # libraries) and NOT grpc (user grpc-python errors say "unavailable"
    # for ordinary service outages; jax's own runtime raises jaxlib types).
    return (mod in ("jax", "jaxlib")
            or mod.startswith(("jax.", "jaxlib.", "jax._src")))


def is_recoverable_distributed_error(exc: BaseException) -> bool:
    """Does this exception look like a peer/communication failure that
    elastic mode should recover from? Matches broad markers only on
    exception types owned by the jax/jaxlib runtime (e.g.
    ``jaxlib...XlaRuntimeError``); builtin-typed exceptions must carry a
    multi-word phrase specific to the coordination/collective runtime."""
    text = f"{type(exc).__name__}: {exc}".lower()
    mod = type(exc).__module__ or ""
    if _is_runtime_module(mod):
        return any(m in text for m in _TRANSIENT_DISTRIBUTED_MARKERS)
    return any(m in text for m in _STRICT_DISTRIBUTED_MARKERS)


def wrap_internal_errors(fn):
    """Decorator translating recoverable jax distributed-runtime errors into
    :class:`HorovodInternalError` so ``hvd.elastic.run`` can catch them."""
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except (HorovodInternalError, HostsUpdatedInterrupt):
            raise
        except Exception as e:
            if is_recoverable_distributed_error(e):
                raise HorovodInternalError(str(e)) from e
            raise

    return wrapper
