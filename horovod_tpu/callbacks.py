"""Training-loop helpers: LR scaling/warmup schedules and metric averaging.

TPU-native rebuild of the reference's Keras callbacks
(``/root/reference/horovod/_keras/callbacks.py:1-493``). Keras mutates the
optimizer's ``lr`` variable from callback hooks; the optax idiom is a
*schedule* — a pure ``fn(step) -> lr`` passed to the optimizer once — so
each callback maps to a schedule factory:

* ``LearningRateScheduleCallback``  → :func:`lr_schedule`
* ``LearningRateWarmupCallback``    → :func:`warmup_schedule`
* ``MetricAverageCallback``         → :func:`metric_average` / :func:`average_metrics`
* ``BroadcastGlobalVariablesCallback`` → ``hvd.broadcast_parameters``
  (call once before step 0; already in :mod:`horovod_tpu.functions`).
"""

from __future__ import annotations

from typing import Callable, Mapping

import jax.numpy as jnp

from . import runtime
from .ops import collectives
from .ops.reduce_ops import ReduceOp


def lr_schedule(initial_lr: float, multiplier, *, steps_per_epoch: int,
                start_epoch: int = 0, end_epoch: int | None = None,
                staircase: bool = True) -> Callable:
    """Epoch-indexed learning-rate schedule (reference
    ``LearningRateScheduleCallbackImpl``, ``_keras/callbacks.py:96-180``).

    ``multiplier`` is ``fn(epoch) -> factor`` or a constant (then it decays
    exponentially: ``multiplier ** (epoch - start_epoch)``, matching the
    reference). ``staircase`` applies the multiplier per epoch; otherwise
    per step with a fractional epoch. Outside [start_epoch, end_epoch) the
    lr stays ``initial_lr``.
    """
    if not callable(multiplier):
        factor = float(multiplier)

        def multiplier(epoch):  # noqa: F811 - reference semantics
            return factor ** (epoch - start_epoch)

    def schedule(step):
        epoch = step / steps_per_epoch
        if staircase:
            epoch = jnp.floor(epoch)
        in_range = epoch >= start_epoch
        if end_epoch is not None:
            in_range = jnp.logical_and(in_range, epoch < end_epoch)
        return jnp.where(in_range, initial_lr * multiplier(epoch),
                         initial_lr)

    return schedule


def warmup_schedule(initial_lr: float, *, steps_per_epoch: int,
                    warmup_epochs: float = 5,
                    size: int | None = None) -> Callable:
    """Gradual learning-rate warmup (reference
    ``LearningRateWarmupCallbackImpl``, ``_keras/callbacks.py:182-250``,
    after Goyal et al. 2017): ramps linearly from ``initial_lr / size`` to
    ``initial_lr`` over ``warmup_epochs``. ``initial_lr`` is the already
    size-scaled target rate, exactly like the reference's usage
    ``lr=base_lr * hvd.size()``.
    """
    n = runtime.size() if size is None else size

    def multiplier(epoch):
        # same fractional-epoch adjustment as the reference so the ramp
        # ends exactly on the epoch boundary
        epoch = epoch + 1.0 / steps_per_epoch
        return 1.0 / n * (epoch * (n - 1) / warmup_epochs + 1)

    return lr_schedule(initial_lr, multiplier,
                       steps_per_epoch=steps_per_epoch, start_epoch=0,
                       end_epoch=warmup_epochs, staircase=False)


def metric_average(value, name: str | None = None, *, process_set=None):
    """Average a scalar metric over all ranks (the reference's per-metric
    ``hvd.allreduce`` inside ``MetricAverageCallbackImpl``). Eager — call
    it outside jit at epoch end."""
    out = collectives.allreduce(jnp.asarray(value, jnp.float32),
                                op=ReduceOp.AVERAGE, name=name,
                                process_set=process_set)
    return float(out)


def average_metrics(logs: Mapping, *, process_set=None) -> dict:
    """Average every value of a metrics dict across ranks, sorted by key
    for deterministic collective order on every rank (reference
    ``_average_metrics_in_place``, ``_keras/callbacks.py:69-88``)."""
    return {k: metric_average(v, name=f"metric.{k}", process_set=process_set)
            for k, v in sorted(logs.items())}
