"""Dynamic engine: negotiation, response cache, fusion planning, stall
detection for the eager path.

Python face of the native engine (``native/engine.cc``, bound via
:mod:`horovod_tpu._native`). The TPU-native rebuild of the reference's core
runtime machinery: TensorQueue (``tensor_queue.cc``), Controller negotiation
(``controller.cc:73-430``), ResponseCache (``response_cache.cc``),
GroupTable (``group_table.cc``) and StallInspector (``stall_inspector.cc``).

The protocol is **symmetric**: instead of the reference's rank-0
master/worker gather+bcast (``controller.h:72-108``), every member ingests
the identical rank-ordered request lists and deterministically computes the
same fused response plan. One negotiation **cycle** is:

1. ``pop_requests()``             — serialize my pending requests
2. transport exchange             — allgather everyone's request bytes
3. ``ingest(rank, bytes)``        — in rank order, on every member
4. ``cache_bits()``               — my cache-hit bitvector
5. transport AND                  — bitwise AND across members
6. ``commit_cache_bits(anded)``   — serve globally cache-hit tensors
7. ``compute_responses()``        — fused plan for globally-ready tensors

Step 3 also performs globally-consistent cache invalidation (every rank
sees the same changed-metadata requests, so every rank erases the same
entries on the same cycle — the analog of the reference's CacheCoordinator
invalid-bit sync, ``response_cache.h:149-151``).
"""

from __future__ import annotations

import ctypes
import dataclasses
import struct
import threading

from . import _native
from .utils import envs
from .utils import logging as hvd_logging

# Request/response type ids (native/hvd_core.h, mirroring the reference's
# message.h:52-54,155-157).
REQ_ALLREDUCE = 0
REQ_ALLGATHER = 1
REQ_BROADCAST = 2
REQ_JOIN = 3
REQ_ADASUM = 4
REQ_ALLTOALL = 5
REQ_BARRIER = 6
REQ_REDUCESCATTER = 7

RESP_ERROR = 8

_RESP_NAMES = {
    0: "ALLREDUCE", 1: "ALLGATHER", 2: "BROADCAST", 3: "JOIN", 4: "ADASUM",
    5: "ALLTOALL", 6: "BARRIER", 7: "REDUCESCATTER", 8: "ERROR",
}


class DuplicateNameError(ValueError):
    """A tensor name was enqueued while a request with the same name is
    still in flight (reference ``common.h:229-232``)."""


class HorovodCollectiveError(RuntimeError):
    """The negotiation produced an ERROR response — ranks disagreed on
    type/dtype/shape/root for a tensor (reference ``ConstructResponse``
    mismatch errors, ``controller.cc``)."""


@dataclasses.dataclass
class Response:
    type: int
    tensor_names: list
    dtype: int = 0
    root_rank: int = -1
    total_bytes: int = 0
    from_cache: bool = False
    error_message: str = ""
    # ALLTOALL: rows this rank receives from each rank (negotiated; the
    # reference's AlltoallGetRecvSplits metadata).
    recv_splits: list = dataclasses.field(default_factory=list)
    # Per-tensor shapes + group ids (aligned with tensor_names) and reduce
    # parameters, so a JOINed rank can execute the identical program with
    # zero inputs (reference JoinOp, collective_operations.h:275-290).
    shapes: list = dataclasses.field(default_factory=list)
    group_ids: list = dataclasses.field(default_factory=list)
    reduce_op: int = -1
    prescale: float = 1.0
    postscale: float = 1.0

    @property
    def type_name(self) -> str:
        return _RESP_NAMES.get(self.type, "?")

    @property
    def is_error(self) -> bool:
        return self.type == RESP_ERROR


@dataclasses.dataclass
class StallEntry:
    tensor_name: str
    ready_ranks: list
    waiting_seconds: float

    def missing_ranks(self, world_size: int) -> list:
        return [r for r in range(world_size) if r not in set(self.ready_ranks)]


class _Reader:
    """Little-endian reader matching native/wire.h."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def u8(self):
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def u32(self):
        (v,) = struct.unpack_from("<I", self.buf, self.pos)
        self.pos += 4
        return v

    def i32(self):
        (v,) = struct.unpack_from("<i", self.buf, self.pos)
        self.pos += 4
        return v

    def i64(self):
        (v,) = struct.unpack_from("<q", self.buf, self.pos)
        self.pos += 8
        return v

    def f64(self):
        (v,) = struct.unpack_from("<d", self.buf, self.pos)
        self.pos += 8
        return v

    def str(self):
        n = self.u32()
        s = self.buf[self.pos:self.pos + n].decode()
        self.pos += n
        return s


def parse_responses(data: bytes) -> list[Response]:
    r = _Reader(data)
    out = []
    for _ in range(r.u32()):
        t = r.u8()
        dtype = r.i32()
        root = r.i32()
        total = r.i64()
        from_cache = r.u8() != 0
        err = r.str()
        names = [r.str() for _ in range(r.u32())]
        recv_splits = [r.i32() for _ in range(r.u32())]
        shapes = [tuple(r.i64() for _ in range(r.u32()))
                  for _ in range(r.u32())]
        group_ids = [r.i32() for _ in range(r.u32())]
        reduce_op = r.i32()
        prescale = r.f64()
        postscale = r.f64()
        out.append(Response(type=t, tensor_names=names, dtype=dtype,
                            root_rank=root, total_bytes=total,
                            from_cache=from_cache, error_message=err,
                            recv_splits=recv_splits, shapes=shapes,
                            group_ids=group_ids, reduce_op=reduce_op,
                            prescale=prescale, postscale=postscale))
    return out


def parse_requests(data: bytes) -> list[dict]:
    """Parse one member's serialized request list (the Python twin of
    ``native/message.h`` ``RequestList::parse``). The coordinator
    ResponseCache's join-race detector scans exchanged frames for JOIN
    requests to name the joining rank (docs/negotiation.md); keys:
    ``rank``, ``request_type``, ``name``."""
    if not data:
        return []
    r = _Reader(data)
    out = []
    for _ in range(r.u32()):
        rank = r.i32()
        rtype = r.u8()
        r.i32()  # dtype
        r.i32()  # element_size
        r.i32()  # root_rank
        r.i32()  # group_id
        name = r.str()
        for _ in range(r.u32()):  # shape
            r.i64()
        for _ in range(r.u32()):  # splits
            r.i32()
        r.i32()  # reduce_op
        r.f64()  # prescale
        r.f64()  # postscale
        r.i32()  # splits_crc
        out.append({"rank": rank, "request_type": rtype, "name": name})
    return out


def parse_stall_report(data: bytes) -> list[StallEntry]:
    r = _Reader(data)
    out = []
    for _ in range(r.u32()):
        name = r.str()
        n = r.u32()
        ranks = [r.u32() for _ in range(n)]
        waited = r.f64()
        out.append(StallEntry(name, ranks, waited))
    return out


def and_bitvectors(vectors: list[bytes]) -> bytes:
    """Bitwise AND of per-rank cache-hit bitvectors (the transport's reduce
    for step 5; reference uses MPI_BAND, ``mpi_controller.cc:115-123``)."""
    if not vectors:
        return b""
    n = max(len(v) for v in vectors)
    acc = bytearray(vectors[0].ljust(n, b"\x00"))
    for v in vectors[1:]:
        padded = v.ljust(n, b"\x00")
        for i in range(n):
            acc[i] &= padded[i]
    return bytes(acc)


class NativeEngine:
    """Thin ownership wrapper over one native engine instance."""

    def __init__(self, world_size: int = 1, rank: int = 0, *,
                 fusion_threshold: int | None = None,
                 cache_capacity: int | None = None,
                 stall_warn: float | None = None,
                 stall_shutdown: float | None = None):
        self._lib = _native.load()
        if fusion_threshold is None:
            fusion_threshold = envs.fusion_threshold_bytes()
        if cache_capacity is None:
            cache_capacity = envs.cache_capacity()
        if stall_warn is None:
            stall_warn = envs.get_float(
                envs.STALL_CHECK_TIME_SECONDS,
                envs.DEFAULT_STALL_WARNING_SECONDS)
        if stall_shutdown is None:
            stall_shutdown = envs.get_float(envs.STALL_SHUTDOWN_TIME_SECONDS,
                                            0.0)
        self.world_size = world_size
        self.rank = rank
        self._h = self._lib.hvd_engine_create(
            world_size, rank, fusion_threshold, cache_capacity,
            float(stall_warn), float(stall_shutdown))
        self._mu = threading.Lock()

    def close(self):
        with self._mu:
            if self._h:
                self._lib.hvd_engine_destroy(self._h)
                self._h = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:  # hvdlint: disable=silent-except
            pass  # GC-time close: logging may itself be torn down

    # -- worker side -------------------------------------------------------

    def enqueue(self, name: str, request_type: int, *, dtype: int = 0,
                element_size: int = 4, shape=(), root_rank: int = -1,
                group_id: int = -1, splits=(), reduce_op: int = -1,
                prescale: float = 1.0, postscale: float = 1.0,
                splits_crc: int = 0) -> None:
        shape = tuple(int(d) for d in shape)
        arr = (ctypes.c_int64 * len(shape))(*shape)
        splits = tuple(int(s) for s in splits)
        sarr = (ctypes.c_int32 * len(splits))(*splits)
        rc = self._lib.hvd_engine_enqueue(
            self._h, name.encode(), request_type, dtype, element_size,
            arr, len(shape), root_rank, group_id, sarr, len(splits),
            int(reduce_op), float(prescale), float(postscale),
            int(splits_crc))
        if rc == -3:
            raise ValueError(
                f"invalid alltoall splits for {name!r}: must be length "
                "world_size, non-negative, and sum to at most the tensor's "
                "first dimension (reference operations.cc:1691-1727)")
        if rc == -2:
            raise DuplicateNameError(
                f"tensor name {name!r} is still in flight from a timed-out "
                "negotiation with different type/dtype/shape/root metadata; "
                "a retry must match the original request (or use a new name)")
        if rc < 0:
            raise DuplicateNameError(
                f"tensor name {name!r} was enqueued while a request with "
                "the same name is still pending; pass a unique name= "
                "(reference detects the same condition, common.h:229-232)")

    def _out_call(self, fn) -> bytes:
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        length = ctypes.c_size_t()
        rc = fn(self._h, ctypes.byref(ptr), ctypes.byref(length))
        data = ctypes.string_at(ptr, length.value) if length.value else b""
        return rc, data

    def pop_requests(self) -> bytes:
        _, data = self._out_call(self._lib.hvd_engine_pop_requests)
        return data

    # -- negotiation -------------------------------------------------------

    def ingest(self, rank: int, data: bytes) -> None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data \
            else (ctypes.c_uint8 * 0)()
        rc = self._lib.hvd_engine_ingest(self._h, rank, buf, len(data))
        if rc != 0:
            raise ValueError(f"malformed request list from rank {rank}")

    def cache_bits(self) -> bytes:
        _, data = self._out_call(self._lib.hvd_engine_cache_bits)
        return data

    def commit_cache_bits(self, bits: bytes) -> None:
        buf = (ctypes.c_uint8 * len(bits)).from_buffer_copy(bits) if bits \
            else (ctypes.c_uint8 * 0)()
        self._lib.hvd_engine_commit_cache_bits(self._h, buf, len(bits))

    def compute_responses(self) -> list[Response]:
        _, data = self._out_call(self._lib.hvd_engine_compute_responses)
        return parse_responses(data)

    def stall_report(self) -> tuple[list[StallEntry], bool]:
        rc, data = self._out_call(self._lib.hvd_engine_stall_report)
        return parse_stall_report(data), rc == 1

    def register_group(self, group_id: int, n_members: int) -> None:
        self._lib.hvd_engine_register_group(self._h, group_id, n_members)

    def abandon(self, name: str) -> bool:
        """Drop a locally-submitted request (post-timeout retry path).
        Returns True if the name was outstanding."""
        return self._lib.hvd_engine_abandon(self._h, name.encode()) == 0

    # -- introspection -----------------------------------------------------

    def pending_count(self) -> int:
        return self._lib.hvd_engine_pending_count(self._h)

    def cache_size(self) -> int:
        return self._lib.hvd_engine_cache_size(self._h)

    def cache_has(self, name: str) -> bool:
        """Whether ``name`` is currently held by the native response
        cache. Invalidation is driven by the globally-ingested request
        stream, so every rank answers identically on the same cycle —
        the coordinator ResponseCache (engine_service) gates its local
        serving on this to stay coherent with the protocol."""
        fn = getattr(self._lib, "hvd_engine_cache_has", None)
        if fn is None:  # pre-r13 library: never serve locally
            return False
        return fn(self._h, name.encode()) == 1

    def join_pending(self) -> bool:
        """Whether any rank's JOIN is currently in flight (ingested but
        not yet completed by every rank joining). Local cache serving
        must pause then: the joined rank only learns about scheduled
        collectives — for its zero executions — from real rounds."""
        fn = getattr(self._lib, "hvd_engine_join_pending", None)
        if fn is None:
            return False
        return fn(self._h) == 1

    # -- timeline ----------------------------------------------------------

    def timeline_start(self, path: str) -> None:
        rc = self._lib.hvd_timeline_start(self._h, path.encode())
        if rc != 0:
            raise OSError(f"cannot open timeline file {path!r}")

    def timeline_stop(self) -> None:
        self._lib.hvd_timeline_stop(self._h)

    def timeline_record(self, tensor: str, activity: str, phase: int,
                        timestamp_us: int = -1) -> None:
        self._lib.hvd_timeline_record(self._h, tensor.encode(),
                                      activity.encode(), phase, timestamp_us)


def drive_cycle(engines: list[NativeEngine]) -> list[list[Response]]:
    """Run one full symmetric negotiation cycle across in-memory engines.

    The reference tests run real 2-process mpirun jobs; this in-memory
    multi-engine driver exercises the identical protocol without processes
    (the transport — one batched allgather of (requests, cache bits) — is
    played by plain Python). Also documents the canonical cycle order for
    real transports: bits are computed against the pre-ingest cache state
    (so bit positions agree on every member), the AND-served set commits
    first, then ingest skips served names.
    """
    datas = [e.pop_requests() for e in engines]
    anded = and_bitvectors([e.cache_bits() for e in engines])
    for e in engines:
        e.commit_cache_bits(anded)
    for e in engines:
        for rank, data in enumerate(datas):
            e.ingest(rank, data)
    return [e.compute_responses() for e in engines]
