"""Worker-side elastic rendezvous: fetch the new round, rebuild the world.

The TPU-native analog of the reference's reset path, where workers rebuild
gloo contexts against the rendezvous server after ``hvd.shutdown()`` /
``hvd.init()`` (reference ``horovod/torch/elastic/__init__.py`` reset +
``gloo_context.cc`` re-init). Here a reset is:

1. wait for the KV round counter to advance past our round,
2. look up this worker's slot (stable ``(hostname, spawn local_rank)`` key)
   in the new round's slot table — if gone, self-exit with
   :data:`~horovod_tpu.elastic.driver.SLOT_LOST_EXIT_CODE`,
3. tear down the jax world (``hvd.shutdown`` → ``jax.distributed.shutdown``
   → ``jax.extend.backend.clear_backends``) and re-initialize it against the
   round's fresh coordinator, then ``hvd.init()``,
4. record readiness in the KV for the driver's registry.

Steps 3 is the piece the reference cannot do — XLA must forget the old
backend before ``jax.distributed`` accepts a new world definition.
"""

from __future__ import annotations

import pickle
import sys
import time

from ..utils import envs
from ..utils import logging as hvd_logging
from ..utils import retry as _retry
from .driver import (
    ROUND_KEY,
    ROUND_SPEC_KEY,
    SLOT_LOST_EXIT_CODE,
    STOP_KEY,
    done_key,
    ready_key,
)


class WorkerRendezvous:
    """Per-worker handle on the elastic round protocol."""

    def __init__(self, kv_client=None):
        if kv_client is None:
            from ..runner.http_kv import KVClient
            addr = envs.get(envs.KV_ADDR)
            # HVD_ELASTIC discriminates elastic from static launches: static
            # hvdrun also seeds HVD_KV_ADDR, but its launcher never publishes
            # rounds — entering the elastic protocol there would stall
            # instead of failing fast.
            if not addr or not envs.get_bool(envs.ELASTIC):
                raise RuntimeError(
                    "not an elastic worker: HVD_ELASTIC/HVD_KV_ADDR not set "
                    "(launch with `hvdrun --min-np/--max-np/"
                    "--host-discovery-script`)")
            kv_client = KVClient(addr, envs.get_int(envs.KV_PORT, 0),
                                 secret=envs.get(envs.SECRET_KEY))
        self.kv = kv_client
        self.hostname = envs.get(envs.HOSTNAME) or "localhost"
        # Stable worker identity: the local slot index assigned at spawn.
        self.slot = envs.get_int(envs.LOCAL_RANK, 0)
        self.round = envs.get_int(envs.ELASTIC_ROUND, 1)
        self.timeout = envs.get_int(envs.ELASTIC_TIMEOUT, 600)
        self._last_round_raw: bytes | None = None

    # -- protocol ----------------------------------------------------------

    def record_ready(self) -> None:
        self.kv.put(ready_key(self.round, self.hostname, self.slot), b"1")

    def record_done(self) -> None:
        """Mark this worker's training as complete — called before any jax
        teardown so driver-side success cannot race a noisy process exit."""
        self.kv.put(done_key(self.hostname, self.slot), b"1")

    def reset(self) -> None:
        """Re-rendezvous into the next round (the ``reset`` callback handed
        to :func:`~horovod_tpu.elastic.state.run_fn`)."""
        spec = self._wait_for_next_round()
        my_slot = self._find_my_slot(spec)
        if my_slot is None:
            hvd_logging.info(
                "slot %s[%d] not assigned in round %d; exiting",
                self.hostname, self.slot, spec["round"])
            # Graceful departure: announce it on the health channel so
            # surviving watchdogs skip this rank's ceased beats instead
            # of reading the clean exit as a death (a preempted worker's
            # exit raced slow survivors into a spurious failure
            # recovery; docs/elastic.md).
            from .. import engine_service
            engine_service.mark_leaving()
            sys.exit(SLOT_LOST_EXIT_CODE)
        self._reinitialize(spec, my_slot)

    def _check_round(self) -> dict | None:
        """One poll of the round protocol: exits on a driver stop, returns
        the next round's spec when published, else None."""
        if self.kv.get(STOP_KEY) is not None:
            hvd_logging.info("driver stopped the job during reset")
            sys.exit(0)
        raw = self.kv.get(ROUND_KEY)
        self._last_round_raw = raw
        if raw is not None:
            round_id = int(raw.decode())
            if round_id > self.round:
                spec_raw = self.kv.get(ROUND_SPEC_KEY.format(round_id))
                if spec_raw is not None:
                    return pickle.loads(spec_raw)
        return None

    def _wait_for_next_round(self) -> dict:
        # Paced by the unified retry helper: jittered 250 ms polls backing
        # off toward 2 s — host replacement takes tens of seconds, so the
        # old fixed-interval spin bought nothing but KV load.
        last_report = time.monotonic()
        spec = self._check_round()
        if spec is not None:
            return spec
        for _ in _retry.poll_intervals("elastic.round-wait",
                                       interval_s=0.25,
                                       deadline_s=float(self.timeout)):
            spec = self._check_round()
            if spec is not None:
                return spec
            now = time.monotonic()
            if now - last_report > 5:
                raw = self._last_round_raw
                hvd_logging.info(
                    "waiting for elastic round > %d (kv reports %s)",
                    self.round, raw.decode() if raw else None)
                last_report = now
        raise TimeoutError(
            f"no new elastic round after {self.timeout}s "
            f"(stuck at round {self.round})")

    def _find_my_slot(self, spec: dict) -> dict | None:
        for slot in spec["slots"]:
            if (slot["hostname"] == self.hostname
                    and slot["local_rank"] == self.slot):
                return slot
        return None

    def _reinitialize(self, spec: dict, my_slot: dict) -> None:
        import jax

        from .. import runtime
        from ..loopback import context as _lbctx

        hvd_logging.info(
            "re-rendezvous into round %d: rank %d/%d via %s:%d",
            spec["round"], my_slot["rank"], spec["world_size"],
            spec["coord_addr"], spec["coord_port"])

        if _lbctx.current() is not None:
            # Loopback rank thread: no jax.distributed world exists (the
            # XLA backend is shared and untouched) — tear down this
            # rank's services, seed the new round's contract into the
            # rank overlay, and rebuild the loopback runtime in place.
            runtime.shutdown()
            self._seed_round_env(spec, my_slot)
            self.round = spec["round"]
            runtime.init()
            from .notification import get_notification_manager
            get_notification_manager().mark_round_joined(self.round)
            self.record_ready()
            return

        runtime.shutdown()  # also stops the old-world negotiation service
        jax.config.update("jax_enable_recoverability", True)
        try:
            jax.distributed.shutdown()
        except Exception as e:
            # Graceful shutdown can fail when the round turned because a
            # peer died. Abandon the old client/service objects so a fresh
            # initialize can proceed; recoverability (set above) keeps the
            # failure from being fatal.
            hvd_logging.warning("jax.distributed shutdown failed (%s); "
                                "abandoning old client", e)
            from jax._src import distributed as _dist
            _dist.global_state.preemption_sync_manager = None
            _dist.global_state.client = None
            _dist.global_state.service = None
        # XLA must forget the old topology before a new world is defined.
        from jax.extend import backend as jex_backend
        jex_backend.clear_backends()
        jax.clear_caches()

        self._seed_round_env(spec, my_slot)

        self.round = spec["round"]
        runtime.init()
        from .notification import get_notification_manager
        get_notification_manager().mark_round_joined(self.round)
        self.record_ready()

    @staticmethod
    def _seed_round_env(spec: dict, my_slot: dict) -> None:
        """Seed the new round's worker contract (into the loopback rank
        overlay on rank threads, else the process env)."""
        env = {
            envs.RANK: my_slot["rank"],
            envs.SIZE: spec["world_size"],
            envs.LOCAL_RANK: my_slot["local_rank"],
            envs.LOCAL_SIZE: my_slot["local_size"],
            envs.CROSS_RANK: my_slot["cross_rank"],
            envs.CROSS_SIZE: my_slot["cross_size"],
            envs.PROCESS_ID: my_slot["rank"],
            envs.NUM_PROCESSES: spec["world_size"],
            envs.COORDINATOR_ADDR: spec["coord_addr"],
            envs.COORDINATOR_PORT: spec["coord_port"],
            # The round this worker now runs in: HVD_FAULT_SPEC at_round
            # filters and at_round-keyed churn schedules read it — the
            # spawn-time seed alone would go stale on the first re-form.
            envs.ELASTIC_ROUND: spec["round"],
        }
        for name, value in env.items():
            envs.set_env(name, value)


_worker_rendezvous: WorkerRendezvous | None = None


def get_worker_rendezvous() -> WorkerRendezvous:
    """The per-worker rendezvous handle — per loopback rank context on
    rank threads (each rank is its own elastic worker), else the
    process-wide singleton."""
    from ..loopback import context as _lbctx
    ctx = _lbctx.current()
    if ctx is not None:
        if ctx.worker_rendezvous is None:
            ctx.worker_rendezvous = WorkerRendezvous()
        return ctx.worker_rendezvous
    global _worker_rendezvous
    if _worker_rendezvous is None:
        _worker_rendezvous = WorkerRendezvous()
    return _worker_rendezvous
