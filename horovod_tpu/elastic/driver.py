"""Elastic driver: host discovery loop, round management, worker lifecycle.

TPU-native rebuild of ``/root/reference/horovod/runner/elastic/driver.py``.
The reference coordinates resets through a worker-count barrier inside
``WorkerStateRegistry`` plus a gloo re-rendezvous; here the protocol is a
monotonically increasing **round** published through the launcher's HTTP KV
store:

1. The discovery thread polls the host set (1 s). On any change — or on a
   worker failure recorded by the registry — the driver computes the next
   host assignment (honoring ``min_np``/``max_np`` and the blacklist),
   publishes round ``R+1`` (slot table + fresh ``jax.distributed``
   coordinator address) to the KV, and notifies workers.
2. Existing workers hit the notification at their next ``state.commit()``,
   raise :class:`HostsUpdatedInterrupt`, fetch round ``R+1``, and
   re-initialize the jax world against the new coordinator.
3. The driver spawns worker processes for newly assigned slots and
   terminates processes whose slot disappeared; a worker whose slot is gone
   self-exits with :data:`SLOT_LOST_EXIT_CODE`.

Rank 0 stays on the oldest surviving host (``HostManager`` ordering), so the
post-reset ``state.sync()`` broadcast always originates from a worker holding
committed state (reference asserts the same invariant,
``driver.py:246-252``).
"""

from __future__ import annotations

import pickle
import random
import threading
import time

from .. import checkpoint as _checkpoint
from ..runner import hosts as hosts_mod
from ..utils import envs
from ..utils import faults as _faults
from ..utils import logging as hvd_logging
from ..utils import retry as _retry
from .discovery import HostManager
from .registration import WorkerStateRegistry
from .state import HostUpdateResult

DISCOVER_HOSTS_FREQUENCY_S = 1.0
DEFAULT_ELASTIC_TIMEOUT_S = 600
# A worker exits with this code when its slot vanished in a resize: a clean,
# expected exit that must be ignored by the registry.
SLOT_LOST_EXIT_CODE = 66

# Canonical KV key layout for the elastic protocol. Every module (driver,
# worker rendezvous, notification poller, launcher observer) must use these
# helpers — the formats are not duplicated anywhere else.
ROUND_KEY = "elastic/round"
ROUND_SPEC_KEY = "elastic/round/{}"
NOTIFY_KEY = "elastic/notify"
STOP_KEY = "elastic/stop"
READY_KEY_PREFIX = "elastic/ready/"


DONE_KEY_PREFIX = "elastic/done/"


def ready_key(round_id: int, host: str, slot: int) -> str:
    return f"{READY_KEY_PREFIX}{round_id}/{host}/{slot}"


def done_key(host: str, slot: int) -> str:
    return f"{DONE_KEY_PREFIX}{host}/{slot}"


def parse_done_key(key: str) -> tuple[str, int] | None:
    """Return (host, slot) if ``key`` records a completed worker, else None.

    Workers PUT this the moment their training function returns — *before*
    any jax teardown — so job success is decided by reaching the end of
    training, not by the process exit code (the distributed-runtime
    teardown can fatally race when the coordinator process exits first)."""
    if not key.startswith(DONE_KEY_PREFIX):
        return None
    parts = key[len(DONE_KEY_PREFIX):].split("/")
    if len(parts) != 2:
        return None
    try:
        return parts[0], int(parts[1])
    except ValueError:
        return None


def parse_ready_key(key: str) -> tuple[str, int] | None:
    """Return (host, slot) if ``key`` is a readiness record, else None."""
    if not key.startswith(READY_KEY_PREFIX):
        return None
    parts = key[len(READY_KEY_PREFIX):].split("/")
    if len(parts) != 3:
        return None
    _round_id, host, slot = parts
    try:
        return host, int(slot)
    except ValueError:
        return None


def _slot_to_dict(s: hosts_mod.SlotInfo) -> dict:
    return {"hostname": s.hostname, "rank": s.rank, "size": s.size,
            "local_rank": s.local_rank, "local_size": s.local_size,
            "cross_rank": s.cross_rank, "cross_size": s.cross_size}


def slot_from_dict(d: dict) -> hosts_mod.SlotInfo:
    return hosts_mod.SlotInfo(**d)


class ElasticRendezvous:
    """Round publication over the launcher-side KV server (the analog of the
    reference's ``ElasticRendezvousServer``)."""

    def __init__(self, kv_server):
        self.kv = kv_server
        self._round = 0

    @property
    def round_id(self) -> int:
        return self._round

    def publish_round(self, slots: list[hosts_mod.SlotInfo],
                      coord_addr: str, coord_port: int,
                      update_res: HostUpdateResult) -> int:
        self._round += 1
        spec = {
            "round": self._round,
            "coord_addr": coord_addr,
            "coord_port": coord_port,
            "world_size": len(slots),
            "slots": [_slot_to_dict(s) for s in slots],
        }
        # A new round makes any pending checkpoint shard hand-off keys
        # stale by definition (the peer-restore KV fallback channel,
        # docs/checkpoint.md): a transfer interrupted by the very churn
        # that triggered this round must not be mistaken for the re-run.
        try:
            self.kv.delete(_checkpoint.PEER_KEY_PREFIX.rstrip("/"))
        except Exception:  # hvdlint: disable=silent-except
            pass  # GC is best-effort; keys are also deleted per-tag
        # Order matters: workers wait on ROUND_KEY, so the spec must be
        # readable before the round number advances.
        self.kv.put(ROUND_SPEC_KEY.format(self._round), pickle.dumps(spec))
        self.kv.put(ROUND_KEY, str(self._round).encode())
        if self._round > 1:
            # The round id doubles as the notification timestamp: strictly
            # increasing, so back-to-back rounds can never collide the way
            # wall-clock stamps can.
            self.kv.put(NOTIFY_KEY,
                        pickle.dumps((self._round, int(update_res))))
        return self._round

    def stop(self) -> None:
        self.kv.put(STOP_KEY, b"1")


class Results:
    def __init__(self, error_message, worker_results):
        self.error_message = error_message
        self.worker_results = worker_results


class ElasticDriver:
    """Drives an elastic job (reference ``ElasticDriver``)."""

    def __init__(self, rendezvous: ElasticRendezvous, discovery,
                 min_np: int, max_np: int | None = None,
                 timeout: float | None = None, reset_limit: int | None = None,
                 cooldown_range=None, verbose: int = 0,
                 remote_port_probe=None):
        self._rendezvous = rendezvous
        # Optional callable(host) -> free port on that host (over ssh);
        # falls back to a random pick when absent or failing.
        self._remote_port_probe = remote_port_probe
        self._host_manager = HostManager(discovery, cooldown_range)
        self._min_np = min_np
        self._max_np = max_np
        self._verbose = verbose
        self._timeout = timeout or envs.get_int(
            envs.ELASTIC_TIMEOUT, DEFAULT_ELASTIC_TIMEOUT_S)

        self._host_assignments: dict[str, list[hosts_mod.SlotInfo]] = {}
        self._rank_assignments: dict[int, hosts_mod.SlotInfo] = {}
        self._world_size = 0

        self._wait_hosts_cond = threading.Condition()
        # host -> grace seconds for the NEXT time its worker goes stale
        # (scripted preemption: the departing worker drains + exits via
        # the slot-lost path inside the window instead of being torn
        # down mid-collective). HVD_ELASTIC_GRACE is the default for
        # hosts without an explicit entry (0 = today's immediate kill).
        self._stale_grace: dict[str, float] = {}
        # Serializes round transitions: _activate_workers can be entered from
        # the discovery thread (host change) and from worker-exit waiter
        # threads (registry resume) concurrently; rounds must be atomic.
        self._round_lock = threading.RLock()
        self._create_worker_fn = None
        self._active_procs: dict[tuple[str, int], object] = {}
        self._proc_lock = threading.Lock()
        self._success = False

        # Host updates that arrived while a round transition held
        # _round_lock; only touched by the discovery thread.
        self._deferred_update = HostUpdateResult.no_update

        self._worker_registry = WorkerStateRegistry(
            self, self._host_manager, reset_limit=reset_limit)
        self._error_message: str | None = None
        self._worker_results: dict[str, tuple[int, float]] = {}
        self._result_threads: list[threading.Thread] = []
        self._shutdown = threading.Event()

        self._discovery_thread = threading.Thread(
            target=self._discover_hosts, daemon=True, name="hvd-elastic-disco")
        self._discovery_thread.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self, np: int, create_worker_fn) -> None:
        """Begin the job: wait for ``np`` slots and launch the first round.

        ``create_worker_fn(slot_info, round_spec)`` must spawn the worker
        process and return a handle with ``wait()/poll()/terminate()``.
        """
        self._create_worker_fn = create_worker_fn
        self._activate_workers(np)

    def resume(self) -> None:
        """Start a new round after failures/blacklisting (registry hook).
        A late failure record landing after the job already stopped
        (e.g. a scripted-churn host's watchdog report racing success
        teardown) must not resurrect the round machinery — resuming a
        shut-down job raised from wait_for_available_slots and turned a
        finished job into an error."""
        if self._shutdown.is_set():
            hvd_logging.debug("ignoring resume after shutdown")
            return
        self._activate_workers(self._min_np)

    def stop(self, error_message: str | None = None,
             success: bool = False) -> None:
        if error_message:
            self._error_message = error_message
        if success:
            self._success = True
        if self._shutdown.is_set():
            return
        self._shutdown.set()
        self._rendezvous.stop()
        with self._wait_hosts_cond:
            self._wait_hosts_cond.notify_all()
        if not success:
            # Failure: tear everything down now. On success, workers are
            # left to exit naturally (they may still be saving checkpoints
            # or running post-training work after recording done);
            # ``join`` terminates stragglers after a grace period.
            self._terminate_active()

    def _terminate_active(self) -> None:
        with self._proc_lock:
            procs = list(self._active_procs.values())
        for p in procs:
            if p.poll() is None:
                p.terminate()

    def finished(self) -> bool:
        return self._shutdown.is_set()

    GRACE_PERIOD_S = 60.0

    def join(self) -> None:
        """Block until the job stops and all exit handlers ran. After a
        success-stop, workers get :data:`GRACE_PERIOD_S` to finish their
        post-training work before stragglers are terminated."""
        while not self._shutdown.wait(0.2):
            pass
        done = False
        with self._proc_lock:
            done = not self._active_procs
        if not done:
            for _ in _retry.poll_intervals("elastic.grace", interval_s=0.2,
                                           deadline_s=self.GRACE_PERIOD_S):
                with self._proc_lock:
                    if not self._active_procs:
                        break
        self._terminate_active()
        for t in list(self._result_threads):
            t.join(timeout=30)
        self._discovery_thread.join(timeout=5)

    def get_results(self) -> Results:
        return Results(self._error_message, dict(self._worker_results))

    @property
    def succeeded(self) -> bool:
        """True when the job stopped because a worker completed successfully
        — failures in *earlier* rounds that elastic recovery absorbed do not
        count against the job."""
        return self._success

    # -- queries (reference driver API) ------------------------------------

    def world_size(self) -> int:
        return self._world_size

    def local_size(self, host: str) -> int:
        return len(self._host_assignments.get(host, []))

    def get_slot_info(self, host: str, slot: int):
        if not self.has_rank_assignment(host, slot):
            return None
        return self._host_assignments[host][slot]

    def get_coordinator_info(self):
        return self._rank_assignments.get(0)

    def has_rank_assignment(self, host: str, slot: int) -> bool:
        if self._host_manager.is_blacklisted(host):
            return False
        return (host in self._host_assignments
                and len(self._host_assignments[host]) > slot)

    @property
    def host_assignments(self):
        return self._host_assignments

    @property
    def registry(self) -> WorkerStateRegistry:
        return self._worker_registry

    def record_ready(self, host: str, slot: int) -> None:
        self._worker_registry.record_ready(host, slot)

    # -- internals ---------------------------------------------------------

    def wait_for_available_slots(self, min_np: int, min_hosts: int = 1):
        deadline = time.monotonic() + self._timeout
        with self._wait_hosts_cond:
            while True:
                current_hosts = self._host_manager.current_hosts
                if (current_hosts.count_available_slots() >= min_np
                        and len(current_hosts.available_hosts) >= min_hosts):
                    return current_hosts
                if self._shutdown.is_set():
                    raise RuntimeError(
                        "job has been shut down, see above errors")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"timed out waiting for at least {min_np} slots "
                        f"on {min_hosts}+ hosts; only "
                        f"{current_hosts.count_available_slots()} available")
                self._wait_hosts_cond.wait(min(remaining, 1.0))

    def _activate_workers(self, min_np: int) -> None:
        with self._round_lock:
            hvd_logging.info("elastic: waiting for %d+ slots", min_np)
            current_hosts = self.wait_for_available_slots(min_np)
            update_res, pending, stale = self._update_host_assignments(
                current_hosts)
            self._worker_registry.reset(self.world_size())
            self._stop_stale_workers(stale)
            self._start_worker_processes(pending)

    def _discover_hosts(self) -> None:
        first_update = True
        while not self._shutdown.is_set():
            with self._wait_hosts_cond:
                try:
                    update_res = self._host_manager.update_available_hosts()
                except Exception as e:
                    # Catch everything: a transiently malformed discovery
                    # output (e.g. ValueError from int()) must not kill the
                    # discovery thread and freeze elasticity.
                    if first_update:
                        hvd_logging.error("initial host discovery failed: %s",
                                          e)
                        self._error_message = str(e)
                        self._shutdown.set()
                        self._wait_hosts_cond.notify_all()
                        return
                    hvd_logging.warning("host discovery failed: %s", e)
                    update_res = HostUpdateResult.no_update
                if update_res != HostUpdateResult.no_update:
                    self._wait_hosts_cond.notify_all()
            pending = update_res | self._deferred_update
            if (pending != HostUpdateResult.no_update and not first_update
                    and self._create_worker_fn is not None):
                self._on_hosts_updated(pending)
            first_update = False
            self._shutdown.wait(DISCOVER_HOSTS_FREQUENCY_S)

    def _on_hosts_updated(self, update_res: HostUpdateResult) -> None:
        """Host set changed mid-run: open a new round if assignments move.

        Runs on the discovery thread; any unexpected error here must stop
        the job loudly rather than silently killing the thread (a dead
        discovery thread would freeze elasticity for the rest of the run).
        """
        # The assignment comparison must run under the round lock: a
        # concurrent registry-driven resume() may be publishing a round for
        # this very host change, and comparing against stale assignments
        # would publish a redundant duplicate round. But the acquire must
        # not block: a resume() parked in wait_for_available_slots (slots <
        # min_np) holds the lock while *depending on this thread* to keep
        # discovering replacement hosts — blocking here would deadlock the
        # scale-down-then-replace scenario. Defer instead and retry on the
        # next discovery tick.
        if not self._round_lock.acquire(blocking=False):
            self._deferred_update |= update_res
            return
        stop_error = None
        try:
            self._deferred_update = HostUpdateResult.no_update
            try:
                current_hosts = self._host_manager.current_hosts
                if current_hosts.count_available_slots() < self._min_np:
                    hvd_logging.warning(
                        "hosts changed but fewer than min_np=%d slots "
                        "available; waiting", self._min_np)
                    return
                try:
                    next_assignments = self._compute_assignments(
                        current_hosts)
                except ValueError as e:
                    hvd_logging.warning("cannot assign hosts yet: %s", e)
                    return
                if {h: [s.rank for s in slots]
                        for h, slots in next_assignments[0].items()} == \
                        {h: [s.rank for s in slots]
                         for h, slots in self._host_assignments.items()}:
                    hvd_logging.debug(
                        "host change does not alter assignments")
                    return
                self._activate_workers(self._min_np)
            except Exception as e:
                hvd_logging.exception("failed to apply host update")
                stop_error = f"host update failed: {e}"
        finally:
            self._round_lock.release()
        if stop_error is not None:
            # stop() tears down worker processes (seconds of grace time per
            # proc) — never do that while holding the round lock.
            self.stop(error_message=stop_error)

    def _compute_assignments(self, current_hosts):
        host_list = [hosts_mod.HostSpec(h, current_hosts.get_slots(h))
                     for h in current_hosts.host_assignment_order]
        assignment_list = hosts_mod.elastic_host_assignments(
            host_list, self._min_np, self._max_np)
        by_host: dict[str, list[hosts_mod.SlotInfo]] = {}
        for slot_info in assignment_list:
            by_host.setdefault(slot_info.hostname, []).append(slot_info)
        return by_host, assignment_list

    def _update_host_assignments(self, current_hosts):
        active = set(self._active_slots())
        by_host, assignment_list = self._compute_assignments(current_hosts)

        if self._host_assignments:
            prev_hosts = set(self._host_assignments)
            if not prev_hosts & set(by_host):
                raise RuntimeError(
                    "no hosts from the previous round remain; committed "
                    "state cannot be broadcast to the new workers")

        prev_world = self._world_size
        self._host_assignments = by_host
        self._rank_assignments = {s.rank: s for s in assignment_list}
        self._world_size = len(assignment_list)

        update_res = HostUpdateResult.no_update
        if self._world_size > prev_world:
            update_res |= HostUpdateResult.added
        if prev_world and self._world_size < prev_world:
            update_res |= HostUpdateResult.removed
        if prev_world and self._world_size == prev_world:
            update_res |= HostUpdateResult.mixed

        coord_host = assignment_list[0].hostname
        coord_addr, coord_port = self._coordinator_endpoint(coord_host)
        self._current_spec_round = self._rendezvous.publish_round(
            assignment_list, coord_addr, coord_port, update_res)

        assigned = {(s.hostname, s.local_rank) for s in assignment_list}
        pending = [s for s in assignment_list
                   if (s.hostname, s.local_rank) not in active]
        stale = [key for key in active if key not in assigned]
        return update_res, pending, stale

    def _coordinator_endpoint(self, coord_host: str) -> tuple[str, int]:
        from ..runner.launch import _free_port, is_local_host
        from ..runner.http_kv import local_addresses
        if is_local_host(coord_host):
            addr = "127.0.0.1" if all(
                is_local_host(h) for h in self._host_assignments) else \
                local_addresses()[0]
            return addr, _free_port()
        # Remote coordinator: ask that host's kernel for a free ephemeral
        # port over ssh; a blind random pick risks a collision that fails
        # the rank-0 worker and blacklists the very host holding committed
        # state. Random fallback only if the probe itself fails.
        if self._remote_port_probe is not None:
            try:
                return coord_host, int(self._remote_port_probe(coord_host))
            except Exception as e:
                hvd_logging.warning(
                    "free-port probe on %s failed (%s); falling back to a "
                    "random port", coord_host, e)
        return coord_host, random.randint(29500, 64000)

    def _active_slots(self):
        with self._proc_lock:
            return list(self._active_procs.keys())

    def set_stale_grace(self, host: str, grace_s: float) -> None:
        """Grant ``host``'s worker a clean-exit window the next time its
        slot disappears (graceful preemption, docs/elastic.md): the
        worker keeps participating until the host-change interrupt lands
        at its commit boundary and then self-exits slot-lost — so a
        scheduled departure loses zero steps instead of the abrupt
        mid-collective kill's <=1."""
        self._stale_grace[host] = float(grace_s)

    def _stop_stale_workers(self, stale_keys) -> None:
        for key in stale_keys:
            with self._proc_lock:
                proc = self._active_procs.get(key)
            if proc is None or proc.poll() is not None:
                continue
            grace = self._stale_grace.pop(
                key[0], envs.get_float(envs.ELASTIC_GRACE, 0.0))
            if grace <= 0:
                hvd_logging.info("terminating worker %s[%d]: slot removed",
                                 *key)
                proc.terminate()
                continue
            hvd_logging.info(
                "worker %s[%d] slot removed; granting %.1fs to exit "
                "cleanly (preemption grace)", key[0], key[1], grace)

            def deferred(proc=proc, key=key, grace=grace):
                for _ in _retry.poll_intervals("elastic.stale-grace",
                                               interval_s=0.2,
                                               deadline_s=grace):
                    if proc.poll() is not None or self._shutdown.is_set():
                        return
                if proc.poll() is None:
                    hvd_logging.warning(
                        "worker %s[%d] did not exit within its %.1fs "
                        "preemption grace; terminating", key[0], key[1],
                        grace)
                    proc.terminate()

            t = threading.Thread(target=deferred, daemon=True,
                                 name=f"hvd-elastic-grace-{key[0]}")
            t.start()
            self._result_threads.append(t)

    def _start_worker_processes(self, pending_slots) -> None:
        spec_round = self._rendezvous.round_id
        for slot_info in pending_slots:
            hvd_logging.info("starting worker %s[%d] (rank %d, round %d)",
                             slot_info.hostname, slot_info.local_rank,
                             slot_info.rank, spec_round)
            self._start_worker_process(slot_info, spec_round)

    def record_peer_failure(self, dead_rank: int, reason: str,
                            round_id: int = -1) -> None:
        """A surviving worker's health watchdog reported ``dead_rank``
        dead (poison/beat-timeout record on the launcher KV, parsed by
        the bootstrap PUT observer): convert the coordinated abort into
        a registry failure so the dead host is blacklisted and
        :meth:`resume` re-forms the round NOW — without waiting for the
        dead process to be reaped by its exit waiter.

        ``round_id`` is the round the REPORTER was in. Global ranks
        renumber every round, so a report from a superseded round must
        be resolved against THAT round's slot table — resolving it
        against the newest one can blacklist an innocent replacement
        worker that inherited the dead rank's number (seen under
        scripted churn: a removed host's watchdog-detected death arrived
        after its slot had already been reassigned)."""
        slot = self._rank_assignments.get(dead_rank)
        current_round = self._rendezvous.round_id
        if round_id >= 0 and round_id != current_round:
            stale_slot = self._slot_in_round(round_id, dead_rank)
            if stale_slot is None or slot is None \
                    or stale_slot.hostname != slot.hostname:
                hvd_logging.info(
                    "ignoring stale peer-failure report for rank %d of "
                    "round %d (now round %d): %s — the host already left "
                    "the assignment", dead_rank, round_id, current_round,
                    reason)
                return
        if slot is None:
            hvd_logging.warning(
                "peer-failure report for unassigned rank %d (%s); ignoring",
                dead_rank, reason)
            return
        hvd_logging.error(
            "worker %s[%d] (rank %d) reported dead by a peer watchdog: %s",
            slot.hostname, slot.local_rank, dead_rank, reason)
        # From a fresh thread, like a process-exit waiter: this is called
        # by the KV server's PUT observer, and the resume() a failure can
        # trigger may block on slot availability — the reporting worker's
        # PUT must not hang on it.
        t = threading.Thread(
            target=self._worker_registry.record_failure,
            args=(slot.hostname, slot.local_rank),
            daemon=True, name=f"hvd-elastic-peerfail-{dead_rank}")
        t.start()
        self._result_threads.append(t)

    def _slot_in_round(self, round_id: int, rank: int):
        """Slot assignment of ``rank`` in a (possibly superseded) round,
        from the published round spec; None when unknown."""
        try:
            raw = self._rendezvous.kv.get(ROUND_SPEC_KEY.format(round_id))
            if raw is None:
                return None
            spec = pickle.loads(raw)
            for s in spec["slots"]:
                if s["rank"] == rank:
                    return slot_from_dict(s)
        except Exception as e:
            hvd_logging.debug("round-%d spec lookup failed: %s", round_id, e)
        return None

    def _start_worker_process(self, slot_info, spec_round: int) -> None:
        try:
            _faults.inject("worker.launch", rank=slot_info.rank)
            proc = self._create_worker_fn(slot_info, spec_round)
        except Exception as e:
            # A failed spawn used to unwind the whole round transition;
            # treat it like an instant worker failure instead — the
            # registry blacklists the host and resumes with the rest.
            # Recorded from a fresh thread, exactly like an exit-waiter
            # thread would, so the (re-entrant) round lock the caller
            # holds is not re-acquired deeper on this stack.
            hvd_logging.error("failed to start worker %s[%d]: %s",
                              slot_info.hostname, slot_info.local_rank, e)
            t = threading.Thread(
                target=self._worker_registry.record_failure,
                args=(slot_info.hostname, slot_info.local_rank),
                daemon=True, name=f"hvd-elastic-spawnfail-{slot_info.rank}")
            t.start()
            self._result_threads.append(t)
            return
        key = (slot_info.hostname, slot_info.local_rank)
        with self._proc_lock:
            self._active_procs[key] = proc

        def waiter():
            exit_code = proc.wait()
            with self._proc_lock:
                if self._active_procs.get(key) is proc:
                    del self._active_procs[key]
            self._handle_worker_exit(slot_info, exit_code)

        t = threading.Thread(target=waiter, daemon=True,
                             name=f"hvd-elastic-wait-{slot_info.rank}")
        t.start()
        self._result_threads.append(t)

    def _handle_worker_exit(self, slot_info, exit_code: int) -> None:
        timestamp = time.time()
        name = f"{slot_info.hostname}[{slot_info.local_rank}]"
        if exit_code == SLOT_LOST_EXIT_CODE:
            hvd_logging.debug("worker %s exited: slot removed", name)
            return
        if not self.has_rank_assignment(slot_info.hostname,
                                        slot_info.local_rank):
            hvd_logging.debug("ignoring exit of unassigned worker %s", name)
            return
        if self.finished() and exit_code != 0:
            # Non-zero exit after the job already stopped is almost always
            # the driver's own SIGTERM during teardown, not a failure.
            hvd_logging.debug("ignoring post-shutdown exit of %s (%d)",
                              name, exit_code)
            return
        self._worker_results.setdefault(name, (exit_code, timestamp))
        if exit_code == 0:
            self._worker_registry.record_success(slot_info.hostname,
                                                 slot_info.local_rank)
        else:
            self._worker_registry.record_failure(slot_info.hostname,
                                                 slot_info.local_rank)
