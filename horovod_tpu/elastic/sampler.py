"""Elastic-aware dataset sampler.

TPU-native rebuild of the reference's ``ElasticSampler``
(``/root/reference/horovod/torch/elastic/sampler.py:1-122``): partitions a
dataset's indices across ranks, tracks how many samples the epoch has
consumed, and repartitions the *remaining* indices over the new world
after an elastic reset — so a grown/shrunk job finishes the epoch without
reprocessing or skipping samples.

Usage with :class:`horovod_tpu.elastic.State`::

    sampler = hvd.elastic.ElasticSampler(len(dataset))
    state = hvd.elastic.ObjectState(sampler=sampler.state_dict(), ...)
    for epoch ...:
        for batch_idx in batches_of(sampler.local_indices(), batch):
            ...
            sampler.record_batch(per_rank_batch_size)
            state.sampler = sampler.state_dict()
            state.commit()
        sampler.set_epoch(epoch + 1)

After a reset, restore with ``sampler.load_state_dict(state.sampler)`` —
``reset()`` re-reads the (new) world size/rank from the runtime.
"""

from __future__ import annotations

import math
import random

from .. import runtime


class ElasticSampler:
    """Deterministic cross-rank index partitioner with processed-sample
    tracking (framework-free: yields plain integer indices)."""

    def __init__(self, dataset_size: int, shuffle: bool = True,
                 seed: int = 0):
        self.dataset_size = int(dataset_size)
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_num = 0
        self.reset()

    # -- epoch / progress --------------------------------------------------

    def set_epoch(self, epoch: int) -> None:
        """Advance to ``epoch`` and clear processed tracking. Call at the
        END of each epoch so partially completed epochs are not
        reprocessed (reference ``sampler.py:61-76``)."""
        self.epoch = epoch
        self.processed_num = 0
        self.reset()

    def record_batch(self, batch_size: int) -> None:
        """Account one processed per-process batch (every data-feeding
        process consumed ``batch_size`` samples this step)."""
        self.processed_num += int(batch_size) * self.num_replicas

    # -- elastic state -----------------------------------------------------

    def state_dict(self) -> dict:
        return dict(epoch=self.epoch, processed_num=self.processed_num)

    def load_state_dict(self, state: dict) -> None:
        self.epoch = state["epoch"]
        self.processed_num = state["processed_num"]
        self.reset()

    def reset(self) -> None:
        """Repartition the unprocessed indices over the current world
        (called automatically after load_state_dict/set_epoch; the elastic
        reset path restores state then continues with the new size).

        The partition unit is the data-feeding *process*, not the chip: in
        the SPMD model one process materializes its whole local batch and
        the mesh sharding spreads it over that process's chips (the
        reference's 1-GPU-per-process sampler generalizes this way)."""
        self.num_replicas = (runtime.process_count()
                             if runtime.is_initialized() else 1)
        self.rank = (runtime.process_rank()
                     if runtime.is_initialized() else 0)
        indices = list(range(self.dataset_size))
        if self.shuffle:
            random.Random(self.seed + self.epoch).shuffle(indices)
        self.remaining_indices = indices[self.processed_num:]
        self.num_samples = int(
            math.ceil(len(self.remaining_indices) / max(self.num_replicas, 1)))
        self.total_size = self.num_samples * self.num_replicas

    # -- iteration ---------------------------------------------------------

    def local_indices(self) -> list:
        """This process's indices for the rest of the epoch (padded
        cyclically so every process yields the same count — SPMD steps
        stay aligned)."""
        indices = list(self.remaining_indices)
        if not indices:
            return []
        reps = -(-self.total_size // len(indices))  # ceil: full cyclic pad
        indices = (indices * reps)[:self.total_size]
        return indices[self.rank:self.total_size:self.num_replicas]

    def __iter__(self):
        return iter(self.local_indices())

    def __len__(self) -> int:
        return self.num_samples
