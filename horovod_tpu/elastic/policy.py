"""Closed-loop elastic autoscaling policy (``HVD_AUTOSCALE``).

PR 14 made membership churn a *scripted*, measured scenario
(``worker:add/remove/preempt`` in the fault grammar); this module closes
the loop: the same membership actions are now chosen by a driver-side
controller reading the metrics registry as its sensor suite
(docs/elastic.md "Autoscaler"). Two halves:

* **Observer** (worker side, every rank) — hooked into
  ``State.commit()``: measures commit-to-commit step time, records it
  into the registry (``hvd_elastic_step_seconds`` /
  ``hvd_elastic_slo_violations_total``), and about twice per policy
  window publishes a compact sensor blob to the launcher KV under
  ``autoscale/sensor/<rank>`` — SLO violation share, fusion
  pending-bytes, QoS admission-wait mean, and this rank's
  :func:`~horovod_tpu.health.straggler_blames` deltas. Publishing is
  windowed *deltas* of registry snapshots, so the driver never has to
  reconcile counters across re-forms (ranks renumber per round; a blob
  is only meaningful inside the round it names).

* **Policy** (driver side) — :class:`AutoscalePolicy`, one daemon
  thread evaluating every ``HVD_AUTOSCALE_INTERVAL`` seconds:

  - **scale-up** when the mean SLO-violation share across reporting
    ranks exceeds half for ``HVD_AUTOSCALE_BREACH_WINDOWS``
    *consecutive* windows and the world is under the ceiling — a fresh
    host joins discovery and the driver grows the world at its next
    poll;
  - **scale-down** when *every* current rank reports a sustained-idle
    window (mean step time under ``HVD_AUTOSCALE_IDLE_FACTOR`` x SLO,
    zero violations, no queued backpressure) for
    ``HVD_AUTOSCALE_IDLE_WINDOWS`` consecutive windows and the world is
    above the floor — the newest (highest-rank) host gets the PR-14
    grace window and leaves through the slot-lost path: a policy
    scale-down loses **zero** steps, exactly like a scripted
    ``preempt``;
  - **evict-and-replace** when the aggregated straggler blames name the
    same global rank for ``HVD_AUTOSCALE_EVICT_WINDOWS`` consecutive
    windows — the slow-not-dead case the watchdog cannot touch: the
    blamed rank's host departs gracefully (grace window, zero steps
    lost) while a replacement host joins in the same discovery tick, so
    the world re-forms once at the same size and the replacement adopts
    the shape-keyed warm shelves (docs/elastic.md "Warm re-form").

**Robustness is the contract.** Decisions are driver-authoritative (no
rank ever branches on policy output — hvdlint pass 7 taints the policy
state exactly like ``rank()``), and **round-tagged**: a decision
evaluated against round R re-validates the round *and* the victim's
assignment at apply time, so an eviction racing a re-form — or blaming
a rank that just left — degrades to a counted ``hold``/``stale-round``
no-op instead of removing an innocent successor. Hysteresis (consecutive
-window streaks with an idle/breach dead band between the thresholds),
a post-decision cooldown, and the min/max world bounds jointly bound
oscillation: an adversarial load flapping faster than the streak
requirement produces **zero** membership changes (tested, and gated by
``bench.py --autoscale-bench``'s flapping phase). A policy-evaluation
error of any kind degrades to "hold current world" with a typed
:class:`PolicyEvalError` warning — never a job failure — and every
decision (including holds) lands in
``hvd_elastic_policy_decisions_total{action,reason,rank}`` plus an
``AUTOSCALE.<action>.<reason>`` timeline instant, so a postmortem can
replay exactly why the world changed.
"""

from __future__ import annotations

import contextlib
import json
import weakref

from .. import health as _health
from .. import metrics as _metrics
from .. import timeline as _timeline
from ..loopback import context as _lbctx
from ..utils import envs
from ..utils import faults as _faults
from ..utils import invariants as _inv
from ..utils import logging as hvd_logging

SENSOR_KEY_PREFIX = "autoscale/sensor/"


class PolicyEvalError(RuntimeError):
    """A policy evaluation window failed (sensor read, aggregation, or
    actuation error). Never propagated into the job: the tick that
    raised it records a ``hold``/``error`` decision and the next window
    starts clean — an autoscaler bug must cost capacity agility, not
    the training run."""


def sensor_key(rank: int) -> str:
    return f"{SENSOR_KEY_PREFIX}{rank}"


# ---------------------------------------------------------------------------
# worker-side observer (the State.commit hook)
# ---------------------------------------------------------------------------

class CommitObserver:
    """One rank's sensor half: step timing at every commit, a sensor
    blob roughly twice per policy window (so the driver always has a
    fresh window to read). All values are windowed deltas of this
    rank's own registry store."""

    def __init__(self):
        self.rank = envs.get_int(envs.RANK, -1)
        self.slo_s = envs.autoscale_slo_s()
        self.interval_s = envs.autoscale_interval_s()
        self._last_commit_t: float | None = None
        self._last_publish_t = 0.0
        self._seq = 0
        self._steps = 0
        self._violations = 0
        self._step_s_sum = 0.0
        self._prev_blames: dict[int, int] = {}
        self._prev_qos: tuple[float, int] = (0.0, 0)
        self._prev_recovery: tuple[float, int] = (0.0, 0)
        self._client = None
        self._client_failed = False

    def _kv(self):
        if self._client is None and not self._client_failed:
            addr = envs.get(envs.KV_ADDR)
            if not addr:
                self._client_failed = True
                return None
            try:
                from ..runner.http_kv import KVClient
                self._client = KVClient(addr,
                                        envs.get_int(envs.KV_PORT, 0),
                                        secret=envs.get(envs.SECRET_KEY))
            except Exception as e:
                self._client_failed = True
                hvd_logging.warning(
                    "autoscale observer: KV client unavailable (%s); "
                    "sensors off for this worker", e)
        return self._client

    def note(self) -> None:
        """One ``State.commit()`` boundary on this rank's thread."""
        now = _inv.monotonic()
        prev = self._last_commit_t
        self._last_commit_t = now
        if prev is None:
            self._last_publish_t = now  # window starts at the 1st commit
            return
        dt = now - prev
        _metrics.ELASTIC_STEP_SECONDS.observe(dt)
        self._steps += 1
        self._step_s_sum += dt
        if self.slo_s > 0 and dt > self.slo_s:
            self._violations += 1
            _metrics.ELASTIC_SLO_VIOLATIONS.inc()
        if now - self._last_publish_t >= self.interval_s / 2.0:
            self._publish(now)

    def _publish(self, now: float) -> None:
        kv = self._kv()
        if kv is None:
            return
        blames = _health.straggler_blames()
        blame_delta = {r: c - self._prev_blames.get(r, 0)
                       for r, c in blames.items()
                       if c - self._prev_blames.get(r, 0) > 0}
        qos_sum, qos_count = _qos_wait_totals()
        d_sum = qos_sum - self._prev_qos[0]
        d_count = qos_count - self._prev_qos[1]
        rec_sum, rec_count = _recovery_totals()
        dr_sum = rec_sum - self._prev_recovery[0]
        dr_count = rec_count - self._prev_recovery[1]
        self._seq += 1
        blob = {
            "rank": envs.get_int(envs.RANK, self.rank),
            "round": envs.get_int(envs.ELASTIC_ROUND, -1),
            "seq": self._seq,
            "steps": self._steps,
            "violations": self._violations,
            "step_s_mean": (self._step_s_sum / self._steps
                            if self._steps else 0.0),
            "pending_bytes": float(_metrics.FUSION_PENDING_BYTES.value()),
            "qos_wait_s_mean": (d_sum / d_count if d_count else 0.0),
            # Measured recovery cost (re-form + state restore, windowed
            # delta): the scale-down brake's sensor — scaling down is
            # only worth it when the restore the next re-form will pay
            # stays inside the idle savings (docs/checkpoint.md).
            "restore_s_sum": dr_sum,
            "restore_count": dr_count,
            "straggler": {str(r): c for r, c in
                          sorted(blame_delta.items())},
        }
        self._prev_blames = blames
        self._prev_qos = (qos_sum, qos_count)
        self._prev_recovery = (rec_sum, rec_count)
        self._steps = 0
        self._violations = 0
        self._step_s_sum = 0.0
        self._last_publish_t = now
        try:
            kv.put(sensor_key(blob["rank"]), json.dumps(blob).encode())
        except Exception as e:
            # Sensor loss degrades the POLICY (it holds), never the job.
            hvd_logging.debug("autoscale sensor publish failed: %s", e)


def _qos_wait_totals() -> tuple[float, int]:
    """(sum_s, count) across this rank's QoS admission-wait series —
    the tail sensor collapses to a windowed mean at the observer."""
    total_s, total_n = 0.0, 0
    for _labels, h in _metrics.QOS_ADMISSION_WAIT.series().items():
        total_s += getattr(h, "sum", 0.0)
        total_n += getattr(h, "count", 0)
    return total_s, total_n


def _recovery_totals() -> tuple[float, int]:
    """(sum_s, count) across this rank's recovery-time series: the full
    re-form spans (catch -> re-rendezvous -> re-sync) plus the state
    restores measured by the checkpoint plane. Loopback ranks share one
    process registry, so the driver-side mean divides out the world."""
    total_s, total_n = 0.0, 0
    for hist in (_metrics.ELASTIC_REFORM_SECONDS,
                 _metrics.CKPT_RESTORE_SECONDS):
        for _labels, h in hist.series().items():
            total_s += getattr(h, "sum", 0.0)
            total_n += getattr(h, "count", 0)
    return total_s, total_n


# Per-world observer registry: one observer per loopback rank context
# (weak keys — a dead elastic round's contexts must not pin observers),
# one for a plain worker process. `False` caches "autoscale off" so the
# per-commit fast path is one dict probe.
_ctx_observers: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_process_observer: "CommitObserver | bool | None" = None


def note_commit() -> None:
    """The ``State.commit()`` seam: near-zero when ``HVD_AUTOSCALE`` is
    off (one registry probe + cached miss)."""
    ctx = _lbctx.current()
    if ctx is None:
        global _process_observer
        obs = _process_observer
        if obs is None:
            obs = _process_observer = (
                CommitObserver() if envs.autoscale_enabled() else False)
    else:
        obs = _ctx_observers.get(ctx)
        if obs is None:
            obs = (CommitObserver() if envs.autoscale_enabled()
                   else False)
            _ctx_observers[ctx] = obs
    if obs is not False:
        obs.note()


def reset_observer() -> None:
    """Drop the calling thread's observer (tests and worker teardown);
    the next commit re-reads the knob."""
    global _process_observer
    ctx = _lbctx.current()
    if ctx is None:
        _process_observer = None
    else:
        _ctx_observers.pop(ctx, None)


# ---------------------------------------------------------------------------
# driver-side policy
# ---------------------------------------------------------------------------

def _env_get(env: dict | None, name: str) -> str | None:
    """Knob lookup with a driver-side overlay: the elastic front ends
    pass the same ``extra_env`` dict they seed into worker overlays, so
    a job configured entirely through ``elastic_run(extra_env=...)``
    (the loopback/bench path — nothing touches ``os.environ``) drives
    the policy and the observers from ONE knob surface."""
    if env:
        for prefix in ("HVD_", "HOROVOD_"):
            v = env.get(prefix + name)
            if v is not None:
                return v
    return envs.get(name)


def _env_int(env, name, default: int) -> int:
    v = _env_get(env, name)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def _env_float(env, name, default: float) -> float:
    v = _env_get(env, name)
    try:
        return float(v) if v is not None else default
    except ValueError:
        return default


def _env_bool(env, name, default: bool = False) -> bool:
    v = _env_get(env, name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


class Decision:
    """One evaluated action, round-tagged at decision time."""

    __slots__ = ("action", "reason", "rank", "round_id", "detail", "t")

    def __init__(self, action: str, reason: str, round_id: int,
                 rank: int | None = None, detail: str = ""):
        self.action = action
        self.reason = reason
        self.rank = rank
        self.round_id = round_id
        self.detail = detail
        self.t = _inv.monotonic()

    def as_dict(self) -> dict:
        return {"action": self.action, "reason": self.reason,
                "rank": self.rank, "round": self.round_id,
                "detail": self.detail, "t": self.t}


class AutoscalePolicy:
    """The driver-side controller: sensors in, membership actions out.

    ``driver`` is the :class:`~horovod_tpu.elastic.driver.ElasticDriver`
    (round id, rank->host table, stale grace); ``hosts`` is the mutable
    discovery source (``FixedHosts``-shaped: ``add_hosts`` /
    ``remove_host``) the decisions actuate through — the same seam
    scripted churn mutates, so the driver's discovery loop applies
    policy output exactly like any other host change. ``kv`` is the
    driver-side KV server (direct in-memory reads)."""

    def __init__(self, driver, hosts, kv, *, min_np: int,
                 max_np: int | None = None, interval_s: float | None = None,
                 env: dict | None = None):
        self.driver = driver
        self.hosts = hosts
        self.kv = kv
        self.min_np = _env_int(env, envs.AUTOSCALE_MIN, min_np)
        self.max_np = _env_int(
            env, envs.AUTOSCALE_MAX,
            max_np if max_np is not None else min_np)
        self.interval_s = (interval_s if interval_s is not None
                           else _env_float(
                               env, envs.AUTOSCALE_INTERVAL,
                               envs.DEFAULT_AUTOSCALE_INTERVAL_S))
        self.slo_s = _env_float(env, envs.AUTOSCALE_SLO_MS, 0.0) / 1e3
        self.idle_factor = _env_float(
            env, envs.AUTOSCALE_IDLE_FACTOR,
            envs.DEFAULT_AUTOSCALE_IDLE_FACTOR)
        self.breach_windows = max(1, _env_int(
            env, envs.AUTOSCALE_BREACH_WINDOWS,
            envs.DEFAULT_AUTOSCALE_BREACH_WINDOWS))
        self.idle_windows = max(1, _env_int(
            env, envs.AUTOSCALE_IDLE_WINDOWS,
            envs.DEFAULT_AUTOSCALE_IDLE_WINDOWS))
        self.evict_windows = max(1, _env_int(
            env, envs.AUTOSCALE_EVICT_WINDOWS,
            envs.DEFAULT_AUTOSCALE_EVICT_WINDOWS))
        self.cooldown_s = _env_float(
            env, envs.AUTOSCALE_COOLDOWN, envs.DEFAULT_AUTOSCALE_COOLDOWN_S)
        self.grace_s = _env_float(env, envs.AUTOSCALE_GRACE,
                                  envs.DEFAULT_AUTOSCALE_GRACE_S)

        self._breach_streak = 0
        self._idle_streak = 0
        self._blame_rank: int | None = None
        self._blame_streak = 0
        # Running recovery-cost sensor (restore_s_sum/_count blob keys):
        # lifetime totals, because re-forms are rare events — a windowed
        # mean would usually be empty exactly when the remove decision
        # needs it.
        self._restore_s_sum = 0.0
        self._restore_count = 0
        self._cooldown_until = 0.0
        self._last_seq: dict[tuple[int, int], int] = {}
        self._added = 0
        self._evictions = 0
        # Decision log (most recent last) — the bench/tests read this;
        # the registry counter is the durable postmortem surface.
        self.decisions: list[Decision] = []
        self.last_decision: Decision | None = None
        self._mu = _inv.make_lock("elastic.policy.mu")
        self._stop = _inv.make_event("elastic.policy.stop")
        self._thread = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = _inv.spawn_thread(self._loop,
                                         name="hvd-autoscale-policy")

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            _inv.join_thread(t, timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    # -- one evaluation window ---------------------------------------------

    def tick(self) -> Decision | None:
        """Evaluate one window. Any error degrades to a counted hold —
        the robustness contract: a policy bug must never fail the job."""
        try:
            _faults.inject("policy.eval")
            return self._evaluate()
        except Exception as e:
            err = PolicyEvalError(
                f"autoscale policy evaluation failed ({type(e).__name__}: "
                f"{e}); holding current world")
            hvd_logging.warning("%s", err)
            return self._record(Decision(
                "hold", "error", self._round(), detail=str(e)))

    def _round(self) -> int:
        return self.driver._rendezvous.round_id

    def _read_sensors(self, round_id: int) -> list[dict]:
        """Fresh blobs for ``round_id``: sequence-advanced since the
        last window and tagged with the decision round (a stale round's
        blob describes ranks that may have renumbered)."""
        # Rounds are monotonic: sequence state for older rounds can
        # never be read again, so prune it (a long churn history must
        # not grow this dict one entry per (round, rank) forever).
        stale = [k for k in self._last_seq if k[0] != round_id]
        for k in stale:
            del self._last_seq[k]
        blobs = []
        for key in self.kv.keys(SENSOR_KEY_PREFIX.rstrip("/")):
            raw = self.kv.get(key)
            if raw is None:
                continue
            try:
                blob = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            if blob.get("round") != round_id:
                continue
            r, seq = int(blob.get("rank", -1)), int(blob.get("seq", 0))
            if seq <= self._last_seq.get((round_id, r), 0):
                continue
            self._last_seq[(round_id, r)] = seq
            blobs.append(blob)
        return blobs

    def _evaluate(self) -> Decision | None:
        now = _inv.monotonic()
        round_id = self._round()
        world = self.driver.world_size()
        blobs = self._read_sensors(round_id)
        if not blobs:
            return None  # nothing fresh: not a window, streaks hold

        # -- sensor aggregation (one window) --
        viol_share = 0.0
        steps = sum(b.get("steps", 0) for b in blobs)
        if steps:
            viol_share = sum(b.get("violations", 0)
                             for b in blobs) / steps
        breach = self.slo_s > 0 and viol_share >= 0.5
        idle = (self.slo_s > 0 and len(blobs) >= world and steps > 0
                and all(b.get("violations", 0) == 0
                        and b.get("step_s_mean", 0.0)
                        <= self.idle_factor * self.slo_s
                        and b.get("pending_bytes", 0.0) <= 0.0
                        for b in blobs))
        blames: dict[int, int] = {}
        for b in blobs:
            for r, c in (b.get("straggler") or {}).items():
                blames[int(r)] = blames.get(int(r), 0) + int(c)
        dominant = (max(sorted(blames), key=lambda r: blames[r])
                    if blames else None)
        for b in blobs:
            self._restore_s_sum += float(b.get("restore_s_sum", 0.0))
            self._restore_count += int(b.get("restore_count", 0))

        # -- streaks (hysteresis state) --
        self._breach_streak = self._breach_streak + 1 if breach else 0
        self._idle_streak = self._idle_streak + 1 if idle else 0
        if dominant is not None and dominant == self._blame_rank:
            self._blame_streak += 1
        elif dominant is not None:
            self._blame_rank, self._blame_streak = dominant, 1
        else:
            self._blame_rank, self._blame_streak = None, 0

        if now < self._cooldown_until:
            return None  # streaks accumulate; actions wait out cooldown

        # -- decide (evict > add > remove: a straggler inflates step
        # time, so replacing it must precede scaling around it) --
        if (self._blame_rank is not None
                and self._blame_streak >= self.evict_windows):
            return self._apply_evict(self._blame_rank, round_id)
        if self._breach_streak >= self.breach_windows:
            if world >= self.max_np:
                return None  # at the ceiling: breach rides, no action
            return self._apply_add(round_id, viol_share)
        if self._idle_streak >= self.idle_windows:
            if world <= self.min_np:
                return None  # at the floor
            # Recovery-cost brake (docs/checkpoint.md): a remove triggers
            # a re-form whose measured restore cost every surviving rank
            # pays; when that projected cost exceeds the idle time the
            # decision is trying to reclaim (the windows of idleness that
            # justified it), shrinking loses throughput on net — hold.
            cost = self._projected_restore_s()
            savings = self.idle_windows * self.interval_s
            if cost > savings:
                return self._record(Decision(
                    "hold", "restore-cost", round_id,
                    detail=f"projected restore {cost:.2f}s exceeds idle "
                           f"savings window {savings:.2f}s"))
            return self._apply_remove(round_id)
        return None

    def _projected_restore_s(self) -> float:
        """Mean measured per-rank recovery time (re-form + restore) —
        the cost the next deliberate re-form is projected to pay. Zero
        until a recovery has been observed: the first scale-down is
        allowed on faith and funds the sensor for the rest."""
        if self._restore_count <= 0:
            return 0.0
        return self._restore_s_sum / self._restore_count

    # -- actuation (round-tag re-validated) ---------------------------------

    def _stale(self, round_id: int) -> bool:
        return self._round() != round_id

    @contextlib.contextmanager
    def _apply_guard(self, round_id: int):
        """Make the round-tag re-validation ATOMIC with actuation: the
        stale check and the host mutation run under the driver's round
        lock, so a re-form can never land between them and have the
        decision actuate against a renamed world (the hvdsched
        ``autoscale-decision`` model's guarded shape). The acquire must
        NOT block: a resume() parked in ``wait_for_available_slots``
        holds the lock while depending on discovery picking up host
        changes — blocking here would deadlock the very scale-up that
        could unpark it (the same rule ``_on_hosts_updated`` follows).
        Yields None (degrade to a stale-round hold) when the lock is
        busy or the tag went stale; yields the decision round otherwise.
        """
        lock = self.driver._round_lock
        if not lock.acquire(blocking=False):
            yield None  # a re-form/resume owns the round right now
            return
        try:
            yield None if self._stale(round_id) else round_id
        finally:
            lock.release()

    def _post_action(self) -> None:
        """Every applied action opens the cooldown and resets the
        hysteresis streaks — the action's own re-form disruption must
        never read as the next window's signal."""
        self._cooldown_until = _inv.monotonic() + self.cooldown_s
        self._breach_streak = 0
        self._idle_streak = 0
        self._blame_rank, self._blame_streak = None, 0

    def _apply_add(self, round_id: int, viol_share: float) -> Decision:
        with self._apply_guard(round_id) as tag:
            if tag is None:
                return self._record(
                    Decision("hold", "stale-round", round_id))
            host = f"auto{self._added}"
            self._added += 1
            self.hosts.add_hosts({host: 1})
        self._post_action()
        return self._record(Decision(
            "add", "slo-breach", round_id,
            detail=f"+{host} (violation share {viol_share:.2f})"))

    def _victim_host(self) -> tuple[str, int] | None:
        """``(hostname, slot_count)`` of the newest (highest-rank) host
        — never one that carries rank 0, which holds the committed
        state the post-reset sync broadcasts from. The slot count bounds
        multi-slot removals (removing a host removes ALL its ranks)."""
        slots = self.driver._rank_assignments
        if not slots:
            return None
        host = slots[max(slots)].hostname
        members = [s for s in slots.values() if s.hostname == host]
        if any(s.rank == 0 for s in members):
            return None
        return host, len(members)

    def _apply_remove(self, round_id: int) -> Decision:
        with self._apply_guard(round_id) as tag:
            if tag is None:
                return self._record(
                    Decision("hold", "stale-round", round_id))
            victim = self._victim_host()
            if victim is None:
                return self._record(Decision(
                    "hold", "protected", round_id,
                    detail="no removable host"))
            host, nslots = victim
            if self.driver.world_size() - nslots < self.min_np:
                # removing a multi-slot host would punch through the
                # floor; hold until capacity justifies losing it whole
                return self._record(Decision(
                    "hold", "protected", round_id,
                    detail=f"removing {host} ({nslots} slots) would "
                           f"break the {self.min_np} floor"))
            self.driver.set_stale_grace(host, self.grace_s)
            self.hosts.remove_host(host)
        self._post_action()
        return self._record(Decision("remove", "idle", round_id,
                                     detail=f"-{host} (graceful)"))

    def _apply_evict(self, rank: int, round_id: int) -> Decision:
        """Evict-and-replace the blamed rank: graceful departure (grace
        window -> zero steps lost) plus a replacement host — matching
        the victim's slot count — in the SAME discovery tick, so the
        world re-forms once at the same size and the replacement adopts
        the shape-keyed warm shelves."""
        with self._apply_guard(round_id) as tag:
            if tag is None:
                return self._record(Decision("hold", "stale-round",
                                             round_id, rank=rank))
            slots = self.driver._rank_assignments
            slot = slots.get(rank)
            if slot is None or not self.driver.has_rank_assignment(
                    slot.hostname, slot.local_rank):
                # The blamed rank already left (re-form between the
                # blame windows and this apply): a stale blame must
                # never evict the successor that inherited the number.
                self._blame_rank, self._blame_streak = None, 0
                return self._record(Decision(
                    "hold", "stale-round", round_id, rank=rank,
                    detail="blamed rank not assigned"))
            members = [s for s in slots.values()
                       if s.hostname == slot.hostname]
            if any(s.rank == 0 for s in members):
                # rank 0's host carries the committed state; replacing
                # it forfeits the sync source. Drop the blame streak so
                # the breach/idle rules get to act on later windows
                # instead of this branch holding them out forever.
                self._blame_rank, self._blame_streak = None, 0
                return self._record(Decision(
                    "hold", "protected", round_id, rank=rank,
                    detail="refusing to evict rank 0's host"))
            replacement = f"auto{self._added}"
            self._added += 1
            self._evictions += 1
            self.driver.set_stale_grace(slot.hostname, self.grace_s)
            self.hosts.remove_host(slot.hostname)
            self.hosts.add_hosts({replacement: len(members)})
        self._post_action()
        return self._record(Decision(
            "evict", "straggler", round_id, rank=rank,
            detail=f"-{slot.hostname} +{replacement}"))

    # -- recording ----------------------------------------------------------

    def _record(self, d: Decision) -> Decision:
        _metrics.ELASTIC_POLICY_DECISIONS.inc(labels={
            "action": d.action, "reason": d.reason,
            "rank": "" if d.rank is None else str(d.rank)})
        _timeline.record_health_event(
            f"AUTOSCALE.{d.action}.{d.reason}")
        with self._mu:
            self.decisions.append(d)
            del self.decisions[:-512]  # registry counters are the
            self.last_decision = d     # durable surface; bound the log
        log = (hvd_logging.warning if d.reason == "error"
               else hvd_logging.info)
        log("autoscale: %s (%s)%s round=%d %s", d.action, d.reason,
            f" rank={d.rank}" if d.rank is not None else "", d.round_id,
            d.detail)
        return d

    def policy_stats(self) -> dict:
        """Controller introspection (tests/bench; rank-LOCAL like every
        dynamic runtime-state surface — hvdlint pass 7 taints reads of
        this under a collective submission)."""
        with self._mu:
            return {
                "world": self.driver.world_size(),
                "bounds": (self.min_np, self.max_np),
                "breach_streak": self._breach_streak,
                "idle_streak": self._idle_streak,
                "blame": (self._blame_rank, self._blame_streak),
                "cooldown_remaining_s": max(
                    0.0, self._cooldown_until - _inv.monotonic()),
                "decisions": [d.as_dict() for d in self.decisions],
            }


def maybe_start(driver, hosts, kv, *, min_np: int,
                max_np: int | None = None,
                env: dict | None = None) -> AutoscalePolicy | None:
    """Wire the policy into an elastic front end when ``HVD_AUTOSCALE``
    is on (process env or the front end's ``extra_env`` overlay) and
    the discovery source is mutable; the caller owns ``stop()``.
    Mirrors ``discovery.install_scripted_churn``'s posture: a
    non-mutable discovery warns and runs without a policy rather than
    failing the job."""
    if not _env_bool(env, envs.AUTOSCALE, False):
        return None
    if hosts is None or not hasattr(hosts, "add_hosts"):
        hvd_logging.warning(
            "HVD_AUTOSCALE=1 but the discovery source is not mutable "
            "(FixedHosts); the autoscale policy is off for this job")
        return None
    policy = AutoscalePolicy(driver, hosts, kv, min_np=min_np,
                             max_np=max_np, env=env)
    policy.start()
    return policy
