"""Elastic launch entry for ``hvdrun``.

TPU-native rebuild of the reference's ``_run_elastic`` + ``gloo_run_elastic``
(``/root/reference/horovod/runner/launch.py:623-672``,
``/root/reference/horovod/runner/gloo_run.py:301-350``): build the discovery
source, stand up the KV server + elastic rendezvous, and hand worker
spawning to :class:`~horovod_tpu.elastic.driver.ElasticDriver`.
"""

from __future__ import annotations

import sys

from ..runner import hosts as hosts_mod
from ..runner import launch as launch_mod
from ..utils import logging as hvd_logging
from .bootstrap import make_elastic_infra
from .discovery import FixedHosts, HostDiscoveryScript


def _build_discovery(args):
    if args.host_discovery_script:
        return HostDiscoveryScript(args.host_discovery_script,
                                   default_slots=args.slots_per_host or 1)
    # Fixed hosts still benefit from elastic mode: failed hosts are
    # blacklisted and the job continues while >= min_np slots remain.
    specs = launch_mod._resolve_hosts(args)
    return FixedHosts({h.hostname: h.slots for h in specs})


def run_elastic(args, command: list[str]) -> int:
    min_np = args.min_np or args.np or 1
    max_np = args.max_np
    discovery = _build_discovery(args)

    from ..utils import envs
    infra = make_elastic_infra(
        discovery, min_np, max_np,
        # HVD_ELASTIC_TIMEOUT wins over the CLI default so driver and
        # workers agree on how long host replacement may take.
        timeout=envs.get_int(envs.ELASTIC_TIMEOUT, int(args.start_timeout)),
        reset_limit=getattr(args, "reset_limit", None),
        cooldown_range=(tuple(args.blacklist_cooldown_range)
                        if getattr(args, "blacklist_cooldown_range", None)
                        else None),
        verbose=1 if args.verbose else 0,
        remote_port_probe=lambda host: launch_mod.probe_remote_free_port(
            host, args.ssh_port, args.ssh_identity_file))
    driver = infra.driver

    extra_base = dict(args._config_env)
    for assignment in args.env:
        k, _, v = assignment.partition("=")
        extra_base[k] = v
    if getattr(args, "metrics_port", None):
        extra_base["HVD_METRICS_PORT"] = str(args.metrics_port)

    lb_world = None
    churn = None
    if getattr(args, "loopback", False):
        # Elastic over rank THREADS: same driver/registry/rendezvous,
        # loopback spawner (docs/loopback.md).
        import sys as _sys

        from ..loopback import engine as lb_engine
        np_cap = max_np or args.np or min_np
        lb_engine._seed_xla_device_flags(np_cap)
        lb_world = lb_engine.LoopbackWorld(
            kv_addr="127.0.0.1", kv_port=infra.kv_port, secret=infra.secret)
        lb_body, lb_argv = lb_engine.script_body(command)
        _sys.argv = lb_argv
        # Scripted churn (docs/elastic.md): membership rules in
        # HVD_FAULT_SPEC drive the discovery set. Loopback only — the
        # handler fires on a worker's commit and must share the
        # driver's process to mutate its discovery.
        from .discovery import install_scripted_churn
        churn = install_scripted_churn(discovery)
        if churn is not None:
            churn.attach_driver(driver)

    def create_worker_fn(slot_info: hosts_mod.SlotInfo, spec_round: int):
        spec = infra.round_spec(spec_round)
        if lb_world is not None:
            env = lb_engine.elastic_worker_env(
                slot_info, spec, "127.0.0.1", infra.kv_port, infra.secret,
                spec_round, extra=extra_base)
            return lb_world.spawn(
                lb_body, env,
                name=f"{slot_info.hostname}[{slot_info.local_rank}]")
        all_local = all(
            launch_mod.is_local_host(s["hostname"]) for s in spec["slots"])
        env = launch_mod.worker_env(
            slot_info,
            coordinator_addr=spec["coord_addr"],
            coordinator_port=spec["coord_port"],
            kv_addr="127.0.0.1" if all_local else infra.kv_addr,
            kv_port=infra.kv_port,
            secret=infra.secret,
            extra=infra.worker_extra_env(spec_round, extra_base))
        return launch_mod.spawn_worker(slot_info, command, env, args)

    # Closed-loop autoscaling (docs/elastic.md "Autoscaler"): the
    # driver-side policy reads worker sensor blobs off the launcher KV
    # and mutates the discovery set; works with FixedHosts-backed
    # discovery (the add/remove seam) — script-discovered host sets
    # stay authoritative and the policy warns itself off.
    from . import policy as _policy_mod
    autoscaler = _policy_mod.maybe_start(
        driver, discovery, infra.kv, min_np=min_np, max_np=max_np,
        env=extra_base)

    try:
        driver.start(args.np or min_np, create_worker_fn)
        driver.join()
        results = driver.get_results()
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        if churn is not None:
            from ..utils import faults as _faults
            _faults.clear_membership_handler()
        infra.stop()
        if lb_world is not None:
            lb_world.shutdown()

    if results.error_message:
        print(f"hvdrun elastic: {results.error_message}", file=sys.stderr)
        return 1
    if driver.succeeded:
        # Elastic recovery absorbed any earlier-round failures: the job
        # completed, so earlier non-zero exits must not fail the run.
        hvd_logging.info("elastic job finished: %s", results.worker_results)
        return 0
    failures = {name: code for name, (code, _ts)
                in results.worker_results.items() if code != 0}
    if failures:
        print(f"hvdrun elastic: worker failures: {failures}", file=sys.stderr)
        return next(iter(failures.values()))
    return 0
