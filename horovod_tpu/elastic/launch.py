"""Elastic launch entry for ``hvdrun``.

TPU-native rebuild of the reference's ``_run_elastic`` + ``gloo_run_elastic``
(``/root/reference/horovod/runner/launch.py:623-672``,
``/root/reference/horovod/runner/gloo_run.py:301-350``): build the discovery
source, stand up the KV server + elastic rendezvous, and hand worker
spawning to :class:`~horovod_tpu.elastic.driver.ElasticDriver`.
"""

from __future__ import annotations

import sys

from ..runner import hosts as hosts_mod
from ..runner import launch as launch_mod
from ..runner.http_kv import KVServer, local_addresses, make_secret
from ..utils import logging as hvd_logging
from .discovery import FixedHosts, HostDiscoveryScript
from .driver import (
    ElasticDriver,
    ElasticRendezvous,
    parse_done_key,
    parse_ready_key,
)


def _build_discovery(args):
    if args.host_discovery_script:
        return HostDiscoveryScript(args.host_discovery_script,
                                   default_slots=args.slots_per_host or 1)
    # Fixed hosts still benefit from elastic mode: failed hosts are
    # blacklisted and the job continues while >= min_np slots remain.
    specs = launch_mod._resolve_hosts(args)
    return FixedHosts({h.hostname: h.slots for h in specs})


def run_elastic(args, command: list[str]) -> int:
    min_np = args.min_np or args.np or 1
    max_np = args.max_np
    discovery = _build_discovery(args)

    secret = make_secret()

    driver_holder: list[ElasticDriver] = []

    def on_put(key: str, _payload: bytes) -> None:
        # Worker readiness and completion flow through KV PUTs (the
        # reference's rendezvous server calls driver.record_ready the same
        # way; completion-by-KV decouples job success from the exit-code
        # race during distributed-runtime teardown).
        if not driver_holder:
            return
        parsed = parse_ready_key(key)
        if parsed is not None:
            driver_holder[0].record_ready(*parsed)
            return
        parsed = parse_done_key(key)
        if parsed is not None:
            driver_holder[0].registry.record_success(*parsed)

    kv = KVServer(secret=secret, on_put=on_put)
    kv_port = kv.start()
    kv_addr_candidates = local_addresses()
    kv_addr = kv_addr_candidates[0]

    rendezvous = ElasticRendezvous(kv)
    from ..utils import envs
    driver = ElasticDriver(
        rendezvous, discovery, min_np, max_np,
        # HVD_ELASTIC_TIMEOUT wins over the CLI default so driver and
        # workers agree on how long host replacement may take.
        timeout=envs.get_int(envs.ELASTIC_TIMEOUT, int(args.start_timeout)),
        reset_limit=getattr(args, "reset_limit", None),
        cooldown_range=(tuple(args.blacklist_cooldown_range)
                        if getattr(args, "blacklist_cooldown_range", None)
                        else None),
        verbose=1 if args.verbose else 0,
        remote_port_probe=lambda host: launch_mod.probe_remote_free_port(
            host, args.ssh_port, args.ssh_identity_file))
    driver_holder.append(driver)

    extra_base = dict(args._config_env)
    for assignment in args.env:
        k, _, v = assignment.partition("=")
        extra_base[k] = v

    spec_cache: dict[int, dict] = {}

    def _round_spec(spec_round: int) -> dict:
        import pickle

        from .driver import ROUND_SPEC_KEY
        if spec_round not in spec_cache:
            spec_cache[spec_round] = pickle.loads(
                kv.get(ROUND_SPEC_KEY.format(spec_round)))
        return spec_cache[spec_round]

    def create_worker_fn(slot_info: hosts_mod.SlotInfo, spec_round: int):
        spec = _round_spec(spec_round)
        all_local = all(
            launch_mod.is_local_host(s["hostname"]) for s in spec["slots"])
        env = launch_mod.worker_env(
            slot_info,
            coordinator_addr=spec["coord_addr"],
            coordinator_port=spec["coord_port"],
            kv_addr="127.0.0.1" if all_local else kv_addr,
            kv_port=kv_port,
            secret=secret,
            extra={**extra_base,
                   "HVD_ELASTIC": "1",
                   "HVD_ELASTIC_ROUND": str(spec_round)})
        return launch_mod.spawn_worker(slot_info, command, env, args)

    try:
        driver.start(args.np or min_np, create_worker_fn)
        driver.join()
        results = driver.get_results()
    finally:
        driver.stop()
        kv.stop()

    if results.error_message:
        print(f"hvdrun elastic: {results.error_message}", file=sys.stderr)
        return 1
    if driver.succeeded:
        # Elastic recovery absorbed any earlier-round failures: the job
        # completed, so earlier non-zero exits must not fail the run.
        hvd_logging.info("elastic job finished: %s", results.worker_results)
        return 0
    failures = {name: code for name, (code, _ts)
                in results.worker_results.items() if code != 0}
    if failures:
        print(f"hvdrun elastic: worker failures: {failures}", file=sys.stderr)
        return next(iter(failures.values()))
    return 0
