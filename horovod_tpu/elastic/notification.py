"""Worker-side host-change notification.

TPU-native rebuild of the reference's ``WorkerNotificationManager`` /
``WorkerNotificationService`` (``/root/reference/horovod/runner/elastic/
worker.py:46-119``). The reference runs a TCP server inside every worker and
the driver pushes ``HostsUpdatedRequest`` to the coordinator; here workers
*poll* the launcher's HTTP KV store for the ``elastic/notify`` key instead —
no per-worker listening sockets, and global consistency still comes from the
rank-0 broadcast inside ``State.check_host_updates``.
"""

from __future__ import annotations

import pickle
import threading

from ..utils import envs
from ..utils import logging as hvd_logging
from .state import HostUpdateResult

POLL_INTERVAL_S = 0.5


def _notify_key() -> str:
    from .driver import NOTIFY_KEY
    return NOTIFY_KEY


class WorkerNotificationManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._listeners = set()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._client = None
        self._last_timestamp = 0

    def init(self, kv_client=None):
        """Start the poll thread (idempotent). Without launcher-seeded KV env
        (non-elastic runs) this is a no-op, mirroring the reference's early
        return when no rendezvous address is set (``worker.py:57-60``)."""
        with self._lock:
            if self._thread is not None:
                return
            if kv_client is None:
                addr = envs.get(envs.KV_ADDR)
                if not addr:
                    return
                from ..runner.http_kv import KVClient
                kv_client = KVClient(addr, envs.get_int(envs.KV_PORT, 0),
                                     secret=envs.get(envs.SECRET_KEY))
            self._client = kv_client
            self._stop.clear()
            from ..utils import invariants as _inv
            self._thread = _inv.spawn_thread(
                self._poll_loop, name="hvd-elastic-notify")

    def register_listener(self, listener):
        with self._lock:
            self._listeners.add(listener)

    def mark_round_joined(self, round_id: int) -> None:
        """Suppress notifications for rounds the worker has already joined.

        Notification timestamps are round ids; once a worker re-rendezvouses
        into round R, the (late-polled) notification that *announced* R is
        stale — delivering it would trigger a spurious interrupt and leave
        the worker waiting for a round R+1 that never comes."""
        with self._lock:
            if round_id > self._last_timestamp:
                self._last_timestamp = round_id
            for listener in self._listeners:
                if round_id > getattr(listener, "_last_updated_timestamp", 0):
                    listener._last_updated_timestamp = round_id

    def remove_listener(self, listener):
        with self._lock:
            self._listeners.discard(listener)

    def shutdown(self):
        with self._lock:
            self._stop.set()
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2 * POLL_INTERVAL_S)

    def _poll_loop(self):
        while not self._stop.wait(POLL_INTERVAL_S):
            try:
                raw = self._client.get(_notify_key())
            except Exception as e:  # launcher gone: stop polling quietly
                hvd_logging.debug("elastic notify poll failed: %s", e)
                continue
            if raw is None:
                continue
            try:
                timestamp, update_res = pickle.loads(raw)
            except Exception:
                continue
            if timestamp <= self._last_timestamp:
                continue
            self._last_timestamp = timestamp
            with self._lock:
                listeners = list(self._listeners)
            for listener in listeners:
                listener.on_hosts_updated(timestamp,
                                          HostUpdateResult(update_res))


notification_manager = WorkerNotificationManager()


def get_notification_manager() -> WorkerNotificationManager:
    """The worker-side notification manager — per loopback rank context
    on rank threads (listeners are per-worker elastic States; a shared
    manager would deliver one rank's interrupts to every rank), else the
    process-wide singleton."""
    from ..loopback import context as _lbctx
    ctx = _lbctx.current()
    if ctx is not None:
        if ctx.notification_manager is None:
            ctx.notification_manager = WorkerNotificationManager()
        return ctx.notification_manager
    return notification_manager
