"""Worker lifecycle registry for the elastic driver.

TPU-native rebuild of ``/root/reference/horovod/runner/elastic/
registration.py``. The reference blocks every recording thread on a
``threading.Barrier`` sized to the world and runs the round transition as the
barrier action; here the driver is the single coordinator and reacts to each
recorded state directly (see ``driver.py`` for the round protocol), so the
registry reduces to a thread-safe state table with the same decision logic:

- any worker SUCCESS        → job is done, stop everything
- all workers FAILURE       → job failed, stop
- some workers FAILURE      → blacklist their hosts and start a new round
- every recorded host blacklisted → stop
- reset count over limit    → stop
"""

from __future__ import annotations

import threading
from collections import defaultdict

from ..utils import logging as hvd_logging

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"

RESET_LIMIT_EXCEEDED_MESSAGE = (
    "Elastic job failed: reached the reset limit of {} rounds. A reset is "
    "triggered every time a worker fails or the host set changes; raise "
    "--reset-limit or investigate the recurring failures."
)


class WorkerStateRegistry:
    """Records READY / SUCCESS / FAILURE per (host, slot) for the current
    rendezvous round and decides the round transition."""

    def __init__(self, driver, host_manager, reset_limit: int | None = None,
                 verbose: bool = False):
        self._driver = driver
        self._host_manager = host_manager
        self._reset_limit = reset_limit
        self._reset_count = 0
        self._lock = threading.Lock()
        self._states: dict[tuple[str, int], str] = {}
        self._workers: dict[str, set] = defaultdict(set)
        self._rendezvous_id = 0
        self._size = 0
        self._verbose = verbose

    def get_recorded_slots(self):
        with self._lock:
            return list(self._states.keys())

    def get(self, state: str) -> set:
        with self._lock:
            return set(self._workers[state])

    def count(self, state: str) -> int:
        with self._lock:
            return len(self._workers[state])

    def reset(self, size: int) -> None:
        """Start a new rendezvous round expecting ``size`` workers."""
        with self._lock:
            hvd_logging.info("reset workers: %d", size)
            self._states.clear()
            self._workers.clear()
            self._rendezvous_id += 1
            self._size = size

    def size(self) -> int:
        return self._size

    def last_rendezvous(self) -> int:
        return self._rendezvous_id

    @property
    def reset_count(self) -> int:
        return self._reset_count

    def record_ready(self, host: str, slot: int) -> int:
        return self._record_state(host, slot, READY)

    def record_success(self, host: str, slot: int) -> int:
        return self._record_state(host, slot, SUCCESS)

    def record_failure(self, host: str, slot: int) -> int:
        return self._record_state(host, slot, FAILURE)

    def _record_state(self, host: str, slot: int, state: str) -> int:
        if self._driver.finished():
            hvd_logging.info(
                "driver finished, ignoring registration: %s[%d] = %s",
                host, slot, state)
            return self._rendezvous_id
        if self._host_manager.is_blacklisted(host):
            hvd_logging.warning(
                "host %s records %s but is blacklisted, ignoring", host, state)
            return self._rendezvous_id

        key = (host, slot)
        with self._lock:
            prev = self._states.get(key)
            if prev == SUCCESS and state == FAILURE:
                # Completion was already recorded via the KV done key; a
                # later non-zero process exit is teardown noise (e.g. the
                # distributed-runtime disconnect race), not a failure.
                hvd_logging.debug(
                    "ignoring FAILURE after SUCCESS for %s[%d]", host, slot)
                return self._rendezvous_id
            if prev is not None and state != FAILURE and prev != state:
                # A worker may go READY → SUCCESS within one round; FAILURE
                # overrides READY (reference ``registration.py:88-105``).
                if not (prev == READY and state == SUCCESS):
                    hvd_logging.error(
                        "state %s ignored for %s[%d]: already %s",
                        state, host, slot, prev)
                    return self._rendezvous_id
            if prev is not None:
                self._workers[prev].discard(key)
            self._states[key] = state
            self._workers[state].add(key)
            rendezvous_id = self._rendezvous_id

        self._on_state_recorded(state)
        return rendezvous_id

    def _on_state_recorded(self, state: str) -> None:
        """Round-transition decision (reference ``_on_workers_recorded``)."""
        if state == READY:
            return  # nothing to decide until a worker terminates

        if self.count(SUCCESS) > 0:
            hvd_logging.info("worker succeeded -> stopping job")
            self._driver.stop(success=True)
            return

        if self._size and self.count(FAILURE) >= self._size:
            hvd_logging.error("all %d workers failed -> stopping job",
                              self._size)
            self._driver.stop()
            return

        for host, _slot in self.get(FAILURE):
            self._host_manager.blacklist(host)

        # When blacklisting drained every slot and nothing can come back via
        # cooldown resurrection, the job cannot continue.
        current = self._host_manager.current_hosts
        if current.count_available_slots() == 0 \
                and not self._host_manager.has_pending_resurrections():
            hvd_logging.error("no available slots remain -> stopping")
            self._driver.stop()
            return

        if self._reset_limit is not None \
                and self._reset_count >= self._reset_limit:
            self._driver.stop(
                error_message=RESET_LIMIT_EXCEEDED_MESSAGE.format(
                    self._reset_limit))
            return

        self._reset_count += 1
        try:
            self._driver.resume()
        except Exception:
            hvd_logging.exception("failed to activate new hosts -> stopping")
            self._driver.stop()
