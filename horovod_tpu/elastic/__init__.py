"""Elastic (fault-tolerant, auto-scaling) training.

TPU-native rebuild of the reference's elastic subsystem
(``/root/reference/horovod/common/elastic.py`` and
``/root/reference/horovod/runner/elastic/``): worker-side state
commit/restore/sync with host-update interrupts, and a driver that discovers
hosts, blacklists failures, and resizes the ``jax.distributed`` world
round-by-round.

Worker usage (mirrors ``hvd.elastic.run`` in the reference)::

    import horovod_tpu as hvd
    hvd.init()

    state = hvd.elastic.JaxState(params=params, opt_state=opt_state,
                                 epoch=0, batch=0)
    state.register_reset_callbacks([rebuild_lr_schedule])

    @hvd.elastic.run
    def train(state):
        for state.epoch in range(state.epoch, epochs):
            for state.batch in range(state.batch, batches):
                step(state)
                if state.batch % 10 == 0:
                    state.commit()

    train(state)

Launch: ``hvdrun -np 2 --min-np 2 --max-np 4
--host-discovery-script ./discover.sh python train.py``.
"""

from __future__ import annotations

from ..exceptions import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
    wrap_internal_errors,
)
from .discovery import (
    FixedHosts,
    HostDiscovery,
    HostDiscoveryScript,
    HostManager,
)
from .driver import ElasticDriver, ElasticRendezvous, Results
from .notification import WorkerNotificationManager, notification_manager
from .policy import AutoscalePolicy, PolicyEvalError
from .registration import WorkerStateRegistry
from .sampler import ElasticSampler
from .state import HostUpdateResult, JaxState, ObjectState, State, run_fn


def run(func):
    """Decorator running ``func(state, ...)`` under elastic recovery
    (reference ``hvd.elastic.run``): on :class:`HostsUpdatedInterrupt` the
    worker re-rendezvouses into the new round and syncs state; on
    :class:`HorovodInternalError` it restores the last commit first."""
    from .rendezvous import get_worker_rendezvous

    def reset():
        get_worker_rendezvous().reset()

    wrapped = run_fn(wrap_internal_errors(func), reset)

    def entry(state, *args, **kwargs):
        try:
            rdv = get_worker_rendezvous()
        except RuntimeError:
            rdv = None  # non-elastic launch: run without the protocol
        if rdv is not None:
            # A worker spawned for round R must ignore the notification that
            # announced R — it is already a member of that round.
            from .notification import get_notification_manager
            manager = get_notification_manager()
            manager.register_listener(state)
            manager.mark_round_joined(rdv.round)
            rdv.record_ready()
        result = wrapped(state, *args, **kwargs)
        if rdv is not None:
            rdv.record_done()
        return result

    return entry


__all__ = [
    "AutoscalePolicy", "PolicyEvalError",
    "ElasticDriver", "ElasticRendezvous", "FixedHosts", "HorovodInternalError",
    "HostDiscovery", "HostDiscoveryScript", "HostManager", "HostUpdateResult",
    "HostsUpdatedInterrupt", "JaxState", "ObjectState", "Results", "State",
    "WorkerNotificationManager", "WorkerStateRegistry",
    "notification_manager", "run", "run_fn", "wrap_internal_errors",
]
