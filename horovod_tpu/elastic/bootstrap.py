"""Shared elastic-job wiring: KV server + driver + round-spec plumbing.

One place for the infrastructure every elastic front end needs — the
``hvdrun --min-np`` CLI (:mod:`horovod_tpu.elastic.launch`) and the Ray
executor (:mod:`horovod_tpu.ray.elastic`) both stand up the same pieces:
a signed KV server whose PUT observer feeds worker readiness/success into
the driver, an :class:`ElasticRendezvous`, the
:class:`~horovod_tpu.elastic.driver.ElasticDriver`, and the cached
round-spec lookup worker spawners need. The reference splits the same
roles between ``gloo_run_elastic`` and ``ElasticRayExecutor``
(``/root/reference/horovod/runner/gloo_run.py:301-350``,
``/root/reference/horovod/ray/elastic_v2.py``), duplicating the
registration plumbing; here it is one helper.
"""

from __future__ import annotations

import pickle

from .. import health
from ..runner.http_kv import KVServer, local_addresses, make_secret
from ..utils import envs
from .driver import (
    ROUND_SPEC_KEY,
    ElasticDriver,
    ElasticRendezvous,
    parse_done_key,
    parse_ready_key,
)


class ElasticInfra:
    """The running pieces of one elastic job (driver side)."""

    def __init__(self, kv: KVServer, kv_addr: str, kv_port: int,
                 secret: str, driver: ElasticDriver):
        self.kv = kv
        self.kv_addr = kv_addr
        self.kv_port = kv_port
        self.secret = secret
        self.driver = driver
        self._spec_cache: dict[int, dict] = {}

    def round_spec(self, spec_round: int) -> dict:
        """The driver-published spec for a round (coordinator address,
        world size, slot table) — what every worker spawner needs."""
        if spec_round not in self._spec_cache:
            self._spec_cache[spec_round] = pickle.loads(
                self.kv.get(ROUND_SPEC_KEY.format(spec_round)))
        return self._spec_cache[spec_round]

    def worker_extra_env(self, spec_round: int,
                         extra: dict | None = None) -> dict:
        """The elastic additions to the launcher env contract."""
        return {**(extra or {}), "HVD_ELASTIC": "1",
                "HVD_ELASTIC_ROUND": str(spec_round)}

    def stop(self) -> None:
        self.driver.stop()
        self.kv.stop()


def make_elastic_infra(discovery, min_np: int, max_np: int | None = None,
                       *, timeout: float | None = None,
                       reset_limit: int | None = None,
                       cooldown_range=None, verbose: int = 0,
                       remote_port_probe=None) -> ElasticInfra:
    """Stand up the KV server and elastic driver with the readiness/success
    PUT observer wired (the protocol half of the reference's rendezvous
    server: worker KV PUTs become ``driver.record_ready`` /
    ``registry.record_success`` calls)."""
    secret = make_secret()
    driver_holder: list[ElasticDriver] = []

    def on_put(key: str, payload: bytes) -> None:
        # Completion-by-KV decouples job success from the exit-code race
        # during distributed-runtime teardown.
        if not driver_holder:
            return
        parsed = parse_ready_key(key)
        if parsed is not None:
            driver_holder[0].record_ready(*parsed)
            return
        parsed = parse_done_key(key)
        if parsed is not None:
            driver_holder[0].registry.record_success(*parsed)
            return
        # Peer-failure reports from worker health watchdogs
        # (horovod_tpu/health.py): blacklist the dead rank's host and
        # re-form the round immediately instead of waiting for the dead
        # process's exit to be reaped.
        failed = health.parse_peer_failure(key, payload)
        if failed is not None:
            driver_holder[0].record_peer_failure(*failed)

    kv = KVServer(secret=secret, on_put=on_put)
    kv_port = kv.start()
    kv_addr = local_addresses()[0]

    driver = ElasticDriver(
        ElasticRendezvous(kv), discovery, min_np, max_np,
        # `is not None`, not `or`: an explicit timeout of 0 means fail
        # fast, which the 600 s default must not swallow
        timeout=(timeout if timeout is not None
                 else envs.get_int(envs.ELASTIC_TIMEOUT, 600)),
        reset_limit=reset_limit, cooldown_range=cooldown_range,
        verbose=verbose, remote_port_probe=remote_port_probe)
    driver_holder.append(driver)
    return ElasticInfra(kv, kv_addr, kv_port, secret, driver)
