"""Elastic worker state: save / restore / sync / commit.

TPU-native rebuild of ``/root/reference/horovod/common/elastic.py`` (State,
ObjectState, run_fn) plus a jax-pytree state class. The semantics are
identical to the reference:

- ``commit()`` saves state to host memory and checks for host-change
  notifications, raising :class:`HostsUpdatedInterrupt` consistently across
  ranks (the decision is broadcast from rank 0 so every rank interrupts at
  the same step, reference ``elastic.py:74-98``).
- ``run_fn`` wraps the user's training function in the recover loop:
  ``HorovodInternalError`` → restore committed state, re-rendezvous, sync;
  ``HostsUpdatedInterrupt`` → re-rendezvous, sync unless only additions
  (reference ``elastic.py:151-174``).
"""

from __future__ import annotations

import enum
import functools
import queue

from .. import checkpoint as _ckpt
from ..exceptions import HorovodInternalError, HostsUpdatedInterrupt
from ..utils import faults as _faults
from . import policy as _policy


class HostUpdateResult(enum.IntFlag):
    """What changed in the host set (reference ``worker.py:38-42``)."""
    no_update = 0
    removed = 1
    added = 2
    mixed = removed | added


class State:
    """Base class tracking in-memory worker state across resets.

    Args:
      bcast_object: callable broadcasting a picklable object from rank 0.
      get_rank: callable returning this worker's current rank.
    """

    def __init__(self, bcast_object, get_rank):
        self._bcast_object = bcast_object
        self._rank = get_rank
        self._host_messages: queue.Queue = queue.Queue()
        self._last_updated_timestamp = 0
        self._reset_callbacks = []
        self._commits = 0

    def register_reset_callbacks(self, callbacks):
        """Register callbacks invoked after every reset event — e.g. rescale
        the learning rate to the new world size."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        # Rank numbers and world size are per-round facts: the state
        # plane's snapshot writer (docs/checkpoint.md) is stopped and
        # re-created lazily under the new round's partition.
        _ckpt.reset_plane()
        self._host_messages = queue.Queue()
        self.reset()
        for callback in self._reset_callbacks:
            callback()

    def on_hosts_updated(self, timestamp, update_res):
        self._host_messages.put((timestamp, update_res))

    def commit(self):
        """Save state and raise :class:`HostsUpdatedInterrupt` if the host
        set changed. Committing copies device arrays to host memory, so
        committing less often than every batch trades throughput against
        lost steps on failure (same trade-off as the reference)."""
        self._commits += 1
        # Chaos seam ("worker" site): `worker:crash:rank=R:at_step=N`
        # hard-exits rank R at its N-th commit — the rehearsal for the
        # whole elastic recovery chain (watchdog -> PeerFailureError ->
        # blacklist -> re-formed round). No-op with HVD_FAULT_SPEC unset.
        _faults.inject("worker", rank=self._rank(), step=self._commits)
        # Autoscale sensor seam (docs/elastic.md): the commit boundary
        # is the per-step clock the policy's SLO rule watches. No-op
        # with HVD_AUTOSCALE unset (cached observer miss).
        _policy.note_commit()
        self.save()
        # State-plane seam (docs/checkpoint.md): with HVD_CKPT_DIR set,
        # every HVD_CKPT_INTERVAL-th committed tree is handed to the
        # background snapshot writer right here — the consistent commit
        # point, after save() replaced the host copy, before a host
        # update can interrupt. No-op otherwise (cached registry miss).
        _ckpt.note_commit(self)
        self.check_host_updates()

    def check_host_updates(self):
        """Raise :class:`HostsUpdatedInterrupt` when a host-change
        notification arrived; globally consistent via rank-0 broadcast."""
        last_updated_timestamp = prev_timestamp = self._last_updated_timestamp
        all_update = HostUpdateResult.no_update
        while not self._host_messages.empty():
            timestamp, update = self._host_messages.get()
            if timestamp > last_updated_timestamp:
                last_updated_timestamp = timestamp
                all_update |= update

        prev_timestamp, self._last_updated_timestamp, all_update = \
            self._bcast_object(
                (prev_timestamp, last_updated_timestamp, all_update))

        if self._last_updated_timestamp > prev_timestamp:
            # Removal-only: surviving workers already share identical state,
            # so the post-reset sync can be skipped. Additions always sync —
            # the new workers must receive rank 0's state (reference
            # ``elastic.py:98``).
            raise HostsUpdatedInterrupt(
                skip_sync=(all_update == HostUpdateResult.removed))

    def save(self):
        """Save state to host memory."""
        raise NotImplementedError()

    def restore(self):
        """Restore the last committed state, dropping uncommitted changes."""
        raise NotImplementedError()

    def sync(self):
        """Synchronize state across workers (broadcast from rank 0)."""
        raise NotImplementedError()

    def reset(self):
        """Hook run on reset before synchronization."""


class ObjectState(State):
    """State for plain picklable Python objects, exposed as attributes
    (reference ``ObjectState``, ``elastic.py:113-148``)."""

    def __init__(self, bcast_object, get_rank, **kwargs):
        self._saved_state = kwargs
        self._set_attrs()
        super().__init__(bcast_object=bcast_object, get_rank=get_rank)

    def save(self):
        self._saved_state = {attr: getattr(self, attr)
                             for attr in self._saved_state}

    def restore(self):
        self._set_attrs()

    def sync(self):
        if self._saved_state:
            self._saved_state = self._bcast_object(self._saved_state)
            self._set_attrs()

    def _set_attrs(self):
        for attr, value in self._saved_state.items():
            setattr(self, attr, value)


class JaxState(ObjectState):
    """Elastic state for jax pytrees (params / opt_state / batch counters).

    The TPU analog of the reference's framework states
    (``torch/elastic/state.py:27-160``, ``tensorflow/elastic.py``): pytree
    attributes are committed by copying to host numpy (device arrays are
    immutable but may live on chips that disappear), synced by broadcasting
    rank 0's committed tree, and restored by re-uploading the host copy.

    Usage::

        state = hvd.elastic.JaxState(params=params, opt_state=opt_state,
                                     epoch=0, batch=0)

        @hvd.elastic.run
        def train(state):
            ...
            state.params = new_params
            state.commit()
    """

    def __init__(self, **kwargs):
        from .. import ops as hvd_ops
        from .. import runtime as hvd_rt
        import jax
        import numpy as np

        def to_host(tree):
            return jax.tree_util.tree_map(np.asarray, tree)

        self._to_host = to_host
        host_kwargs = {
            k: to_host(v) if self._is_pytree_of_arrays(v) else v
            for k, v in kwargs.items()
        }
        super().__init__(
            bcast_object=lambda obj: hvd_ops.broadcast_object(obj, root_rank=0),
            get_rank=hvd_rt.rank,
            **host_kwargs,
        )

    @staticmethod
    def _is_pytree_of_arrays(value) -> bool:
        import jax
        leaves = jax.tree_util.tree_leaves(value)
        return bool(leaves) and all(hasattr(leaf, "shape") for leaf in leaves)

    def save(self):
        self._saved_state = {
            attr: self._to_host(getattr(self, attr))
            if self._is_pytree_of_arrays(getattr(self, attr))
            else getattr(self, attr)
            for attr in self._saved_state
        }

    def sync(self):
        """Re-sync state across a (re-)formed world.

        With ``HVD_CKPT_PEER_RESTORE`` on (the default) and a real
        multi-rank world, the re-sync is the peer-restore protocol
        (docs/checkpoint.md): every rank allgathers a fingerprint of its
        committed state, derives the identical :class:`RestorePlan`, and
        joining/replacement ranks pull their shards from the survivors —
        rank 0 serves only its 1/K share instead of rebroadcasting the
        whole tree. Any degradation (no survivor quorum, structure
        disagreement, unrecoverable pull failures) falls back to the
        reference rank-0 broadcast, typed and metered — never silently.
        """
        if not self._saved_state:
            return
        import time as _time

        from .. import metrics as _metrics
        from .. import runtime as hvd_rt
        t0 = _time.monotonic()
        restored = False
        plan = None
        world = hvd_rt.process_count() if hvd_rt.is_initialized() else 1
        if world > 1 and _ckpt.peer_restore_active():
            import jax

            from .. import conformance as _conformance
            from .. import ops as hvd_ops
            me = hvd_rt.process_rank()
            leaves, treedef = jax.tree_util.tree_flatten(self._saved_state)
            blob = _ckpt.fingerprint_blob(me, self._commits, leaves,
                                          treedef)
            blobs = hvd_ops.allgather_object(blob)
            plan = _ckpt.make_restore_plan(blobs, world=world)
            # Lockstep by construction: every rank derives the plan from
            # the same allgathered fingerprints.
            _conformance.record(
                "elastic/state.py::JaxState.sync", "manifest_agree",
                (plan.step, plan.survivors, plan.needy, plan.n_leaves,
                 plan.degraded_reason))
            if not plan.fresh:
                restored = self._peer_restore(plan, me, leaves, treedef)
        if not restored:
            # The reference path: rank 0 rebroadcasts the whole tree.
            # Metered per receiving rank so the recovery lane can gate
            # peer-restore's rank-0 bytes against this baseline.
            if world > 1 and hvd_rt.process_rank() != 0:
                import jax
                _metrics.CKPT_RESTORE_BYTES.inc(
                    _ckpt.tree_nbytes(
                        jax.tree_util.tree_leaves(self._saved_state)),
                    labels={"source": "rank0"})
            super().sync()
            # Keep commit counts aligned after a broadcast restore: the
            # snapshot trigger and the fault grammar's at_step both key
            # on _commits, so a joiner starting back at 0 would shard
            # its snapshots under a different step than the survivors.
            if plan is not None and not plan.fresh:
                self._commits = max(self._commits, plan.step)
        if world > 1:
            _metrics.CKPT_RESTORE_SECONDS.observe(
                _time.monotonic() - t0)

    def _peer_restore(self, plan, me, leaves, treedef) -> bool:
        """Execute this rank's side of the restore plan. True = state is
        synced (attrs re-set from peer shards or already-agreed local
        state); False = the caller must take the degraded broadcast."""
        from .. import conformance as _conformance
        from .. import metrics as _metrics
        from .. import ops as hvd_ops

        def _degraded(reason):
            _conformance.record(
                "elastic/state.py::JaxState._peer_restore",
                "restore_source", (plan.step, "degraded", reason))
            _metrics.CKPT_DEGRADED_RESTORES.inc(
                labels={"reason": reason})
            return False

        if plan.degraded_reason is not None:
            return _degraded(plan.degraded_reason)
        if not plan.needy:
            # Removal-only world agreement: every rank holds the committed
            # step already — skipping the broadcast IS the restore.
            _conformance.record(
                "elastic/state.py::JaxState._peer_restore",
                "restore_source", (plan.step, "peer", 0))
            self._set_attrs()
            return True
        new_leaves, reason = _ckpt.run_peer_transfers(
            plan, me, leaves, allgather=hvd_ops.allgather_object)
        if reason is not None:
            return _degraded(reason)
        _conformance.record(
            "elastic/state.py::JaxState._peer_restore",
            "restore_source", (plan.step, "peer", len(plan.needy)))
        if me in plan.needy:
            import jax
            self._saved_state = jax.tree_util.tree_unflatten(
                treedef, new_leaves)
            self._commits = plan.step
        self._set_attrs()
        return True


def run_fn(func, reset):
    """Wrap ``func(state, ...)`` in the elastic recover loop (reference
    ``run_fn``, ``elastic.py:151-174``). Each recovery is measured
    (docs/elastic.md SLOs): the re-form duration histogram spans
    catch -> re-rendezvous -> state re-sync, events are counted by kind,
    and a failure restore counts its rolled-back in-flight step."""
    import time as _time

    from .. import metrics as _metrics
    from .notification import get_notification_manager

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        notification_manager = get_notification_manager()
        notification_manager.init()
        notification_manager.register_listener(state)
        skip_sync = False
        t0 = None  # start of the recovery in flight (None = training)
        try:
            while True:
                try:
                    # The post-reset re-sync runs at the loop top INSIDE
                    # this try: a second failure landing during the
                    # rank-0 broadcast (overlapping churn — exactly the
                    # window scripted schedules create) must start
                    # another recovery round, never escape the loop.
                    if not skip_sync:
                        state.sync()
                    if t0 is not None:
                        _metrics.ELASTIC_REFORM_SECONDS.observe(
                            _time.monotonic() - t0)
                        t0 = None
                    return func(state, *args, **kwargs)
                except HorovodInternalError:
                    first = t0 is None
                    if first:
                        t0 = _time.monotonic()
                    kind = "peer-failure"
                    state.restore()
                    skip_sync = False
                    # Commit-per-step convention: the step in flight when
                    # the failure landed rolls back to the last commit.
                    # (Commit-every-N loops lose up to N; the elastic
                    # bench measures the exact count from its step log.)
                    # A double-fault caught during the re-sync itself had
                    # no step in flight — only the first catch counts.
                    if first:
                        _metrics.ELASTIC_STEPS_LOST.inc()
                except HostsUpdatedInterrupt as e:
                    if t0 is None:
                        t0 = _time.monotonic()
                    kind = "hosts-updated"
                    skip_sync = e.skip_sync
                    if skip_sync:
                        # Removal-only re-form: the rank-0 broadcast is
                        # skipped (survivors already hold identical
                        # state) — but live attrs may be DEVICE arrays
                        # produced by the departing world's mesh, which
                        # the re-formed world's programs reject
                        # ("incompatible devices"). restore() re-sets
                        # them from the just-committed host copies —
                        # value-identical, since the interrupt fires
                        # inside commit() right after save().
                        state.restore()

                _metrics.ELASTIC_EVENTS.inc(labels={"kind": kind})
                reset()
                state.on_reset()
        finally:
            notification_manager.remove_listener(state)

    return wrapper
