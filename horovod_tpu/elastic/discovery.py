"""Host discovery and blacklist management for elastic jobs.

TPU-native rebuild of ``/root/reference/horovod/runner/elastic/discovery.py``:
a pluggable :class:`HostDiscovery` source (script / fixed), per-host blacklist
state with exponential-backoff cooldown and resurrection, and a
:class:`HostManager` that diffs successive discoveries into
:class:`~horovod_tpu.elastic.state.HostUpdateResult` updates while keeping a
stable host ordering (oldest hosts first, so rank 0 stays on a host that
holds committed state).
"""

from __future__ import annotations

import random
import threading
import time

from ..runner import safe_exec
from ..utils import logging as hvd_logging
from .state import HostUpdateResult

# Bounds for the blacklist cooldown backoff (reference
# ``discovery.py:27-31``).
COOLDOWN_LOWER_LIMIT_S = 1
COOLDOWN_UPPER_LIMIT_S = 60 * 60


class HostState:
    """Blacklist + cooldown state of one host (reference ``HostState``)."""

    def __init__(self, cooldown_range: tuple[float, float] | None = None):
        self._event = threading.Event()
        self._blacklisted = False
        self._blacklist_count = 0
        if cooldown_range:
            lo, hi = cooldown_range
            if lo < COOLDOWN_LOWER_LIMIT_S:
                raise ValueError(
                    f"cooldown lower limit {lo} below minimum "
                    f"{COOLDOWN_LOWER_LIMIT_S}")
            if hi > COOLDOWN_UPPER_LIMIT_S:
                raise ValueError(
                    f"cooldown upper limit {hi} above maximum "
                    f"{COOLDOWN_UPPER_LIMIT_S}")
            self._cooldown_lo, self._cooldown_hi = lo, hi
        else:
            self._cooldown_lo = self._cooldown_hi = -1.0
        self._cooldown_end_ts = 0.0

    def get_event(self) -> threading.Event:
        if self._event.is_set():
            self._event = threading.Event()
        return self._event

    def set_event(self) -> None:
        self._event.set()

    def _in_cooldown(self, now: float) -> bool:
        return self._cooldown_end_ts > now

    def blacklist(self) -> None:
        """Blacklist the host and start (or extend) its cooldown."""
        self._blacklisted = True
        now = time.time()
        if self._in_cooldown(now):
            return
        if self._cooldown_lo > 0:
            self._blacklist_count += 1
            # exponential backoff with jitter, clamped to the range
            delay = (self._cooldown_lo * (1 << self._blacklist_count)
                     + random.uniform(0, 1) * self._cooldown_lo)
            delay = max(self._cooldown_lo, min(self._cooldown_hi, delay))
            self._cooldown_end_ts = now + delay
        self.set_event()

    def whitelist(self) -> None:
        """End the cooldown and clear the blacklist flag."""
        self._cooldown_end_ts = 0.0
        self._blacklisted = False

    def is_blacklisted(self) -> bool:
        return self._blacklisted

    def is_resurrected(self) -> bool:
        """Blacklisted host whose cooldown expired: eligible to rejoin."""
        if self._cooldown_end_ts > 0:
            return not self._in_cooldown(time.time())
        return False


class DiscoveredHosts:
    """Immutable snapshot of one discovery result (reference
    ``DiscoveredHosts``)."""

    def __init__(self, host_slots: dict[str, int],
                 host_assignment_order: list[str]):
        self._host_slots = dict(host_slots)
        self._host_assignment_order = list(host_assignment_order)

    @property
    def host_slots(self) -> dict[str, int]:
        return self._host_slots

    @property
    def available_hosts(self) -> set[str]:
        return set(self._host_assignment_order)

    @property
    def host_assignment_order(self) -> list[str]:
        return self._host_assignment_order

    def get_slots(self, host: str) -> int:
        return self._host_slots.get(host, 0)

    def count_available_slots(self) -> int:
        return sum(self.get_slots(h) for h in self._host_assignment_order)

    def update(self, hosts_state) -> "DiscoveredHosts":
        self._host_assignment_order = [
            h for h in self._host_assignment_order
            if not hosts_state[h].is_blacklisted()]
        return self

    def __str__(self):
        return (f"slots: {self._host_slots} "
                f"order: {self._host_assignment_order}")


class HostManager:
    """Tracks the evolving host set and its blacklist (reference
    ``HostManager``)."""

    def __init__(self, discovery: "HostDiscovery",
                 cooldown_range: tuple[float, float] | None = None):
        self._current_hosts = DiscoveredHosts({}, [])
        self._hosts_state: dict[str, HostState] = {}
        self._cooldown_range = cooldown_range
        self._discovery = discovery

    def _state(self, host: str) -> HostState:
        if host not in self._hosts_state:
            self._hosts_state[host] = HostState(self._cooldown_range)
        return self._hosts_state[host]

    def update_available_hosts(self) -> HostUpdateResult:
        """Run one discovery and diff it against the previous snapshot."""
        prev_slots = self._current_hosts.host_slots
        prev_order = self._current_hosts.host_assignment_order
        host_slots = self._discovery.find_available_hosts_and_slots()

        resurrected = [h for h in host_slots if self._state(h).is_resurrected()]
        if prev_slots == host_slots and not resurrected:
            return HostUpdateResult.no_update

        res = HostUpdateResult.no_update
        for h in prev_slots:
            if h not in host_slots:
                res |= HostUpdateResult.removed
        for h, n in host_slots.items():
            if h not in prev_slots:
                res |= HostUpdateResult.added
            elif n > prev_slots[h]:
                res |= HostUpdateResult.added
            elif n < prev_slots[h]:
                res |= HostUpdateResult.removed
            elif self._state(h).is_resurrected():
                res |= HostUpdateResult.added

        available = {h for h in host_slots
                     if not (self._state(h).is_blacklisted()
                             and not self._state(h).is_resurrected())}
        order = self.order_available_hosts(available, prev_order)
        self._current_hosts = DiscoveredHosts(host_slots, order)
        for h in resurrected:
            self._state(h).whitelist()
        return res

    @property
    def current_hosts(self) -> DiscoveredHosts:
        return self._current_hosts.update(self._hosts_state_default())

    def _hosts_state_default(self):
        class _Default(dict):
            def __missing__(inner, key):  # noqa: N805
                return self._state(key)
        return _Default()

    def blacklist(self, host: str) -> None:
        if not self._state(host).is_blacklisted():
            hvd_logging.info("blacklisting failing host: %s", host)
        self._state(host).blacklist()

    def is_blacklisted(self, host: str) -> bool:
        return self._state(host).is_blacklisted()

    def has_pending_resurrections(self) -> bool:
        """Any blacklisted host that will become eligible again after its
        cooldown (only possible when a cooldown range is configured)."""
        return any(s.is_blacklisted() and s._cooldown_end_ts > 0
                   for s in self._hosts_state.values())

    def get_host_event(self, host: str) -> threading.Event:
        return self._state(host).get_event()

    @staticmethod
    def order_available_hosts(available_hosts: set[str],
                              prev_order: list[str]) -> list[str]:
        """Preserve relative order so the oldest hosts keep the lowest ranks
        (rank 0 must stay on a host holding committed state)."""
        order = [h for h in prev_order if h in available_hosts]
        known = set(order)
        order.extend(h for h in sorted(available_hosts) if h not in known)
        return order


class HostDiscovery:
    """Interface: return ``{hostname: slots}`` for currently usable hosts."""

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        raise NotImplementedError()


class HostDiscoveryScript(HostDiscovery):
    """Runs a user script printing one ``host[:slots]`` per line (reference
    ``HostDiscoveryScript``; the CLI flag is ``--host-discovery-script``)."""

    def __init__(self, discovery_script: str, default_slots: int = 1):
        self._script = discovery_script
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        import io
        buf = io.StringIO()
        code = safe_exec.run(self._script, prefix_output=False,
                             stdout=buf, shell=True)
        if code != 0:
            raise RuntimeError(
                f"host discovery script {self._script!r} failed "
                f"with exit code {code}")
        host_slots: dict[str, int] = {}
        for line in set(buf.getvalue().strip().split("\n")):
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.rsplit(":", 1)
                host_slots[host] = int(slots)
            else:
                host_slots[line] = self._default_slots
        return host_slots


class FixedHosts(HostDiscovery):
    """Static (but settable) host set — the unit-test hook (reference
    ``FixedHosts``, used by ``test_elastic_driver.py``) and the substrate
    scripted churn mutates (:class:`ScriptedChurn`)."""

    def __init__(self, host_slots: dict[str, int]):
        self._mu = threading.Lock()
        self._host_slots = dict(host_slots)

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        with self._mu:
            return dict(self._host_slots)

    def set(self, host_slots: dict[str, int]) -> None:
        with self._mu:
            self._host_slots = dict(host_slots)

    def add_hosts(self, host_slots: dict[str, int]) -> None:
        """Grow the discovered set (scripted scale-up)."""
        with self._mu:
            self._host_slots.update(host_slots)

    def remove_host(self, host: str) -> bool:
        """Shrink the discovered set (scripted reclaim/preemption).
        Returns whether the host was present."""
        with self._mu:
            return self._host_slots.pop(host, None) is not None


class ScriptedChurn:
    """The ``HVD_FAULT_SPEC`` membership-action handler (docs/elastic.md):
    turns ``worker:add/remove/preempt`` rules fired at a rank's commit
    boundary into discovery-set mutations on a :class:`FixedHosts`, so
    spot/preemptible churn is a seeded, replayable schedule.

    * ``add``: ``count`` fresh hosts (``churn0``, ``churn1``, ...) join
      the discovered set; the driver grows the world at its next poll.
    * ``remove``: the firing rank's host leaves the set; the driver
      reclaims its worker abruptly when the round re-forms (spot
      reclaim with no warning).
    * ``preempt``: SIGTERM-style departure — the firing rank drains its
      in-flight flushes at the commit boundary (its state is committed
      by the time the interrupt lands), the driver is told to give the
      host ``grace`` seconds to exit through the clean slot-lost path
      instead of terminating it mid-collective, and only then does the
      host leave the set. Survivors interrupt at the same commit via
      the rank-0 broadcast, so a graceful preemption loses zero steps.

    Installed by ``loopback.elastic_run`` via
    ``faults.set_membership_handler``; runs on the firing rank's thread.
    """

    def __init__(self, hosts: FixedHosts, *, slots_per_host: int = 1,
                 host_prefix: str = "churn", events: list | None = None):
        from ..utils import invariants as _inv
        self._hosts = hosts
        self._slots = int(slots_per_host)
        self._prefix = host_prefix
        self._driver = None
        self._added = 0
        self._mu = _inv.make_lock("elastic.churn.mu")
        # (monotonic seconds, action, host) — the bench/test event log
        # (callers may inject their own list to read it after the run)
        self.events: list[tuple[float, str, str | None]] = \
            events if events is not None else []

    def attach_driver(self, driver) -> None:
        self._driver = driver

    def _my_host(self) -> str | None:
        from ..utils import envs
        return envs.get(envs.HOSTNAME)

    def __call__(self, action: str, rule) -> None:
        import time as _time
        from .. import metrics as _metrics
        from ..utils import logging as hvd_logging
        host = self._my_host()
        if action == "add":
            with self._mu:
                fresh = {f"{self._prefix}{self._added + i}": self._slots
                         for i in range(rule.count)}
                self._added += rule.count
            self._hosts.add_hosts(fresh)
            hvd_logging.info("scripted churn: +%d host(s) %s",
                             rule.count, sorted(fresh))
            host = ",".join(sorted(fresh))
        elif action == "remove":
            if host is None:
                hvd_logging.warning(
                    "scripted churn: remove fired with no HVD_HOSTNAME")
                return
            self._hosts.remove_host(host)
            hvd_logging.info("scripted churn: -host %s (abrupt)", host)
        elif action == "preempt":
            if host is None:
                hvd_logging.warning(
                    "scripted churn: preempt fired with no HVD_HOSTNAME")
                return
            # Drain this rank's in-flight flushes BEFORE the host leaves
            # discovery: the departing rank's queued collectives land,
            # its state is committed (we run inside commit()), and the
            # driver's grace window lets it exit slot-lost instead of
            # being torn down mid-collective — the 0-steps-lost contract.
            from ..ops import fusion_cycle
            try:
                fusion_cycle.flush_all("preempt-drain")
            except Exception:
                hvd_logging.exception(
                    "scripted churn: preempt drain failed; continuing")
            if self._driver is not None:
                self._driver.set_stale_grace(host, rule.grace_s)
            self._hosts.remove_host(host)
            hvd_logging.info("scripted churn: -host %s (preempt, grace %.1fs)",
                             host, rule.grace_s)
        else:  # pragma: no cover - grammar rejects unknown actions
            return
        with self._mu:
            self.events.append((_time.monotonic(), action, host))
        _metrics.ELASTIC_EVENTS.inc(labels={"kind": action})


def install_scripted_churn(discovery, *, events: list | None = None,
                           warn: bool = False):
    """Wire ``HVD_FAULT_SPEC`` membership rules to ``discovery``: when the
    spec schedules ``worker:add/remove/preempt`` and the discovery set is
    mutable (:class:`FixedHosts`), install a :class:`ScriptedChurn` as the
    process membership handler and return it — the caller must
    ``attach_driver()`` once the driver exists and
    ``faults.clear_membership_handler()`` on teardown. Returns ``None``
    (optionally warning) when no rules are scheduled or the discovery
    source cannot be mutated."""
    from ..utils import faults as _faults
    if not _faults.has_membership_rules():
        return None
    if discovery is None or not hasattr(discovery, "add_hosts"):
        if warn:
            hvd_logging.warning(
                "HVD_FAULT_SPEC schedules membership churn but the "
                "discovery source is not mutable (FixedHosts); membership "
                "rules will no-op")
        return None
    churn = ScriptedChurn(discovery, events=events)
    _faults.set_membership_handler(churn)
    return churn
