"""Background negotiation service for multi-process eager collectives.

The TPU-native analog of the reference's background thread loop
(``BackgroundThreadLoop`` → ``RunLoopOnce`` every ``CycleTimeMs``,
``operations.cc:385-806``): each controller process ticks the symmetric
negotiation protocol of :mod:`horovod_tpu.dynamic` over the launcher's HTTP
KV store. Eager collectives in multi-process jobs call :func:`negotiate`
before executing — the service guarantees

* every process executes collectives in the identical globally-agreed
  order (the reference's core ordering guarantee, ``operations.cc:363-382``),
* metadata disagreements (shape/dtype/op/root) surface as informative
  :class:`~horovod_tpu.dynamic.HorovodCollectiveError`\\ s instead of hangs
  or corrupt reductions (``ConstructResponse`` ERRORs, ``controller.cc``),
* tensors submitted by some-but-not-all processes are reported by the
  stall inspector after ``HVD_STALL_CHECK_TIME_SECONDS`` (default 60 s,
  ``stall_inspector.h:75-86``).

Single-process jobs (the normal SPMD single-controller case) never start
the service: one process sees every rank's data, so ordering and metadata
agreement hold by construction.
"""

from __future__ import annotations

import threading
import time

from . import conformance as _conformance
from . import health as _health
from . import metrics as _metrics
from . import timeline as _timeline
from .loopback import context as _lbctx
from .negotiation import response_cache as _rcache
from .utils import invariants as _inv
from .dynamic import (
    REQ_JOIN,
    HorovodCollectiveError,
    NativeEngine,
    Response,
    and_bitvectors,
    parse_requests,
)
from .exceptions import ResponseCacheJoinError
from .utils import envs
from .utils import faults as _faults
from .utils import logging as hvd_logging

# Default cycle time over the HTTP KV transport. The reference's 1 ms
# default assumes an in-process MPI transport; an HTTP KV round costs
# single-digit milliseconds, so ticking faster only burns CPU — when idle.
# When work IS in flight the service ticks event-driven instead (fresh
# enqueues wake the loop immediately, and in-flight negotiations lower
# the pace to DEFAULT_PENDING_CYCLE_TIME_MS), recovering the reference's
# low-latency rationale (``operations.cc:499-506``) without idle spin;
# HVD_ADAPTIVE_CYCLE=0 restores the fixed cadence.
DEFAULT_KV_CYCLE_TIME_MS = 20.0
DEFAULT_PENDING_CYCLE_TIME_MS = 2.0
_STALL_CHECK_INTERVAL_S = 5.0


class KVTransport:
    """Allgather/AND over the launcher KV server (the analog of the
    reference controller's MPI_Gatherv/Bcast transport,
    ``mpi_controller.cc:135-207``).

    One negotiation cycle costs exactly one KV round per member: the
    request bytes and cache bitvector travel in one framed value, and the
    server assembles all members' values in a single long-poll gather
    (``KVClient.gather``). Scaling: per cycle the server handles O(world)
    requests totalling O(sum of request bytes) — the same asymptotics as
    the reference's MPI_Gatherv+Bcast, with the KV server in the
    coordinator role. The earlier design's two sequential phases of
    per-key polling (O(world²) server ops per cycle across the fleet) is
    gone; for pod-scale worlds the remaining ceiling is the single
    server's fan-in, which is also the reference's rank-0 ceiling."""

    def __init__(self, kv_client, world_size: int, rank: int,
                 prefix: str = "engine"):
        self.kv = kv_client
        self.world_size = world_size
        self.rank = rank
        self.prefix = prefix
        # Observability of the LAST exchange, read by the service's
        # round-metrics hook: wall seconds publish->gathered, and each
        # member's submit lag behind the round's first submitter
        # (local rank -> seconds; server-receipt clock, so cross-host
        # clock skew cannot fake a straggler).
        self.last_round_s = 0.0
        self.last_lags: dict[int, float] = {}

    def exchange(self, cycle: int, req_bytes: bytes, bits: bytes,
                 timeout: float) -> tuple[list[bytes], list[bytes]]:
        """One round: publish (requests, bits), collect everyone's."""
        import struct
        _faults.inject("svc.exchange")
        t0 = time.monotonic()
        frame = struct.pack("<I", len(req_bytes)) + req_bytes + bits
        self.kv.put(f"{self.prefix}/x/{cycle}/{self.rank}", frame)
        got, times = self.kv.gather(f"{self.prefix}/x/{cycle}",
                                    self.world_size, timeout=timeout,
                                    with_times=True)
        self.last_round_s = time.monotonic() - t0
        datas: list = [b""] * self.world_size
        bitvs: list = [b""] * self.world_size
        for k, v in got.items():
            r = int(k.rsplit("/", 1)[1])
            (ln,) = struct.unpack_from("<I", v, 0)
            datas[r] = v[4:4 + ln]
            bitvs[r] = v[4 + ln:]
        receipt: dict[int, float] = {}
        for k, t in times.items():
            try:
                receipt[int(k.rsplit("/", 1)[1])] = t
            except ValueError:
                continue
        first = min(receipt.values()) if receipt else 0.0
        self.last_lags = {r: t - first for r, t in sorted(receipt.items())}
        # Everyone read cycle-c data before anyone can write cycle c+2 (a
        # process must finish cycle c+1's own reads first), so deleting our
        # *previous* cycle's keys here is safe and bounds KV memory.
        if cycle > 0:
            try:
                self.kv.delete(f"{self.prefix}/x/{cycle - 1}/{self.rank}")
            except Exception:  # hvdlint: disable=silent-except
                pass  # best-effort memory bound; next cycle retries the key
        return datas, bitvs


class _Pending:
    __slots__ = ("event", "response")

    def __init__(self):
        self.event = threading.Event()
        self.response: Response | None = None


class NegotiationTicket:
    """An in-flight ``negotiate_many`` round, split from its wait so the
    fusion scheduler's pipelined flush executor can *submit* flush k+1's
    negotiation at the (rank-deterministic) trigger point and only *wait*
    for it when the executor reaches the batch — the KV round trip then
    overlaps flush k's in-flight collective instead of serializing after
    it. Exactly one of :meth:`DynamicService.negotiate_many_wait` /
    :meth:`DynamicService.negotiate_many_cancel` must consume a ticket."""

    __slots__ = ("requests", "pends", "submitted_at", "served")

    def __init__(self, requests, pends, served: bool = False):
        self.requests = requests
        self.pends = pends
        self.submitted_at = time.monotonic()
        # True when the whole batch was answered by the coordinator
        # ResponseCache (docs/negotiation.md): the pends are already
        # satisfied, no engine/KV work is in flight, and the wait path
        # must not re-feed the cache with its own output.
        self.served = served


class DynamicService:
    """Owns one engine + transport and ticks negotiation cycles on a
    background thread."""

    def __init__(self, engine: NativeEngine, transport,
                 cycle_time_s: float | None = None, global_ranks=None,
                 pset_key: str = "global"):
        self.engine = engine
        self.transport = transport
        self.pset_key = pset_key  # metrics process_set label
        _conformance.record(
            "engine_service.py::DynamicService.__init__", "svc_start",
            (pset_key, getattr(transport, "world_size", 1),
             getattr(transport, "rank", 0)))
        # Idle-cadence default scales with world size: every member's
        # cycle thread exchanges every tick (the rounds are lockstep),
        # so a 64-rank world at the 20 ms small-world cadence would put
        # ~6400 idle HTTP ops/s on the one coordinator KV server. The
        # scaled default bounds idle fleet load at O(world/idle_cycle);
        # the PENDING floor is untouched, so busy rounds still tick
        # fast, and worlds <= 16 keep today's cadence byte-for-byte.
        world = getattr(transport, "world_size", 1)
        self._idle_cycle_default_ms = (
            DEFAULT_KV_CYCLE_TIME_MS * max(1.0, world / 16.0))
        # With no explicit value the knob is re-read every cycle so the
        # autotuner's CYCLE_TIME override takes effect live (the reference's
        # ParameterManager adjusts cycle time mid-run the same way).
        self._cycle_time_from_knob = cycle_time_s is None
        if cycle_time_s is None:
            cycle_time_s = envs.get_float(
                envs.CYCLE_TIME, self._idle_cycle_default_ms) / 1000.0
        self.cycle_time_s = cycle_time_s
        # Coordinator ResponseCache (docs/negotiation.md): steady-state
        # batches whose responses are confirmed globally coherent are
        # answered locally with zero KV rounds. AUTO-on whenever the
        # hierarchical control plane is active for this world
        # (HVD_RESPONSE_CACHE=0 is a hard off); invalidated on
        # knob-override epoch (which also flips it on/off/resized live —
        # see _rc_refresh_epoch), coordinated abort, and service
        # stop/reset (which is how process-set changes and elastic
        # re-forms reach it — a new world builds new services).
        cap = envs.response_cache_capacity(world)
        self._rcache = (_rcache.ResponseCache(cap, pset_key)
                        if cap > 0 else None)
        self._rc_epoch = envs.override_epoch()
        # Batches served locally since the previous negotiation cycle —
        # the join-race detection window (see _check_join_race).
        self._rc_serves_window = 0
        # Elastic warm re-form (docs/elastic.md): adopt the same-shape
        # predecessor's shelved entries as WARM (unserveable), publish
        # this rank's warm-content digest, and resolve on the first
        # cycle: all-equal digests re-arm the cache after one
        # confirmation round; any disagreement (fresh member, divergent
        # shelf) drops the warm set and takes the cold two-round path.
        self._rc_warm_pending = False
        _ctx = _lbctx.current()
        self._rc_shape_key = (
            _ctx.world.name if _ctx is not None else "proc",
            pset_key, getattr(transport, "world_size", 1),
            getattr(transport, "rank", 0))
        if (self._rcache is not None and envs.elastic_warm_enabled()
                and getattr(transport, "kv", None) is not None
                and getattr(transport, "prefix", None) is not None):
            shelved = _rcache.take_shelved(self._rc_shape_key)
            if shelved:
                n = self._rcache.restore_warm(shelved)
                hvd_logging.info(
                    "response cache: restored %d warm entries for shape "
                    "%s", n, self._rc_shape_key)
            try:
                transport.kv.put(
                    f"{transport.prefix}/warm/{transport.rank}",
                    self._rcache.warm_digest())
                # Publish unconditionally (peers' gathers need every
                # member's digest — an empty marker is the veto) but only
                # GATHER when this rank actually holds warm entries.
                self._rc_warm_pending = self._rcache.warm_count() > 0
            except Exception as e:
                hvd_logging.warning(
                    "response cache: warm digest publish failed (%s); "
                    "cold re-form", e)
                self._rcache.drop_warm()
        # Latched once any JOIN is observed: a joined rank only learns
        # of scheduled collectives (for its zero executions) from real
        # rounds, and a peer's locally-served uneven tail would starve
        # it forever — see docs/negotiation.md "Joins". Joins cluster at
        # end-of-training/elastic drains, so the lost steady-state wins
        # after one are noise.
        self._rc_join_latch = False
        self._cycle = 0
        self._mu = threading.Lock()
        self._pending: dict[str, _Pending] = {}
        self._joined = False
        self._failure: str | None = None
        self._failure_exc: Exception | None = None
        self._shutdown = threading.Event()
        self._tick = threading.Event()  # fresh work: skip the cycle sleep
        self._exchange_timeout = envs.get_float(envs.ELASTIC_TIMEOUT, 600.0)
        # whole-step batched negotiation rounds served for replayed
        # captured steps (ops/step_capture.py) — one KV cycle covering
        # every flush of the step
        self.step_negotiations = 0
        self._last_stall_check = time.monotonic()
        # Health watchdog over the same KV channel the transport uses:
        # liveness beats + poison records turn a dead peer into a
        # PeerFailureError on every waiter in ~HVD_HEALTH_TIMEOUT instead
        # of the full exchange deadline (docs/robustness.md). Only real
        # KV transports carry it; in-memory test transports have no .kv.
        self._watchdog: _health.HealthWatchdog | None = None
        kv = getattr(transport, "kv", None)
        if (kv is not None and _health.enabled()
                and getattr(transport, "world_size", 1) > 1):
            self._watchdog = _health.HealthWatchdog(
                kv, transport.world_size, transport.rank,
                prefix=f"{getattr(transport, 'prefix', 'engine')}/health",
                on_failure=self._on_peer_failure,
                # Per-set services run on transport-local indices; the
                # watchdog reports failures in GLOBAL process ranks so
                # the elastic driver blacklists the right host.
                global_ranks=global_ranks,
                # Hierarchical transports share their group layout so
                # beats aggregate leader-side and the monitor reads
                # O(G + world/G) keys per tick instead of O(world).
                layout=getattr(transport, "group_layout", None))
            self._watchdog.start()
        # Straggler attribution over the transport's per-round submit
        # lags (health.StragglerTracker, docs/metrics.md): counted and
        # warned on busy rounds only — idle cycles' phase offsets are
        # cadence jitter, not lag.
        world = getattr(transport, "world_size", 1)
        self._straggler = _health.StragglerTracker(
            getattr(transport, "rank", 0),
            (list(global_ranks) if global_ranks is not None
             else list(range(world))))
        # Through the invariants seam: hvdsched can serialize the cycle
        # thread, and a loopback rank's cycle thread inherits that
        # rank's context (joined-rank zero executions run on it).
        self._thread = _inv.spawn_thread(self._loop,
                                         name="hvd-engine-cycle")

    # -- public ------------------------------------------------------------

    def negotiate(self, name: str, request_type: int, *, dtype: int = 0,
                  element_size: int = 4, shape=(), root_rank: int = -1,
                  group_id: int = -1, splits=(), reduce_op: int = -1,
                  prescale: float = 1.0, postscale: float = 1.0,
                  splits_crc: int = 0,
                  timeout: float | None = None) -> Response:
        """Enqueue a request and block until the global plan includes it
        (the eager analog of ``EnqueueTensorAllreduce`` + handle wait).
        ``splits`` carries uneven-alltoall metadata; the negotiated
        recv-splits come back on ``Response.recv_splits``."""
        return self.negotiate_many([dict(
            name=name, request_type=request_type, dtype=dtype,
            element_size=element_size, shape=shape, root_rank=root_rank,
            group_id=group_id, splits=splits, reduce_op=reduce_op,
            prescale=prescale, postscale=postscale,
            splits_crc=splits_crc)], timeout=timeout)[0]

    def join(self, name: str, timeout: float | None = None) -> int:
        """Reference ``hvd.join`` (``operations.cc:1729-1761``): this
        process stops contributing data; until every process joins, it
        participates in collectives scheduled by the others with
        zero-filled inputs (executed by the cycle thread from response
        metadata). Returns the last joined process rank.

        Blocks without a deadline by default, like the reference — peers
        may legitimately train for arbitrarily long before joining (the
        whole point of join); stall warnings still fire for visibility."""
        from .dynamic import REQ_JOIN
        self._joined = True
        self._rc_join_latch = True  # see __init__: joins end local serving
        _conformance.record("engine_service.py::DynamicService.join",
                            "join", (self.pset_key, name))
        try:
            resp = self.negotiate(name, REQ_JOIN,
                                  timeout=timeout if timeout is not None
                                  else float("inf"))
        finally:
            self._joined = False
        return resp.root_rank

    def negotiate_many(self, requests: list[dict],
                       timeout: float | None = None) -> list[Response]:
        """Enqueue a batch (e.g. one grouped op) and wait for all plans —
        all requests land in one cycle, so the wait is one round trip."""
        return self.negotiate_many_wait(self.negotiate_many_submit(requests),
                                        timeout=timeout)

    def negotiate_step(self, requests: list[dict],
                       timeout: float | None = None) -> list[Response]:
        """Batched negotiation for a replayed captured step
        (``ops/step_capture.py``): every flush of the step's recorded
        stream lands in ONE ``negotiate_many`` round — one KV cycle for
        the whole step instead of one per flush. The round is submitted
        at the stream-completion point, which is a rank-deterministic
        program point (the same submission completes the stream on every
        process running the same program), so the cross-process program
        issue order is preserved exactly like any user-thread trigger."""
        self.step_negotiations += 1
        return self.negotiate_many(requests, timeout=timeout)

    def negotiate_many_submit(self, requests: list[dict]) -> NegotiationTicket:
        """First half of :meth:`negotiate_many`: register and enqueue the
        batch (waking the cycle loop) without waiting. The negotiation
        round proceeds on the cycle thread; the returned ticket must be
        consumed by ``negotiate_many_wait`` or ``negotiate_many_cancel``."""
        _faults.inject("svc.submit")
        served = self._try_serve_cached(requests)
        if served is not None:
            return served
        pends = []
        with self._mu:
            # Failure check under the SAME lock that inserts the pends:
            # _fail_all snapshots self._pending under _mu, so a submission
            # racing a coordinated abort either sees the failure here or
            # lands its pends before the snapshot and is failed with the
            # rest — never registered-after-snapshot with no one left to
            # set its events (that waiter would block out the full
            # exchange deadline, the exact hang the watchdog removes).
            if self._failure:
                raise self._failure_error()
            for req in requests:
                name = req["name"]
                if name in self._pending:
                    from .dynamic import DuplicateNameError
                    raise DuplicateNameError(
                        f"tensor name {name!r} is already being negotiated; "
                        "pass a unique name=")
            for req in requests:
                pend = _Pending()
                self._pending[req["name"]] = pend
                pends.append(pend)
                try:
                    self.engine.enqueue(
                        req["name"], req["request_type"],
                        dtype=req.get("dtype", 0),
                        element_size=req.get("element_size", 4),
                        shape=req.get("shape", ()),
                        root_rank=req.get("root_rank", -1),
                        group_id=req.get("group_id", -1),
                        splits=req.get("splits", ()),
                        reduce_op=req.get("reduce_op", -1),
                        prescale=req.get("prescale", 1.0),
                        postscale=req.get("postscale", 1.0),
                        splits_crc=req.get("splits_crc", 0))
                except Exception:
                    # Roll back this batch's already-enqueued members so a
                    # mid-batch failure doesn't poison their names forever.
                    # The failing member itself was NOT enqueued — only drop
                    # its _pending entry (abandoning it would cancel the
                    # older in-flight request that made it a duplicate).
                    self._pending.pop(req["name"], None)
                    for done in requests[:len(pends) - 1]:
                        self._pending.pop(done["name"], None)
                        self.engine.abandon(done["name"])
                    raise
        self._tick.set()  # event-driven cycle: don't wait out the sleep
        for req in requests:
            _timeline.record(req["name"], _timeline.NEGOTIATE,
                             _timeline.PHASE_BEGIN)
        return NegotiationTicket(requests, pends)

    def negotiate_many_wait(self, ticket: NegotiationTicket,
                            timeout: float | None = None) -> list[Response]:
        """Second half of :meth:`negotiate_many`: block until every plan
        in the ticket's batch arrives (or times out). The timeout budget
        starts at *submission*, so an overlapped round whose responses
        already landed while other flushes executed returns immediately."""
        requests, pends = ticket.requests, ticket.pends
        deadline = (timeout if timeout is not None
                    else self._exchange_timeout)
        end = ticket.submitted_at + deadline
        timed_out = False
        try:
            for req, pend in zip(requests, pends):
                remaining = end - time.monotonic()
                if remaining == float("inf"):  # join: block like the reference
                    while not pend.event.wait(60.0):
                        if self._failure:
                            break
                    continue
                if pend.event.is_set():
                    # overlapped round already served while other flushes
                    # executed — never a timeout, however late the wait
                    # starts (the pipelined executor may reach this batch
                    # long after submission)
                    continue
                if remaining <= 0 or not pend.event.wait(remaining):
                    timed_out = True
                    # Name the actual debt: which tensors of this batch
                    # never got a plan, and when each peer was last seen
                    # alive — "see stall warnings in the log" made the
                    # operator go digging for what the error already knew.
                    undelivered = sorted(
                        r["name"] for r, p in zip(requests, pends)
                        if p.response is None)
                    liveness = (self._watchdog.describe_peers()
                                if self._watchdog is not None
                                else "health watchdog off")
                    raise HorovodCollectiveError(
                        f"negotiation of {req['name']!r} timed out after "
                        f"{deadline}s (some processes never submitted it). "
                        f"Undelivered tensors: {undelivered}; "
                        f"peer liveness: {liveness}")
        finally:
            for req in requests:
                _timeline.record(req["name"], _timeline.NEGOTIATE,
                                 _timeline.PHASE_END)
            if not ticket.served:
                # A cache-served ticket never registered its names: the
                # pop would orphan a CONCURRENT real negotiation of the
                # same name (its delivery would find no pend and its
                # waiter would block out the full exchange deadline).
                with self._mu:
                    for req, pend in zip(requests, pends):
                        self._pending.pop(req["name"], None)
                        # On timeout, also abandon undelivered members in
                        # the native engine so the name can be retried
                        # (otherwise it sits in outstanding_ forever and
                        # any reuse raises DuplicateNameError with no
                        # recovery path).
                        if timed_out and pend.response is None:
                            self.engine.abandon(req["name"])
        out = []
        for req, pend in zip(requests, pends):
            resp = pend.response
            if resp is None:
                if self._failure:
                    raise self._failure_error()
                raise HorovodCollectiveError(
                    f"negotiation of {req['name']!r} aborted")
            if resp.is_error:
                raise HorovodCollectiveError(resp.error_message)
            if self._rcache is not None and not ticket.served:
                # Feed the coordinator cache from real rounds only. A
                # from_cache response CONFIRMS the entry: the AND-ed
                # cache bit vector proved every rank held it that cycle
                # and delivered it at the same negotiation index, so
                # every rank flips to local serving deterministically
                # at the same occurrence (docs/negotiation.md).
                self._rcache.note_response(req, resp)
            out.append(resp)
        return out

    def negotiate_many_cancel(self, ticket: NegotiationTicket) -> None:
        """Release a submitted-but-never-waited ticket (the flush executor
        aborting mid-pipeline): drop the pending registrations and abandon
        undelivered names in the native engine so they can be reused —
        a leaked ticket would otherwise pin its names in ``_pending``
        forever and raise DuplicateNameError on any retry."""
        for req in ticket.requests:
            _timeline.record(req["name"], _timeline.NEGOTIATE,
                             _timeline.PHASE_END)
        if ticket.served:
            return  # nothing registered, nothing in the engine to drop
        with self._mu:
            for req, pend in zip(ticket.requests, ticket.pends):
                self._pending.pop(req["name"], None)
                if pend.response is None:
                    try:
                        self.engine.abandon(req["name"])
                    except Exception:  # hvdlint: disable=silent-except
                        pass  # engine may already be torn down

    def stop(self):
        _conformance.record("engine_service.py::DynamicService.stop",
                            "svc_stop", (self.pset_key,))
        # Elastic warm re-form: a GRACEFULLY stopping service (re-form
        # teardown — no failure recorded) shelves its coordinator-cache
        # entries under its shape key; the same-shape successor restores
        # them warm. A service failed by a coordinated abort already
        # invalidated its cache — a broken world's coherence proof must
        # not carry over.
        if (self._rcache is not None and self._failure is None
                and envs.elastic_warm_enabled()):
            items = self._rcache.export_entries()
            if items:
                _rcache.shelve(self._rc_shape_key, items)
        self._shutdown.set()
        self._tick.set()  # the adaptive sleep waits on _tick, not _shutdown
        if self._watchdog is not None:
            # A stop() is a DELIBERATE departure from this service's
            # health channel (re-form teardown, slot-lost exit, job
            # end): publish the leave marker BEFORE beats cease, so a
            # peer still watching the old channel (ranks re-initialize
            # at different speeds) never reads the silence as a death.
            # Abrupt paths (_abrupt_stop, crash) never come through
            # here — real deaths stay detectable.
            self._watchdog.mark_leaving()
            self._watchdog.stop()
        # Short join: a cycle thread parked in the KV gather long-poll
        # (waiting for peers that are also shutting down) can take the
        # full server-side wait to notice; it is a daemon and _fail_all
        # below settles every waiter, so teardown must not serialize on
        # it (loopback worlds stop one service per rank — a long join
        # here multiplies across the world).
        _inv.join_thread(self._thread, timeout=2)
        self._fail_all("engine service stopped")

    def health_watchdog(self) -> _health.HealthWatchdog | None:
        return self._watchdog

    def response_cache_stats(self) -> dict | None:
        """This service's coordinator ResponseCache view, or None when
        ``HVD_RESPONSE_CACHE`` is off."""
        return self._rcache.stats() if self._rcache is not None else None

    # -- internals ---------------------------------------------------------

    def _rc_refresh_epoch(self) -> None:
        """Apply a mid-job ``HVD_RESPONSE_CACHE`` flip on the
        knob-override epoch boundary (the flip-the-cache-mid-job
        ergonomics of the default-on rollout): an override can turn the
        cache ON (starts cold — the standard two confirmation rounds),
        OFF (every entry drops), or RESIZE it, with no service rebuild.
        Any epoch change invalidates a surviving cache exactly as
        before — tuned knobs change wire composition like the dispatch
        plan cache's flush."""
        epoch = envs.override_epoch()
        if epoch == self._rc_epoch:
            return
        self._rc_epoch = epoch
        cap = envs.response_cache_capacity(
            getattr(self.transport, "world_size", 1))
        rc = self._rcache
        if cap <= 0:
            if rc is not None:
                rc.invalidate("knob override epoch: cache disabled")
                self._rcache = None
            return
        if rc is None or rc.capacity != cap:
            self._rcache = _rcache.ResponseCache(cap, self.pset_key)
        else:
            rc.invalidate("knob override epoch")

    def _try_serve_cached(self, requests) -> NegotiationTicket | None:
        """Answer the whole batch from the coordinator ResponseCache —
        or None to take the full negotiation path. All-or-nothing per
        batch: a mixed batch keeps its one-round semantics. Serving
        requires every entry confirmed globally coherent (see
        ``negotiation/response_cache.py``), still present in the NATIVE
        cache (stream-driven invalidation: every rank stops serving on
        the cycle a peer's changed-metadata request lands), and no JOIN
        in flight (a joined rank only learns of scheduled collectives
        from real rounds — serving locally would starve its zero
        executions)."""
        self._rc_refresh_epoch()
        rc = self._rcache
        if rc is None or not requests:
            return None
        if (self._rc_join_latch or self._joined
                or self.engine.join_pending()):
            self._rc_join_latch = True
            return None
        responses = []
        for req in requests:
            resp = rc.lookup_confirmed(req)
            if resp is None or not self.engine.cache_has(req["name"]):
                rc.count_missed(sum(
                    1 for r in requests if _rcache.cacheable(r)))
                return None
            responses.append(resp)
        with self._mu:
            if self._failure:
                raise self._failure_error()
            # Join-latch re-check under the SAME lock the cycle thread
            # latches under (_check_join_race): a serve racing the latch
            # either observes it here (and takes the real path) or lands
            # its window increment before the cycle's read — so a
            # pre-join-latch serve is always either prevented or
            # DETECTED, never silently unpaired.
            if self._rc_join_latch:
                return None
            for req in requests:
                # Same deterministic duplicate-name contract as the full
                # path: a name still registered by an in-flight REAL
                # negotiation must raise here, not be served — and the
                # served ticket must never touch that registration.
                if req["name"] in self._pending:
                    from .dynamic import DuplicateNameError
                    raise DuplicateNameError(
                        f"tensor name {req['name']!r} is already being "
                        "negotiated; pass a unique name=")
            self._rc_serves_window += 1
        pends = []
        for resp in responses:
            pend = _Pending()
            pend.response = resp
            pend.event.set()
            pends.append(pend)
        rc.count_served(len(requests))
        for req in requests:
            _timeline.record(req["name"], _timeline.NEGOTIATE,
                             _timeline.PHASE_BEGIN)
        return NegotiationTicket(requests, pends, served=True)

    def _failure_error(self) -> Exception:
        return (self._failure_exc
                if self._failure_exc is not None
                else HorovodCollectiveError(self._failure or "service failed"))

    def _fail_all(self, message: str, exc: Exception | None = None):
        with self._mu:
            # Failure state and the pending snapshot commit atomically
            # (see negotiate_many_submit): any submission not failed by
            # this snapshot observes self._failure and raises.
            if exc is not None and self._failure_exc is None:
                self._failure_exc = exc
            self._failure = message
            pend = list(self._pending.values())
            self._pending.clear()
        if self._rcache is not None:
            # coordinated abort / stop: whatever world comes next (an
            # elastic re-form, a fresh service) must re-prove coherence
            self._rcache.invalidate(message)
        for p in pend:
            p.event.set()

    def _on_peer_failure(self, dead_rank: int, reason: str) -> None:
        """Watchdog callback: coordinated abort. Ordering matters and
        mirrors the PR-3 pipeline contract (docs/robustness.md): set the
        failure FIRST (new submissions raise immediately), then unblock
        every in-flight ticket waiter, then abort the fusion executor so
        queued-but-unsubmitted batches fail and their tickets are
        cancelled — no waiter can hang on a flush that will never run."""
        with self._mu:
            owed = sorted(self._pending)
        exc = _health.make_peer_failure_error(dead_rank, reason, owed)
        _timeline.record_health_event(f"PEER_DEAD.{dead_rank}")
        _conformance.record(
            "engine_service.py::DynamicService._on_peer_failure",
            "svc_abort", (self.pset_key, dead_rank))
        # A failure decision on a peer that announced a GRACEFUL
        # departure is not a broken world — owed work still fails fast
        # below, but the confirmed coordinator-cache entries (proven
        # coherent at their confirm cycles; re-proven by the successor's
        # digest round regardless) shelve like a clean re-form teardown
        # would. Without this, one slow survivor crossing the silence
        # timeout on an already-left peer cold-started the ENTIRE next
        # world: its missing shelf made its digest the empty veto
        # (observed at world=8 churn — docs/elastic.md "Warm re-form").
        # Shelve BEFORE _fail_all: the abort invalidates the cache.
        if (self._rcache is not None and self._failure is None
                and envs.elastic_warm_enabled()
                and self._watchdog is not None
                and self._watchdog.peer_left(dead_rank)):
            items = self._rcache.export_entries()
            if items:
                _rcache.shelve(self._rc_shape_key, items)
                hvd_logging.info(
                    "response cache: shelved %d entries at graceful-"
                    "departure failure (shape %s)", len(items),
                    self._rc_shape_key)
        self._fail_all(str(exc), exc)
        from .ops import fusion_cycle
        aborted = fusion_cycle.abort(str(exc))
        if aborted:
            hvd_logging.warning(
                "peer failure aborted %d queued async collectives", aborted)
        self._shutdown.set()
        self._tick.set()

    def _loop(self):
        while not self._shutdown.is_set():
            start = time.monotonic()
            # Clear BEFORE the cycle: an enqueue racing the cycle body
            # re-sets it and the next sleep is skipped, never lost.
            self._tick.clear()
            try:
                self._run_cycle()
            except Exception as e:
                hvd_logging.exception("engine cycle failed")
                # Poison BEFORE failing local waiters: this process is
                # alive (its beats keep flowing from the watchdog thread),
                # so without an explicit record peers would only notice
                # at the exchange deadline. The poison key fails them
                # within one monitor tick.
                if self._watchdog is not None:
                    _timeline.record_health_event("POISON")
                    self._watchdog.poison(f"engine cycle failed: {e}")
                self._fail_all(f"engine negotiation failed: {e}")
                return
            if self._cycle_time_from_knob:
                self.cycle_time_s = envs.get_float(
                    envs.CYCLE_TIME, self._idle_cycle_default_ms) / 1000.0
            cycle_s = self.cycle_time_s
            adaptive = envs.get_bool(envs.ADAPTIVE_CYCLE, True)
            if adaptive:
                with self._mu:
                    busy = bool(self._pending)
                if busy:
                    # in-flight negotiation: tick near the transport floor
                    # so served-next-cycle latency is ~KV RTT, not the
                    # idle cadence (reference 1 ms CycleTimeMs rationale)
                    cycle_s = min(cycle_s, envs.get_float(
                        envs.PENDING_CYCLE_TIME,
                        DEFAULT_PENDING_CYCLE_TIME_MS) / 1000.0)
            remaining = max(0.0, cycle_s - (time.monotonic() - start))
            if remaining <= 0:
                continue
            if adaptive:
                self._tick.wait(remaining)  # fresh enqueues end the sleep
            else:
                self._shutdown.wait(remaining)

    def _run_cycle(self):
        # Canonical batched cycle (matches dynamic.drive_cycle): bits are
        # computed against the PRE-ingest cache state on every member (so
        # bit positions agree), the AND-served set commits first, and
        # ingest then skips served names — one KV round per cycle.
        if self._rc_warm_pending:
            self._resolve_warm()
        with self._mu:
            busy = bool(self._pending)
        mine = self.engine.pop_requests()
        mybits = self.engine.cache_bits()
        cycle = self._cycle
        self._cycle += 1
        datas, bitvs = self.transport.exchange(cycle, mine, mybits,
                                               self._exchange_timeout)
        if busy:
            self._record_round_metrics()
        self._check_join_race(datas)
        self.engine.commit_cache_bits(and_bitvectors(bitvs))
        for rank, data in enumerate(datas):
            self.engine.ingest(rank, data)
        responses = self.engine.compute_responses()
        _timeline.mark_cycle()  # HVD_TIMELINE_MARK_CYCLES instant marker
        if responses:
            self._deliver(responses)
        now = time.monotonic()
        if now - self._last_stall_check > _STALL_CHECK_INTERVAL_S:
            self._last_stall_check = now
            self._check_stalls()

    def _resolve_warm(self) -> None:
        """One-time warm-digest resolution (docs/elastic.md): every
        member published its warm-content digest at service start; all
        equal and non-empty means every member restored the identical
        shelved entries, so warm entries flip to confirmed on every rank
        at this same pre-serving point — local serving then resumes
        after ONE real round per name (the native-cache gate), instead
        of the cold populate+confirm two. Any disagreement — a fresh
        replacement rank publishes the empty marker — or a gather
        failure drops the warm set everywhere."""
        self._rc_warm_pending = False
        rc = self._rcache
        transport = self.transport
        if rc is None:
            return
        try:
            got = transport.kv.gather(f"{transport.prefix}/warm",
                                      transport.world_size,
                                      timeout=self._exchange_timeout)
            digests = set(got.values())
            mine = rc.warm_digest()
        except Exception as e:
            dropped = rc.drop_warm()
            if dropped:
                hvd_logging.warning(
                    "response cache: warm digest exchange failed (%s); "
                    "dropped %d warm entries (cold re-form)", e, dropped)
            return
        if len(digests) == 1 and mine in digests and mine != b"\x00" * 8:
            n = rc.confirm_warm()
            if n:
                _metrics.ELASTIC_WARM_REUSE.inc(
                    n, labels={"kind": "response"})
                hvd_logging.info(
                    "response cache: %d warm entries confirmed after one "
                    "digest round (shape %s)", n, self._rc_shape_key)
        else:
            dropped = rc.drop_warm()
            if dropped:
                hvd_logging.info(
                    "response cache: warm digests diverge (fresh member "
                    "or different shelf); dropped %d entries (cold "
                    "re-form)", dropped)

    def _check_join_race(self, datas) -> None:
        """Coordinator-side join-latch race detection (ROADMAP protocol
        follow-on (a)): the cycle that first observes a peer's JOIN
        latches local serving off — and if any batch was served locally
        in the window since the previous cycle (a decision made without
        knowledge of the join), those collectives were never scheduled
        through a real round and the joined rank can never pair them.
        Surface that as a typed :class:`ResponseCacheJoinError` naming
        the joining rank NOW instead of letting the unpaired work burn
        the full exchange deadline."""
        if self._rcache is None or self._rc_join_latch:
            return
        joiner = -1
        found = False
        for data in datas:
            if not data:
                continue
            try:
                reqs = parse_requests(data)
            except Exception:  # hvdlint: disable=silent-except
                continue  # corrupt frame: ingest will raise the real error
            for req in reqs:
                if req["request_type"] == REQ_JOIN:
                    joiner = req["rank"]
                    found = True
                    break
            if found:
                break
        with self._mu:
            served = self._rc_serves_window
            self._rc_serves_window = 0  # new cycle, new window
            if found:
                self._rc_join_latch = True
        if found and served:
            gr = self._straggler.global_ranks
            exc = ResponseCacheJoinError(
                gr[joiner] if 0 <= joiner < len(gr) else joiner, served)
            hvd_logging.error("%s", exc)
            _timeline.record_health_event("RC_JOIN_RACE")
            self._fail_all(str(exc), exc)
            self._shutdown.set()
            self._tick.set()

    def _record_round_metrics(self) -> None:
        """Registry samples for one BUSY negotiation round (local work
        was pending, so the round's latency and its members' submit lags
        are load-bearing): the ROADMAP's protocol-scalability curve
        (round latency + KV ops/round vs world) reads straight off
        these, and the straggler tracker turns sustained lag into the
        named-rank warning/counter (docs/metrics.md)."""
        transport = self.transport
        round_s = getattr(transport, "last_round_s", None)
        if round_s is None:  # in-memory test transports: no KV timing
            return
        label = {"process_set": self.pset_key}
        _metrics.NEGOTIATION_ROUNDS.inc(labels=label)
        _metrics.NEGOTIATION_ROUND_SECONDS.observe(round_s, labels=label)
        lags = getattr(transport, "last_lags", None) or {}
        gr = self._straggler.global_ranks
        for r in sorted(lags):
            if 0 <= r < len(gr):
                _metrics.NEGOTIATION_SUBMIT_LAG.observe(
                    lags[r], labels={"rank": gr[r]})
        with self._mu:
            owed = sorted(self._pending)
        self._straggler.observe(lags, owed)

    def straggler_stats(self) -> dict:
        """This service's straggler-attribution view
        (``health.StragglerTracker.stats``)."""
        return self._straggler.stats()

    def _deliver(self, responses: list[Response]):
        # While joined, responses for tensors this process never submitted
        # are executed with zero inputs (reference JoinOp) BEFORE any
        # claimed responses are delivered — the JOIN completion arrives
        # last in the cycle, so the user thread cannot race the zero
        # executions and cross-process collective order is preserved.
        exec_batch: list[Response] = []
        claimed_resps: list[Response] = []
        with self._mu:
            joined = self._joined
        for resp in responses:
            with self._mu:
                claimed = any(t in self._pending for t in resp.tensor_names)
            if claimed:
                claimed_resps.append(resp)
            elif joined and not resp.is_error:
                exec_batch.append(resp)
        if exec_batch:
            from .ops import collectives as _coll
            _coll._execute_joined_zeros(exec_batch)  # raises on unsupported
        with self._mu:
            for resp in claimed_resps:
                for tname in resp.tensor_names:
                    pend = self._pending.get(tname)
                    if pend is not None:
                        pend.response = resp
                        pend.event.set()

    def _check_stalls(self):
        if envs.get_bool(envs.STALL_CHECK_DISABLE):
            return
        report, shutdown = self.engine.stall_report()
        for entry in report:
            hvd_logging.warning(
                "One or more tensors were submitted to be reduced/gathered "
                "but were not ready on all processes for %.0f seconds. This "
                "may indicate diverged control flow. Tensor: %s, ready "
                "ranks: %s, missing ranks: %s",
                entry.waiting_seconds, entry.tensor_name, entry.ready_ranks,
                entry.missing_ranks(self.engine.world_size))
        if shutdown:
            self._fail_all(
                "stalled tensors exceeded HVD_STALL_SHUTDOWN_TIME_SECONDS; "
                "shutting down negotiation (reference semantics, "
                "stall_inspector.h:71-86)")
            self._shutdown.set()


# --------------------------------------------------------------------------
# process-wide services (created lazily for multi-process eager jobs) — one
# per process set, mirroring the reference's per-ProcessSet controller
# (process_set.h:26-84): subset eager ops get the same ordering/mismatch/
# stall guarantees as global ones, negotiated only among the member
# processes (so non-members legally never submitting is not a stall).
# --------------------------------------------------------------------------

_services: dict = {}          # set key -> DynamicService
_service_lock = threading.Lock()
_service_unavailable = False  # infra-level: knob off / no KV / no native


class _ServiceScope:
    """Resolution of the per-world service table: a loopback rank thread
    owns ITS rank's services (one ``DynamicService`` per rank per set —
    N ranks in one interpreter means N global-set services negotiating
    with each other over the shared KV); everything else shares the
    process-wide table."""

    __slots__ = ("table", "ctx")

    def __init__(self):
        self.ctx = _lbctx.current()
        self.table = self.ctx.services if self.ctx is not None else _services

    @property
    def unavailable(self) -> bool:
        if self.ctx is not None:
            return self.ctx.service_unavailable
        return _service_unavailable

    @unavailable.setter
    def unavailable(self, value: bool) -> None:
        global _service_unavailable
        if self.ctx is not None:
            self.ctx.service_unavailable = value
        else:
            _service_unavailable = value


def _set_key(pset) -> str:
    """Stable cross-process key for a process set: registered id when
    available, else a digest of the rank list (deterministic everywhere,
    unlike id())."""
    if pset is None or pset.is_global:
        return "0"
    if pset.process_set_id is not None:
        return str(pset.process_set_id)
    import zlib
    return "u%x" % (zlib.crc32(repr(tuple(pset.ranks)).encode()) & 0xFFFFFFFF)


def get_service(pset=None) -> DynamicService | None:
    """The negotiation service for ``pset`` (default: global set), or None
    when not applicable (single-process job, this process not a member,
    knob disabled, no launcher KV, native engine unavailable)."""
    scope = _ServiceScope()
    if scope.unavailable:
        return None
    if not envs.get_bool(envs.DYNAMIC_ENGINE, True):
        scope.unavailable = True
        return None
    from . import runtime
    if not runtime.is_initialized() or runtime.process_count() <= 1:
        return None  # may become multi-process after a later init
    kv_addr = envs.get(envs.KV_ADDR)
    if not kv_addr:
        scope.unavailable = True
        return None

    if pset is None or pset.is_global:
        member_procs = list(range(runtime.process_count()))
    else:
        member_procs = sorted({runtime.process_of_rank(r)
                               for r in pset.ranks})
    me = runtime.process_rank()
    if me not in member_procs or len(member_procs) <= 1:
        return None
    key = _set_key(pset)
    services = scope.table
    svc = services.get(key)
    if svc is not None:
        return svc
    with _service_lock:
        svc = services.get(key)
        if svc is not None or scope.unavailable:
            return svc
        try:
            from ._native import available
            if not available():
                scope.unavailable = True
                return None
            from .runner.http_kv import KVClient
            kv = KVClient(kv_addr, envs.get_int(envs.KV_PORT, 0),
                          secret=envs.get(envs.SECRET_KEY))
            engine = NativeEngine(world_size=len(member_procs),
                                  rank=member_procs.index(me))
            # Scope keys to this world instance AND this process set: the
            # coordinator endpoint changes every elastic round, so a fresh
            # service can never read stale cycle keys left by the previous
            # round; per-set scoping keeps concurrent sets' cycles apart.
            prefix = "engine/{}:{}/ps{}".format(
                envs.get(envs.COORDINATOR_ADDR, "local"),
                envs.get(envs.COORDINATOR_PORT, "0"), key)
            # Control-plane topology (docs/negotiation.md): past one
            # leader group ('auto', HVD_NEGOTIATION_GROUP_SIZE) the
            # round runs member -> leader -> cross-leader -> fan-down,
            # dropping per-gather server fan-in from O(world) keys to
            # O(world/G + G); small worlds keep the flat exchange
            # byte-for-byte.
            if envs.hier_negotiation_enabled(len(member_procs)):
                from .negotiation import HierarchicalTransport
                transport = HierarchicalTransport(
                    kv, len(member_procs), member_procs.index(me),
                    prefix=prefix)
            else:
                transport = KVTransport(kv, len(member_procs),
                                        member_procs.index(me),
                                        prefix=prefix)
            svc = DynamicService(engine, transport,
                                 global_ranks=member_procs,
                                 # one tenant, one label value: the
                                 # global set is "global" here exactly
                                 # as in the fusion counters
                                 # (fusion_cycle._pset_label), so
                                 # per-tenant series join across
                                 # negotiation and fusion instruments
                                 pset_key="global" if key == "0" else key)
            services[key] = svc
            hvd_logging.info(
                "dynamic engine service started for set %s: %d processes "
                "over KV %s", key, len(member_procs), kv_addr)
        except Exception as e:
            hvd_logging.warning("dynamic engine service unavailable: %s", e)
            scope.unavailable = True
    return svc


def response_cache_stats() -> dict:
    """Per-process-set coordinator ResponseCache views for this world's
    services (exported as ``hvd.response_cache_stats()``); empty when
    ``HVD_RESPONSE_CACHE`` is off or no service is up."""
    scope = _ServiceScope()
    with _service_lock:
        svcs = dict(scope.table)
    out = {}
    for key, svc in svcs.items():
        stats = svc.response_cache_stats()
        if stats is not None:
            out["global" if key == "0" else key] = stats
    return out


def mark_leaving() -> None:
    """Announce this world's GRACEFUL departure on every service's
    health channel (elastic slot-lost exit, docs/elastic.md): peers'
    silence detection then skips this rank's ceased beats."""
    scope = _ServiceScope()
    with _service_lock:
        svcs = list(scope.table.values())
    for svc in svcs:
        wd = svc.health_watchdog()
        if wd is not None:
            wd.mark_leaving()


def reset_service() -> None:
    """Tear down all per-set services (elastic re-init / tests). On a
    loopback rank thread this tears down THAT rank's services only."""
    scope = _ServiceScope()
    # Entries still queued in the fusion cycle pinned THIS world's
    # services and negotiation names — they can never execute after the
    # reset. Fail them (handles raise at synchronize) instead of leaving
    # their waiters hanging; a clean shutdown() drains the queues first,
    # so this only bites abandoned handles and elastic teardowns.
    from .ops import fusion_cycle
    aborted = fusion_cycle.abort("engine service reset")
    if aborted:
        hvd_logging.warning(
            "engine service reset aborted %d queued async collectives "
            "(synchronize their handles before shutdown/reset to land "
            "them)", aborted)
    with _service_lock:
        for svc in scope.table.values():
            svc.stop()
        scope.table.clear()
        scope.unavailable = False
    # Auto-generated op names must restart from zero everywhere after a
    # world reset: surviving workers would otherwise keep counting while
    # replacement workers start at 0, desynchronizing negotiation names.
    from .ops import collectives as _coll
    _coll._reset_auto_counters()
    # Dispatch plans pin their negotiation decision (service object + the
    # stable tensor names) — all stale after a service teardown.
    from .ops import dispatch_cache
    dispatch_cache.invalidate("engine service reset")
