"""Multi-tenant QoS for the collective engine: priority classes,
weighted-fair admission, and load shedding.

The fusion scheduler (``ops/fusion_cycle.py``) was single-tenant: one
FIFO flush pipeline shared by every process set, so one runaway tenant's
flush stream could queue arbitrarily far ahead of a latency-sensitive
tenant's gradient sync. This module adds the production-serving layer on
top of the per-tenant ``hvd_fusion_*_total{process_set=...}`` seam
(PAPER.md's ``ProcessSetTable`` is the tenancy boundary; PR 11's
registry counters are the measurement):

* **Priority classes** — :func:`set_qos` attaches ``(priority tier,
  DRR weight, pending-bytes quota, block/shed policy)`` to a process
  set; ``HVD_QOS_*`` knobs configure defaults and per-tenant classes
  from the environment (docs/qos.md grammar).
* **Weighted-fair admission** — :class:`QosGate` sits between
  ``flush_queue``'s batch submission and the pipelined executor's FIFO:
  batches park per tenant, and an arbiter grants them into the
  ``HVD_MAX_INFLIGHT_FLUSHES`` slots by strict-priority tiers with
  deficit-round-robin (byte-weighted) inside a tier, preserving
  per-signature FIFO within a tenant.
* **Admission control / shedding** — per-tenant pending-bytes quotas
  enforced at enqueue: ``block`` backpressures the producer until
  granted work settles; ``shed`` fails the submission with a typed
  :class:`~horovod_tpu.exceptions.QosAdmissionError` on the handle.

Determinism contract (docs/qos.md). In multi-process/loopback worlds
every rank's executor must issue the identical wire-program sequence
(the loopback hub's rendezvous — and any real backend's — deadlocks on
a cross-rank order swap), so grant order must be a pure function of the
submission stream + static QoS config, never of completion timing:

* gate state mutates ONLY at rank-deterministic program points — batch
  submission (a flush trigger on the user thread), handle observation
  (``synchronize``/first ``poll``: forced release), name-reuse guards,
  and ``flush_all``/``abort``;
* the **arbitration window** (``HVD_QOS_WINDOW``): a submission pump
  grants parked *negotiated* (svc) batches down to the window in fair
  order — the window is the deterministic reordering span;
* **single-controller** batches (no negotiation service — one process
  drives every chip, so there is no peer to diverge from) additionally
  grant on executor demand: work-conserving true priority scheduling,
  which is where the inference-serving workload's tail-latency
  protection comes from;
* the starvation valve ages by **grant count**, never wall-clock
  (``HVD_QOS_STARVE_LIMIT``): every N grants the globally oldest parked
  batch is served regardless of tier, so strict priority cannot park a
  bulk tenant forever;
* the ``shed`` quota is measured on *unacknowledged* bytes (enqueue ->
  ``synchronize`` return — both rank-deterministic stream points), so
  every member rank sheds the identical submissions; the ``block``
  quota waits on *granted-but-unsettled + parked single-controller*
  bytes — all drained by the executor with no producer action — and
  never mutates the gate (a wait that re-ordered grants would be a
  completion-timing input — and a wait that could only be satisfied by
  a batch the gate still holds is the planted priority-inversion
  deadlock hvdsched's ``qos-inversion-demo`` finds).

Instrumentation: ``hvd_qos_admission_wait_seconds`` /
``hvd_qos_granted_bytes_total`` / ``hvd_qos_slot_share`` /
``hvd_qos_shed_total`` / ``hvd_qos_quota_blocks_total`` (docs/metrics.md)
plus ``QOS_*`` instants on the timeline's ``qos`` lane. ``HVD_QOS=0``
(the default) keeps the single-tenant FIFO pipeline byte-for-byte.
"""

from __future__ import annotations

import threading
from collections import deque

from . import conformance as _conformance
from . import metrics as _metrics
from . import timeline as _timeline
from .exceptions import QosAdmissionError
from .utils import envs
from .utils import invariants as _inv

__all__ = ["QosAdmissionError", "QosClass", "QosGate", "set_qos",
           "configure_label", "get_class", "tenant_label", "classes",
           "qos_stats", "enabled", "reset"]

POLICIES = ("block", "shed")


def enabled() -> bool:
    """Whether the multi-tenant QoS engine is on (``HVD_QOS``)."""
    return envs.qos_enabled()


def tenant_label(pset) -> str:
    """Tenant label for a process set — THE derivation shared with the
    per-tenant fusion/negotiation registry counters
    (``engine_service._set_key``), with the global set's ``"0"`` key
    spelled ``"global"``. One function, so QoS classes, fusion counters,
    and negotiation instruments can never drift apart on a tenant's
    identity."""
    if pset is None or getattr(pset, "is_global", True):
        return "global"
    from . import engine_service as _es
    key = _es._set_key(pset)
    return "global" if key == "0" else key


class QosClass:
    """One tenant's service class: strict-priority ``priority`` tier
    (higher = served first), DRR ``weight`` (byte share within a tier),
    ``quota`` pending bytes (0 = unlimited), and the quota ``policy``
    (``block`` backpressure / ``shed`` with QosAdmissionError)."""

    __slots__ = ("priority", "weight", "quota", "policy")

    def __init__(self, priority: int = 0, weight: float = 1.0,
                 quota: int = 0, policy: str = "block"):
        if weight <= 0.0:
            raise ValueError(f"QoS weight must be > 0, got {weight}")
        if policy not in POLICIES:
            raise ValueError(
                f"QoS policy must be one of {POLICIES}, got {policy!r}")
        self.priority = int(priority)
        self.weight = float(weight)
        self.quota = int(quota)
        self.policy = policy

    def as_dict(self) -> dict:
        return {"priority": self.priority, "weight": self.weight,
                "pending_bytes_quota": self.quota, "policy": self.policy}

    def __repr__(self) -> str:
        return (f"QosClass(priority={self.priority}, weight={self.weight}"
                f", quota={self.quota}, policy={self.policy!r})")


# --------------------------------------------------------------------------
# tenant-class registry (static config; reads on the enqueue hot path)
# --------------------------------------------------------------------------

# Plain leaf lock, like the metrics registry's: nothing is acquired under
# it and it never blocks on anything, so routing it through the
# cooperative scheduler would only widen hvdsched's schedule space.
_mu = threading.Lock()
_classes: dict[str, QosClass] = {}
_explicit: set[str] = set()          # labels set via the API (these win)
_env_labels: set[str] = set()        # labels installed from the env spec
_env_classes_raw: str | None = None  # last-parsed HVD_QOS_CLASSES value
# per-label resolution cache: get_class rides the per-submission enqueue
# hot path, so steady state must be one env read + one dict hit, not a
# lock + a default-class rebuild. Invalidated on configure/reset and on
# any HVD_QOS_CLASSES change; HVD_QOS_DEFAULT_* knobs are resolved at a
# label's first lookup (static-config contract — docs/qos.md).
_resolved: dict[str, QosClass] = {}


def _parse_spec(label: str, spec: str) -> QosClass:
    """One ``HVD_QOS_CLASSES`` entry body: ``key=value[,key=value...]``
    with keys priority/weight/quota/policy."""
    kw: dict = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(
                f"HVD_QOS_CLASSES entry for {label!r}: expected key=value, "
                f"got {item!r}")
        key, _, val = item.partition("=")
        key = key.strip()
        if key == "priority":
            kw["priority"] = int(val)
        elif key == "weight":
            kw["weight"] = float(val)
        elif key == "quota":
            kw["quota"] = int(val)
        elif key == "policy":
            kw["policy"] = val.strip()
        else:
            raise ValueError(
                f"HVD_QOS_CLASSES entry for {label!r}: unknown key {key!r} "
                "(valid: priority, weight, quota, policy)")
    return QosClass(**{**_default_kw(), **kw})


def _default_kw() -> dict:
    return {
        "priority": envs.get_int(envs.QOS_DEFAULT_PRIORITY, 0),
        "weight": envs.get_float(envs.QOS_DEFAULT_WEIGHT,
                                 envs.DEFAULT_QOS_WEIGHT),
        "quota": envs.get_int(envs.QOS_PENDING_QUOTA, 0),
        "policy": (envs.get(envs.QOS_SHED_POLICY, "block")
                   or "block").strip().lower(),
    }


def _sync_env_classes_locked() -> None:
    """Fold ``HVD_QOS_CLASSES`` into the registry (re-parsed when the
    knob's value changes; explicit set_qos/configure_label entries win —
    the API is the more specific configuration). Parsing is
    all-or-nothing: the spec is validated in full BEFORE anything is
    installed or marked parsed, so a malformed entry raises on every
    lookup instead of raising once and then silently running with a
    half-applied config. A changed spec REPLACES the previously
    env-installed entries (stale classes, and labels deleted from the
    spec, are dropped); only explicit API registrations survive it."""
    global _env_classes_raw
    raw = envs.get(envs.QOS_CLASSES)
    if raw == _env_classes_raw:
        return
    parsed: list[tuple[str, QosClass]] = []
    for entry in (raw or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        label, _, spec = entry.partition(":")
        label = label.strip()
        if not label:
            raise ValueError(
                f"HVD_QOS_CLASSES entry {entry!r}: missing tenant label "
                "(grammar: '<tenant>:key=value,...;...' — docs/qos.md)")
        parsed.append((label, _parse_spec(label, spec)))
    _env_classes_raw = raw
    for label in _env_labels - _explicit:
        _classes.pop(label, None)
    _env_labels.clear()
    _resolved.clear()
    for label, cls in parsed:
        if label not in _explicit:
            _classes[label] = cls
            _env_labels.add(label)


def configure_label(label: str, *, priority=None, weight=None,
                    pending_bytes_quota=None, policy=None) -> QosClass:
    """Install/update the class for tenant ``label`` (the string form of
    :func:`tenant_label` — tests and the env parser use this directly;
    users go through :func:`set_qos`). Unspecified fields keep the
    tenant's current value, else the ``HVD_QOS_DEFAULT_*`` defaults."""
    with _mu:
        _sync_env_classes_locked()
        base = _classes.get(label)
        if base is not None:
            kw = {"priority": base.priority, "weight": base.weight,
                  "quota": base.quota, "policy": base.policy}
        else:
            kw = _default_kw()
        if priority is not None:
            kw["priority"] = int(priority)
        if weight is not None:
            kw["weight"] = float(weight)
        if pending_bytes_quota is not None:
            kw["quota"] = int(pending_bytes_quota)
        if policy is not None:
            kw["policy"] = policy
        cls = QosClass(**kw)
        _classes[label] = cls
        _explicit.add(label)
        _env_labels.discard(label)
        _resolved.clear()
        return cls


def set_qos(process_set=None, *, priority=None, weight=None,
            pending_bytes_quota=None, policy=None) -> QosClass:
    """Attach a QoS class to ``process_set`` (None = the global set):
    ``hvd.set_qos(ps, priority=1, weight=4.0,
    pending_bytes_quota=1 << 20, policy="shed")``. Static config by
    contract: in multi-process jobs every member rank must apply the
    identical configuration at the same program point (like every other
    collective-affecting call), and changes apply from the next
    submission."""
    return configure_label(tenant_label(process_set), priority=priority,
                           weight=weight,
                           pending_bytes_quota=pending_bytes_quota,
                           policy=policy)


def get_class(label: str) -> QosClass:
    """The effective class for tenant ``label``: explicit registration,
    else an ``HVD_QOS_CLASSES`` entry, else the env-default class
    (frozen at the label's first lookup)."""
    if envs.get(envs.QOS_CLASSES) == _env_classes_raw:
        cls = _resolved.get(label)  # benign racy read under the GIL
        if cls is not None:
            return cls
    with _mu:
        _sync_env_classes_locked()
        cls = _classes.get(label)
        if cls is None:
            cls = QosClass(**_default_kw())
        _resolved[label] = cls
        return cls


def classes() -> dict:
    """Configured tenant classes (label -> dict), for stats surfaces."""
    with _mu:
        _sync_env_classes_locked()
        return {label: cls.as_dict() for label, cls in
                sorted(_classes.items())}


def reset() -> None:
    """Drop every configured class (tests / teardown)."""
    global _env_classes_raw
    with _mu:
        _classes.clear()
        _explicit.clear()
        _env_labels.clear()
        _resolved.clear()
        _env_classes_raw = None


# --------------------------------------------------------------------------
# the admission gate
# --------------------------------------------------------------------------

class _Rec:
    """One parked batch: the batch itself plus the admission metadata
    frozen at submission time (class changes never reorder already-
    parked work)."""

    __slots__ = ("batch", "tenant", "tier", "weight", "nbytes", "seq",
                 "svc", "names", "t_submit")

    def __init__(self, batch, tenant, cls, nbytes, seq, names, t_submit):
        self.batch = batch
        self.tenant = tenant
        self.tier = cls.priority
        self.weight = cls.weight
        self.nbytes = nbytes
        self.seq = seq
        self.svc = batch.spec.svc is not None
        self.names = names
        self.t_submit = t_submit


class QosGate:
    """Strict-priority + deficit-round-robin admission gate in front of
    the pipelined flush executor.

    All state is guarded by the OWNING scheduler's ``_exec_cv`` (passed
    in), so grant emission into the executor queue is atomic with the
    arbitration decision — two concurrent release points can never
    interleave their grant sequences. Methods suffixed ``_locked``
    assume the condition is held. ``emit(batch)`` is invoked under the
    condition and must enqueue the batch onto the executor FIFO."""

    def __init__(self, cv, emit, on_park=None):
        self._cv = cv
        self._emit = emit
        self._on_park = on_park  # invoked under cv after each park
        self._parked: dict[str, deque] = {}   # tenant -> FIFO of _Rec
        self._order: list[str] = []           # tenant first-arrival order
        self._deficit: dict[str, float] = {}
        self._cursor: dict[int, int] = {}     # per-tier DRR rotation
        self._credited: dict[int, bool] = {}  # cursor tenant credited?
        self._seq = 0
        self._count = 0
        self._svc_count = 0
        # per-tenant parked single-controller bytes: counted by the
        # block-policy quota (they drain via executor demand pulls with
        # no producer action, so a blocked producer cannot deadlock on
        # them — parked NEGOTIATED bytes are excluded: window-bounded,
        # and grantable only at deterministic points the blocked
        # producer would never reach)
        self._sc_bytes: dict[str, float] = {}
        self._valve = 0                       # grants since starve valve
        self._by_entry: dict[int, _Rec] = {}  # id(entry) -> rec
        self._tenant_stats: dict[str, dict] = {}
        self._total_granted_bytes = 0.0
        self._forced = 0
        self._starve_grants = 0
        # deterministic grant record (tenant, seq) — the determinism
        # tests compare it across schedulers fed identical streams
        self.grant_history: deque = deque(maxlen=256)
        self._series: dict[str, dict] = {}    # bound metric handles

    # -- metric plumbing ---------------------------------------------------

    def _tenant_series(self, tenant: str) -> dict:
        s = self._series.get(tenant)
        if s is None:
            labels = {"process_set": tenant}
            s = self._series[tenant] = {
                "wait": _metrics.QOS_ADMISSION_WAIT.bind(labels),
                "granted": _metrics.QOS_GRANTED_BYTES.bind(labels),
                "share": _metrics.QOS_SLOT_SHARE.bind(labels),
            }
        return s

    def _tstats(self, tenant: str) -> dict:
        t = self._tenant_stats.get(tenant)
        if t is None:
            t = self._tenant_stats[tenant] = {
                "granted_batches": 0, "granted_bytes": 0.0}
        return t

    # -- submission (a rank-deterministic flush trigger point) -------------

    def submit(self, batch, tenant: str, cls: QosClass) -> None:
        nbytes = sum(e.nbytes for e in batch.entries)
        names = frozenset(n for e in batch.entries for n in e.names if n)
        with self._cv:
            rec = _Rec(batch, tenant, cls, nbytes, self._seq, names,
                       _inv.monotonic())
            self._seq += 1
            dq = self._parked.get(tenant)
            if dq is None:
                dq = self._parked[tenant] = deque()
                self._order.append(tenant)
            dq.append(rec)
            self._count += 1
            if rec.svc:
                self._svc_count += 1
            else:
                self._sc_bytes[tenant] = (self._sc_bytes.get(tenant, 0.0)
                                          + nbytes)
            for e in batch.entries:
                self._by_entry[id(e)] = rec
            _timeline.record_qos("PARK", tenant)
            if self._on_park is not None:
                # single-controller batches may grant ONLY on executor
                # demand — the executor thread must exist to demand
                self._on_park()
            # deterministic window pump: grant fair-order picks until the
            # negotiated (svc) backlog fits the arbitration window —
            # single-controller batches instead grant on executor demand
            window = max(envs.qos_window(), 0)
            while self._svc_count > window:
                self._grant_locked(self._pick_locked())
            self._cv.notify_all()  # wake the executor for demand pulls

    # -- arbitration -------------------------------------------------------

    def _active_tenants(self, sc_only: bool) -> list[str]:
        return [t for t in self._order
                if self._parked.get(t)
                and not (sc_only and self._parked[t][0].svc)]

    def _pick_locked(self, sc_only: bool = False) -> _Rec | None:
        """The next batch in fair order: the starvation valve's
        oldest-first grant every ``HVD_QOS_STARVE_LIMIT`` grants, else
        strict-priority tiers with deficit-round-robin (byte-weighted)
        inside the top tier. Deterministic: depends only on parked state
        (a pure function of the submission stream) and static config."""
        active = self._active_tenants(sc_only)
        if not active:
            return None
        limit = envs.qos_starve_limit()
        if limit > 0 and self._valve >= limit:
            self._valve = 0
            self._starve_grants += 1
            oldest = min(active, key=lambda t: self._parked[t][0].seq)
            return self._parked[oldest][0]
        top = max(self._parked[t][0].tier for t in active)
        tier = [t for t in active if self._parked[t][0].tier == top]
        quantum = max(envs.qos_quantum_bytes(), 1)
        cur = self._cursor.get(top, 0) % len(tier)
        credited = self._credited.get(top, False)
        # classic DRR: a tenant is credited quantum*weight ONCE on
        # arrival of the rotation cursor, serves while its deficit
        # lasts, then the cursor moves on. Terminates: every full
        # rotation credits each tenant quantum*weight > 0, so some head
        # batch eventually fits.
        while True:
            t = tier[cur]
            head = self._parked[t][0]
            if self._deficit.get(t, 0.0) >= head.nbytes:
                self._cursor[top] = cur
                self._credited[top] = credited
                return head
            if not credited:
                self._deficit[t] = (self._deficit.get(t, 0.0)
                                    + quantum * head.weight)
                credited = True
                continue
            cur = (cur + 1) % len(tier)
            credited = False

    def _grant_locked(self, rec: _Rec | None, forced: bool = False) -> None:
        if rec is None:
            return
        dq = self._parked[rec.tenant]
        assert dq[0] is rec, "QoS grant must serve the tenant's FIFO head"
        dq.popleft()
        self._count -= 1
        if rec.svc:
            self._svc_count -= 1
        else:
            self._sc_bytes[rec.tenant] = max(
                0.0, self._sc_bytes.get(rec.tenant, 0.0) - rec.nbytes)
        # forced grants still consume deficit: observed service counts
        # against the tenant's fair share either way
        self._deficit[rec.tenant] = max(
            0.0, self._deficit.get(rec.tenant, 0.0) - rec.nbytes)
        if not dq:
            # classic DRR: an emptied tenant keeps no residual credit
            self._deficit[rec.tenant] = 0.0
        self._valve += 1
        if forced:
            self._forced += 1
        for e in rec.batch.entries:
            self._by_entry.pop(id(e), None)
        ts = self._tstats(rec.tenant)
        ts["granted_batches"] += 1
        ts["granted_bytes"] += rec.nbytes
        self._total_granted_bytes += rec.nbytes
        series = self._tenant_series(rec.tenant)
        series["granted"].inc(rec.nbytes)
        series["wait"].observe(max(_inv.monotonic() - rec.t_submit, 0.0))
        # only the GRANTING tenant's share gauge updates per grant (an
        # all-tenant refresh would make grant cost O(tenants) inside
        # the executor condition); other tenants' gauges refresh at
        # their own grants and on every stats read (stats_locked), so
        # scrapes between a tenant's grants read its share as of its
        # most recent grant — documented in docs/metrics.md
        if self._total_granted_bytes > 0:
            series["share"].set(
                ts["granted_bytes"] / self._total_granted_bytes)
        self.grant_history.append((rec.tenant, rec.seq))
        # Lockstep decision point (docs/conformance.md): the arbiter's
        # grant order — tenant, per-tenant submission seq, and whether
        # the starvation valve forced it — must be identical rank-wise.
        _conformance.record("qos.py::QosGate._grant_locked", "grant",
                            (rec.tenant, rec.seq, bool(forced)))
        _timeline.record_qos("FORCE" if forced else "GRANT", rec.tenant)
        self._emit(rec.batch)

    # -- demand pull (single-controller batches only) ----------------------

    def demand_pull_locked(self) -> bool:
        """Executor-side work-conserving grant: when the executor FIFO
        runs dry, grant the fair-order pick among parked
        single-controller batches (no negotiation service — no peer
        executor whose issue order could diverge). Returns True when a
        batch was emitted. Negotiated batches are never demand-pulled:
        their grant points must be rank-deterministic."""
        rec = self._pick_locked(sc_only=True)
        if rec is None:
            return False
        self._grant_locked(rec)
        return True

    # -- forced releases (handle observation / drains) ---------------------

    def _release_through_locked(self, rec: _Rec) -> None:
        """Grant ``rec``'s tenant FIFO up to and including ``rec``
        (earlier same-tenant batches must dispatch first: per-signature
        FIFO within a tenant)."""
        dq = self._parked.get(rec.tenant)
        while dq:
            head = dq[0]
            self._grant_locked(head, forced=True)
            if head is rec:
                return

    def release_entry(self, entry) -> None:
        """Handle-observation release (synchronize / first poll) for
        NEGOTIATED batches: if the entry's batch is parked, grant it
        now — a rank-deterministic program point, so every rank's gate
        jumps identically. Single-controller batches deliberately do
        NOT force-release: the executor's demand pull already
        guarantees their progress in tier-first fair order, and a
        forced jump here would let a bulk tenant's synchronize dump its
        parked backlog into the executor FIFO ahead of a latency
        tenant's next request (measured as ~10x p99 spikes in
        ``bench.py --serve-bench`` before this rule)."""
        with self._cv:
            rec = self._by_entry.get(id(entry))
            if rec is not None and rec.svc:
                self._release_through_locked(rec)

    def release_names(self, names) -> None:
        """Name-reuse guard support: grant every parked batch holding
        one of ``names`` (the enqueue-side clash wait would otherwise
        park forever behind the gate)."""
        with self._cv:
            self.release_names_locked(names)

    def release_names_locked(self, names) -> None:
        """Locked body of :meth:`release_names` — also called from
        ``_wait_names_clear``'s wait loop under the shared condition:
        the clashing batch may only PARK after the waiter's first
        release attempt (the drain registers its names before the
        negotiate-submit round trip that precedes the park), so the
        waiter must re-attempt the release on every wakeup or that
        window would park it forever."""
        pending = set(names)
        while pending:
            hit = None
            for tenant in self._order:
                for rec in self._parked.get(tenant, ()):
                    if not pending.isdisjoint(rec.names):
                        if hit is None or rec.seq < hit.seq:
                            hit = rec
                        break
            if hit is None:
                return
            pending.difference_update(hit.names)
            self._release_through_locked(hit)

    def release_all(self) -> None:
        """Drain the gate in fair order (flush_all / barrier / shutdown:
        callers need everything dispatched on return)."""
        with self._cv:
            self.release_all_locked()

    def release_all_locked(self) -> None:
        while self._count:
            self._grant_locked(self._pick_locked())

    def drain_locked(self) -> list:
        """Abort path: pop every parked batch WITHOUT emitting (the
        world the batches were negotiated against is gone); the caller
        fails their entries. Resets arbitration state."""
        batches = []
        for tenant in self._order:
            dq = self._parked.get(tenant)
            while dq:
                rec = dq.popleft()
                for e in rec.batch.entries:
                    self._by_entry.pop(id(e), None)
                batches.append(rec.batch)
        self._count = 0
        self._svc_count = 0
        self._sc_bytes.clear()
        self._deficit.clear()
        return batches

    # -- introspection -----------------------------------------------------

    def parked_depth_locked(self) -> int:
        return self._count

    def sc_parked_bytes_locked(self, tenant: str) -> float:
        """Parked single-controller bytes for ``tenant`` (the
        block-quota component that drains on executor demand)."""
        return self._sc_bytes.get(tenant, 0.0)

    def stats_locked(self) -> dict:
        # union of granted AND parked tenants: a never-granted tenant
        # parked behind higher tiers (the starvation condition this
        # surface exists to expose) must still show its parked depth
        names = set(self._tenant_stats)
        names.update(t for t, dq in self._parked.items() if dq)
        tenants = {}
        for tenant in sorted(names):
            st = self._tenant_stats.get(
                tenant, {"granted_batches": 0, "granted_bytes": 0.0})
            share = (st["granted_bytes"] / self._total_granted_bytes
                     if self._total_granted_bytes else 0.0)
            if st["granted_bytes"]:
                # stats reads re-true every tenant's share gauge (the
                # per-grant path only updates the granting tenant's)
                self._tenant_series(tenant)["share"].set(share)
            tenants[tenant] = {
                "granted_batches": st["granted_batches"],
                "granted_bytes": st["granted_bytes"],
                "share": share,
                "parked": len(self._parked.get(tenant, ())),
            }
        return {
            "parked": self._count,
            "parked_svc": self._svc_count,
            "forced_grants": self._forced,
            "starve_grants": self._starve_grants,
            "granted_bytes_total": self._total_granted_bytes,
            "tenants": tenants,
        }


def qos_stats() -> dict:
    """The ``hvd.qos_stats()`` surface: static config (knobs + tenant
    classes) plus the calling world's scheduler-side admission counters
    (``fusion_stats()["qos"]``)."""
    from .ops import fusion_cycle as _fc
    return {
        "enabled": enabled(),
        "window": envs.qos_window(),
        "quantum_bytes": envs.qos_quantum_bytes(),
        "starve_limit": envs.qos_starve_limit(),
        "classes": classes(),
        **_fc.scheduler().stats().get("qos", {}),
    }
