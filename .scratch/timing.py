import time
import jax, jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P
import horovod_tpu as hvd
from horovod_tpu.models import ResNet50

def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)

log("imports done")
hvd.init()
n = hvd.size(); axis = hvd.axis_name(); mesh = hvd.mesh()
log(f"hvd.init done n={n}")
BS = 256
model = ResNet50(num_classes=1000, dtype=jnp.bfloat16, axis_name=axis)
rng = jax.random.PRNGKey(0)
images = jnp.asarray(np.random.default_rng(0).standard_normal((BS, 224, 224, 3), dtype=np.float32))
labels = jnp.asarray(np.random.default_rng(1).integers(0, 1000, size=(BS,)))
log("data on device")
variables = model.init(rng, jnp.zeros((1, 224, 224, 3), jnp.float32), train=True)
params, batch_stats = variables["params"], variables["batch_stats"]
tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
opt_state = tx.init(params)
log("init done")

def train_step(params, batch_stats, opt_state, images, labels):
    def loss_fn(p):
        logits, mutated = model.apply({"params": p, "batch_stats": batch_stats}, images, train=True, mutable=["batch_stats"])
        one_hot = jax.nn.one_hot(labels, 1000)
        loss = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits), -1))
        return loss, mutated["batch_stats"]
    (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, new_opt = tx.update(grads, opt_state, params)
    new_params = optax.apply_updates(params, updates)
    return new_params, new_stats, new_opt, loss

step = jax.jit(jax.shard_map(train_step, mesh=mesh,
    in_specs=(P(), P(), P(), P(axis), P(axis)), out_specs=(P(), P(), P(), P()),
    check_vma=False), donate_argnums=(0, 1, 2))

log("compiling...")
params, batch_stats, opt_state, loss = step(params, batch_stats, opt_state, images, labels)
log("first step dispatched")
lf = float(loss)
log(f"first step complete loss={lf:.3f}")
for i in range(2):
    params, batch_stats, opt_state, loss = step(params, batch_stats, opt_state, images, labels)
    lf = float(loss)
    log(f"warmup {i} complete loss={lf:.3f}")

for N in (10, 20):
    t0 = time.perf_counter()
    for _ in range(N):
        params, batch_stats, opt_state, loss = step(params, batch_stats, opt_state, images, labels)
    lf = float(loss)
    dt = time.perf_counter() - t0
    per = dt / N
    log(f"N={N}: {per*1e3:.2f} ms/step  {BS/per:.0f} img/s  MFU {6.12e12/per/197e12:.2%}  loss={lf:.3f}")
