"""hvdlint static-analysis suite: fixture-driven per-pass tests + the
repo-tree gate.

Every pass gets (at least) one fixture that TRIPS the rule and one that
PASSES it, exercised through the same ``Project``/``run_all`` machinery
the CLI uses; the final test runs the whole suite over the real
``horovod_tpu`` tree and requires zero findings — the same gate ci.sh
enforces.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from tools.hvdlint import Project, run_all  # noqa: E402

ENVS_FIXTURE = 'GOOD_KNOB = "GOOD_KNOB"\n'
KNOBS_DOC_FIXTURE = "| `HVD_GOOD_KNOB` | documented |\n"


def make_project(tmp_path, ops_sources: dict[str, str], *,
                 envs_py: str = ENVS_FIXTURE,
                 knobs_md: str = KNOBS_DOC_FIXTURE,
                 extra: dict[str, str] | None = None) -> Project:
    pkg = tmp_path / "pkg"
    (pkg / "ops").mkdir(parents=True)
    (pkg / "utils").mkdir()
    (tmp_path / "docs").mkdir()
    (pkg / "utils" / "envs.py").write_text(envs_py)
    (tmp_path / "docs" / "knobs.md").write_text(knobs_md)
    for name, src in ops_sources.items():
        (pkg / "ops" / name).write_text(textwrap.dedent(src))
    for rel, src in (extra or {}).items():
        path = pkg / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return Project(tmp_path, package_rel="pkg")


def findings_for(tmp_path, pass_name: str, ops_sources: dict[str, str],
                 **kwargs):
    project = make_project(tmp_path, ops_sources, **kwargs)
    return run_all(project, only=[pass_name])


# ---------------------------------------------------------------------------
# issue-lock
# ---------------------------------------------------------------------------

class TestIssueLock:
    def test_trips_on_unwrapped_jit(self, tmp_path):
        src = """
            import jax

            def build():
                return jax.jit(jax.shard_map(lambda x: x, mesh=None))
        """
        found = findings_for(tmp_path, "issue-lock", {"bad.py": src})
        assert len(found) == 1
        assert "issue_serialized" in found[0].message
        assert found[0].path == "pkg/ops/bad.py"

    def test_trips_on_eager_shard_map_invocation(self, tmp_path):
        src = """
            import jax

            def run(x):
                return jax.shard_map(lambda y: y, mesh=None)(x)
        """
        found = findings_for(tmp_path, "issue-lock", {"bad.py": src})
        assert len(found) == 1
        assert "shard_map" in found[0].message

    def test_passes_when_wrapped(self, tmp_path):
        src = """
            import jax
            from .program_issue import issue_serialized as _issue_serialized

            def build():
                return _issue_serialized(
                    jax.jit(jax.shard_map(lambda x: x, mesh=None)))
        """
        found = findings_for(tmp_path, "issue-lock", {"good.py": src})
        assert found == []

    def test_pragma_suppresses(self, tmp_path):
        src = """
            import jax

            def build():
                return jax.jit(lambda x: x)  # hvdlint: disable=issue-lock
        """
        found = findings_for(tmp_path, "issue-lock", {"ok.py": src})
        assert found == []

    def test_wrapper_in_enclosing_scope_does_not_cover_nested_def(
            self, tmp_path):
        src = """
            import jax
            from .program_issue import issue_serialized

            def build():
                return issue_serialized(make())

            def make():
                def inner():
                    return jax.jit(lambda x: x)
                return inner
        """
        # the jit inside `inner` is NOT lexically wrapped
        found = findings_for(tmp_path, "issue-lock", {"bad.py": src})
        assert len(found) == 1


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

class TestLockOrder:
    def test_trips_on_nested_with_cycle(self, tmp_path):
        src = """
            import threading
            _a_lock = threading.Lock()
            _b_lock = threading.Lock()

            def ab():
                with _a_lock:
                    with _b_lock:
                        pass

            def ba():
                with _b_lock:
                    with _a_lock:
                        pass
        """
        found = findings_for(tmp_path, "lock-order", {"cycle.py": src})
        assert len(found) == 1
        assert "cycle" in found[0].message
        assert "_a_lock" in found[0].message and "_b_lock" in found[0].message

    def test_trips_on_interprocedural_cycle(self, tmp_path):
        src = """
            import threading
            _a_lock = threading.Lock()
            _b_lock = threading.Lock()

            def ab():
                with _a_lock:
                    with _b_lock:
                        pass

            def ba():
                with _b_lock:
                    helper()

            def helper():
                with _a_lock:
                    pass
        """
        found = findings_for(tmp_path, "lock-order", {"cycle.py": src})
        assert len(found) == 1
        assert "call into helper" in found[0].message

    def test_passes_on_consistent_order(self, tmp_path):
        src = """
            import threading
            _a_lock = threading.Lock()
            _b_lock = threading.Lock()

            def one():
                with _a_lock:
                    with _b_lock:
                        pass

            def two():
                with _a_lock:
                    with _b_lock:
                        pass

            def sequential():
                with _b_lock:
                    pass
                with _a_lock:
                    pass
        """
        found = findings_for(tmp_path, "lock-order", {"ok.py": src})
        assert found == []

    def test_nested_def_not_under_enclosing_lock(self, tmp_path):
        # a closure DEFINED under a lock runs later: no A->B edge
        src = """
            import threading
            _a_lock = threading.Lock()
            _b_lock = threading.Lock()

            def build():
                with _a_lock:
                    def cb():
                        with _b_lock:
                            pass
                return cb

            def other():
                with _b_lock:
                    with _a_lock:
                        pass
        """
        found = findings_for(tmp_path, "lock-order", {"ok.py": src})
        assert found == []


# ---------------------------------------------------------------------------
# timer-purity
# ---------------------------------------------------------------------------

TIMER_PRELUDE = "import time\nimport random\n"


class TestTimerPurity:
    def _fixture(self, body: str) -> str:
        return TIMER_PRELUDE + textwrap.dedent(body)

    def test_trips_on_wallclock_random_and_set_iteration(self, tmp_path):
        src = self._fixture("""
            class FusionScheduler:
                def _loop(self):  # hvdlint: timer-root
                    t = time.time()
                    random.random()
                    for name in {"a", "b"}:
                        self.flush(name)

                def flush(self, name):
                    pass
        """)
        found = findings_for(tmp_path, "timer-purity", {"sched.py": src})
        msgs = "\n".join(f.message for f in found)
        assert len(found) == 3
        assert "time.time" in msgs
        assert "random" in msgs
        assert "unordered set" in msgs

    def test_trips_on_reachable_negotiation(self, tmp_path):
        src = self._fixture("""
            class FusionScheduler:
                def _loop(self):  # hvdlint: timer-root
                    self.flush("x")

                def flush(self, key):
                    self.svc.negotiate_many([])
        """)
        found = findings_for(tmp_path, "timer-purity", {"sched.py": src})
        assert len(found) == 1
        assert "negotiate" in found[0].message

    def test_monotonic_and_boundary_pass(self, tmp_path):
        src = self._fixture("""
            class FusionScheduler:
                def _loop(self):  # hvdlint: timer-root
                    now = time.monotonic()
                    self.flush("x")

                def flush(self, key):
                    dispatch(key)

            def dispatch(key):  # hvdlint: timer-boundary
                import time as _t
                _t.time()  # unreachable for svc queues: boundary stops here
        """)
        found = findings_for(tmp_path, "timer-purity", {"sched.py": src})
        assert found == []

    def test_pragma_suppresses_guarded_call(self, tmp_path):
        src = self._fixture("""
            class FusionScheduler:
                def _loop(self):  # hvdlint: timer-root
                    self.flush("x")

                def flush(self, key):
                    self.svc.negotiate_many([])  # hvdlint: disable=timer-purity
        """)
        found = findings_for(tmp_path, "timer-purity", {"sched.py": src})
        assert found == []


# ---------------------------------------------------------------------------
# knob-registry
# ---------------------------------------------------------------------------

class TestKnobRegistry:
    def test_trips_on_direct_environ_read(self, tmp_path):
        src = """
            import os

            def read():
                return os.environ.get("HVD_SOMETHING")
        """
        found = findings_for(tmp_path, "knob-registry", {"bad.py": src})
        assert len(found) == 1
        assert "bypasses the utils/envs.py registry" in found[0].message

    def test_trips_on_literal_getter_arg(self, tmp_path):
        src = """
            from ..utils import envs

            def read():
                return envs.get_bool("GOOD_KNOB")
        """
        found = findings_for(tmp_path, "knob-registry", {"bad.py": src})
        assert len(found) == 1
        assert "registry constants" in found[0].message

    def test_trips_on_doc_drift_both_directions(self, tmp_path):
        found = findings_for(
            tmp_path, "knob-registry", {"empty.py": ""},
            envs_py='GOOD_KNOB = "GOOD_KNOB"\nNEW_KNOB = "NEW_KNOB"\n',
            knobs_md="`HVD_GOOD_KNOB` `HVD_GHOST_KNOB`\n")
        msgs = "\n".join(f.message for f in found)
        assert "HVD_NEW_KNOB" in msgs and "undocumented" in msgs
        assert "HVD_GHOST_KNOB" in msgs and "stale" in msgs
        assert len(found) == 2

    def test_passes_on_registry_usage_and_env_writes(self, tmp_path):
        src = """
            import os
            from ..utils import envs

            def read():
                return envs.get_bool(envs.GOOD_KNOB)

            def seed():
                os.environ["HVD_SEEDED"] = "1"  # launcher writes are legal
        """
        found = findings_for(tmp_path, "knob-registry", {"ok.py": src})
        assert found == []

    def test_trips_on_literal_tunable(self, tmp_path):
        project = make_project(
            tmp_path, {"empty.py": ""},
            extra={"autotune.py": """
                class Tunable:
                    def __init__(self, knob, candidates):
                        pass

                def tunables():
                    return [Tunable("GOOD_KNOB", [1, 2])]
            """})
        found = run_all(project, only=["knob-registry"])
        assert len(found) == 1
        assert "Tunable" in found[0].message


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

class TestDonation:
    def test_trips_on_read_after_donating_call(self, tmp_path):
        src = """
            import jax

            def run(buf):
                f = jax.jit(lambda x: x, donate_argnums=(0,))
                out = f(buf)
                return buf.sum() + out
        """
        found = findings_for(tmp_path, "donation", {"bad.py": src})
        assert len(found) == 1
        assert "'buf' was donated" in found[0].message

    def test_trips_through_issue_serialized_wrapper_and_star_args(
            self, tmp_path):
        src = """
            import jax
            from .program_issue import issue_serialized as _issue_serialized

            def run(bufs):
                wire_fn = _issue_serialized(
                    jax.jit(lambda *xs: xs, donate_argnums=(0, 1)))
                outs = wire_fn(*bufs)
                return bufs[0], outs
        """
        found = findings_for(tmp_path, "donation", {"bad.py": src})
        assert len(found) == 1

    def test_passes_when_rebound_or_unused(self, tmp_path):
        src = """
            import jax

            def rebound(buf):
                f = jax.jit(lambda x: x, donate_argnums=(0,))
                buf = f(buf)
                return buf  # rebinding makes the later read safe

            def composed(a, b):
                f = jax.jit(lambda x: x, donate_argnums=(0,))
                g = jax.jit(lambda x: x)
                return f(g(a)) + b  # only a temporary is donated
        """
        found = findings_for(tmp_path, "donation", {"ok.py": src})
        assert found == []

    def test_closure_violation_reported_exactly_once(self, tmp_path):
        # the donating binding lives in the builder; the bad read lives in
        # the nested execute closure — one finding, not two (the outer
        # sweep must not descend into nested defs)
        src = """
            import jax

            def build(bufs):
                wire_fn = jax.jit(lambda *xs: xs, donate_argnums=(0,))

                def execute():
                    outs = wire_fn(*bufs)
                    return bufs, outs

                return execute
        """
        found = findings_for(tmp_path, "donation", {"bad.py": src})
        assert len(found) == 1
        assert "'bufs' was donated" in found[0].message

    def test_non_donating_positions_are_free(self, tmp_path):
        src = """
            import jax

            def run(scratch, data):
                f = jax.jit(lambda s, d: d, donate_argnums=(0,))
                out = f(scratch, data)
                return data.sum() + out  # position 1 is not donated
        """
        found = findings_for(tmp_path, "donation", {"ok.py": src})
        assert found == []

    def test_trips_on_step_capture_buffer_read_after_donate(self, tmp_path):
        # the step capture constructor's wire stage donates EVERY fused
        # buffer — naming the fuse outputs and reading them after the
        # wire call is the read-after-donate class the registration of
        # _plan_step_programs catches
        src = """
            def replay(parts, flat):
                fuse_fn, wire_fn = _plan_step_programs(parts)
                bufs = fuse_fn(*flat)
                outs = wire_fn(*bufs)
                return bufs[0], outs  # bufs was donated into wire_fn
        """
        found = findings_for(tmp_path, "donation", {"bad.py": src})
        assert len(found) == 1
        assert "'bufs' was donated" in found[0].message

    def test_passes_on_step_capture_inline_composition(self, tmp_path):
        # the in-tree idiom: the fused buffers never get a name, so no
        # read-after-donate is possible
        src = """
            def replay(parts, flat):
                fuse_fn, wire_fn = _plan_step_programs(parts)
                outs = wire_fn(*fuse_fn(*flat))
                return list(outs)
        """
        found = findings_for(tmp_path, "donation", {"ok.py": src})
        assert found == []

    def test_trips_on_gspmd_cached_step_read_after_donate(self, tmp_path):
        # the ISSUE-16 seam: params/opt-state handed to a donated
        # cached-step position belong to the step — a dynamic donate=
        # mask conservatively donates every position, so reading params
        # after the call is the read-after-donate class
        src = """
            def train(fn, params, batch, mask):
                step = _gspmd_step_program(fn, (params, batch),
                                           donate=mask)
                out = step(params, batch)
                return params, out  # params was donated into step
        """
        found = findings_for(tmp_path, "donation", {"bad.py": src})
        assert len(found) == 1
        assert "'params' was donated" in found[0].message

    def test_passes_on_gspmd_cached_step_rebinding(self, tmp_path):
        # the training-loop idiom: the donated carry is rebound from the
        # step's outputs, so later reads see fresh buffers; donate=()
        # never donates at all
        src = """
            def train(fn, params, batch, mask):
                step = _gspmd_step_program(fn, (params, batch),
                                           donate=mask)
                params = step(params, batch)
                return params

            def undonated(fn, params, batch):
                step = _gspmd_step_program(fn, (params, batch), donate=())
                out = step(params, batch)
                return params, out
        """
        found = findings_for(tmp_path, "donation", {"ok.py": src})
        assert found == []


# ---------------------------------------------------------------------------
# issue-lock x step capture (the un-serialized-jit-in-step_capture class)
# ---------------------------------------------------------------------------

class TestStepCaptureIssueLock:
    def test_trips_on_unserialized_step_jit(self, tmp_path):
        # a whole-step program compiled without the program-issue lock is
        # exactly the concurrent-enqueue rendezvous-deadlock class PR 3
        # reproduced — pass 1 must catch it in step_capture-style code
        src = """
            import jax

            def _plan_step_programs(parts):
                fuse_fn = jax.jit(lambda *xs: xs)
                wire_fn = jax.jit(lambda *xs: xs, donate_argnums=(0,))
                return fuse_fn, wire_fn
        """
        found = findings_for(tmp_path, "issue-lock",
                             {"step_capture.py": src})
        assert len(found) == 2
        assert all("issue_serialized" in f.message for f in found)

    def test_passes_on_serialized_step_jit(self, tmp_path):
        src = """
            import jax
            from .program_issue import issue_serialized as _issue_serialized

            def _plan_step_programs(parts):
                fuse_fn = _issue_serialized(jax.jit(lambda *xs: xs))
                wire_fn = _issue_serialized(jax.jit(
                    lambda *xs: xs, donate_argnums=(0,)))
                return fuse_fn, wire_fn
        """
        found = findings_for(tmp_path, "issue-lock",
                             {"step_capture.py": src})
        assert found == []


class TestGspmdCacheIssueLock:
    def test_trips_on_unserialized_aot_compile(self, tmp_path):
        # an AOT-compiled GSPMD step enqueued without the program-issue
        # lock is the same concurrent-enqueue deadlock class — the
        # .lower().compile() chain does not exempt the jit call
        src = """
            import jax

            def _gspmd_step_program(fn, args, donate=()):
                return jax.jit(
                    fn, donate_argnums=tuple(donate)).lower(*args).compile()
        """
        found = findings_for(tmp_path, "issue-lock",
                             {"gspmd_cache.py": src})
        assert len(found) == 1
        assert "issue_serialized" in found[0].message

    def test_passes_on_serialized_aot_compile(self, tmp_path):
        # the in-tree gspmd_cache idiom: the whole lower/compile chain
        # nests inside the _issue_serialized argument expression
        src = """
            import jax
            from .program_issue import issue_serialized as _issue_serialized

            def _gspmd_step_program(fn, args, donate=()):
                return _issue_serialized(jax.jit(
                    fn, donate_argnums=tuple(donate)).lower(*args).compile())
        """
        found = findings_for(tmp_path, "issue-lock",
                             {"gspmd_cache.py": src})
        assert found == []


# ---------------------------------------------------------------------------
# silent-except
# ---------------------------------------------------------------------------

class TestSilentExcept:
    def test_trips_on_broad_silent_handlers(self, tmp_path):
        src = """
            def swallow():
                try:
                    risky()
                except Exception:
                    pass
                try:
                    risky()
                except:
                    pass
                try:
                    risky()
                except (ValueError, BaseException):
                    pass
        """
        found = findings_for(tmp_path, "silent-except", {"bad.py": src})
        assert len(found) == 3
        assert all("silent handler" in f.message for f in found)

    def test_narrow_typed_pass_is_legal(self, tmp_path):
        src = """
            import queue

            def drain(q):
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                try:
                    q.close()
                except (OSError, ValueError):
                    pass
        """
        found = findings_for(tmp_path, "silent-except", {"ok.py": src})
        assert found == []

    def test_nonempty_handler_body_is_legal(self, tmp_path):
        src = """
            def logged(log):
                try:
                    risky()
                except Exception:
                    log.warning("risky failed")
        """
        found = findings_for(tmp_path, "silent-except", {"ok.py": src})
        assert found == []

    def test_pragma_suppresses_handler(self, tmp_path):
        src = """
            def vetted():
                try:
                    risky()
                except Exception:  # hvdlint: disable=silent-except
                    pass  # torn down at GC time; nothing can be done
        """
        found = findings_for(tmp_path, "silent-except", {"ok.py": src})
        assert found == []

    def test_trips_on_sleep_retry_loop(self, tmp_path):
        src = """
            import time

            def poll(ready):
                while not ready():
                    time.sleep(0.1)
        """
        found = findings_for(tmp_path, "silent-except", {"bad.py": src})
        assert len(found) == 1
        assert "utils/retry.py" in found[0].message

    def test_sleep_outside_loop_and_in_retry_home_are_legal(self, tmp_path):
        loop_src = """
            import time

            def backoff_loop():
                while True:
                    time.sleep(0.1)
        """
        src = """
            import time

            def one_shot():
                time.sleep(0.5)
        """
        found = findings_for(
            tmp_path, "silent-except", {"ok.py": src},
            extra={"utils/retry.py": loop_src})
        assert found == []

    def test_sleep_in_nested_def_inside_loop_is_that_funcs_business(
            self, tmp_path):
        src = """
            import time

            def build():
                fns = []
                for _ in range(3):
                    def waiter():
                        time.sleep(0.1)
                    fns.append(waiter)
                return fns
        """
        found = findings_for(tmp_path, "silent-except", {"ok.py": src})
        assert found == []

    def test_sleep_pragma_suppresses(self, tmp_path):
        src = """
            import time

            def escalate(alive):
                while alive():
                    time.sleep(0.1)  # hvdlint: disable=silent-except
        """
        found = findings_for(tmp_path, "silent-except", {"ok.py": src})
        assert found == []


# ---------------------------------------------------------------------------
# rank-divergence
# ---------------------------------------------------------------------------


class TestRankDivergence:
    def test_trips_on_rank_conditioned_submission(self, tmp_path):
        src = """
            from ..core import rank

            def broadcast_params(h):
                if rank() == 0:
                    h.allreduce_async([1.0], name="params")
        """
        found = findings_for(tmp_path, "rank-divergence", {"bad.py": src})
        assert len(found) == 1
        assert "allreduce_async" in found[0].message
        assert "rank()" in found[0].message

    def test_trips_on_tainted_local_and_wallclock(self, tmp_path):
        src = """
            import time
            from ..core import local_rank

            def flush(sched, entry):
                me = local_rank()
                if me < 2:
                    sched.flush_entry(entry)

            def timed(sched, entry):
                while time.monotonic() < 5.0:
                    sched.flush_entry(entry)

            def seam_clock(sched, entry, _inv):
                if _inv.monotonic() > 1.0:
                    sched.flush_entry(entry)
        """
        found = findings_for(tmp_path, "rank-divergence", {"bad.py": src})
        msgs = "\n".join(f.message for f in found)
        assert len(found) == 3
        assert "me (from local_rank())" in msgs
        assert "time.monotonic() (wall clock)" in msgs
        assert "_inv.monotonic() (wall clock)" in msgs  # the seam alias

    def test_trips_on_set_iteration_order(self, tmp_path):
        src = """
            def submit_all(svc, names):
                pending = set(names)
                for n in pending:
                    svc.negotiate_many_submit([n])
        """
        found = findings_for(tmp_path, "rank-divergence", {"bad.py": src})
        assert len(found) == 1
        assert "unordered set" in found[0].message

    def test_trips_on_dynamic_queue_and_tenant_state(self, tmp_path):
        # ISSUE 12: a collective conditioned on dynamic queue depth or
        # tenant runtime state (completion-timed values that differ per
        # rank) is the same mismatched-collective hang class
        src = """
            import horovod_tpu as hvd

            def adaptive(h):
                if hvd.fusion_stats()["pending_bytes"] > 1024:
                    h.allreduce_async([1.0], name="adaptive")

            def tenant_gated(h):
                load = hvd.qos_stats()["quota_blocks"]
                if load > 3:
                    h.allreduce_async([1.0], name="gated")
        """
        found = findings_for(tmp_path, "rank-divergence", {"bad.py": src})
        msgs = "\n".join(f.message for f in found)
        assert len(found) == 2, msgs
        assert "dynamic queue/tenant runtime state" in msgs

    def test_trips_on_autoscale_policy_state(self, tmp_path):
        # ISSUE 15: autoscale decisions are DRIVER-authoritative — a
        # rank branching a collective on policy output (or on its own
        # straggler observations feeding the policy) is the
        # mismatched-collective hang class, exactly like rank()
        src = """
            import horovod_tpu as hvd

            def policy_gated(h, pol):
                if pol.policy_stats()["breach_streak"] > 0:
                    h.allreduce_async([1.0], name="gated")

            def decision_gated(h, pol, entry):
                d = pol.last_decision
                if d is not None:
                    h.flush_entry(entry)

            def blame_gated(h, svc):
                lag = svc.straggler_stats()["current_streak"]
                if lag:
                    h.allreduce_async([1.0], name="blamed")

            def blames_gated(h, health):
                if health.straggler_blames():
                    h.allreduce_async([1.0], name="blames")
        """
        found = findings_for(tmp_path, "rank-divergence", {"bad.py": src})
        msgs = "\n".join(f.message for f in found)
        assert len(found) == 4, msgs
        assert "autoscale policy decision state" in msgs
        assert "dynamic queue/tenant runtime state" in msgs

    def test_autoscale_state_as_value_passes(self, tmp_path):
        # reading policy/straggler state as a VALUE (logging, sensor
        # blobs, stats surfaces) is fine; only branching a collective
        # on it diverges
        src = """
            def report(pol, svc, log):
                log.append(pol.policy_stats())
                log.append(svc.straggler_stats())

            def stats_near_collective(h, pol):
                h.allreduce_async([1.0], name="x")
                snapshot = pol.policy_stats()
                return snapshot
        """
        found = findings_for(tmp_path, "rank-divergence", {"ok.py": src})
        assert found == []

    def test_static_qos_config_passes(self, tmp_path):
        # static weights/priorities/quotas are pure config (identical on
        # every rank by the set_qos contract) — NOT flagged
        src = """
            import horovod_tpu as hvd
            from horovod_tpu import qos

            def class_gated(h, ps):
                cls = qos.get_class(qos.tenant_label(ps))
                if cls.priority > 0:
                    h.allreduce_async([1.0], name="prio")
        """
        found = findings_for(tmp_path, "rank-divergence", {"ok.py": src})
        assert found == []

    def test_trips_on_leader_role_state(self, tmp_path):
        # ISSUE 13: "am I a leader" is rank-local exactly like rank() —
        # a collective conditioned on it hangs the member ranks
        src = """
            def leader_gated(h, layout, me):
                if layout.is_leader(me):
                    h.allreduce_async([1.0], name="agg")

            def cached_role(h, transport, entry):
                role = transport.is_leader
                if role:
                    h.flush_entry(entry)
        """
        found = findings_for(tmp_path, "rank-divergence", {"bad.py": src})
        msgs = "\n".join(f.message for f in found)
        assert len(found) == 2, msgs
        assert "leader-role state" in msgs

    def test_static_group_layout_shape_passes(self, tmp_path):
        # the layout's rank-SYMMETRIC shape queries are pure functions
        # of (world, G): every rank computes the same value — NOT flagged
        src = """
            from horovod_tpu.negotiation import GroupLayout

            def per_group(h, world):
                layout = GroupLayout(world, 8)
                if layout.n_groups > 1:
                    h.allreduce_async([1.0], name="per_group")
                for g in range(layout.n_groups):
                    h.allreduce_async([float(g)], name=f"g{g}")

            def leader_as_value(h, layout, gid):
                h.allreduce_async([1.0], name=f"lead.{layout.leader_of(gid)}")
        """
        found = findings_for(tmp_path, "rank-divergence", {"ok.py": src})
        assert found == []

    def test_rank_symmetric_conditionals_pass(self, tmp_path):
        # every rank evaluates the same test the same way: no divergence
        src = """
            def bcast(h, root_rank, tensors):
                if root_rank is not None:
                    h.broadcast_async(tensors, root_rank)

            def drain(sched, entries):
                for e in sorted(entries):
                    sched.flush_entry(e)

            def guarded(h, enabled):
                if enabled:
                    h.allreduce_async([1.0], name="x")
        """
        found = findings_for(tmp_path, "rank-divergence", {"ok.py": src})
        assert found == []

    def test_rank_read_without_control_flow_passes(self, tmp_path):
        # using rank() as a VALUE is fine; only branching on it diverges
        src = """
            from ..core import rank

            def tagged(h):
                h.allreduce_async([1.0], name=f"grad.{rank()}")
        """
        found = findings_for(tmp_path, "rank-divergence", {"ok.py": src})
        assert found == []

    def test_trips_on_data_axis_index_queries(self, tmp_path):
        # ISSUE 17: the composed-mesh layer makes "my coordinate in the
        # gradient-sync group" as reachable as rank() — axis_index on a
        # data axis (literal or canonical constant) and mesh coordinate
        # lookups taint exactly like rank()
        src = """
            from jax import lax
            from ..parallel.mesh import DATA_AXES, DCN_AXIS

            def two_level(h):
                if lax.axis_index("ici_dp") == 0:
                    h.allreduce_async([1.0], name="cross")

            def cross_slice(h, entry):
                d = lax.axis_index(DCN_AXIS)
                if d > 0:
                    h.flush_entry(entry)

            def subscripted(h):
                if lax.axis_index(DATA_AXES[0]) == 0:
                    h.allreduce_async([1.0])

            def coords(h, mesh, dev, entry):
                if mesh.coords_of(dev)[0] == 0:
                    h.flush_entry(entry)
        """
        found = findings_for(tmp_path, "rank-divergence", {"bad.py": src})
        msgs = "\n".join(f.message for f in found)
        assert len(found) == 4, msgs
        assert "on a data axis" in msgs
        assert "mesh coordinate lookup" in msgs

    def test_model_axis_index_queries_stay_legal(self, tmp_path):
        # a schedule's own positioning math — axis_index over a MODEL
        # axis (cfg.seq_axis / "expert") or a variable axis name — is
        # legal traced compute, not submission-conditioning divergence
        src = """
            from jax import lax

            def schedule(h, cfg, axis):
                if lax.axis_index(cfg.seq_axis) == 0:
                    h.allreduce_async([1.0], name="pos")
                if lax.axis_index("expert") == 0:
                    h.allreduce_async([1.0], name="route")
                if lax.axis_index(axis) == 0:
                    h.allreduce_async([1.0], name="var")
        """
        found = findings_for(tmp_path, "rank-divergence", {"ok.py": src})
        assert found == []

    def test_pragma_suppresses(self, tmp_path):
        src = """
            from ..core import rank

            def vetted(h):
                if rank() == 0:
                    # out-of-band agreement: every rank knows rank 0 submits
                    h.allreduce_async([1.0])  # hvdlint: disable=rank-divergence
        """
        found = findings_for(tmp_path, "rank-divergence", {"ok.py": src})
        assert found == []


# ---------------------------------------------------------------------------
# the real tree + CLI contract
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# metrics-registry
# ---------------------------------------------------------------------------

METRICS_PY_FIXTURE = """
    def counter(name, help, labels=(), always=False):
        return name


    def histogram(name, help, labels=(), always=False):
        return name


    GOOD = counter("hvd_good_total", "a registered counter")
    LAT = histogram("hvd_lat_seconds", "a registered histogram")
"""

METRICS_DOC_FIXTURE = (
    "| `hvd_good_total` | counter |\n"
    "| `hvd_lat_seconds` | histogram | (series: `hvd_lat_seconds_bucket`,"
    " `hvd_lat_seconds_sum`, `hvd_lat_seconds_count`) |\n")


class TestMetricsRegistry:
    def _findings(self, tmp_path, sources, *, metrics_py=METRICS_PY_FIXTURE,
                  metrics_md=METRICS_DOC_FIXTURE):
        project = make_project(
            tmp_path, sources,
            extra={"metrics.py": metrics_py} if metrics_py else None)
        if metrics_md is not None:
            (tmp_path / "docs" / "metrics.md").write_text(metrics_md)
            # Project snapshots files at construction; the doc is read
            # at run time, so writing it after make_project is fine.
        return run_all(project, only=["metrics-registry"])

    def test_trips_on_adhoc_module_counter(self, tmp_path):
        src = """
            _hits = 0


            def lookup():
                global _hits
                _hits += 1
        """
        found = self._findings(tmp_path, {"bad.py": src})
        assert len(found) == 1
        assert "module-level counter '_hits'" in found[0].message

    def test_trips_on_adhoc_dict_telemetry(self, tmp_path):
        src = """
            _by_site = {}


            def note(site):
                _by_site[site] += 1


            def note2(site):
                _by_site[site] = _by_site.get(site, 0) + 1
        """
        found = self._findings(tmp_path, {"bad.py": src})
        assert len(found) == 2
        assert all("dict '_by_site'" in f.message for f in found)

    def test_instance_and_local_state_is_legal(self, tmp_path):
        src = """
            _epoch_base = 7


            class Sched:
                def __init__(self):
                    self._stats = {"flushes": 0}

                def flush(self):
                    self._stats["flushes"] += 1


            def pure(counts):
                total = 0
                for c in counts:
                    total += c
                return total + _epoch_base
        """
        assert self._findings(tmp_path, {"ok.py": src}) == []

    def test_pragma_suppresses_epoch_counter(self, tmp_path):
        src = """
            _epoch = 0


            def bump():
                global _epoch
                _epoch += 1  # hvdlint: disable=metrics-registry
        """
        assert self._findings(tmp_path, {"ok.py": src}) == []

    def test_trips_on_constructor_outside_metrics_py(self, tmp_path):
        src = """
            from .. import metrics
            from ..metrics import counter


            MINE = metrics.counter("hvd_rogue_total", "declared elsewhere")
            BARE = counter("hvd_sneaky_total", "bare-name escape hatch")
        """
        found = self._findings(tmp_path, {"bad.py": src})
        assert len(found) == 2
        assert all("declared outside" in f.message for f in found)
        assert {"'hvd_rogue_total'" in f.message
                or "'hvd_sneaky_total'" in f.message for f in found} == {True}

    def test_doc_roundtrip_both_directions(self, tmp_path):
        # registered-but-undocumented direction
        a = tmp_path / "a"
        a.mkdir()
        found = self._findings(a, {"ok.py": "X = 1\n"},
                               metrics_md="no instruments here\n")
        assert any("undocumented in docs/metrics.md" in f.message
                   for f in found)
        # documented-but-unregistered direction
        b = tmp_path / "b"
        b.mkdir()
        found = self._findings(
            b, {"ok.py": "X = 1\n"},
            metrics_md=METRICS_DOC_FIXTURE
            + "| `hvd_stale_total` | counter |\n")
        assert any("hvd_stale_total" in f.message for f in found)

    def test_histogram_series_suffixes_are_derived(self, tmp_path):
        # _bucket/_sum/_count tokens for a registered histogram are
        # derived series names, not stale instruments
        assert self._findings(tmp_path, {"ok.py": "X = 1\n"}) == []

    def test_counter_suffix_tokens_are_stale(self, tmp_path):
        # ...but the same suffixes hanging off a COUNTER name are stale
        # doc entries (e.g. left behind by a histogram->counter change)
        found = self._findings(
            tmp_path, {"ok.py": "X = 1\n"},
            metrics_md=METRICS_DOC_FIXTURE
            + "| `hvd_good_total_sum` | stale |\n")
        assert any("hvd_good_total_sum" in f.message for f in found)

    def test_missing_doc_is_a_finding(self, tmp_path):
        found = self._findings(tmp_path, {"ok.py": "X = 1\n"},
                               metrics_md=None)
        assert any("docs/metrics.md is missing" in f.message
                   for f in found)


class TestRepoGate:
    def test_repo_tree_is_clean(self):
        project = Project(REPO_ROOT, package_rel="horovod_tpu")
        found = run_all(project)
        assert found == [], "\n".join(f.format() for f in found)

    def test_cli_exit_codes(self, tmp_path):
        clean = subprocess.run(
            [sys.executable, "-m", "tools.hvdlint", "horovod_tpu"],
            cwd=REPO_ROOT, capture_output=True, text=True)
        assert clean.returncode == 0, clean.stdout + clean.stderr
        assert "clean" in clean.stdout

        missing = subprocess.run(
            [sys.executable, "-m", "tools.hvdlint", "no_such_pkg"],
            cwd=REPO_ROOT, capture_output=True, text=True)
        assert missing.returncode == 2

    def test_cli_nonzero_on_findings(self, tmp_path):
        make_project(tmp_path, {"bad.py": """
            import os

            def read():
                return os.environ.get("HVD_X")
        """})
        proc = subprocess.run(
            [sys.executable, "-m", "tools.hvdlint", "pkg"],
            cwd=tmp_path, env={"PYTHONPATH": str(REPO_ROOT),
                               "PATH": "/usr/bin:/bin"},
            capture_output=True, text=True)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "[knob-registry]" in proc.stdout

    def test_every_pass_registered(self):
        from tools.hvdlint import PASSES
        assert list(PASSES) == ["issue-lock", "lock-order", "timer-purity",
                                "knob-registry", "donation", "silent-except",
                                "rank-divergence", "metrics-registry",
                                "trace-coverage"]

    def test_cli_json_report(self, tmp_path):
        import json as _json
        from tools.hvdlint import PASSES

        clean = subprocess.run(
            [sys.executable, "-m", "tools.hvdlint", "horovod_tpu",
             "--json"],
            cwd=REPO_ROOT, capture_output=True, text=True)
        assert clean.returncode == 0, clean.stdout + clean.stderr
        doc = _json.loads(clean.stdout)
        assert doc["clean"] is True and doc["findings"] == []
        assert [p["name"] for p in doc["passes"]] == list(PASSES)
        assert all(p["seconds"] >= 0 for p in doc["passes"])

        make_project(tmp_path, {"bad.py": """
            import os

            def read():
                return os.environ.get("HVD_X")
        """})
        dirty = subprocess.run(
            [sys.executable, "-m", "tools.hvdlint", "pkg", "--json"],
            cwd=tmp_path, env={"PYTHONPATH": str(REPO_ROOT),
                               "PATH": "/usr/bin:/bin"},
            capture_output=True, text=True)
        assert dirty.returncode == 1, dirty.stdout + dirty.stderr
        doc = _json.loads(dirty.stdout)
        assert doc["clean"] is False
        # the fixture project also trips metrics-registry (no
        # docs/metrics.md there); pick the knob-registry record
        rec = next(r for r in doc["findings"]
                   if r["pass"] == "knob-registry")
        assert rec["file"] == "pkg/ops/bad.py" and rec["line"] > 0
        assert "message" in rec
