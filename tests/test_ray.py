"""Ray executor tests with an in-process stub of the Ray API (Ray itself
is not installed here; the reference tests run against local Ray,
``test/single/test_ray.py`` — the stub checks the same contract: actor
creation, env seeding, per-rank fn execution, shutdown)."""

import os
import sys
import types

import pytest

from horovod_tpu.ray import RayExecutor


class _Future:
    def __init__(self, value):
        self.value = value


class _ActorMethod:
    def __init__(self, bound):
        self._bound = bound

    def remote(self, *args, **kwargs):
        return _Future(self._bound(*args, **kwargs))


class _ActorHandle:
    def __init__(self, instance):
        self._instance = instance

    def __getattr__(self, name):
        return _ActorMethod(getattr(self._instance, name))


class _RemoteCls:
    def __init__(self, cls):
        self._cls = cls

    def options(self, **kwargs):
        return self

    def remote(self, *args, **kwargs):
        return _ActorHandle(self._cls(*args, **kwargs))


def _make_stub_ray():
    ray = types.ModuleType("ray")
    ray.util = types.SimpleNamespace(
        get_node_ip_address=lambda: "127.0.0.1")
    ray.is_initialized = lambda: True
    ray.init = lambda *a, **k: None
    ray.remote = lambda cls: _RemoteCls(cls)
    ray.get = lambda futures: ([f.value for f in futures]
                               if isinstance(futures, list) else futures.value)
    ray.kill = lambda actor: None
    return ray


@pytest.fixture()
def stub_ray(monkeypatch):
    ray = _make_stub_ray()
    monkeypatch.setitem(sys.modules, "ray", ray)
    # in-process stub actors mutate the shared os.environ via set_env;
    # scrub the launcher contract afterwards so later tests don't inherit
    # a stale rank/size or a dead rendezvous address
    before = dict(os.environ)
    yield ray
    for k in [k for k in os.environ if k.startswith("HVD_")
              and k not in before]:
        del os.environ[k]


def test_ray_executor_runs_fn_per_worker(stub_ray):
    ex = RayExecutor(num_workers=3)
    ex.start()
    try:
        results = ex.run(lambda x: x * 2, args=(21,))
        assert results == [42, 42, 42]
        assert ex.execute_single(lambda: "rank0") == "rank0"
    finally:
        ex.shutdown()


def test_ray_executor_seeds_launcher_env(stub_ray):
    ex = RayExecutor(num_workers=2, env_vars={"MY_FLAG": "7"})
    ex.start()
    try:
        # stub actors run in-process: set_env mutated our os.environ
        envs = ex.run(lambda: {k: v for k, v in os.environ.items()
                               if k.startswith("HVD_") or k == "MY_FLAG"})
        # every worker saw the full launcher contract
        for env in envs:
            assert env["HVD_SIZE"] == "2"
            assert env["HVD_NUM_PROCESSES"] == "2"
            assert env["HVD_KV_ADDR"]
            assert env["HVD_KV_PORT"]
            assert env["HVD_COORDINATOR_ADDR"] == "127.0.0.1"
            assert env["HVD_SECRET_KEY"]
            assert env["MY_FLAG"] == "7"
        # in-process actors share one os.environ, so the distinct per-rank
        # values can't be observed here; check the seeded dicts instead
        slots_env = [ex._rdv.worker_env(s) for s in ex._build_slots(
            ["127.0.0.1", "127.0.0.1"])]
        assert [e["HVD_RANK"] for e in slots_env] == ["0", "1"]
        assert [e["HVD_LOCAL_RANK"] for e in slots_env] == ["0", "1"]
    finally:
        ex.shutdown()


def test_ray_executor_multi_host_slots(stub_ray):
    ex = RayExecutor(num_workers=4)
    slots = ex._build_slots(["h1", "h1", "h2", "h2"])
    assert [s.rank for s in slots] == [0, 1, 2, 3]
    assert [s.local_rank for s in slots] == [0, 1, 0, 1]
    assert [s.cross_rank for s in slots] == [0, 0, 1, 1]
    assert all(s.cross_size == 2 and s.local_size == 2 for s in slots)


def test_ray_executor_requires_start(stub_ray):
    ex = RayExecutor(num_workers=1)
    with pytest.raises(RuntimeError, match="start"):
        ex.run(lambda: 1)


def test_module_imports_without_ray(monkeypatch):
    monkeypatch.setitem(sys.modules, "ray", None)
    # constructing the executor must not import ray; only start() does
    ex = RayExecutor(num_workers=2)
    with pytest.raises((ImportError, RuntimeError)):
        ex.start()
