"""Training utilities: LR schedules, metric averaging, SyncBatchNorm,
ElasticSampler, data loaders (reference ``_keras/callbacks.py``,
``torch/sync_batch_norm.py``, ``torch/elastic/sampler.py``,
``data/data_loader_base.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.callbacks import lr_schedule, warmup_schedule
from horovod_tpu.data import (
    AsyncDataLoaderMixin,
    BaseDataLoader,
    ShardedArrayLoader,
)
from horovod_tpu.elastic import ElasticSampler


# --- schedules -------------------------------------------------------------

def test_warmup_schedule_ramps_to_target():
    n = hvd.size()
    target = 0.1 * n  # user passes the size-scaled rate, reference-style
    sched = warmup_schedule(target, steps_per_epoch=10, warmup_epochs=5)
    first = float(sched(0))
    last = float(sched(5 * 10))
    assert first == pytest.approx(target / n, rel=0.15)
    assert last == pytest.approx(target, rel=1e-6)
    # monotone ramp
    vals = [float(sched(s)) for s in range(0, 51, 5)]
    assert all(b >= a for a, b in zip(vals, vals[1:]))


def test_lr_schedule_staircase_decay():
    sched = lr_schedule(1.0, 0.5, steps_per_epoch=10, start_epoch=2)
    assert float(sched(0)) == 1.0       # before start_epoch: initial
    assert float(sched(25)) == 0.5 ** 0  # epoch 2
    assert float(sched(35)) == 0.5      # epoch 3
    assert float(sched(45)) == 0.25     # epoch 4


def test_lr_schedule_in_optax():
    import optax
    sched = warmup_schedule(0.8, steps_per_epoch=4, warmup_epochs=2)
    tx = optax.sgd(sched)
    params = {"w": jnp.ones(3)}
    state = tx.init(params)
    g = {"w": jnp.ones(3)}
    _, state = tx.update(g, state, params)  # schedules must be traceable


# --- metric averaging ------------------------------------------------------

def test_metric_average():
    n = hvd.size()
    # every rank passes the same concrete value here (single controller);
    # a PerRank bundle exercises the true cross-rank average
    v = hvd.per_rank([jnp.asarray(float(r)) for r in range(n)])
    out = hvd.allreduce(v, op=hvd.Average)
    assert float(out) == pytest.approx((n - 1) / 2)
    assert hvd.metric_average(3.5, "loss") == pytest.approx(3.5)


def test_average_metrics_sorted_and_complete():
    logs = {"b_metric": 2.0, "a_metric": 1.0}
    out = hvd.average_metrics(logs)
    assert out == {"a_metric": pytest.approx(1.0),
                   "b_metric": pytest.approx(2.0)}


# --- SyncBatchNorm ---------------------------------------------------------

def test_sync_batch_norm_cross_replica_stats():
    """Stats must be computed over the GLOBAL batch: per-shard inputs with
    different means normalize identically to a single-device batch norm
    over the concatenation."""
    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n * 4, 8)).astype(np.float32) * 3 + 1
    model = hvd.SyncBatchNorm(use_running_average=False)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))

    def fwd(x):
        out, _ = model.apply(variables, x, mutable=["batch_stats"])
        return out

    sharded = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False))
    out = np.asarray(sharded(jax.device_put(
        x, NamedSharding(mesh, P(axis)))))
    # reference: plain flax BatchNorm over the full batch on one device
    import flax.linen as nn
    ref_model = nn.BatchNorm(use_running_average=False, momentum=0.9,
                             epsilon=1e-5)
    ref_vars = ref_model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
    ref, _ = ref_model.apply(ref_vars, jnp.asarray(x),
                             mutable=["batch_stats"])
    assert np.allclose(out, np.asarray(ref), atol=1e-4)


def test_sync_batch_norm_eager_fallback():
    model = hvd.SyncBatchNorm(use_running_average=False)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 4)))
    out, _ = model.apply(variables, jnp.ones((2, 4)),
                         mutable=["batch_stats"])  # no bound axis: local BN
    assert out.shape == (2, 4)


# --- ElasticSampler --------------------------------------------------------

def _as_world(sampler, num_replicas, rank):
    """Simulate a multi-process world (tests run single-process)."""
    sampler.num_replicas = num_replicas
    sampler.rank = rank
    import math
    sampler.num_samples = int(
        math.ceil(len(sampler.remaining_indices) / num_replicas))
    sampler.total_size = sampler.num_samples * num_replicas
    return sampler


def test_elastic_sampler_partitions_all_indices():
    seen = set()
    counts = set()
    for r in range(4):
        sampler = _as_world(ElasticSampler(40, shuffle=False), 4, r)
        local = sampler.local_indices()
        counts.add(len(local))
        seen.update(local)
    assert seen == set(range(40))
    assert counts == {10}  # every process yields the same step count


def test_elastic_sampler_uses_process_not_chip_partition():
    """Single process driving 8 chips feeds the WHOLE dataset (the mesh
    sharding spreads each batch over chips) — chip-count partitioning
    would silently drop 7/8 of the data (code-review r3 regression)."""
    assert hvd.size() == 8 and hvd.process_count() == 1
    sampler = ElasticSampler(24, shuffle=False)
    assert sampler.num_replicas == 1
    assert sampler.local_indices() == list(range(24))


def test_elastic_sampler_pad_underfill():
    """Fewer remaining indices than the pad needed: the cyclic pad must
    still fill every rank's slice (code-review r3 regression)."""
    sampler = ElasticSampler(32, shuffle=False)
    sampler.processed_num = 29  # 3 remaining, 8 replicas
    sampler.reset()
    lens = set()
    for r in range(8):
        _as_world(sampler, 8, r)
        lens.add(len(sampler.local_indices()))
    assert lens == {1}


def test_elastic_sampler_skips_processed_after_reset():
    sampler = ElasticSampler(32, shuffle=True, seed=7)
    first = sampler.local_indices()[:2]
    sampler.record_batch(2 // sampler.num_replicas or 1)
    state = sampler.state_dict()
    # simulate a reset: a fresh sampler restores and continues
    restored = ElasticSampler(32, shuffle=True, seed=7)
    restored.load_state_dict(state)
    processed = sampler.processed_num
    assert len(restored.remaining_indices) == 32 - processed
    # epoch rollover clears tracking
    restored.set_epoch(1)
    assert restored.processed_num == 0
    assert len(restored.remaining_indices) == 32


def test_elastic_sampler_same_order_across_ranks():
    a = ElasticSampler(16, shuffle=True, seed=3)
    b = ElasticSampler(16, shuffle=True, seed=3)
    assert a.remaining_indices == b.remaining_indices


# --- data loaders ----------------------------------------------------------

class _RangeLoader(BaseDataLoader):
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def _iterate(self):
        yield from range(self.n)


class _AsyncRangeLoader(AsyncDataLoaderMixin, _RangeLoader):
    pass


def test_base_loader_iterates():
    assert list(_RangeLoader(5)) == [0, 1, 2, 3, 4]


def test_async_loader_prefetches_same_batches():
    loader = _AsyncRangeLoader(50, async_loader_queue_size=4)
    assert list(loader) == list(range(50))
    # reusable across epochs
    assert list(loader) == list(range(50))


def test_async_loader_sync_mode():
    loader = _AsyncRangeLoader(5, async_loader_queue_size=0)
    assert list(loader) == list(range(5))


def test_async_loader_early_close():
    loader = _AsyncRangeLoader(10_000, async_loader_queue_size=2)
    it = iter(loader)
    assert next(it) == 0
    loader.close_async_loader()  # must not hang on the full queue


def test_sharded_array_loader():
    n = hvd.size()
    xs = np.arange(32, dtype=np.float32).reshape(32, 1)
    ys = np.arange(32)
    loader = ShardedArrayLoader(xs, ys, batch_size=2 * n, shuffle=False)
    batches = list(loader)
    assert len(batches) == len(loader) == 32 // (2 * n)
    bx, by = batches[0]
    assert bx.shape == (2 * n, 1) and by.shape == (2 * n,)
    # sharded over the mesh data axis
    assert bx.sharding.spec == P(hvd.axis_name())
    # shuffling is deterministic per epoch and differs across epochs
    loader2 = ShardedArrayLoader(xs, ys, batch_size=2 * n, seed=1)
    e0 = [np.asarray(b[1]).tolist() for b in loader2]
    loader2.set_epoch(1)
    e1 = [np.asarray(b[1]).tolist() for b in loader2]
    assert e0 != e1
    flat0 = sorted(i for b in e0 for i in b)
    assert flat0 == list(range(32))


def test_sharded_array_loader_validation():
    with pytest.raises(ValueError, match="leading dimension"):
        ShardedArrayLoader(np.zeros(4), np.zeros(5), batch_size=2)
    bad = ShardedArrayLoader(np.zeros(16), batch_size=3)  # 3 % 8 != 0
    if hvd.size() > 1:
        with pytest.raises(ValueError, match="divide"):
            list(bad)


class _FailingLoader(BaseDataLoader):
    def __len__(self):
        return 10

    def _iterate(self):
        yield 1
        raise IOError("bad record")


class _AsyncFailingLoader(AsyncDataLoaderMixin, _FailingLoader):
    pass


def test_async_loader_propagates_producer_errors():
    """A prefetch-thread exception must surface in the consumer, not end
    the epoch silently (code-review r3 regression)."""
    loader = _AsyncFailingLoader(async_loader_queue_size=4)
    it = iter(loader)
    assert next(it) == 1
    with pytest.raises(IOError, match="bad record"):
        next(it)


def test_sync_batch_norm_forwards_axis_field():
    """hvd.SyncBatchNorm(axis=1) must normalize channel axis 1 (NCHW),
    not silently fall back to -1 (code-review r3 regression)."""
    model = hvd.SyncBatchNorm(use_running_average=False, axis=1)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 3, 5)))
    # scale/bias shaped by the chosen channel axis
    assert variables["params"]["sync_bn"]["scale"].shape == (3,)


def test_sharded_loader_rejects_unshardable_remainder():
    if hvd.size() == 1:
        pytest.skip("needs a multi-device mesh")
    xs = np.zeros((2 * hvd.size() + 1, 2), np.float32)  # remainder of 1
    loader = ShardedArrayLoader(xs, batch_size=2 * hvd.size(),
                                drop_remainder=False)
    with pytest.raises(ValueError, match="remainder"):
        list(loader)
