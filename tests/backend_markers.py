"""Shared backend-capability skip markers for the spawn-based suites.

The multi-process integration tests launch real ``hvdrun -np 2`` jobs
whose workers execute cross-process XLA collectives. jax 0.4.x's CPU
backend does not implement those ("Multiprocess computations aren't
implemented on the CPU backend", raised from the compiled program), so on
the virtual-CPU CI mesh these tests are known-red for environmental
reasons, not product bugs. Marking them skipped gives tier-1 a clean
signal; on a TPU backend (or a jax >= 0.5 CPU backend, which added
cross-process CPU computations) they run for real.

Tests that only exercise the negotiation layer — metadata mismatch
errors, stall warnings, knob gating — stay unmarked: they fail before any
cross-process program executes and pass on every backend.
"""

import os

import jax
import pytest


def _cpu_backend_lacks_multiprocess() -> bool:
    platforms = (os.environ.get("JAX_PLATFORMS")
                 or str(getattr(jax.config, "jax_platforms", "") or ""))
    if "cpu" not in platforms.lower():
        return False
    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:  # pragma: no cover - unparseable dev version
        return False
    return (major, minor) < (0, 5)


skip_if_cpu_backend = pytest.mark.skipif(
    _cpu_backend_lacks_multiprocess(),
    reason="jax < 0.5 CPU backend: \"Multiprocess computations aren't "
           "implemented on the CPU backend\" — cross-process collective "
           "execution needs a real accelerator (or jax >= 0.5) here")
