"""Shared backend-capability markers + the loopback world fixture.

The multi-process integration tests launch real ``hvdrun -np 2`` jobs
whose workers execute cross-process XLA collectives. jax 0.4.x's CPU
backend does not implement those ("Multiprocess computations aren't
implemented on the CPU backend", raised from the compiled program), so on
the virtual-CPU CI mesh these tests are known-red for environmental
reasons, not product bugs. Marking them skipped gives tier-1 a clean
signal; on a TPU backend (or a jax >= 0.5 CPU backend, which added
cross-process CPU computations) they run for real.

The world>1 coverage those skips used to leave behind now runs in tier-1
through the loopback world (``hvd.loopback.world(n)``; docs/loopback.md):
``tests/test_loopback_world.py`` and the loopback variants in the
``test_integration_*`` files boot N ranks as threads in ONE interpreter —
real negotiation/elastic/watchdog protocol, emulated collective
execution — so no cross-process XLA program is ever built. The
:func:`loopback_world` fixture below parametrizes worlds at N in {2, 4}.

Tests that only exercise the negotiation layer — metadata mismatch
errors, stall warnings, knob gating — stay unmarked: they fail before any
cross-process program executes and pass on every backend.
"""

import os

import jax
import pytest


def _cpu_backend_lacks_multiprocess() -> bool:
    platforms = (os.environ.get("JAX_PLATFORMS")
                 or str(getattr(jax.config, "jax_platforms", "") or ""))
    if "cpu" not in platforms.lower():
        return False
    try:
        major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    except ValueError:  # pragma: no cover - unparseable dev version
        return False
    return (major, minor) < (0, 5)


skip_if_cpu_backend = pytest.mark.skipif(
    _cpu_backend_lacks_multiprocess(),
    reason="jax < 0.5 CPU backend: \"Multiprocess computations aren't "
           "implemented on the CPU backend\" — cross-process collective "
           "execution needs a real accelerator (or jax >= 0.5) here. "
           "The loopback world (tests/test_loopback_world.py, "
           "docs/loopback.md) covers the same world>1 stack in tier-1.")


@pytest.fixture(params=[2, 4], ids=lambda n: f"world{n}")
def loopback_world(request):
    """A fresh loopback world per test, at N in {2, 4} — the ISSUE-10
    tier-1 stand-in for the spawn-based world>1 suites. Import it into a
    test module (``from backend_markers import loopback_world``) and take
    it as a fixture argument."""
    import horovod_tpu as hvd
    with hvd.loopback.world(request.param) as w:
        yield w
