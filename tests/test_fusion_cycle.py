"""Cycle-driven cross-call fusion scheduler (ISSUE 2 tentpole): *_async
submissions must queue per signature, flush on threshold / cycle time /
synchronize / poll / barrier / shutdown with rank-deterministic
composition, coalesce into grouped dispatches, and produce numerics
identical to the scheduler-off (immediate dispatch) path."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import fusion_cycle
from horovod_tpu.ops.compression import Compression

N = 8
LONG_CYCLE_MS = "2000"  # timer never fires during a test unless asked


@pytest.fixture(autouse=True)
def _fresh_scheduler(monkeypatch):
    monkeypatch.setenv("HVD_CYCLE_TIME", LONG_CYCLE_MS)
    # also pin the in-flight pace: after any dispatch the scheduler
    # flushes at PENDING_CYCLE_TIME for one cycle window, which would let
    # the timer fire mid-test (default: min(cycle/2, 2 ms))
    monkeypatch.setenv("HVD_PENDING_CYCLE_TIME", LONG_CYCLE_MS)
    fusion_cycle.reset()
    yield
    fusion_cycle.reset()


def _vals(shape=(8,), dtype=jnp.float32, mult=1.0):
    return [jnp.full(shape, (i + 1) * mult, dtype) for i in range(N)]


def _sum_expected(shape=(8,), mult=1.0):
    return np.full(shape, 36.0 * mult)


# ------------------------------------------------------------ flush triggers

def test_flush_on_synchronize_coalesces_whole_queue(hvd):
    handles = [hvd.allreduce_async(hvd.per_rank(_vals(mult=i + 1)),
                                   op=hvd.Sum) for i in range(6)]
    st = hvd.fusion_stats()
    assert st["pending_tensors"] == 6
    assert all(not h._entry.done for h in handles)
    out0 = hvd.synchronize(handles[0])  # flushes the WHOLE queue
    # the batch's events are set in submission order after its one
    # dispatch; settle the peers before asserting done-ness (synchronize
    # only promises ITS entry — the whole-queue coalescing is what the
    # dispatch/coalesce stats below pin down)
    for h in handles[1:]:
        hvd.synchronize(h)
    assert all(h._entry.done for h in handles)
    st = hvd.fusion_stats()
    assert st["flushes"]["synchronize"] == 1
    assert st["dispatches"] == 1  # one grouped dispatch for 6 submissions
    assert st["coalesce_ratio"] == 6.0
    assert st["pending_tensors"] == 0
    np.testing.assert_allclose(np.asarray(out0), _sum_expected(mult=1))
    for i, h in enumerate(handles):
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   _sum_expected(mult=i + 1))


def test_flush_on_threshold(hvd, monkeypatch):
    # per-rank payload: 8 f32 = 32 bytes; threshold trips on the 4th
    monkeypatch.setenv("HVD_FUSION_THRESHOLD", "100")
    handles = [hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
               for _ in range(4)]
    st = hvd.fusion_stats()
    assert st["flushes"]["threshold"] == 1
    # the trigger only DRAINS the queue — execution happens on the
    # pipelined executor thread, so the enqueueing thread returns before
    # the entries complete (ISSUE 3 tentpole); the events carry completion
    for h in handles:
        assert h._entry.event.wait(10.0), "executor never ran the flush"
    for h in handles:
        np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                                   _sum_expected())


def test_flush_on_cycle_time(hvd, monkeypatch):
    monkeypatch.setenv("HVD_CYCLE_TIME", "30")  # ms
    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    assert h._entry.event.wait(5.0), "cycle timer never flushed the queue"
    st = hvd.fusion_stats()
    assert st["flushes"]["cycle"] >= 1
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               _sum_expected())


def test_flush_on_barrier(hvd):
    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    assert not h._entry.done
    hvd.barrier()
    assert h._entry.done
    assert hvd.fusion_stats()["flushes"]["barrier"] >= 1


def test_backpressure_cap(hvd, monkeypatch):
    monkeypatch.setenv("HVD_FUSION_THRESHOLD", str(1 << 30))
    monkeypatch.setenv("HVD_FUSION_MAX_PENDING", "100")
    handles = [hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
               for _ in range(4)]
    st = hvd.fusion_stats()
    assert st["flushes"]["backpressure"] >= 1
    assert st["pending_bytes"] <= 100
    for h in handles:
        hvd.synchronize(h)


# --------------------------------------------------------- handle semantics

def test_poll_triggers_own_flush(hvd):
    """ISSUE 2 satellite: poll() on an unflushed handle must trigger a
    flush of its own entry — otherwise a poll loop would spin forever on
    a dispatch nothing else triggers."""
    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    assert not h._entry.done
    deadline = time.monotonic() + 5.0
    while not hvd.poll(h):
        assert time.monotonic() < deadline, "poll() never became ready"
    assert hvd.fusion_stats()["flushes"]["poll"] >= 1
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               _sum_expected())


def test_synchronize_idempotent_and_cheap(hvd):
    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    out1 = h.synchronize()
    assert h._synced
    out2 = h.synchronize()
    assert out2 is out1  # cached result object, no re-walk
    assert hvd.poll(h)
    # the immediate-dispatch Handle is idempotent too
    h2 = hvd.ops.collectives.Handle(jnp.ones(3))
    assert h2.synchronize() is h2.synchronize()


def test_grouped_async_entry_is_atomic(hvd):
    t1, t2 = _vals((4,)), _vals((2,), mult=10.0)
    hg = hvd.grouped_allreduce_async(
        [hvd.per_rank(t1), hvd.per_rank(t2)], op=hvd.Sum)
    hs = hvd.allreduce_async(hvd.per_rank(_vals((4,))), op=hvd.Sum)
    outs = hvd.synchronize(hg)
    np.testing.assert_allclose(np.asarray(outs[0]), _sum_expected((4,)))
    np.testing.assert_allclose(np.asarray(outs[1]),
                               _sum_expected((2,), mult=10.0))
    # the single rode the same flush (same signature queue)
    assert hs._entry.done
    st = hvd.fusion_stats()
    assert st["dispatches"] == 1 and st["flushed_tensors"] == 3


def test_aborted_entries_raise_at_synchronize(hvd):
    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    aborted = fusion_cycle.scheduler().abort("test abort")
    assert aborted == 1
    # poll never raises: True means "synchronize() will not block"
    assert hvd.poll(h) is True
    with pytest.raises(RuntimeError, match="test abort"):
        hvd.synchronize(h)


def test_empty_group_async(hvd):
    h = hvd.grouped_allreduce_async([])
    assert hvd.synchronize(h) == []
    assert hvd.poll(h)


def test_mis_sized_bundle_raises_through_plan_path(hvd):
    """The plan-cache fast path must enforce the PerRank leading-axis
    check (_as_bundle's contract), not silently drop rows."""
    from horovod_tpu.ops.collectives import PerRank
    bad = PerRank(jnp.ones((2 * N, 4)))  # leading axis != pset size
    with pytest.raises(ValueError, match="leading axis"):
        hvd.allreduce(bad, op=hvd.Sum)
    h = hvd.allreduce_async(bad, op=hvd.Sum)
    with pytest.raises(ValueError, match="leading axis"):
        hvd.synchronize(h)


# ------------------------------------------------------- determinism contract

def _submit_stream(hvd, ps):
    """An interleaved mixed-dtype / mixed-pset / mixed-op submission
    stream with explicit names (deterministic across schedulers)."""
    sub = [jnp.full((4,), float(i + 1)) for i in range(4)]
    return [
        hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum, name="a0"),
        hvd.allreduce_async(hvd.per_rank(_vals(dtype=jnp.int32)),
                            op=hvd.Sum, name="a1"),
        hvd.allreduce_async(hvd.per_rank(sub, process_set=ps), op=hvd.Sum,
                            process_set=ps, name="a2"),
        hvd.broadcast_async(hvd.per_rank(_vals()), 0, name="b0"),
        hvd.allreduce_async(hvd.per_rank(_vals(mult=2.0)), op=hvd.Sum,
                            name="a3"),
        hvd.allreduce_async(hvd.per_rank(sub, process_set=ps),
                            op=hvd.Average, process_set=ps, name="a4"),
    ]


def test_flush_composition_deterministic(hvd):
    """Identical submission streams + identical trigger sequences must
    yield identical flush compositions (queue partitions and in-queue
    order), independent of scheduler instance — the single-controller
    statement of the reference coordinator's rank-determinism contract."""
    ps = hvd.add_process_set([0, 1, 2, 3])
    try:
        histories = []
        for _ in range(2):
            fusion_cycle.reset()
            handles = _submit_stream(hvd, ps)
            fusion_cycle.scheduler().flush_all("barrier")
            histories.append(list(fusion_cycle.scheduler().flush_history))
            for h in handles:
                hvd.synchronize(h)
        assert histories[0] == histories[1]
        # composition facts: mixed dtypes share the global allreduce queue
        # (wire bucketing happens inside the grouped dispatch); subset and
        # broadcast submissions get their own queues, in submission order
        comps = [(key[0], names) for (_t, key, names) in histories[0]]
        assert comps[0] == ("allreduce", ("a0", "a1", "a3"))
        assert comps[1][0] == "allreduce" and comps[1][1] == ("a2",)
        assert ("broadcast", ("b0",)) in comps
    finally:
        hvd.remove_process_set(ps)


def test_mixed_pset_results_correct(hvd):
    ps = hvd.add_process_set([0, 1, 2, 3])
    try:
        sub = [jnp.full((4,), float(i + 1)) for i in range(4)]
        handles = _submit_stream(hvd, ps)
        outs = [hvd.synchronize(h) for h in handles]
        np.testing.assert_allclose(np.asarray(outs[0]), _sum_expected())
        np.testing.assert_allclose(np.asarray(outs[1]),
                                   _sum_expected().astype(np.int32))
        np.testing.assert_allclose(np.asarray(outs[2]), np.full((4,), 10.0))
        np.testing.assert_allclose(np.asarray(outs[3]), np.full((8,), 1.0))
        np.testing.assert_allclose(np.asarray(outs[4]),
                                   _sum_expected(mult=2.0))
        np.testing.assert_allclose(np.asarray(outs[5]), np.full((4,), 2.5))
    finally:
        hvd.remove_process_set(ps)


# ------------------------------------------------------------ numerics parity

def test_numerics_parity_scheduler_on_off(hvd, monkeypatch):
    def run_all():
        h1 = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Average)
        h2 = hvd.grouped_allreduce_async(
            [hvd.per_rank(_vals((3,))), hvd.per_rank(_vals((5,), mult=3.0))],
            op=hvd.Sum)
        h3 = hvd.broadcast_async(hvd.per_rank(_vals((2,))), 3)
        h4 = hvd.allgather_async(hvd.per_rank(_vals((2,))))
        outs = [hvd.synchronize(h1), *hvd.synchronize(h2),
                hvd.synchronize(h3), hvd.synchronize(h4)]
        return [np.asarray(o) for o in outs]

    queued = run_all()
    monkeypatch.setenv("HVD_CYCLE_TIME", "0")  # scheduler off: immediate
    immediate = run_all()
    assert len(queued) == len(immediate)
    for q, im in zip(queued, immediate):
        np.testing.assert_allclose(q, im)


# ------------------------------------------------------------ queue lifecycle

def test_queue_drain_on_shutdown_hook(hvd):
    """drain() (called by hvd.shutdown) executes pending entries instead
    of dropping them."""
    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    assert not h._entry.done
    fusion_cycle.drain()
    assert h._entry.done
    assert hvd.fusion_stats()["flushes"]["shutdown"] >= 1
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               _sum_expected())


def test_scheduler_off_switch(hvd, monkeypatch):
    monkeypatch.setenv("HVD_CYCLE_TIME", "0")
    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    assert type(h).__name__ == "Handle"  # immediate dispatch, no entry
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               _sum_expected())


def test_broadcast_parameters_rides_queue(hvd):
    params = {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
              "b": jnp.ones((4,), jnp.int32)}
    synced = hvd.broadcast_parameters(params, root_rank=0)
    st = hvd.fusion_stats()
    assert st["enqueued_tensors"] >= 2
    assert st["flushes"]["synchronize"] >= 1
    np.testing.assert_allclose(np.asarray(synced["w"]),
                               np.arange(6).reshape(2, 3))


def test_sparse_async_rides_queue(hvd):
    from horovod_tpu.ops.sparse import SparseRows, sparse_allreduce_async
    rows = SparseRows(indices=jnp.asarray([0, 2]), values=jnp.ones((2, 3)),
                      num_rows=4)
    h = sparse_allreduce_async(rows, op=hvd.Sum)
    assert not h._entry.done  # deferred, not dispatched at submit
    out = hvd.synchronize(h)
    dense = np.asarray(hvd.rows_to_dense(out))
    np.testing.assert_allclose(dense[0], N * 1.0)
    np.testing.assert_allclose(dense[1], 0.0)


def test_allgather_async_rides_queue(hvd):
    h = hvd.allgather_async(hvd.per_rank(_vals((2,))))
    assert not h._entry.done
    out = hvd.synchronize(h)
    assert out.shape == (2 * N,)


# ------------------------------------------- wire-dtype fusion (satellite)

def test_wire_dtype_buckets_fuse_mixed_sources(hvd):
    """_fuse_by_dtype keyed by WIRE dtype: f32 and bf16 tensors routed
    through Compression.bf16 share ONE wire bucket; results decompress
    back to their source dtypes after the split."""
    from horovod_tpu.ops.collectives import (_fuse_by_dtype, _split_fused,
                                             _wire_dtype_of)
    bundles = [jnp.ones((N, 4), jnp.float32), jnp.ones((N, 6), jnp.bfloat16),
               jnp.ones((N, 3), jnp.int32)]
    wire = [_wire_dtype_of(b, Compression.bf16) for b in bundles]
    assert [w.name for w in wire] == ["bfloat16", "bfloat16", "int32"]
    fused, metas = _fuse_by_dtype(bundles, N, wire_dtypes=wire)
    assert len(fused) == 2  # one bf16 wire buffer + the int bucket
    assert fused[0].dtype == jnp.bfloat16 and fused[0].shape == (N, 10)
    out = _split_fused([f[0] for f in fused], metas, 3)
    assert out[0].dtype == jnp.float32  # decompressed after split
    assert out[1].dtype == jnp.bfloat16
    assert out[2].dtype == jnp.int32


def test_grouped_allreduce_compression_numerics(hvd):
    ts = [jnp.full((4,), 2.0, jnp.float32), jnp.full((6,), 1.0, jnp.bfloat16),
          jnp.arange(3, dtype=jnp.int32)]
    outs = hvd.grouped_allreduce(ts, op=hvd.Sum, compression=Compression.bf16)
    assert [o.dtype for o in outs] == [jnp.float32, jnp.bfloat16, jnp.int32]
    np.testing.assert_allclose(np.asarray(outs[0]), np.full((4,), 16.0))
    np.testing.assert_allclose(np.asarray(outs[1]), np.full((6,), 8.0))
    np.testing.assert_allclose(np.asarray(outs[2]), np.arange(3) * N)


def test_async_compression_queue_key(hvd):
    """Compressed and uncompressed submissions of the same signature land
    in different queues (wire dtype is part of the queue key)."""
    h1 = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum,
                             compression=Compression.bf16)
    h2 = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    assert h1._entry.queue_key != h2._entry.queue_key
    out1, out2 = hvd.synchronize(h1), hvd.synchronize(h2)
    assert out1.dtype == out2.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out1), _sum_expected())
    np.testing.assert_allclose(np.asarray(out2), _sum_expected())


def test_async_default_op_is_average(hvd):
    """allreduce_async with no op= must keep the reference default
    (Average), queued or not."""
    h = hvd.allreduce_async(hvd.per_rank(_vals()))
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               _sum_expected() / N)
    hg = hvd.grouped_allreduce_async([hvd.per_rank(_vals())])
    np.testing.assert_allclose(np.asarray(hvd.synchronize(hg)[0]),
                               _sum_expected() / N)


def test_none_compression_shares_queue(hvd):
    """Compression.none is the same wire behavior as no compression —
    the two spellings must coalesce into one queue."""
    h1 = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum,
                             compression=Compression.none)
    h2 = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    assert h1._entry.queue_key == h2._entry.queue_key
    hvd.synchronize(h1), hvd.synchronize(h2)
    assert hvd.fusion_stats()["dispatches"] == 1


def test_custom_compressor_still_applied(hvd):
    """A user Compressor subclass (compress/decompress, no wire_dtype)
    must wrap the collective, not be silently dropped."""
    calls = []

    class Halver(Compression.none):
        @staticmethod
        def compress(t):
            calls.append("c")
            return t * 0.5, None

        @staticmethod
        def decompress(t, ctx):
            calls.append("d")
            return t * 2.0

    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum,
                            compression=Halver)
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               _sum_expected())
    assert "c" in calls and "d" in calls
    # and through the optimizer-facing grouped path
    calls.clear()
    outs = hvd.grouped_allreduce([hvd.per_rank(_vals())], op=hvd.Sum,
                                 compression=Halver)
    np.testing.assert_allclose(np.asarray(outs[0]), _sum_expected())
    assert "c" in calls and "d" in calls


def test_inputs_released_after_flush(hvd):
    h = hvd.allreduce_async(hvd.per_rank(_vals()), op=hvd.Sum)
    assert len(h._entry.tensors) == 1
    hvd.synchronize(h)
    assert h._entry.tensors == ()  # inputs freed; handle keeps results


# ------------------------------------------------------------------- stats

def test_fusion_stats_shape(hvd):
    st = hvd.fusion_stats()
    assert st["enabled"] is True
    for trigger in fusion_cycle.FLUSH_TRIGGERS:
        assert trigger in st["flushes"]
    for key in ("coalesce_ratio", "tensors_per_flush", "pending_bytes",
                "enqueued_tensors", "dispatches"):
        assert key in st
