"""Expert-parallel MoE dispatch/combine: the alltoall-routed result must
equal a dense per-token reference when nothing is dropped, respect
capacity bounds, and carry gradients to both experts and router."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel import load_balance_loss, moe_alltoall, route_top_k

TOKENS, D = 12, 6


def _run(fn, *arrays, out_spec=None):
    """shard_map a function over the hvd axis with per-chip shards."""
    out_spec = out_spec if out_spec is not None else P(hvd.axis_name())
    mesh, axis = hvd.mesh(), hvd.axis_name()
    sharding = NamedSharding(mesh, P(axis))
    f = jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(P(axis),) * len(arrays),
        out_specs=out_spec, check_vma=False))
    return f(*[jax.device_put(a, sharding) for a in arrays])


def _scaled_expert(axis):
    """Deterministic per-chip expert: multiply by (expert index + 1), so
    the dense reference is computable on the host."""
    def expert_fn(t):
        e = lax.axis_index(axis)
        return t * (e + 1).astype(t.dtype)
    return expert_fn


@pytest.mark.parametrize("k", [1, 2])
def test_moe_matches_dense_reference_when_nothing_drops(hvd, k):
    n = hvd.size()
    axis = hvd.axis_name()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, TOKENS, D)).astype(np.float32)
    logits = rng.standard_normal((n, TOKENS, n)).astype(np.float32)

    def body(xb, lb):
        y, aux = moe_alltoall(xb[0], lb[0], _scaled_expert(axis), axis,
                              k=k, capacity=k * TOKENS)  # nothing drops
        return y[None]

    out = np.asarray(_run(body, x, logits))  # (n, TOKENS, D) chip-major

    # dense reference: every token times its gate-weighted (e+1) factors
    for chip in range(n):
        eidx, gates = jax.jit(lambda l: route_top_k(l, k))(logits[chip])
        eidx, gates = np.asarray(eidx), np.asarray(gates)
        factor = np.sum(gates * (eidx + 1), axis=-1, keepdims=True)
        np.testing.assert_allclose(out[chip], x[chip] * factor,
                                   rtol=1e-5, atol=1e-5)


def test_moe_capacity_drops_overflow(hvd):
    n = hvd.size()
    axis = hvd.axis_name()
    # every token on every chip wants expert 0, capacity 2: only the
    # first 2 per chip survive, the rest combine to exactly zero
    x = np.ones((n, TOKENS, D), np.float32)
    logits = np.full((n, TOKENS, n), -10.0, np.float32)
    logits[:, :, 0] = 10.0

    def body(xb, lb):
        y, aux = moe_alltoall(xb[0], lb[0], _scaled_expert(axis), axis,
                              k=1, capacity=2)
        return y[None]

    out = np.asarray(_run(body, x, logits))  # (n, TOKENS, D)
    for chip in range(n):
        kept = np.abs(out[chip]).sum(axis=-1) > 0
        assert kept.sum() == 2, kept  # capacity per (chip, expert) pair


@pytest.mark.parametrize("k", [1, 2])
def test_moe_gradients_flow_to_router_and_input(hvd, k):
    """Router gradients must flow through the TASK loss (aux coefficient
    zero here) for both k=1 (raw Switch gate — renormalizing would zero
    it, the code-review r4 regression) and k=2 (renormalized blend)."""
    n = hvd.size()
    axis = hvd.axis_name()
    rng = np.random.default_rng(1)
    x = rng.standard_normal((n, TOKENS, D)).astype(np.float32)
    logits = rng.standard_normal((n, TOKENS, n)).astype(np.float32)

    def loss_body(xb, lb):
        def local_loss(xs, ls):
            y, _aux = moe_alltoall(xs, ls, _scaled_expert(axis), axis,
                                   k=k, capacity=k * TOKENS)
            return jnp.sum(y ** 2)  # task loss only: no aux crutch
        gx, gl = jax.grad(local_loss, argnums=(0, 1))(xb[0], lb[0])
        return gx[None], gl[None]

    mesh = hvd.mesh()
    sharding = NamedSharding(mesh, P(hvd.axis_name()))
    f = jax.jit(jax.shard_map(
        loss_body, mesh=mesh, in_specs=(P(hvd.axis_name()),) * 2,
        out_specs=(P(hvd.axis_name()), P(hvd.axis_name())),
        check_vma=False))
    gx, gl = f(jax.device_put(x, sharding), jax.device_put(logits, sharding))
    assert float(jnp.abs(gx).sum()) > 0
    assert float(jnp.abs(gl).sum()) > 0  # router learns through the gates


def test_load_balance_loss_uniform_is_one(hvd):
    n = 4
    logits = jnp.zeros((32, n))  # uniform router
    eidx, _ = route_top_k(logits, 1)
    # uniform probs and (any) assignment: n * sum(frac_e * 1/n) = 1
    assert np.isclose(float(load_balance_loss(logits, eidx)), 1.0)


def test_moe_transformer_trains(hvd):
    """TransformerLM(moe_experts=n) inside shard_map over the mesh: the
    MoE FFN routes tokens across chips and the LM still trains (loss
    decreases with the aux loss collected from intermediates)."""
    import optax
    from horovod_tpu.models import TransformerConfig, TransformerLM

    n = hvd.size()
    mesh, axis = hvd.mesh(), hvd.axis_name()
    cfg = TransformerConfig(vocab_size=32, num_layers=1, num_heads=2,
                            d_model=16, d_ff=32, max_seq_len=8,
                            dtype=jnp.float32, moe_experts=n, moe_axis=axis)
    model = TransformerLM(cfg)
    tokens = np.random.default_rng(0).integers(0, 32, (2 * n, 8))
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens))["params"]
    tx = optax.adam(5e-3)
    opt = tx.init(params)

    def step(p, o, t):
        def loss_fn(p):
            logits, inter = model.apply(
                {"params": p}, t, mutable=["intermediates"])
            tgt = jnp.roll(t, -1, axis=1)
            ce = -jnp.mean(jnp.take_along_axis(
                jax.nn.log_softmax(logits), tgt[..., None], -1))
            aux = sum(jnp.sum(a) for a in
                      jax.tree_util.tree_leaves(inter["intermediates"]))
            return ce + 0.01 * aux
        loss, g = jax.value_and_grad(loss_fn)(p)
        g = jax.tree.map(lambda x: lax.pmean(x, axis), g)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, lax.pmean(loss, axis)

    sharded = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P(axis)),
        out_specs=(P(), P(), P()), check_vma=False))
    t = jax.device_put(tokens, NamedSharding(mesh, P(axis)))
    w_in_before = np.asarray(
        params["block_0"]["moe_mlp"]["w_in"]).copy()
    first = last = None
    for _ in range(15):
        params, opt, loss = sharded(params, opt, t)
        jax.block_until_ready(loss)
        last = float(jnp.ravel(loss)[0])
        if first is None:
            first = last
    assert last < first, (first, last)
    # the expert weights themselves must have received gradient — a loss
    # decrease alone could come from the router/dense params while expert
    # grads were zeroed or mis-routed (code-review r4)
    w_in_after = np.asarray(params["block_0"]["moe_mlp"]["w_in"])
    per_expert_delta = np.abs(w_in_after - w_in_before).reshape(n, -1).sum(1)
    assert (per_expert_delta > 0).all(), per_expert_delta


def test_moe_mlp_grad_boost_cancels_average_sync(hvd):
    """The expert-weight gradient pre-scaling must be forward-identical
    and backward x n_experts, so AVERAGE sync returns the true per-expert
    gradient (code-review r4: 1/n silent shrink under pmean)."""
    n = 8
    w = jnp.asarray([[1.234, -0.5], [0.25, 3.0]])

    def boost(w):
        return w * n - jax.lax.stop_gradient(w) * (n - 1)

    np.testing.assert_allclose(np.asarray(boost(w)), np.asarray(w),
                               rtol=1e-6)
    g = jax.grad(lambda w: jnp.sum(boost(w) * 2.0))(w)
    np.testing.assert_allclose(np.asarray(g), 2.0 * n, rtol=1e-6)
