"""Multi-process dynamic engine integration: real 2-process hvdrun jobs
negotiating eager collectives over the launcher KV (the analog of the
reference's mpirun-driven parallel tests).

The ``skip_if_cpu_backend``-marked tests here stay as the real-hardware
spawn variants; their loopback ports — identical semantics at world
N in {2, 4}, running unconditionally in tier-1 — live in
``tests/test_loopback_world.py`` (negotiation, per-process-set subsets,
ragged allgather, join/zero-contribution, env-contract rejection)."""

import os
import subprocess
import sys
import textwrap

import pytest

from backend_markers import skip_if_cpu_backend
from horovod_tpu import _native

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable")

_PRELUDE = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
try: jax.config.update("jax_platforms", "cpu")
except Exception: pass
import jax.numpy as jnp
import horovod_tpu as hvd
hvd.init()
rank = int(os.environ["HVD_RANK"])
"""


def _run(tmp_path, body, np=2, timeout=300, extra_env=None):
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(_PRELUDE) + textwrap.dedent(body))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", str(np),
         "--", sys.executable, str(worker)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=timeout)


class TestNegotiatedCollectives:
    @skip_if_cpu_backend
    def test_matching_metadata_succeeds(self, tmp_path):
        proc = _run(tmp_path, """
        out = hvd.allreduce(jnp.ones(4), op=hvd.Sum, name="grads")
        assert out.shape == (4,)
        out2 = hvd.allreduce(jnp.ones(3), op=hvd.Sum)  # auto-named
        print("WORKER_OK", rank, flush=True)
        """)
        assert proc.returncode == 0, proc.stdout
        assert proc.stdout.count("WORKER_OK") == 2

    def test_shape_mismatch_raises_informative_error(self, tmp_path):
        proc = _run(tmp_path, """
        from horovod_tpu.dynamic import HorovodCollectiveError
        shape = 4 if rank == 0 else 5
        try:
            hvd.allreduce(jnp.ones(shape), op=hvd.Sum, name="bad")
            print("NO_ERROR", rank, flush=True)
        except HorovodCollectiveError as e:
            assert "Mismatched ALLREDUCE tensor shapes" in str(e), str(e)
            assert "[4]" in str(e) and "[5]" in str(e), str(e)
            print("GOT_MISMATCH_ERROR", rank, flush=True)
        """)
        assert proc.stdout.count("GOT_MISMATCH_ERROR") == 2, proc.stdout
        assert "NO_ERROR" not in proc.stdout

    def test_op_mismatch_raises(self, tmp_path):
        proc = _run(tmp_path, """
        from horovod_tpu.dynamic import HorovodCollectiveError
        try:
            if rank == 0:
                hvd.allreduce(jnp.ones(4), op=hvd.Sum, name="op_clash")
            else:
                hvd.allgather(jnp.ones(4), name="op_clash")
            print("NO_ERROR", rank, flush=True)
        except HorovodCollectiveError as e:
            assert "Mismatched collective operations" in str(e), str(e)
            print("GOT_OP_ERROR", rank, flush=True)
        """)
        assert proc.stdout.count("GOT_OP_ERROR") == 2, proc.stdout

    def test_stall_warning_logged(self, tmp_path):
        proc = _run(tmp_path, """
        import time
        from horovod_tpu.dynamic import HorovodCollectiveError
        if rank == 0:
            try:
                hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="lonely",
                              )
            except HorovodCollectiveError as e:
                print("TIMED_OUT", rank, flush=True)
        else:
            time.sleep(8)  # never submits "lonely"
            print("SAT_OUT", rank, flush=True)
        """, extra_env={"HVD_STALL_CHECK_TIME_SECONDS": "1",
                        "HVD_ELASTIC_TIMEOUT": "6"})
        assert "TIMED_OUT" in proc.stdout, proc.stdout
        assert "SAT_OUT" in proc.stdout
        assert "not ready on all processes" in proc.stdout, proc.stdout

    @skip_if_cpu_backend
    def test_engine_disabled_by_knob(self, tmp_path):
        proc = _run(tmp_path, """
        from horovod_tpu import engine_service
        assert engine_service.get_service() is None
        out = hvd.allreduce(jnp.ones(4), op=hvd.Sum)
        print("WORKER_OK", rank, flush=True)
        """, extra_env={"HVD_DYNAMIC_ENGINE": "0"})
        assert proc.returncode == 0, proc.stdout
        assert proc.stdout.count("WORKER_OK") == 2


_PRELUDE_1DEV = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
try: jax.config.update("jax_platforms", "cpu")
except Exception: pass
import jax.numpy as jnp
import horovod_tpu as hvd
hvd.init(process_sets="dynamic")
rank = int(os.environ["HVD_RANK"])
"""


def _run_1dev(tmp_path, body, np=3, timeout=300, extra_env=None):
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(_PRELUDE_1DEV) + textwrap.dedent(body))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", str(np),
         "--", sys.executable, str(worker)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=timeout)


@skip_if_cpu_backend
class TestPerProcessSetNegotiation:
    """Subset eager ops negotiate among member processes only (the
    reference's per-ProcessSet controller, process_set.h:26-84), exercised
    on a 2-of-3-process subset (r2 VERDICT item 7)."""

    def test_subset_collectives_without_nonmember(self, tmp_path):
        proc = _run_1dev(tmp_path, """
        import numpy as np
        ps = hvd.add_process_set([0, 1])
        if rank < 2:
            x = hvd.per_rank([jnp.full((4,), float(r + 1)) for r in (0, 1)],
                             process_set=ps)
            out = hvd.allreduce(x, op=hvd.Sum, process_set=ps, name="sub")
            assert np.allclose(np.asarray(out), 3.0), out
            # auto-named subset op: names must agree on members only
            out2 = hvd.allreduce(x, op=hvd.Sum, process_set=ps)
            g = hvd.allgather(hvd.per_rank(
                [jnp.full((1,), float(r)) for r in (0, 1)], process_set=ps),
                process_set=ps)
            assert np.allclose(np.asarray(g), [0.0, 1.0]), g
        # all three processes: a global op after the subset traffic —
        # auto-name counters must still agree across processes
        out3 = hvd.allreduce(jnp.ones(3), op=hvd.Sum)
        print("WORKER_OK", rank, flush=True)
        """, extra_env={"HVD_STALL_CHECK_TIME_SECONDS": "2",
                        "HVD_ELASTIC_TIMEOUT": "60"})
        assert proc.returncode == 0, proc.stdout
        assert proc.stdout.count("WORKER_OK") == 3, proc.stdout
        assert "not ready on all processes" not in proc.stdout, proc.stdout

    def test_subset_mismatch_detected_among_members(self, tmp_path):
        proc = _run_1dev(tmp_path, """
        from horovod_tpu.dynamic import HorovodCollectiveError
        ps = hvd.add_process_set([0, 1])
        if rank < 2:
            shape = 4 if rank == 0 else 5
            x = hvd.per_rank([jnp.ones(shape) for _ in (0, 1)],
                             process_set=ps)
            try:
                hvd.allreduce(x, op=hvd.Sum, process_set=ps, name="clash")
                print("NO_ERROR", rank, flush=True)
            except HorovodCollectiveError as e:
                assert "Mismatched ALLREDUCE tensor shapes" in str(e), str(e)
                print("GOT_MISMATCH", rank, flush=True)
        print("WORKER_OK", rank, flush=True)
        """)
        assert proc.stdout.count("GOT_MISMATCH") == 2, proc.stdout
        assert "NO_ERROR" not in proc.stdout
        assert proc.stdout.count("WORKER_OK") == 3, proc.stdout


@skip_if_cpu_backend
class TestRaggedAllgather:
    """Per-rank first dims negotiated through the engine (the reference's
    allgatherv displacement exchange, collective_operations.h:143-178 +
    controller.cc tensor-shape negotiation)."""

    def test_local_tensors_with_different_first_dims(self, tmp_path):
        proc = _run_1dev(tmp_path, """
        import numpy as np
        d0 = 2 if rank == 0 else 5
        x = jnp.full((d0, 3), float(rank + 1))
        out = hvd.allgather(x, name="rag")
        assert out.shape == (7, 3), out.shape
        assert np.allclose(np.asarray(out[:2]), 1.0), out
        assert np.allclose(np.asarray(out[2:]), 2.0), out
        # repeat with DIFFERENT dims under the same tensor name pattern:
        # per-call sizes must renegotiate, not come from a stale cache
        d0b = 4 if rank == 0 else 1
        out2 = hvd.allgather(jnp.full((d0b, 3), float(rank + 1)),
                             name="rag2")
        assert out2.shape == (5, 3), out2.shape
        print("WORKER_OK", rank, flush=True)
        """, np=2)
        assert proc.returncode == 0, proc.stdout
        assert proc.stdout.count("WORKER_OK") == 2, proc.stdout

    def test_allgather_sizes_not_cache_stale(self, tmp_path):
        """Same name, same local shape on THIS rank, but the peer's dim
        changes between calls — the response cache must not serve stale
        recv_splits (allgather is negotiated every call)."""
        proc = _run_1dev(tmp_path, """
        import numpy as np
        for step, peer_d0 in enumerate((3, 6)):
            d0 = 2 if rank == 0 else peer_d0
            out = hvd.allgather(jnp.full((d0, 2), float(rank)),
                                name=f"s{step}")
            assert out.shape == (2 + peer_d0, 2), (step, out.shape)
        print("WORKER_OK", rank, flush=True)
        """, np=2)
        assert proc.returncode == 0, proc.stdout
        assert proc.stdout.count("WORKER_OK") == 2, proc.stdout


@skip_if_cpu_backend
class TestJoin:
    """Real join semantics: joined processes contribute zeros while the
    others finish (reference operations.cc:1729-1761, r2 VERDICT missing
    item 7)."""

    def test_uneven_steps_with_join(self, tmp_path):
        proc = _run_1dev(tmp_path, """
        import numpy as np
        n = hvd.size()
        if rank == 0:
            # two extra steps after rank 1 runs out of data; each process
            # passes its LOCAL tensor (reference-parity usage — per_rank's
            # cross-process device_put would itself be a collective the
            # joined rank never mirrors)
            for step in range(2):
                out = hvd.allreduce(jnp.full((3,), 6.0), op=hvd.Average,
                                    name=f"g{step}")
                # joined rank contributes zeros; average divides by world
                assert np.allclose(np.asarray(out), 3.0), (step, out)
            last = hvd.join()
        else:
            last = hvd.join()
        print("LAST", rank, last, flush=True)
        """, np=2)
        assert proc.returncode == 0, proc.stdout
        lines = [l for l in proc.stdout.splitlines() if "LAST" in l]
        assert len(lines) == 2, proc.stdout
        # both report the same last joined rank
        assert len({l.split()[-1] for l in lines}) == 1, lines

    def test_join_with_grouped_and_barrier(self, tmp_path):
        proc = _run_1dev(tmp_path, """
        import numpy as np
        n = hvd.size()
        if rank == 0:
            xs = [jnp.full((2,), float(i + 1)) for i in range(3)]
            outs = hvd.grouped_allreduce(xs, op=hvd.Sum, name="grp")
            for i, o in enumerate(outs):
                assert np.allclose(np.asarray(o), i + 1.0), (i, o)
            hvd.barrier()
            hvd.join()
        else:
            hvd.join()
        print("WORKER_OK", rank, flush=True)
        """, np=2)
        assert proc.returncode == 0, proc.stdout
        assert proc.stdout.count("WORKER_OK") == 2, proc.stdout

    def test_allgather_while_joined(self, tmp_path):
        """A joined process contributes ZERO ROWS to peers' allgathers
        (reference controller.cc:269-281 counts joined ranks toward every
        request type; r3 VERDICT item 3) — a 2-D gather and a 1-D gather
        while the peer is joined."""
        proc = _run_1dev(tmp_path, """
        import numpy as np
        if rank == 0:
            out = hvd.allgather(jnp.full((3, 2), 7.0), name="g1")
            assert out.shape == (3, 2), out.shape  # peer joined: 0 rows
            assert np.allclose(np.asarray(out), 7.0), out
            out2 = hvd.allgather(jnp.full((5,), 2.0), name="g2")
            assert out2.shape == (5,), out2.shape
            # zero-row gather while the peer is joined: engine dims are
            # all 0, both sides must pick the SAME (uniform, empty)
            # program — this deadlocked before the code-review r4 fix
            out3 = hvd.allgather(jnp.zeros((0, 3)), name="g3")
            assert out3.shape == (0, 3), out3.shape
            hvd.join()
        else:
            hvd.join()
        print("WORKER_OK", rank, flush=True)
        """, np=2)
        assert proc.returncode == 0, proc.stdout
        assert proc.stdout.count("WORKER_OK") == 2, proc.stdout


@skip_if_cpu_backend
class TestKvBootstrap:
    """Worlds NOT launched by hvdrun (srun/mpirun/user jax.distributed)
    bootstrap the negotiation KV over jax's distributed store
    (runtime._maybe_bootstrap_kv): process 0 serves, everyone seeds
    HVD_KV_* — the dynamic engine then works exactly as under hvdrun."""

    def test_engine_works_without_launcher_kv(self, tmp_path):
        # strip the launcher KV contract BEFORE importing horovod_tpu so
        # init() sees a coordinator (simulating a pre-initialized world)
        # but no KV — the bootstrap path must provide one
        body = """
        import numpy as np
        from horovod_tpu import engine_service
        from horovod_tpu.dynamic import HorovodCollectiveError
        assert engine_service.get_service() is not None, \\
            "bootstrap KV did not reach the engine"
        out = hvd.allreduce(jnp.ones(4), op=hvd.Sum, name="boot")
        assert np.allclose(np.asarray(out), 2.0), out
        # negotiation really runs: a metadata mismatch must ERROR, not hang
        shape = 3 if rank == 0 else 5
        try:
            hvd.allreduce(jnp.ones(shape), op=hvd.Sum, name="clash")
            print("NO_ERROR", rank, flush=True)
        except HorovodCollectiveError:
            print("GOT_MISMATCH", rank, flush=True)
        print("WORKER_OK", rank, flush=True)
        """
        prelude = textwrap.dedent("""\
            import os
            os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
            rank = int(os.environ["HVD_RANK"])
            for k in ("HVD_KV_ADDR", "HVD_KV_PORT", "HVD_SECRET_KEY"):
                os.environ.pop(k, None)
            import jax
            try: jax.config.update("jax_platforms", "cpu")
            except Exception: pass
            import jax.numpy as jnp
            import horovod_tpu as hvd
            hvd.init()
            """)
        worker = tmp_path / "worker.py"
        worker.write_text(prelude + textwrap.dedent(body))
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
             "--", sys.executable, str(worker)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout
        assert proc.stdout.count("WORKER_OK") == 2, proc.stdout
        assert proc.stdout.count("GOT_MISMATCH") == 2, proc.stdout
        assert "NO_ERROR" not in proc.stdout
