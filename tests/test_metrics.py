"""Unified metrics registry (ISSUE 11, docs/metrics.md): instrument
semantics, Prometheus/JSON exposition, per-rank loopback isolation, and
negotiation straggler attribution.

The loopback classes run the REAL negotiation wire format at world=4
(PR-10 substrate), so per-rank store isolation and the fault-injected
straggler path are tier-1 facts, not claims.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from backend_markers import loopback_world  # noqa: F401  (fixture)
from horovod_tpu import _native
from horovod_tpu import metrics as m
from horovod_tpu.utils import faults as _faults


@pytest.fixture(autouse=True)
def _clean_metrics():
    m.set_enabled(None)
    yield
    m.set_enabled(None)


# ---------------------------------------------------------------------------
# instrument semantics
# ---------------------------------------------------------------------------

class TestInstruments:
    def test_counter_inc_and_labels(self):
        before = m.KV_OPS.value({"op": "testop"})
        m.KV_OPS.inc(labels={"op": "testop"})
        m.KV_OPS.inc(3, labels={"op": "testop"})
        assert m.KV_OPS.value({"op": "testop"}) == before + 4

    def test_label_validation(self):
        with pytest.raises(ValueError):
            m.KV_OPS.inc()  # missing required label
        with pytest.raises(ValueError):
            m.KV_OPS.inc(labels={"verb": "put"})  # wrong label name
        with pytest.raises(ValueError):
            m.FUSION_PENDING_BYTES.set(1, labels={"op": "x"})  # undeclared

    def test_gauge_set_add(self):
        m.FUSION_PENDING_BYTES.set(10)
        m.FUSION_PENDING_BYTES.add(5)
        assert m.FUSION_PENDING_BYTES.value() == 15

    def test_histogram_buckets_sum_count(self):
        h = m.NEGOTIATION_ROUND_SECONDS
        labels = {"process_set": "t-hist"}
        base = h.series().get((("process_set", "t-hist"),))
        assert base is None
        h.observe(0.003, labels=labels)
        h.observe(0.2, labels=labels)
        h.observe(99.0, labels=labels)  # past the last bound: +Inf only
        series = h.series()[(("process_set", "t-hist"),)]
        assert series.count == 3
        assert abs(series.sum - 99.203) < 1e-9
        # cumulative bucket counts appear in the exposition
        text = m.prometheus_text()
        assert ('hvd_negotiation_round_seconds_bucket'
                '{le="0.005",process_set="t-hist"} 1') in text
        assert ('hvd_negotiation_round_seconds_bucket'
                '{le="+Inf",process_set="t-hist"} 3') in text
        assert ('hvd_negotiation_round_seconds_count'
                '{process_set="t-hist"} 3') in text

    def test_duplicate_registration_rejected(self):
        with pytest.raises(ValueError):
            m.counter("hvd_kv_ops_total", "dup")

    def test_snapshot_delta(self):
        a = m.snapshot()
        m.KV_OPS.inc(2, labels={"op": "snap"})
        m.NEGOTIATION_ROUND_SECONDS.observe(0.1,
                                            labels={"process_set": "snap"})
        d = m.delta(m.snapshot(), a)
        assert d[("hvd_kv_ops_total", (("op", "snap"),))] == 2
        assert d[("hvd_negotiation_round_seconds_count",
                  (("process_set", "snap"),))] == 1

    def test_disabled_gates_hot_instruments_only(self):
        m.set_enabled(False)
        try:
            before_hot = m.KV_OPS.value({"op": "gated"})
            before_always = m.DISPATCH_MISSES.value()
            m.KV_OPS.inc(labels={"op": "gated"})
            m.DISPATCH_MISSES.inc()
            assert m.KV_OPS.value({"op": "gated"}) == before_hot
            # always=True instruments back legacy *_stats() APIs and
            # keep recording (docs/metrics.md overhead contract)
            assert m.DISPATCH_MISSES.value() == before_always + 1
        finally:
            m.set_enabled(None)


# ---------------------------------------------------------------------------
# exposition surfaces
# ---------------------------------------------------------------------------

class TestExposition:
    def test_every_instrument_emits_headers(self):
        text = m.prometheus_text()
        for name, inst in m.instruments().items():
            assert f"# HELP {name} " in text
            assert f"# TYPE {name} {inst.kind}" in text

    def test_dump_is_json_shaped(self):
        m.KV_OPS.inc(labels={"op": "dumped"})
        d = hvd.metrics_dump()
        json.dumps(d)  # must be serializable as-is
        entry = d["hvd_kv_ops_total"]
        assert entry["type"] == "counter"
        assert "op" in entry["labels"]
        assert any(s["labels"].get("op") == "dumped"
                   for s in entry["series"])

    def test_standalone_server(self):
        port = m.serve(0)
        try:
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            assert "# TYPE hvd_kv_ops_total counter" in text
            # idempotent: a second serve keeps the port
            assert m.serve(0) == port
        finally:
            m.stop_serving()

    def test_kv_server_metrics_route_unsigned(self):
        from horovod_tpu.runner.http_kv import KVServer, make_secret
        server = KVServer(secret=make_secret())
        port = server.start()
        try:
            # no HMAC header: the /metrics route must serve anyway
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            assert "# TYPE hvd_negotiation_rounds_total counter" in text
            # ...while the KV routes stay signed (403 without a header)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/some/key", timeout=10)
            assert ei.value.code == 403
        finally:
            server.stop()

    def test_prometheus_text_parses(self):
        """Every sample line is `name{labels} value` with a float value
        — the same check the ci.sh scrape gate applies."""
        m.KV_OPS.inc(labels={"op": "parse"})
        m.NEGOTIATION_SUBMIT_LAG.observe(0.01, labels={"rank": 1})
        for line in m.prometheus_text().splitlines():
            if not line or line.startswith("#"):
                continue
            name_part, _, value = line.rpartition(" ")
            float(value)
            assert name_part.split("{")[0].startswith("hvd_")


# ---------------------------------------------------------------------------
# legacy views stay API-compatible
# ---------------------------------------------------------------------------

class TestLegacyViews:
    def test_dispatch_cache_stats_shape(self, hvd):
        s = hvd.dispatch_cache_stats()
        assert set(s) == {"enabled", "capacity", "size", "hits",
                          "hits_by_source", "misses", "invalidations",
                          "evictions", "negotiation_skips",
                          "chunked_builds", "step_builds",
                          # ISSUE 16: GSPMD cached-program executables
                          "gspmd_builds",
                          # ISSUE 14: elastic warm re-form pool/grafts
                          "warm_pool", "warm_reuses"}
        assert set(s["hits_by_source"]) >= {"call", "flush", "step"}
        assert s["hits"] == sum(s["hits_by_source"].values())

    def test_health_stats_shape(self, hvd):
        s = hvd.health_stats()
        assert set(s) == {"retries", "faults", "watchdogs"}
        for site, counts in s["retries"].items():
            assert set(counts) == {"retries", "giveups"}

    def test_retry_counters_round_trip(self):
        from horovod_tpu.utils import retry as _retry
        _retry._note("test.site", "retries")
        _retry._note("test.site", "giveups")
        s = _retry.stats()["test.site"]
        assert s["retries"] >= 1 and s["giveups"] >= 1
        assert m.RETRY_RETRIES.value({"site": "test.site"}) >= 1


# ---------------------------------------------------------------------------
# loopback: per-rank isolation + the world /metrics scrape
# ---------------------------------------------------------------------------

pytestmark_native = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable")


@pytestmark_native
class TestLoopbackIsolation:
    def test_per_rank_counters_do_not_bleed(self, loopback_world):
        """Every rank runs the SAME three collectives (the protocol
        requires symmetric streams) plus a rank-distinct direct
        increment; each rank's OWN view must read exactly its own
        values — never a peer's, never a world aggregate."""
        n = loopback_world.size

        def body():
            r = hvd.rank()
            for i in range(3):
                h = hvd.allreduce_async(jnp.ones(4), op=hvd.Sum,
                                        name=f"iso{i}")
                hvd.synchronize(h)
            m.KV_OPS.inc(r + 1, labels={"op": "isotest"})
            d = hvd.metrics_dump()
            flushed = [
                s for s in
                d["hvd_fusion_flushed_tensors_total"]["series"]
                if s["labels"]["process_set"] == "global"]
            assert len(flushed) == 1, flushed
            direct = [s for s in d["hvd_kv_ops_total"]["series"]
                      if s["labels"]["op"] == "isotest"]
            assert len(direct) == 1, direct
            return (r, flushed[0]["value"], direct[0]["value"])

        outs = [o.result for o in loopback_world.run(body)]
        # 3 flushed tensors each (its own, not 3*world), and the direct
        # counter reads the rank's own increment only
        assert sorted(outs) == [(r, 3.0, float(r + 1)) for r in range(n)]

    def test_world_scrape_carries_every_rank(self, loopback_world):
        n = loopback_world.size

        def body():
            hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="scrape")
            return "OK"

        assert all(o.result == "OK" for o in loopback_world.run(body))
        addr, port = loopback_world.kv_endpoint
        text = urllib.request.urlopen(
            f"http://{addr}:{port}/metrics", timeout=10).read().decode()
        # every instrument's headers are present...
        for name in m.instruments():
            assert f"# TYPE {name} " in text, name
        # ...and every rank reported its negotiation rounds
        for r in range(n):
            assert (f'hvd_negotiation_rounds_total'
                    f'{{process_set="global",rank="{r}"}}') in text
        # no duplicate series after the rank/reporter injection
        samples = [ln for ln in text.splitlines()
                   if ln and not ln.startswith("#")]
        assert len(samples) == len(set(samples))


@pytestmark_native
class TestStragglerAttribution:
    def test_delayed_rank_named_on_all_survivors(self):
        """HVD_FAULT_SPEC delay on rank 2's svc.exchange makes rank 2
        the named straggler on every survivor: counter labels, tracker
        stats, and the rate-limited warning all say rank 2; rank 2
        never blames itself (ISSUE 11 acceptance)."""
        os.environ["HVD_FAULT_SPEC"] = \
            "svc.exchange:delay=0.4:rank=2:after=4"
        _faults.refresh()
        try:
            with hvd.loopback.world(
                    4, extra_env={"HVD_STRAGGLER_THRESHOLD": "0.15"}) as w:
                def body():
                    from horovod_tpu import engine_service
                    for i in range(8):
                        hvd.allreduce(jnp.ones(4), op=hvd.Sum,
                                      name=f"lag{i}")
                    svc = engine_service.get_service()
                    series = hvd.metrics_dump()[
                        "hvd_straggler_rounds_total"]["series"]
                    return (hvd.rank(), series, svc.straggler_stats())

                outs = [o.result for o in w.run(body)]
        finally:
            os.environ.pop("HVD_FAULT_SPEC", None)
            _faults.refresh()
        # On a share-throttled CI box a survivor's own exchange thread
        # can occasionally be descheduled past the (deliberately low)
        # test threshold and pick up a stray straggler round of its own
        # — so assert rank 2 is present and DOMINANT, not exclusive.
        total_warnings = 0
        for rank, series, stats in outs:
            by_rank = {s["labels"]["rank"]: s["value"] for s in series}
            # a rank never blames itself (its own lag is unobservable)
            assert str(rank) not in by_rank, series
            if rank == 2:
                continue
            assert by_rank.get("2", 0) >= 1, series
            assert by_rank["2"] == max(by_rank.values()), series
            assert stats["straggler_rounds"].get(2, 0) >= 1
            total_warnings += stats["warnings"]
            if stats["last_warning"] is not None:
                assert "global rank 2" in stats["last_warning"]
                assert "HVD_STRAGGLER_THRESHOLD" in stats["last_warning"]
        # the injected ~15 over-threshold rounds make a 3-round streak
        # (and so at least one warning somewhere) effectively certain
        assert total_warnings >= 1, outs

    def test_submit_lag_histogram_covers_every_member(self, loopback_world):
        n = loopback_world.size

        def body():
            for i in range(3):
                hvd.allreduce(jnp.ones(2), op=hvd.Sum, name=f"sl{i}")
            d = hvd.metrics_dump()
            lag = d["hvd_negotiation_submit_lag_seconds"]["series"]
            return sorted(s["labels"]["rank"] for s in lag)

        for o in loopback_world.run(body):
            assert o.result == [str(r) for r in range(n)]
