"""Real-pyspark integration tests, skipped when pyspark is not installed
(the reference runs against a local Spark,
``/root/reference/test/integration/test_spark.py``). The stub tests in
test_spark.py cover the contract; these catch barrier scheduling and
executor-process behavior stubs cannot."""

import os

import pytest

pyspark = pytest.importorskip("pyspark")

import horovod_tpu.spark as hvd_spark


@pytest.fixture(scope="module")
def spark_session():
    from pyspark.sql import SparkSession
    spark = (SparkSession.builder.master("local[2]")
             .appName("horovod_tpu-spark-test")
             .config("spark.ui.enabled", "false")
             .getOrCreate())
    yield spark
    spark.stop()


def _worker_env():
    return {k: v for k, v in os.environ.items() if k.startswith("HVD_")}


def test_real_spark_run_rank_ordered(spark_session):
    results = hvd_spark.run(lambda x: x * 2, args=(21,), num_proc=2)
    assert results == [42, 42]


def test_real_spark_run_seeds_env(spark_session):
    envs = hvd_spark.run(_worker_env, num_proc=2)
    ranks = sorted(int(e["HVD_RANK"]) for e in envs)
    assert ranks == [0, 1]
    for e in envs:
        assert e["HVD_SIZE"] == "2"
        assert e["HVD_KV_ADDR"] and e["HVD_SECRET_KEY"]


def test_real_spark_estimator_fit(spark_session, tmp_path):
    """fit(dataset) -> params over real barrier tasks (estimator-lite)."""
    import numpy as np
    import jax.numpy as jnp
    import optax

    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 3)).astype(np.float32)
    y = (x @ np.array([1.0, 2.0, 3.0], np.float32))

    def init_fn(_rng, batch):
        return {"w": jnp.zeros((batch[0].shape[1], 1), jnp.float32)}

    def loss_fn(params, batch):
        xb, yb = batch
        return jnp.mean(((xb @ params["w"])[:, 0] - yb) ** 2)

    params = hvd_spark.fit((x, y), init_fn, loss_fn,
                           optimizer=optax.sgd(0.05), epochs=4,
                           batch_size=16, num_proc=2,
                           store_path=str(tmp_path / "store"))
    mse = float(np.mean(((x @ np.asarray(params["w"]))[:, 0] - y) ** 2))
    assert mse < float(np.mean(y ** 2))
