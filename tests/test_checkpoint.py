"""Persistent checkpointing (SURVEY §5.4: the orbax-backed unification of
the reference's Spark Store epoch checkpoints)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.checkpoint import Checkpointer, restore_or_none


def make_state(scale=1.0):
    mesh, axis = hvd.mesh(), hvd.axis_name()
    sharded = jax.device_put(
        np.arange(hvd.size() * 4, dtype=np.float32).reshape(-1, 1) * scale,
        NamedSharding(mesh, P(axis)))
    replicated = jax.device_put(jnp.full((3,), 2.0 * scale),
                                NamedSharding(mesh, P()))
    return {"params": {"w": sharded, "b": replicated},
            "step": jnp.asarray(int(scale), jnp.int32)}


def test_save_restore_round_trip(tmp_path):
    state = make_state(3.0)
    with Checkpointer(str(tmp_path / "ck")) as mgr:
        mgr.save(7, state, wait=True)
        assert mgr.latest_step() == 7
        out = mgr.restore(target=make_state(0.0))
    assert np.allclose(np.asarray(out["params"]["w"]),
                       np.asarray(state["params"]["w"]))
    assert np.allclose(np.asarray(out["params"]["b"]), 6.0)
    # restored with the template's shardings
    assert out["params"]["w"].sharding.spec == P(hvd.axis_name())


def test_retention_and_latest(tmp_path):
    with Checkpointer(str(tmp_path / "ck"), max_to_keep=2) as mgr:
        for step in (1, 2, 3):
            mgr.save(step, {"x": jnp.full((2,), float(step))}, wait=True)
        assert mgr.latest_step() == 3
        assert mgr.all_steps() == [2, 3]
        out = mgr.restore()
    assert np.allclose(np.asarray(out["x"]), 3.0)


def test_restore_specific_step(tmp_path):
    with Checkpointer(str(tmp_path / "ck"), max_to_keep=None) as mgr:
        mgr.save(1, {"x": jnp.ones((2,))}, wait=True)
        mgr.save(2, {"x": jnp.ones((2,)) * 2}, wait=True)
        out = mgr.restore(step=1)
    assert np.allclose(np.asarray(out["x"]), 1.0)


def test_restore_or_none(tmp_path):
    assert restore_or_none(str(tmp_path / "missing")) is None
    hvd.checkpoint.save(str(tmp_path / "ck2"), 0, {"y": jnp.zeros((1,))})
    out = restore_or_none(str(tmp_path / "ck2"))
    assert out is not None and "y" in out


def test_restore_empty_dir_raises(tmp_path):
    d = tmp_path / "empty"
    d.mkdir()
    with Checkpointer(str(d)) as mgr:
        with pytest.raises(FileNotFoundError):
            mgr.restore()


def test_elastic_resume_idiom(tmp_path):
    """Durable layer under elastic state: save at epoch end, resume after
    a full restart via restore + broadcast."""
    ckdir = str(tmp_path / "run")
    state = make_state(5.0)
    hvd.checkpoint.save(ckdir, 4, state)
    # "restarted" job: fresh template, resume-if-present
    resumed = restore_or_none(ckdir, target=make_state(0.0))
    assert resumed is not None
    assert int(resumed["step"]) == 5
    params = hvd.broadcast_parameters(resumed["params"], root_rank=0)
    assert np.allclose(np.asarray(params["b"]), 10.0)
