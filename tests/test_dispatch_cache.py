"""Dispatch plan cache (ISSUE 1 tentpole): the steady-state eager fast
path must hit/miss/invalidate correctly, produce numerics identical to the
cache-off (pre-cache) dispatch path, and never let wire-buffer donation
corrupt a caller's reused input."""

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops import dispatch_cache
from horovod_tpu.utils import envs

N = 8


@pytest.fixture(autouse=True)
def _fresh_cache():
    dispatch_cache.reset()
    yield
    dispatch_cache.reset()


def _vals(shape=(4,), dtype=jnp.float32, mult=1.0):
    return [jnp.full(shape, (i + 1) * mult, dtype) for i in range(N)]


# ------------------------------------------------------------- hit / miss

def test_repeated_signature_hits(hvd):
    pr = hvd.per_rank(_vals())
    hvd.allreduce(pr, op=hvd.Sum)
    s0 = dispatch_cache.stats()
    assert s0["misses"] >= 1 and s0["size"] >= 1
    hvd.allreduce(pr, op=hvd.Sum)
    hvd.allreduce(hvd.per_rank(_vals()), op=hvd.Sum)  # fresh arrays, same sig
    s1 = dispatch_cache.stats()
    assert s1["hits"] == s0["hits"] + 2
    assert s1["misses"] == s0["misses"]


def test_negotiation_skips_counted(hvd):
    pr = hvd.per_rank(_vals())
    for _ in range(3):
        hvd.allreduce(pr, op=hvd.Sum)
    # single-process job: every plan run skips the negotiation entry
    assert dispatch_cache.stats()["negotiation_skips"] >= 3


def test_shape_change_is_a_miss(hvd):
    hvd.allreduce(hvd.per_rank(_vals((4,))), op=hvd.Sum)
    s0 = dispatch_cache.stats()
    hvd.allreduce(hvd.per_rank(_vals((5,))), op=hvd.Sum)
    s1 = dispatch_cache.stats()
    assert s1["misses"] == s0["misses"] + 1
    assert s1["size"] == s0["size"] + 1


def test_dtype_change_is_a_miss(hvd):
    hvd.allreduce(hvd.per_rank(_vals(dtype=jnp.float32)), op=hvd.Sum)
    s0 = dispatch_cache.stats()
    hvd.allreduce(hvd.per_rank(_vals(dtype=jnp.int32)), op=hvd.Sum)
    s1 = dispatch_cache.stats()
    assert s1["misses"] == s0["misses"] + 1


def test_op_and_scale_in_key(hvd):
    pr = hvd.per_rank(_vals())
    hvd.allreduce(pr, op=hvd.Sum)
    s0 = dispatch_cache.stats()
    hvd.allreduce(pr, op=hvd.Max)
    hvd.allreduce(pr, op=hvd.Sum, postscale_factor=0.5)
    s1 = dispatch_cache.stats()
    assert s1["misses"] == s0["misses"] + 2


# ---------------------------------------------------------- invalidation

def test_process_set_removal_invalidates(hvd):
    ps = hvd.add_process_set([0, 1, 2, 3])
    vals = [jnp.full((3,), i + 1.0) for i in range(4)]
    out = hvd.allreduce(hvd.per_rank(vals, ps), op=hvd.Sum, process_set=ps)
    np.testing.assert_allclose(np.asarray(out), np.full((3,), 10.0))
    assert dispatch_cache.stats()["size"] >= 1
    hvd.remove_process_set(ps)
    s = dispatch_cache.stats()
    assert s["size"] == 0
    assert s["invalidations"] >= 1


def test_knob_override_change_flushes(hvd):
    pr = hvd.per_rank(_vals())
    hvd.allreduce(pr, op=hvd.Sum)
    assert dispatch_cache.stats()["size"] >= 1
    envs.set_override(envs.FUSION_THRESHOLD, 12345)
    try:
        hvd.allreduce(pr, op=hvd.Sum)  # epoch drift -> flush, then rebuild
        s = dispatch_cache.stats()
        assert s["invalidations"] >= 1
    finally:
        envs.clear_override(envs.FUSION_THRESHOLD)


def test_capacity_zero_disables(hvd, monkeypatch):
    monkeypatch.setenv("HVD_CACHE_CAPACITY", "0")
    out = hvd.allreduce(hvd.per_rank(_vals()), op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out), np.full((4,), 36.0))
    s = dispatch_cache.stats()
    assert s["enabled"] is False
    assert s["size"] == 0 and s["hits"] == 0 and s["misses"] == 0


def test_lru_eviction(hvd, monkeypatch):
    monkeypatch.setenv("HVD_CACHE_CAPACITY", "2")
    for d in (3, 4, 5, 6):
        hvd.allreduce(hvd.per_rank(_vals((d,))), op=hvd.Sum)
    s = dispatch_cache.stats()
    assert s["size"] <= 2
    assert s["evictions"] >= 2


# ------------------------------------------------- cache on/off numerics

def _run_ops(hvd):
    pr = hvd.per_rank(_vals((6,)))
    group = [hvd.per_rank(_vals((6,))), hvd.per_rank(_vals((2, 3), mult=10.0)),
             jnp.ones((5,))]
    return [
        hvd.allreduce(pr, op=hvd.Sum),
        hvd.allreduce(jnp.arange(12.0), op=hvd.Sum),        # replicated
        *hvd.grouped_allreduce(group, op=hvd.Average),
        hvd.broadcast(pr, root_rank=2),
        hvd.broadcast(jnp.arange(4.0), root_rank=0),        # replicated
        hvd.allgather(pr),
        hvd.allgather(jnp.ones((2, 2))),                    # replicated
        *hvd.grouped_broadcast(group, root_rank=1),
    ]


def test_numerics_identical_cache_on_off(hvd, monkeypatch):
    first = _run_ops(hvd)     # cache on: plan builds
    hits = _run_ops(hvd)      # cache on: plan hits
    assert dispatch_cache.stats()["hits"] > 0
    monkeypatch.setenv("HVD_CACHE_CAPACITY", "0")
    off = _run_ops(hvd)       # pre-cache dispatch path
    for a, b, c in zip(first, hits, off):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(a), np.asarray(c))


# ------------------------------------------------------- donation safety

def test_donation_does_not_corrupt_reused_inputs(hvd):
    """Grouped wire buffers are donated; calling again with the SAME input
    arrays (the training-loop pattern) must neither fail on a deleted
    buffer nor change results."""
    group = [hvd.per_rank(_vals((4,))), hvd.per_rank(_vals((2, 3))),
             jnp.arange(8.0)]
    ref = [np.asarray(o) for o in hvd.grouped_allreduce(group, op=hvd.Sum)]
    for _ in range(3):
        outs = hvd.grouped_allreduce(group, op=hvd.Sum)
    for a, b in zip(ref, outs):
        np.testing.assert_allclose(np.asarray(b), a)
    # the inputs themselves must still be readable and unchanged
    np.testing.assert_allclose(np.asarray(group[0].array[3]),
                               np.full((4,), 4.0))
    np.testing.assert_allclose(np.asarray(group[2]), np.arange(8.0))


def test_donation_single_tensor_group_aliasing(hvd):
    """A single-tensor bucket's wire buffer can be the caller's own array
    (identity-reshape fast path) — it must be excluded from donation."""
    pr = hvd.per_rank(_vals((4,)))  # (8, 4) bundle: the aliasing shape
    for _ in range(3):
        out = hvd.grouped_allreduce([pr], op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out[0]), np.full((4,), 36.0))
    np.testing.assert_allclose(np.asarray(pr.array[0]), np.full((4,), 1.0))
    x = jnp.arange(8.0)  # 1-D raw array: flat-path aliasing shape
    for _ in range(3):
        out2 = hvd.grouped_allreduce([x], op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out2[0]), np.arange(8.0) * 8)
    np.testing.assert_allclose(np.asarray(x), np.arange(8.0))


def test_grouped_broadcast_donation_safe(hvd):
    group = [hvd.per_rank(_vals((4,))), hvd.per_rank(_vals((3,), mult=2.0))]
    for _ in range(3):
        outs = hvd.grouped_broadcast(group, root_rank=5)
    np.testing.assert_allclose(np.asarray(outs[0]), np.full((4,), 6.0))
    np.testing.assert_allclose(np.asarray(outs[1]), np.full((3,), 12.0))
    np.testing.assert_allclose(np.asarray(group[0].array[7]),
                               np.full((4,), 8.0))


# ----------------------------------------------------------- stats API

def test_stats_api_exported(hvd):
    s = hvd.dispatch_cache_stats()
    for key in ("enabled", "capacity", "size", "hits", "misses",
                "invalidations", "negotiation_skips"):
        assert key in s
