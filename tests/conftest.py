"""Test harness: run the full suite on a virtual 8-device CPU mesh.

The analog of the reference's "multi-node without a cluster" strategy
(SURVEY.md §4): instead of spawning mpirun/horovodrun worker processes, we
give one process 8 XLA host devices (``--xla_force_host_platform_device_count``)
and treat each device as a rank.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

# The axon TPU plugin (if present) force-selects itself via jax.config at
# interpreter start; override back to CPU for the unit suite.
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _hvd_runtime():
    import horovod_tpu as hvd
    hvd.init(process_sets="dynamic")
    yield
    hvd.shutdown()


@pytest.fixture()
def hvd():
    import horovod_tpu as hvd
    return hvd
