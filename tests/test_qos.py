"""Multi-tenant QoS collective engine (ISSUE 12; docs/qos.md).

Four layers, mirroring the subsystem's own structure:

* class registry — ``set_qos`` / ``HVD_QOS_CLASSES`` parsing, defaults,
  validation;
* the admission gate in isolation — strict-priority tiers, DRR byte
  shares, the starvation valve, and grant-order determinism (two gates
  fed identical streams agree byte-for-byte);
* scheduler integration — shed handles raise ``QosAdmissionError``
  (never data), deterministic unacked accounting, block-policy
  backpressure, stats/metrics surfaces, flush-history + grant-history
  determinism across schedulers, numerics parity QoS on/off;
* the loopback world=4 tenant-isolation suite — slot-share convergence
  to skewed weights, shed parity across member ranks, starved-tenant
  aging.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu import _native
from horovod_tpu import qos
from horovod_tpu.exceptions import QosAdmissionError
from horovod_tpu.ops import fusion_cycle
from horovod_tpu.utils import invariants as _inv


@pytest.fixture(autouse=True)
def _qos_clean():
    qos.reset()
    yield
    qos.reset()
    fusion_cycle.reset()
    os.environ.pop("HVD_QOS", None)


def _qos_env(monkeypatch, **extra):
    monkeypatch.setenv("HVD_QOS", "1")
    for k, v in extra.items():
        monkeypatch.setenv(k, str(v))


# ---------------------------------------------------------------------------
# class registry
# ---------------------------------------------------------------------------

class TestClassRegistry:
    def test_defaults_and_set_qos_merge(self):
        cls = qos.get_class("global")
        assert (cls.priority, cls.weight, cls.quota, cls.policy) == \
            (0, 1.0, 0, "block")
        hvd.set_qos(None, priority=2, weight=3.0)
        cls = qos.get_class("global")
        assert cls.priority == 2 and cls.weight == 3.0
        # partial update keeps the other fields
        hvd.set_qos(None, pending_bytes_quota=4096, policy="shed")
        cls = qos.get_class("global")
        assert (cls.priority, cls.weight, cls.quota, cls.policy) == \
            (2, 3.0, 4096, "shed")

    def test_env_classes_grammar(self, monkeypatch):
        monkeypatch.setenv(
            "HVD_QOS_CLASSES",
            "serve:priority=1,weight=8;bulk:quota=1048576,policy=shed")
        assert qos.get_class("serve").priority == 1
        assert qos.get_class("serve").weight == 8.0
        assert qos.get_class("bulk").quota == 1048576
        assert qos.get_class("bulk").policy == "shed"
        # explicit API wins over the env entry
        qos.configure_label("serve", weight=2.0)
        assert qos.get_class("serve").weight == 2.0

    def test_env_classes_bad_entries_raise(self, monkeypatch):
        # a malformed spec is all-or-nothing: it raises on EVERY lookup
        # (regression: it used to raise once, mark itself parsed, and
        # silently run with the valid prefix half-applied)
        monkeypatch.setenv("HVD_QOS_CLASSES",
                           "serve:priority=1;bulk:frobnicate=1")
        with pytest.raises(ValueError, match="unknown key"):
            qos.get_class("serve")
        with pytest.raises(ValueError, match="unknown key"):
            qos.get_class("bulk")
        qos.reset()
        monkeypatch.setenv("HVD_QOS_CLASSES", ":weight=1")
        with pytest.raises(ValueError, match="missing tenant label"):
            qos.get_class("x")

    def test_env_classes_change_replaces_stale_entries(self, monkeypatch):
        monkeypatch.setenv("HVD_QOS_CLASSES", "7:weight=2")
        assert qos.get_class("7").weight == 2.0
        # a CHANGED spec replaces the env-installed entry...
        monkeypatch.setenv("HVD_QOS_CLASSES", "7:weight=8")
        assert qos.get_class("7").weight == 8.0
        # ...a deleted label falls back to defaults...
        monkeypatch.setenv("HVD_QOS_CLASSES", "other:weight=3")
        assert qos.get_class("7").weight == 1.0
        # ...and explicit API registrations survive env changes
        qos.configure_label("7", weight=5.0)
        monkeypatch.setenv("HVD_QOS_CLASSES", "7:weight=9")
        assert qos.get_class("7").weight == 5.0

    def test_validation(self):
        with pytest.raises(ValueError, match="weight"):
            qos.QosClass(weight=0.0)
        with pytest.raises(ValueError, match="policy"):
            qos.QosClass(policy="drop")

    def test_tenant_label_derivation(self):
        assert qos.tenant_label(None) == "global"
        ps = hvd.add_process_set([0, 1])
        try:
            assert qos.tenant_label(ps) == str(ps.process_set_id)
        finally:
            hvd.remove_process_set(ps)


# ---------------------------------------------------------------------------
# the admission gate in isolation
# ---------------------------------------------------------------------------

class _Spec:
    def __init__(self, svc):
        self.svc = svc


class _Ent:
    def __init__(self, nbytes, name):
        self.nbytes = nbytes
        self.names = (name,)
        self.qos_tenant = None
        self.qos_inflight = False


class _B:
    """Gate-level fake batch: spec.svc + entries with nbytes/names."""

    def __init__(self, nbytes, name="b", svc=True):
        self.spec = _Spec(object() if svc else None)
        self.entries = [_Ent(nbytes, name)]


def _gate(emitted):
    cv = _inv.make_condition("test.qos.gate")
    return qos.QosGate(cv, lambda b: emitted.append(b))


class TestGate:
    def test_drr_byte_shares_within_tier(self, monkeypatch):
        # quantum = batch size: grants interleave 3:1 by weight
        _qos_env(monkeypatch, HVD_QOS_WINDOW=64, HVD_QOS_QUANTUM=100,
                 HVD_QOS_STARVE_LIMIT=0)
        qos.configure_label("A", weight=3.0)
        qos.configure_label("B", weight=1.0)
        emitted = []
        g = _gate(emitted)
        for i in range(8):
            g.submit(_B(100, f"a{i}"), "A", qos.get_class("A"))
            g.submit(_B(100, f"b{i}"), "B", qos.get_class("B"))
        g.release_all()
        order = [t for t, _ in g.grant_history]
        assert order[:8] == ["A", "A", "A", "B", "A", "A", "A", "B"], order
        st = g.stats_locked()
        assert st["tenants"]["A"]["granted_bytes"] == 800
        assert st["tenants"]["B"]["granted_bytes"] == 800  # all drained

    def test_strict_priority_tiers(self, monkeypatch):
        _qos_env(monkeypatch, HVD_QOS_WINDOW=64, HVD_QOS_QUANTUM=100,
                 HVD_QOS_STARVE_LIMIT=0)
        qos.configure_label("lo", priority=0, weight=10.0)
        qos.configure_label("hi", priority=1, weight=1.0)
        emitted = []
        g = _gate(emitted)
        for i in range(3):
            g.submit(_B(100, f"lo{i}"), "lo", qos.get_class("lo"))
        for i in range(3):
            g.submit(_B(100, f"hi{i}"), "hi", qos.get_class("hi"))
        g.release_all()
        order = [t for t, _ in g.grant_history]
        # the later-submitted higher tier is served entirely first
        assert order == ["hi"] * 3 + ["lo"] * 3, order

    def test_starvation_valve_serves_oldest(self, monkeypatch):
        _qos_env(monkeypatch, HVD_QOS_WINDOW=64, HVD_QOS_QUANTUM=100,
                 HVD_QOS_STARVE_LIMIT=3)
        qos.configure_label("lo", priority=0)
        qos.configure_label("hi", priority=1)
        emitted = []
        g = _gate(emitted)
        g.submit(_B(100, "lo0"), "lo", qos.get_class("lo"))
        for i in range(8):
            g.submit(_B(100, f"hi{i}"), "hi", qos.get_class("hi"))
        g.release_all()
        order = [t for t, _ in g.grant_history]
        # strict priority alone would starve "lo" to the end; the valve
        # forces the globally oldest batch every 3rd grant
        assert order.index("lo") == 3, order
        assert g.stats_locked()["starve_grants"] >= 1

    def test_window_pump_holds_svc_backlog(self, monkeypatch):
        _qos_env(monkeypatch, HVD_QOS_WINDOW=2, HVD_QOS_QUANTUM=1000,
                 HVD_QOS_STARVE_LIMIT=0)
        emitted = []
        g = _gate(emitted)
        for i in range(5):
            g.submit(_B(100, f"s{i}"), "T", qos.get_class("T"))
        # pump keeps at most window=2 svc batches parked
        assert len(emitted) == 3
        with g._cv:
            assert g.parked_depth_locked() == 2
        g.release_all()
        assert len(emitted) == 5

    def test_single_controller_waits_for_demand(self, monkeypatch):
        _qos_env(monkeypatch, HVD_QOS_WINDOW=2, HVD_QOS_QUANTUM=1000)
        emitted = []
        g = _gate(emitted)
        for i in range(5):
            g.submit(_B(100, f"s{i}", svc=False), "T", qos.get_class("T"))
        assert emitted == []  # no window pump for single-controller
        with g._cv:
            # the block-quota component: parked sc bytes are tracked...
            assert g.sc_parked_bytes_locked("T") == 500.0
            assert g.demand_pull_locked() is True
            # ...and released per grant
            assert g.sc_parked_bytes_locked("T") == 400.0
        assert len(emitted) == 1
        g.release_all()
        assert len(emitted) == 5
        with g._cv:
            assert g.sc_parked_bytes_locked("T") == 0.0

    def test_grant_order_deterministic_across_gates(self, monkeypatch):
        _qos_env(monkeypatch, HVD_QOS_WINDOW=3, HVD_QOS_QUANTUM=128,
                 HVD_QOS_STARVE_LIMIT=4)
        qos.configure_label("A", priority=1, weight=2.0)
        qos.configure_label("B", priority=0, weight=1.0)
        qos.configure_label("C", priority=1, weight=1.0)

        def run_stream():
            emitted = []
            g = _gate(emitted)
            for i in range(6):
                tenant = ("A", "B", "C")[i % 3]
                g.submit(_B(64 * (1 + i % 2), f"{tenant}{i}"), tenant,
                         qos.get_class(tenant))
            g.release_all()
            return list(g.grant_history)

        assert run_stream() == run_stream()


# ---------------------------------------------------------------------------
# scheduler integration (single-controller opaque entries)
# ---------------------------------------------------------------------------

class _Pset:
    is_global = False

    def __init__(self, pid):
        self.process_set_id = pid


def _opaque(name, nbytes, run=None, delay=0.0):
    def _run():
        if delay:
            time.sleep(delay)
        return name
    return fusion_cycle._Entry([None], False, nbytes, [name],
                               run=run or _run, label=name)


def _spec(pset, svc=None):
    return fusion_cycle._QueueSpec("sparse", pset, None, svc=svc)


class TestSchedulerIntegration:
    def test_shed_handle_raises_never_returns_data(self, monkeypatch):
        _qos_env(monkeypatch)
        qos.configure_label("7", pending_bytes_quota=100, policy="shed")
        sched = fusion_cycle.FusionScheduler()
        ps = _Pset(7)
        e1 = _opaque("s1", 60)
        e2 = _opaque("s2", 60)  # 60 + 60 > 100: deterministic shed
        sched.enqueue(("sparse", "k"), _spec(ps), e1)
        sched.enqueue(("sparse", "k"), _spec(ps), e2)
        assert e2.done and isinstance(e2.error, QosAdmissionError)
        assert e2.results is None and e2.tensors == ()
        with pytest.raises(QosAdmissionError, match="shed"):
            sched.wait_result(e2)
        # regression (code review): synchronizing the SHED handle must
        # not deflate the unacked measure — e2 was never charged, so
        # the tenant's pending stays exactly e1's 60 bytes and a
        # would-be-over-quota submission still sheds
        assert sched.stats()["qos"]["unacked_bytes"]["7"] == 60.0
        e2b = _opaque("s2b", 60)
        sched.enqueue(("sparse", "k"), _spec(ps), e2b)
        assert isinstance(e2b.error, QosAdmissionError)
        assert sched.wait_result(e1) == ["s1"]
        # synchronize acked e1's bytes: the next submission readmits
        e3 = _opaque("s3", 60)
        sched.enqueue(("sparse", "k"), _spec(ps), e3)
        assert not isinstance(e3.error, QosAdmissionError)
        assert sched.wait_result(e3) == ["s3"]
        st = sched.stats()["qos"]
        assert st["shed"] == {"7": 2}
        sched.stop()

    def test_oversized_entry_sheds_deterministically(self, monkeypatch):
        _qos_env(monkeypatch)
        qos.configure_label("7", pending_bytes_quota=100, policy="shed")
        sched = fusion_cycle.FusionScheduler()
        e = _opaque("big", 1000)
        sched.enqueue(("sparse", "k"), _spec(_Pset(7)), e)
        assert isinstance(e.error, QosAdmissionError)
        sched.stop()

    def test_block_policy_waits_on_inflight_then_admits(self, monkeypatch):
        _qos_env(monkeypatch)
        qos.configure_label("7", pending_bytes_quota=150, policy="block")
        sched = fusion_cycle.FusionScheduler()
        ps = _Pset(7)
        e1 = _opaque("b1", 100, delay=0.3)
        sched.enqueue(("sparse", "k"), _spec(ps), e1)
        sched.flush_queue(("sparse", "k"), "threshold")
        # wait for the executor's demand pull to grant e1 (charging the
        # tenant's in-flight bytes) — it then executes for ~0.3 s; e2
        # over the quota must block until e1 settles, then admit
        deadline = time.monotonic() + 10.0
        while (sched.stats()["qos"]["inflight_bytes"].get("7", 0) < 100
               and time.monotonic() < deadline):
            time.sleep(0.005)
        t0 = time.monotonic()
        e2 = _opaque("b2", 100)
        sched.enqueue(("sparse", "k2"), _spec(ps), e2)
        blocked_for = time.monotonic() - t0
        assert sched.stats()["qos"]["quota_blocks"] >= 1
        assert blocked_for > 0.05, blocked_for
        assert sched.wait_result(e1) == ["b1"]
        assert sched.wait_result(e2) == ["b2"]
        sched.stop()

    def test_flush_and_grant_history_deterministic(self, monkeypatch):
        """ISSUE 12 acceptance: two schedulers fed identical streams
        produce byte-identical flush histories AND grant histories with
        QoS enabled (svc-marked batches: every grant point is a
        deterministic program point)."""
        _qos_env(monkeypatch, HVD_QOS_WINDOW=2, HVD_QOS_QUANTUM=64,
                 HVD_QOS_STARVE_LIMIT=3)
        qos.configure_label("7", priority=1, weight=2.0)
        qos.configure_label("8", priority=0, weight=1.0)

        def run_stream():
            sched = fusion_cycle.FusionScheduler()
            svc = object()  # svc-marked: sparse batches never consult it
            psets = {7: _Pset(7), 8: _Pset(8)}
            for i in range(8):
                pid = 7 if i % 3 != 0 else 8
                e = _opaque(f"t{pid}.{i}", 48 + 16 * (i % 2))
                sched.enqueue(("sparse", f"k{pid}"), _spec(
                    psets[pid], svc=svc), e)
                sched.flush_queue(("sparse", f"k{pid}"), "threshold")
            sched.flush_all("barrier")
            flushes = list(sched.flush_history)
            grants = list(sched._qos_gate.grant_history)
            sched.stop()
            return flushes, grants

        assert run_stream() == run_stream()

    def test_abort_fails_parked_batches(self, monkeypatch):
        _qos_env(monkeypatch, HVD_QOS_WINDOW=64)
        sched = fusion_cycle.FusionScheduler()
        svc = object()
        entries = []
        for i in range(4):
            e = _opaque(f"p{i}", 32)
            entries.append(e)
            sched.enqueue(("sparse", f"k{i}"), _spec(_Pset(7), svc=svc), e)
            sched.flush_queue(("sparse", f"k{i}"), "threshold")
        # svc batches under the window stay parked; abort must fail them
        n = sched.abort("test reset")
        assert n >= 1
        for e in entries:
            assert e.done
            if e.error is not None:
                assert "aborted" in str(e.error)
        st = sched.stats()["qos"]
        assert st["unacked_bytes"] == {} and st["inflight_bytes"] == {}
        sched.stop()

    def test_abort_acks_dead_entries(self, monkeypatch):
        """Regression (code review): synchronizing a handle that died
        in abort() must not deflate unacked bytes charged by POST-abort
        submissions (the shed quota would leak pre-abort headroom)."""
        _qos_env(monkeypatch, HVD_QOS_WINDOW=64)
        qos.configure_label("7", pending_bytes_quota=1000, policy="shed")
        sched = fusion_cycle.FusionScheduler()
        svc = object()
        e1 = _opaque("pre", 100)
        sched.enqueue(("sparse", "k"), _spec(_Pset(8), svc=svc), e1)
        sched.flush_queue(("sparse", "k"), "threshold")  # parks (svc)
        # plus the subtler population: an entry that already EXECUTED
        # pre-abort but was never synchronized (it lives in no queue,
        # gate, or executor batch at abort time); its tenant is the
        # quota'd one so the late ack targets the post-abort charge
        ps = _Pset(7)
        e0 = _opaque("done-pre", 100)
        sched.enqueue(("sparse", "k0"), _spec(ps), e0)
        sched.flush_queue(("sparse", "k0"), "threshold")
        assert e0.event.wait(10.0) and e0.error is None  # executed
        sched.abort("test reset")
        e2 = _opaque("post", 100)
        sched.enqueue(("sparse", "k2"), _spec(ps), e2)
        assert sched.stats()["qos"]["unacked_bytes"]["7"] == 100.0
        with pytest.raises(RuntimeError, match="aborted"):
            sched.wait_result(e1)
        assert sched.wait_result(e0) == ["done-pre"]
        # neither late observation released e2's live charge
        assert sched.stats()["qos"]["unacked_bytes"]["7"] == 100.0
        assert sched.wait_result(e2) == ["post"]
        sched.stop()

    def test_starved_tenant_completes_under_flood(self, monkeypatch):
        _qos_env(monkeypatch, HVD_QOS_WINDOW=2, HVD_QOS_QUANTUM=64,
                 HVD_QOS_STARVE_LIMIT=4)
        qos.configure_label("9", priority=5, weight=8.0)
        qos.configure_label("3", priority=0, weight=1.0)
        sched = fusion_cycle.FusionScheduler()
        svc = object()
        lo = _opaque("lo", 32)
        sched.enqueue(("sparse", "klo"), _spec(_Pset(3), svc=svc), lo)
        sched.flush_queue(("sparse", "klo"), "threshold")
        for i in range(12):
            e = _opaque(f"hi{i}", 32)
            sched.enqueue(("sparse", f"khi{i}"),
                          _spec(_Pset(9), svc=svc), e)
            sched.flush_queue(("sparse", f"khi{i}"), "threshold")
        grants = [t for t, _ in sched._qos_gate.grant_history]
        # the valve granted the starved tier-0 batch mid-flood, not last
        assert "3" in grants, grants
        assert grants.index("3") <= 2 * 4, grants
        sched.flush_all("barrier")
        assert sched.wait_result(lo) == ["lo"]
        sched.stop()

    def test_qos_off_is_inert(self):
        assert not qos.enabled()
        sched = fusion_cycle.FusionScheduler()
        e = _opaque("x", 64)
        sched.enqueue(("sparse", "k"), _spec(None), e)
        assert e.qos_tenant is None
        assert sched.wait_result(e) == ["x"]
        assert sched._qos_gate is None
        sched.stop()


# ---------------------------------------------------------------------------
# end-to-end eager collectives (real dispatch, 8-chip CPU mesh)
# ---------------------------------------------------------------------------

class TestEagerQos:
    def test_numerics_parity_qos_on_off(self, hvd, monkeypatch):
        tensors = [hvd.per_rank(
            [jnp.full((16,), float((r + 1) * (i + 1))) for r in
             range(hvd.size())]) for i in range(6)]

        def run_round():
            hs = [hvd.allreduce_async(t, op=hvd.Sum) for t in tensors]
            return [np.asarray(hvd.synchronize(h)) for h in hs]

        base = run_round()
        _qos_env(monkeypatch, HVD_QOS_WINDOW=2)
        hvd.set_qos(None, priority=1, weight=2.0)
        fusion_cycle.reset()
        on = run_round()
        for a, b in zip(base, on):
            assert a.tobytes() == b.tobytes()

    def test_qos_metrics_series_live(self, hvd, monkeypatch):
        from horovod_tpu import metrics as m
        _qos_env(monkeypatch)
        hvd.set_qos(None, weight=2.0)
        fusion_cycle.reset()
        h = hvd.allreduce_async(jnp.ones(8), op=hvd.Sum)
        hvd.synchronize(h)
        text = m.prometheus_text()
        assert "hvd_qos_granted_bytes_total{" in text
        assert "hvd_qos_slot_share{" in text
        assert "hvd_qos_admission_wait_seconds_count{" in text
        stats = hvd.qos_stats()
        assert stats["enabled"] is True
        assert "global" in stats["classes"]
        assert stats["tenants"]["global"]["granted_bytes"] > 0

    def test_shed_on_real_async_handle(self, hvd, monkeypatch):
        _qos_env(monkeypatch)
        hvd.set_qos(None, pending_bytes_quota=64, policy="shed")
        fusion_cycle.reset()
        h = hvd.allreduce_async(jnp.ones(128), op=hvd.Sum)  # 512 B > 64
        with pytest.raises(QosAdmissionError):
            hvd.synchronize(h)


# ---------------------------------------------------------------------------
# loopback world=4 tenant isolation (the ISSUE 12 satellite suite)
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not _native.available(),
                    reason="native engine unavailable")
class TestLoopbackTenantIsolation:
    QOS_ENV = {
        "HVD_QOS": "1",
        "HVD_DYNAMIC_PROCESS_SETS": "1",
        # every 1 KiB submission threshold-flushes its own batch, so the
        # admission gate sees a stream of batches to arbitrate
        "HVD_FUSION_THRESHOLD": "512",
        "HVD_QOS_QUANTUM": "1024",
        "HVD_QOS_STARVE_LIMIT": "0",
    }

    def test_slot_share_converges_to_weights_world4(self):
        """Two tenants with 4:1 weights submit equal demand from ranks
        0/1; the window holds half the backlog, so the granted half's
        byte share converges to the weight ratio — read off
        hvd_qos_slot_share in each member rank's world."""
        n_bursts = 24
        env = dict(self.QOS_ENV)
        env["HVD_QOS_WINDOW"] = str(n_bursts)  # half of the 2x backlog
        with hvd.loopback.world(4, extra_env=env) as w:
            def body():
                from horovod_tpu import metrics as m
                r = hvd.rank()
                ps = hvd.add_process_set([0, 1])
                hvd.set_qos(ps, weight=4.0)
                hvd.set_qos(None, weight=1.0)
                handles = []
                for i in range(n_bursts):
                    if r < 2:
                        handles.append(hvd.allreduce_async(
                            jnp.full((256,), float(r + i)), op=hvd.Sum,
                            process_set=ps, name=f"a{i}"))
                    handles.append(hvd.allreduce_async(
                        jnp.full((256,), float(r + i)), op=hvd.Sum,
                        name=f"g{i}"))
                share = None
                if r < 2:
                    label = str(ps.process_set_id)
                    share = m.QOS_SLOT_SHARE.value(
                        labels={"process_set": label}, default=None)
                outs = [np.asarray(hvd.synchronize(h)) for h in handles]
                ok = all(np.isfinite(o).all() for o in outs)
                return share, ok

            results = [o.result for o in w.run(body, timeout=240)]
        for r, (share, ok) in enumerate(results):
            assert ok, f"rank {r} got bad numerics"
            if r < 2:
                # weights 4:1 over equal demand: the granted half is
                # ~80% tenant-A bytes (tolerance for the window edge)
                assert share is not None, f"rank {r}: no share series"
                assert 0.6 <= share <= 0.95, (r, share)

    def test_shed_parity_world4(self):
        """Shed decisions ride the rank-deterministic unacked measure:
        every member rank sheds the IDENTICAL submissions, shed handles
        raise (never return wrong data), and the surviving entries'
        numerics are correct."""
        env = dict(self.QOS_ENV)
        with hvd.loopback.world(4, extra_env=env) as w:
            def body():
                r = hvd.rank()
                ps = hvd.add_process_set([0, 1])
                # quota fits exactly two 1 KiB submissions
                hvd.set_qos(ps, pending_bytes_quota=2048, policy="shed")
                outcome = []
                if r < 2:
                    hs = [hvd.allreduce_async(
                              jnp.full((256,), float(i + 1)), op=hvd.Sum,
                              process_set=ps, name=f"s{i}")
                          for i in range(4)]  # 3rd and 4th shed
                    for h in hs:
                        try:
                            out = np.asarray(hvd.synchronize(h))
                            outcome.append(("ok", float(out[0])))
                        except QosAdmissionError:
                            outcome.append(("shed", None))
                    shed = hvd.fusion_stats()["qos"]["shed"]
                    outcome.append(("count", shed.get(
                        str(ps.process_set_id), 0)))
                return outcome

            results = [o.result for o in w.run(body, timeout=240)]
        member0, member1 = results[0], results[1]
        assert member0 == member1, (member0, member1)
        kinds = [k for k, _ in member0[:4]]
        assert kinds == ["ok", "ok", "shed", "shed"], member0
        # sum over both members of full((256,), i+1): 2 * (i+1)
        assert member0[0][1] == 2.0 and member0[1][1] == 4.0, member0
        assert member0[4] == ("count", 2), member0

    def test_starved_tenant_aging_bounded_world4(self):
        """A tier-0 tenant's oldest parked flush must not age without
        bound under a tier-1 flood: the starvation valve grants it
        within HVD_QOS_STARVE_LIMIT grants."""
        env = dict(self.QOS_ENV)
        env["HVD_QOS_STARVE_LIMIT"] = "4"
        env["HVD_QOS_WINDOW"] = "2"
        with hvd.loopback.world(4, extra_env=env) as w:
            def body():
                r = hvd.rank()
                ps = hvd.add_process_set([0, 1])
                hvd.set_qos(ps, priority=1, weight=4.0)
                hvd.set_qos(None, priority=0, weight=1.0)
                handles = []
                # one low-tier (global) submission, then a high-tier
                # flood from the subset tenant
                handles.append(hvd.allreduce_async(
                    jnp.ones(256), op=hvd.Sum, name="lo"))
                if r < 2:
                    for i in range(12):
                        handles.append(hvd.allreduce_async(
                            jnp.ones(256), op=hvd.Sum, process_set=ps,
                            name=f"hi{i}"))
                sched = fusion_cycle.scheduler()
                grants = [t for t, _ in sched._qos_gate.grant_history] \
                    if sched._qos_gate is not None else []
                for h in handles:
                    hvd.synchronize(h)
                return grants

            results = [o.result for o in w.run(body, timeout=240)]
        for r in (0, 1):
            grants = results[r]
            assert "global" in grants, (r, grants)
            # the valve bounds the low-tier batch's age in grants
            assert grants.index("global") <= 8, (r, grants)
