"""Functional elastic-Ray test against a stubbed ``ray`` module: a fake
node dies mid-run, discovery (live fake-cluster state) surfaces a
replacement node, the driver turns the round, and the replacement joins
with state re-synced from the last commit — the
``/root/reference/horovod/ray/elastic_v2.py`` node-replacement semantics,
driven end-to-end through :class:`RayHostDiscovery` +
:class:`ElasticRayExecutor` + the real elastic driver/KV (the discovery,
driver, and rendezvous logic is pure Python; only actor placement is
faked, as in-process threads)."""

import sys
import threading
import time
import types

import pytest

from horovod_tpu.elastic.driver import SLOT_LOST_EXIT_CODE
from horovod_tpu.elastic.rendezvous import WorkerRendezvous
from horovod_tpu.ray import elastic as ray_elastic
from horovod_tpu.runner.http_kv import KVClient

HOST_A, HOST_B, HOST_C = "10.9.0.1", "10.9.0.2", "10.9.0.3"
TOTAL_EPOCHS = 4
STATE_KEY = "test/elastic_state"


class FakeCluster:
    """Mutable fake Ray cluster state, read by RayHostDiscovery."""

    def __init__(self, hosts):
        self._alive = {h: True for h in hosts}
        self._lock = threading.Lock()

    def nodes(self):
        with self._lock:
            return [{"Alive": alive, "NodeManagerAddress": h,
                     "Resources": {"CPU": 1.0}}
                    for h, alive in self._alive.items()]

    def kill(self, host):
        with self._lock:
            self._alive[host] = False

    def add(self, host):
        with self._lock:
            self._alive[host] = True


class _Ref:
    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.exc = None


class _ActorMethod:
    def __init__(self, bound):
        self._bound = bound

    def remote(self, *args, **kwargs):
        ref = _Ref()

        def run():
            try:
                ref.value = self._bound(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 - surfaced via ray.get
                ref.exc = e
            finally:
                ref.event.set()

        threading.Thread(target=run, daemon=True).start()
        return ref


class _ActorHandle:
    def __init__(self, instance):
        self._instance = instance

    def __getattr__(self, name):
        return _ActorMethod(getattr(self._instance, name))


class _RemoteCls:
    def __init__(self, cls):
        self._cls = cls

    def options(self, **kwargs):
        return self

    def remote(self, *args, **kwargs):
        return _ActorHandle(self._cls(*args, **kwargs))


def _make_stub_ray(cluster: FakeCluster):
    ray = types.ModuleType("ray")
    ray.nodes = cluster.nodes
    ray.is_initialized = lambda: True
    ray.init = lambda *a, **k: None
    ray.remote = lambda cls: _RemoteCls(cls)
    ray.kill = lambda actor: None

    def wait(refs, timeout=None):
        ref = refs[0]
        done = ref.event.wait(timeout if timeout is not None else None)
        return ([ref], []) if done else ([], [ref])

    def get(ref):
        if ref.exc is not None:
            raise ref.exc
        return ref.value

    ray.wait = wait
    ray.get = get
    return ray


class _EnvPassingWorker:
    """In-process actors share os.environ; hand the seeded env dict to the
    fn directly instead (the `_make_elastic_worker_cls` test hook)."""

    def execute(self, env, fn, args, kwargs):
        try:
            return ("ok", fn(env, *args, **(kwargs or {})))
        except SystemExit as e:
            return ("exit", int(e.code or 0))


def _elastic_train(env, cluster, doomed):
    """A jax-free elastic worker speaking the real round protocol: ready
    registration, commit-to-KV "training state", blocking re-rendezvous on
    a round turn, and state restore after rejoin."""
    kv = KVClient(env["HVD_KV_ADDR"], int(env["HVD_KV_PORT"]),
                  secret=env["HVD_SECRET_KEY"])
    rdv = WorkerRendezvous(kv_client=kv)
    rdv.hostname = env["HVD_HOSTNAME"]
    rdv.slot = int(env["HVD_LOCAL_RANK"])
    rdv.round = int(env["HVD_ELASTIC_ROUND"])
    rdv.timeout = 30
    rank = int(env["HVD_RANK"])
    world = int(env["HVD_SIZE"])
    rdv.record_ready()

    raw = kv.get(STATE_KEY)
    epoch = int(raw.decode()) if raw else 0
    restored_from = epoch
    while epoch < TOTAL_EPOCHS:
        if rdv.round == 1 and epoch >= 2:
            if rdv.hostname == doomed:
                # the node "dies": Ray marks it dead, a spare appears
                cluster.kill(doomed)
                cluster.add(HOST_C)
                raise RuntimeError("simulated node failure")
            # survivor: peer died — block for the next round, rejoin,
            # restore committed state (run_fn's reset path, jax-free)
            spec = rdv._wait_for_next_round()
            my_slot = rdv._find_my_slot(spec)
            if my_slot is None:
                sys.exit(SLOT_LOST_EXIT_CODE)
            rdv.round = spec["round"]
            rank = my_slot["rank"]
            world = spec["world_size"]
            rdv.record_ready()
            raw = kv.get(STATE_KEY)
            epoch = int(raw.decode()) if raw else 0
        # lockstep epoch barrier, the stand-in for real training's per-step
        # collectives: nobody advances (or finishes, triggering driver
        # success) until every rank of this round reached this epoch
        scope = f"test/ep/{rdv.round}/{epoch}/"
        kv.put(scope + str(rank), b"1")
        deadline = time.monotonic() + 20
        while len(kv.keys(scope)) < world:
            if time.monotonic() > deadline:
                raise TimeoutError(f"epoch barrier stuck at {scope}")
            time.sleep(0.02)
        epoch += 1
        if rank == 0:
            kv.put(STATE_KEY, str(epoch).encode())
    rdv.record_done()
    return {"host": rdv.hostname, "round": rdv.round, "epoch": epoch,
            "restored_from": restored_from}


def test_node_death_replacement_rejoins_with_state(monkeypatch):
    cluster = FakeCluster([HOST_A, HOST_B])
    stub = _make_stub_ray(cluster)
    monkeypatch.setitem(sys.modules, "ray", stub)
    monkeypatch.setattr(ray_elastic, "_make_elastic_worker_cls",
                        lambda ray_module=None: _EnvPassingWorker)

    ex = ray_elastic.ElasticRayExecutor(min_workers=2, max_workers=2,
                                        elastic_timeout=30)
    ex.start()
    try:
        results = ex.run(_elastic_train, args=(cluster, HOST_B))
    finally:
        ex.shutdown()

    by_host = {r["host"]: r for r in results}
    # final round ran on the survivor + the replacement; the dead node's
    # failed handle contributes nothing (final-round result filter)
    assert set(by_host) == {HOST_A, HOST_C}, by_host
    # every result is from the post-replacement round
    assert all(r["round"] >= 2 for r in results), results
    assert all(r["epoch"] == TOTAL_EPOCHS for r in results), results
    # the replacement did NOT start from scratch: it restored the state
    # committed before the failure (epoch 2), the re-sync the reference's
    # elastic_v2 guarantees via state.sync() on rebuilt worlds
    assert by_host[HOST_C]["restored_from"] >= 2, results
    # the survivor lived through both rounds from the beginning
    assert by_host[HOST_A]["restored_from"] == 0, results


def test_discovery_reflects_live_cluster_state():
    cluster = FakeCluster([HOST_A, HOST_B])
    disco = ray_elastic.RayHostDiscovery(_make_stub_ray(cluster),
                                         cpus_per_worker=1)
    assert disco.find_available_hosts_and_slots() == {HOST_A: 1, HOST_B: 1}
    cluster.kill(HOST_B)
    cluster.add(HOST_C)
    assert disco.find_available_hosts_and_slots() == {HOST_A: 1, HOST_C: 1}
