"""Checkpoint state plane (docs/checkpoint.md): sharded async snapshots,
torn-tree-free restore, and peer-restore on re-form.

Unit layers run without a world (the plan algebra, the snapshot writer
against a tmpdir, the transfer protocol over an in-memory KV); the
loopback classes run real elastic churn at world>=4 and assert the
ISSUE acceptance: bitwise restore parity vs a no-churn control, zero
steps lost on graceful preempt, and survivor-death failover that never
hangs past the watchdog budget.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import _native
from horovod_tpu import checkpoint as ck
from horovod_tpu.utils import faults as _faults


@pytest.fixture
def fault_spec():
    """Install an HVD_FAULT_SPEC for the test and always clear it."""
    def install(spec):
        os.environ["HVD_FAULT_SPEC"] = spec
        _faults.refresh()

    yield install
    os.environ.pop("HVD_FAULT_SPEC", None)
    _faults.refresh()
    _faults.clear_membership_handler()


# ---------------------------------------------------------------------------
# partition algebra
# ---------------------------------------------------------------------------

class TestLeafRange:
    def test_covers_and_disjoint(self):
        for total in (0, 1, 3, 7, 16, 101):
            for n in (1, 2, 3, 4, 8):
                ranges = [ck.leaf_range(i, n, total) for i in range(n)]
                seen = [x for lo, hi in ranges for x in range(lo, hi)]
                assert seen == list(range(total)), (n, total, ranges)

    def test_balanced(self):
        for total, n in ((10, 3), (7, 4), (16, 5)):
            sizes = [hi - lo for lo, hi in
                     (ck.leaf_range(i, n, total) for i in range(n))]
            assert max(sizes) - min(sizes) <= 1, (total, n, sizes)

    def test_world_change_repartitions(self):
        """4->2 and 2->4: the same leaves fall into recomputed ranges —
        the single partition function is the whole re-partitioning
        story (survivors serve overlapping ranges of their live tree)."""
        four = [ck.leaf_range(i, 4, 10) for i in range(4)]
        two = [ck.leaf_range(i, 2, 10) for i in range(2)]
        assert four == [(0, 3), (3, 6), (6, 8), (8, 10)]
        assert two == [(0, 5), (5, 10)]
        # each 2-way range overlaps multiple 4-way shards and vice versa
        assert two[0][1] > four[0][1]


# ---------------------------------------------------------------------------
# restore-plan algebra
# ---------------------------------------------------------------------------

def _blob(rank, commits, n_leaves=4, struct=7):
    return {"rank": rank, "commits": commits, "n_leaves": n_leaves,
            "struct": struct, "manifest": -1}


class TestRestorePlan:
    def test_all_agree_no_needy(self):
        plan = ck.make_restore_plan(
            [_blob(0, 5), _blob(1, 5), _blob(2, 5)], world=3)
        assert (plan.survivors, plan.needy) == ((0, 1, 2), ())
        assert plan.degraded_reason is None and not plan.fresh

    def test_fresh_world(self):
        plan = ck.make_restore_plan(
            [_blob(0, 0), _blob(1, 0)], world=2)
        assert plan.fresh

    def test_joiner_is_needy(self):
        plan = ck.make_restore_plan(
            [_blob(0, 5), _blob(1, 5), _blob(2, 0)], world=3)
        assert plan.survivors == (0, 1) and plan.needy == (2,)
        assert plan.step == 5 and plan.degraded_reason is None

    def test_quorum_degrades(self):
        plan = ck.make_restore_plan(
            [_blob(0, 5), _blob(1, 0)], world=2, quorum=2)
        assert plan.degraded_reason == "quorum"

    def test_split_brain_degrades(self):
        """Equally-committed survivors with different structures: no
        consistent manifest exists to serve from."""
        plan = ck.make_restore_plan(
            [_blob(0, 5, struct=1), _blob(1, 5, struct=2)], world=2)
        assert plan.degraded_reason == "quorum"

    def test_structure_mismatch_degrades(self):
        plan = ck.make_restore_plan(
            [_blob(0, 5), _blob(1, 5), _blob(2, 2, n_leaves=9)], world=3)
        assert plan.degraded_reason == "structure"

    def test_transfer_schedule_and_failover(self):
        plan = ck.make_restore_plan(
            [_blob(0, 5), _blob(1, 5), _blob(2, 0), _blob(3, 0)],
            world=4)
        t0 = plan.transfers(0)
        # every needy rank pulls every survivor range, owner = range owner
        assert t0 == [(2, 0, 0, 0, 2), (2, 1, 1, 2, 4),
                      (3, 0, 0, 0, 2), (3, 1, 1, 2, 4)]
        # attempt 1 rotates each failed pull to the NEXT survivor
        t1 = plan.transfers(1, [(2, 0), (3, 1)])
        assert t1 == [(2, 1, 0, 0, 2), (3, 0, 1, 2, 4)]


# ---------------------------------------------------------------------------
# snapshot writer + on-disk restore (no world needed)
# ---------------------------------------------------------------------------

def _tree(v):
    return {"w": np.full((3, 2), float(v)),
            "opt": {"m": np.arange(4.0) * v, "count": np.int64(v)}}


class _FakeState:
    def __init__(self):
        self._commits = 0
        self._saved_state = {}

    def commit_tree(self, plane, v):
        self._commits += 1
        self._saved_state = _tree(v)
        plane.note_commit(self)


def _wait_for(pred, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def _assert_trees_equal(a, b):
    import jax
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestSnapshotPlane:
    def _plane(self, tmp_path, interval=1):
        return ck.StatePlane(str(tmp_path), rank=0, world=1,
                             interval=interval)

    def test_snapshot_round_trip(self, tmp_path):
        plane = self._plane(tmp_path)
        st = _FakeState()
        try:
            st.commit_tree(plane, 1)
            assert _wait_for(lambda: plane.last_manifest_step == 1)
        finally:
            plane.stop()
        with open(ck.latest_path(str(tmp_path))) as f:
            assert int(f.read()) == 1
        got = ck.restore_or_none(str(tmp_path), target=_tree(0))
        assert got is not None
        _assert_trees_equal(got, _tree(1))

    def test_interval_and_latest_wins(self, tmp_path):
        plane = self._plane(tmp_path, interval=2)
        st = _FakeState()
        try:
            for v in range(1, 7):
                st.commit_tree(plane, v)
            assert _wait_for(lambda: plane.last_manifest_step == 6)
        finally:
            plane.stop()
        steps = sorted(int(n.split("-")[1].split(".")[0])
                       for n in os.listdir(str(tmp_path))
                       if n.startswith("manifest-"))
        assert all(s % 2 == 0 for s in steps), steps
        got = ck.sharded_restore_or_none(str(tmp_path), target=_tree(0))
        _assert_trees_equal(got, _tree(6))

    def test_torn_write_restores_previous_step(self, tmp_path,
                                               fault_spec):
        """A rank killed mid-snapshot (ckpt.write fault) leaves a torn
        step directory: no sidecar, no manifest, `latest` unmoved —
        restore_or_none returns the previous complete step."""
        fault_spec("ckpt.write:error:at_step=2")
        plane = self._plane(tmp_path)
        st = _FakeState()
        try:
            st.commit_tree(plane, 1)
            assert _wait_for(lambda: plane.last_manifest_step == 1)
            st.commit_tree(plane, 2)  # this snapshot is killed
            st.commit_tree(plane, 3)
            assert _wait_for(lambda: plane.last_manifest_step == 3)
        finally:
            plane.stop()
        assert not os.path.exists(
            ck.manifest_path(str(tmp_path), 2))
        got = ck.sharded_restore_or_none(str(tmp_path), step=2,
                                         target=_tree(0))
        assert got is None  # step 2 is torn: never served
        _assert_trees_equal(
            ck.restore_or_none(str(tmp_path), target=_tree(0)), _tree(3))

    def test_corrupt_shard_falls_back_to_older_manifest(self, tmp_path):
        plane = self._plane(tmp_path)
        st = _FakeState()
        try:
            st.commit_tree(plane, 1)
            assert _wait_for(lambda: plane.last_manifest_step == 1)
            st.commit_tree(plane, 2)
            assert _wait_for(lambda: plane.last_manifest_step == 2)
        finally:
            plane.stop()
        # flip bytes in step 2's shard: its digest no longer verifies
        sdir = ck.step_dir(str(tmp_path), 2)
        shard = [n for n in os.listdir(sdir) if n.endswith(".bin")][0]
        with open(os.path.join(sdir, shard), "r+b") as f:
            f.write(b"\xff\xff\xff\xff")
        got = ck.restore_or_none(str(tmp_path), target=_tree(0))
        _assert_trees_equal(got, _tree(1))

    def test_restore_or_none_empty_dir(self, tmp_path):
        assert ck.restore_or_none(str(tmp_path)) is None
        assert ck.restore_or_none(
            str(tmp_path / "never-created")) is None

    def test_stop_is_idempotent_and_joins(self, tmp_path):
        plane = self._plane(tmp_path)
        st = _FakeState()
        st.commit_tree(plane, 1)
        plane.stop()
        plane.stop()
        st.commit_tree(plane, 2)  # post-stop commits are dropped
        assert plane._thread is None


# ---------------------------------------------------------------------------
# peer-transfer protocol over the KV fallback (no loopback world): this
# IS the fallback-channel coverage — outside a loopback context
# peer_channel() returns None and every shard rides the KV transport.
# ---------------------------------------------------------------------------

class _MemKV:
    """In-memory KVClient stand-in (put/wait/delete)."""

    def __init__(self):
        self.cv = threading.Condition()
        self.store = {}

    def put(self, key, value):
        with self.cv:
            self.store[key] = value
            self.cv.notify_all()

    def wait(self, key, timeout=60.0, poll_interval=0.1):
        end = time.monotonic() + min(timeout, 10.0)
        with self.cv:
            while key not in self.store:
                if time.monotonic() > end:
                    raise TimeoutError(key)
                self.cv.wait(0.05)
            return self.store[key]

    def delete(self, key):
        with self.cv:
            self.store.pop(key, None)


def _run_world_transfers(plan, trees, monkeypatch):
    """Run every rank's side of run_peer_transfers on its own thread,
    with a barrier allgather and the in-memory KV as the transport.
    Returns {rank: (new_leaves, reason)}."""
    import jax
    kv = _MemKV()
    monkeypatch.setattr(ck, "_kv_client", lambda: kv)
    n = plan.world
    barrier = {"cv": threading.Condition(), "calls": {}, "vals": {}}

    def allgather(obj):
        # lockstep allgather: the round is each thread's OWN call count
        # (a shared bumped counter races — a waiter can re-enter for the
        # next round before the bumper wakes and read stale deposits)
        cv = barrier["cv"]
        with cv:
            me = threading.current_thread().name
            rnd = barrier["calls"].get(me, 0)
            barrier["calls"][me] = rnd + 1
            barrier["vals"].setdefault(rnd, {})[me] = obj
            cv.notify_all()
            end = time.monotonic() + 15.0
            while len(barrier["vals"][rnd]) < n:
                if time.monotonic() > end:
                    raise TimeoutError("allgather barrier")
                cv.wait(0.05)
            vals = barrier["vals"][rnd]
            return [vals[k] for k in sorted(vals)]

    out = {}

    def one(rank):
        leaves = jax.tree_util.tree_leaves(trees[rank])
        out[rank] = ck.run_peer_transfers(plan, rank, leaves,
                                          allgather=allgather)

    ts = [threading.Thread(target=one, args=(r,), name=f"r{r:02d}",
                           daemon=True) for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
        assert not t.is_alive(), "transfer thread hung"
    return out


class TestPeerTransfersKV:
    def test_two_joiners_pull_from_two_survivors(self, monkeypatch):
        """2 survivors re-serve a tree snapshotted 4-wide: ranges are
        re-partitioned 2-wide on the fly and both joiners assemble the
        survivors' exact leaves (2->4 world growth)."""
        import jax
        plan = ck.make_restore_plan(
            [_blob_t(0, 5), _blob_t(1, 5), _blob_t(2, 0), _blob_t(3, 0)],
            world=4)
        good = _tree(9)
        trees = {0: good, 1: good, 2: _tree(0), 3: _tree(0)}
        out = _run_world_transfers(plan, trees, monkeypatch)
        for r in (0, 1):
            assert out[r] == (None, None)  # survivors: nothing to apply
        want = jax.tree_util.tree_leaves(good)
        for r in (2, 3):
            got, reason = out[r]
            assert reason is None
            for x, y in zip(got, want):
                np.testing.assert_array_equal(np.asarray(x),
                                              np.asarray(y))

    def test_digest_mismatch_rejected_and_repulled(self, monkeypatch):
        """A corrupted shard (digest mismatch) is rejected and re-pulled
        from the next survivor on attempt 1 — restore still succeeds."""
        import jax
        plan = ck.make_restore_plan(
            [_blob_t(0, 5), _blob_t(1, 5), _blob_t(2, 0)], world=3)
        good = _tree(4)
        trees = {0: good, 1: good, 2: _tree(0)}
        corrupted = []

        def corrupt_once(tag, payload):
            # flip rank 0's served shard on attempt 0 only
            step, d, owner, lo, hi, attempt = tag
            if owner == 0 and attempt == 0:
                corrupted.append(tag)
                return b"\x00" + payload[1:]
            return payload

        monkeypatch.setattr(ck, "_corrupt_shard_hook", corrupt_once)
        out = _run_world_transfers(plan, trees, monkeypatch)
        assert corrupted, "hook never fired"
        got, reason = out[2]
        assert reason is None
        want = jax.tree_util.tree_leaves(good)
        for x, y in zip(got, want):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_unrecoverable_pulls_degrade(self, monkeypatch):
        """Every serve corrupt on every attempt: both attempts fail and
        every rank agrees on the typed degraded reason."""
        plan = ck.make_restore_plan(
            [_blob_t(0, 5), _blob_t(1, 5), _blob_t(2, 0)], world=3)
        trees = {0: _tree(4), 1: _tree(4), 2: _tree(0)}
        monkeypatch.setattr(ck, "_corrupt_shard_hook",
                            lambda tag, p: b"\x00" + p[1:])
        out = _run_world_transfers(plan, trees, monkeypatch)
        for r in range(3):
            assert out[r] == (None, "pull-failed"), (r, out[r])

    def test_shard_pull_fault_fails_over(self, monkeypatch, fault_spec):
        """The ckpt.shard_pull chaos seam: survivor 0 refuses its serves
        once; the pull fails over to survivor 1 and completes."""
        import jax
        fault_spec("ckpt.shard_pull:error:rank=0:times=1")
        plan = ck.make_restore_plan(
            [_blob_t(0, 5), _blob_t(1, 5), _blob_t(2, 0)], world=3)
        good = _tree(3)
        trees = {0: good, 1: good, 2: _tree(0)}
        out = _run_world_transfers(plan, trees, monkeypatch)
        got, reason = out[2]
        assert reason is None
        want = jax.tree_util.tree_leaves(good)
        for x, y in zip(got, want):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _blob_t(rank, commits):
    """Fingerprint blob matching _tree()'s real structure."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(_tree(0))
    return {"rank": rank, "commits": commits, "n_leaves": len(leaves),
            "struct": ck.structure_digest(leaves, treedef),
            "manifest": -1}


# ---------------------------------------------------------------------------
# verification guards
# ---------------------------------------------------------------------------

class TestShardVerification:
    def _payload(self, leaves):
        import pickle
        data = pickle.dumps(leaves, protocol=pickle.HIGHEST_PROTOCOL)
        return ("ok", ck.shard_digest(data), data)

    def test_accepts_matching(self):
        import jax
        leaves = jax.tree_util.tree_leaves(_tree(2))
        got = ck._verify_shard(self._payload(leaves[0:2]), leaves, 0, 2)
        assert len(got) == 2

    def test_rejects_digest_mismatch(self):
        import jax
        leaves = jax.tree_util.tree_leaves(_tree(2))
        ok, digest, data = self._payload(leaves[0:2])
        with pytest.raises(ck._ShardRejected, match="digest"):
            ck._verify_shard((ok, digest ^ 1, data), leaves, 0, 2)

    def test_rejects_refusal_and_shape_mismatch(self):
        import jax
        leaves = jax.tree_util.tree_leaves(_tree(2))
        with pytest.raises(ck._ShardRejected, match="refused"):
            ck._verify_shard(("err", "boom"), leaves, 0, 2)
        wrong = [np.zeros((9, 9)), np.zeros((9, 9))]
        with pytest.raises(ck._ShardRejected, match="mismatch"):
            ck._verify_shard(self._payload(wrong), leaves, 0, 2)


# ---------------------------------------------------------------------------
# KV server GC surface
# ---------------------------------------------------------------------------

class TestKVDelete:
    def test_server_side_prefix_delete(self):
        from horovod_tpu.runner.http_kv import KVServer
        srv = KVServer()
        srv.start(0)
        try:
            srv.put("ckpt/peer/1/a", b"x")
            srv.put("ckpt/peer/1/b", b"y")
            srv.put("elastic/round", b"3")
            srv.delete("ckpt/peer")
            assert srv.keys("ckpt/peer") == []
            assert srv.get("elastic/round") == b"3"
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# loopback churn end to end (the ISSUE acceptance)
# ---------------------------------------------------------------------------

needs_native = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable")

FAST_HEALTH = {"HVD_HEALTH_INTERVAL": "0.2", "HVD_HEALTH_TIMEOUT": "2",
               "HVD_RESPONSE_CACHE": "1", "HVD_METRICS": "1"}


def _param_body(box, total_steps, until_transitions=0, sleep_s=0.03):
    """Training body with a real param/opt pytree updated by a
    world-size-independent rule: the Average of identical 0.25
    contributions is bitwise 0.25 at every world size (0.25*w/w is an
    exact binary division), so two runs that commit the same number of
    steps — churned or not — must end bitwise identical. With
    ``until_transitions`` the run continues past ``total_steps`` until
    that many world transitions were observed (the churn-test idiom:
    a fixed budget races discovery latency on a loaded box)."""
    import jax.numpy as jnp

    cap = total_steps * (4 if until_transitions else 1)

    def body():
        hvd.init()
        state = hvd.elastic.JaxState(
            params={"w": np.zeros((4, 3), np.float32),
                    "b": np.zeros(3, np.float32)},
            opt_state={"m": np.zeros((4, 3), np.float32), "count": 0},
            step=0, trans=0, lastw=0)

        @hvd.elastic.run
        def train(state):
            from horovod_tpu import metrics as _metrics
            while state.step < cap and not (
                    until_transitions and state.step >= total_steps
                    and state.trans >= until_transitions):
                probe = hvd.allreduce(jnp.ones(1), op=hvd.Sum,
                                      name="ckpt_probe")
                world = int(round(float(np.asarray(probe)[0])))
                if state.lastw and world != state.lastw:
                    state.trans += 1
                state.lastw = world
                g = np.asarray(
                    hvd.allreduce(jnp.full((4, 3), 0.25),
                                  op=hvd.Average, name="ckpt_grad"),
                    np.float32)
                state.params = {"w": state.params["w"] + g,
                                "b": state.params["b"] + g[0]}
                state.opt_state = {
                    "m": np.float32(0.5) * state.opt_state["m"] + g,
                    "count": state.opt_state["count"] + 1}
                state.step += 1
                time.sleep(sleep_s)
                state.commit()
            def tot(inst):
                # metric stores are per rank context: the joiner's pull
                # counters live on ITS thread's store, so sum all stores
                out = {}
                for s in _metrics._all_stores():
                    for k, v in inst.series(s).items():
                        out[k] = out.get(k, 0) + v
                return out

            return (state.step, state.trans, state.params,
                    state.opt_state,
                    int(_metrics.ELASTIC_STEPS_LOST.value()),
                    {"pulled": tot(_metrics.CKPT_PEER_SHARDS_PULLED),
                     "degraded": tot(
                         _metrics.CKPT_DEGRADED_RESTORES)})

        result = train(state)
        if hvd.rank() == 0:
            box["result"] = result
        return 0

    return body


def _series_total(series_dict):
    return sum(int(v) for v in series_dict.values())


def _replay(steps):
    """The no-churn control, replayed with the body's exact float32
    numpy ops."""
    w = np.zeros((4, 3), np.float32)
    m = np.zeros((4, 3), np.float32)
    g = np.full((4, 3), 0.25, np.float32)
    for _ in range(steps):
        w = w + g
        m = np.float32(0.5) * m + g
    return w, m


CHURN_4_3_4 = ("worker:preempt:rank=3:at_round=1:at_step=4:grace=30;"
               "worker:add:rank=0:at_round=2:after=4")


@needs_native
class TestPeerRestoreChurn:
    def _run(self, fault_spec, spec=None, np_=4, min_np=2, steps=24,
             until_transitions=0, extra=None, timeout=180):
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        if spec is not None:
            fault_spec(spec)
        else:
            os.environ.pop("HVD_FAULT_SPEC", None)
            _faults.refresh()
            _faults.clear_membership_handler()
        # The body's counters sum over EVERY live store — drop what
        # earlier tests (this file's KV unit tests, prior loopback
        # worlds elsewhere in the session) already recorded, so the
        # assertions see only this run.
        from horovod_tpu import metrics as _metrics
        _metrics.reset_all(_metrics.CKPT_PEER_SHARDS_PULLED,
                           _metrics.CKPT_DEGRADED_RESTORES)
        disco = FixedHosts({f"c{i}": 1 for i in range(np_)})
        box = {}
        env = dict(FAST_HEALTH)
        env.update(extra or {})
        results, ok = elastic_run(
            _param_body(box, steps, until_transitions=until_transitions),
            np=np_, min_np=min_np, max_np=np_, discovery=disco,
            timeout=timeout, extra_env=env)
        assert ok, results.error_message
        return box["result"]

    def test_churn_restore_bitwise_parity_vs_control(self, fault_spec):
        """World 4 -> 3 (graceful preempt) -> 4 (joiner peer-restores
        from survivor shards): final params AND optimizer state are
        bitwise identical to an unchurned world-4 control committing
        the same number of steps, zero steps rolled back, shards
        actually pulled, zero degraded restores."""
        step, trans, params, opt, lost, m = self._run(
            fault_spec, CHURN_4_3_4, until_transitions=2)
        assert trans >= 2, f"churn never completed: {trans} transitions"
        assert lost == 0, "graceful preempt rolled back steps"
        assert _series_total(m["pulled"]) > 0, \
            f"no peer shards pulled: {m}"
        assert _series_total(m["degraded"]) == 0, \
            f"peer restore degraded: {m}"
        # the control commits exactly as many steps, with zero churn
        cstep, _ct, cparams, copt, _cl, _cm = self._run(
            fault_spec, None, steps=step)
        assert cstep == step
        for k in ("w", "b"):
            np.testing.assert_array_equal(params[k], cparams[k])
        np.testing.assert_array_equal(opt["m"], copt["m"])
        assert opt["count"] == copt["count"] == step

    def test_survivor_death_mid_serve_fails_over(self, fault_spec):
        """Chaos (docs/robustness.md): a survivor dying mid-shard-serve
        (``ckpt.shard_pull:crash``) must fail over — the watchdog turns
        the dead serve into a PeerFailureError re-form, never a hang —
        and the job still completes inside the run timeout."""
        step, trans, params, _opt, _lost, m = self._run(
            fault_spec,
            CHURN_4_3_4 + ";ckpt.shard_pull:crash:rank=1:times=1",
            min_np=1, until_transitions=2, timeout=240)
        # the failover re-form can be size-preserving (dead survivor out,
        # joiner in -> 3->3), which the numeric world probe cannot see:
        # completion inside the timeout + a restore that actually served
        # the joiner (peer or typed-degraded) is the acceptance here.
        assert trans >= 1, f"preempt shrink never observed: {trans}"
        assert (_series_total(m["pulled"])
                + _series_total(m["degraded"])) > 0, m
        w, _ = _replay(step)
        np.testing.assert_array_equal(params["w"], w)

    def test_degraded_pull_failure_takes_typed_broadcast(self,
                                                        fault_spec):
        """Every serve refused on every attempt: the restore degrades
        to the rank-0 broadcast, counted under its typed reason — and
        the run still completes with the exact control numerics."""
        step, trans, params, opt, _lost, m = self._run(
            fault_spec, CHURN_4_3_4 + ";ckpt.shard_pull:error",
            until_transitions=2)
        assert trans >= 2, f"churn never completed: {trans}"
        assert _series_total(m["degraded"]) >= 1, m
        w, mm = _replay(step)
        np.testing.assert_array_equal(params["w"], w)
        np.testing.assert_array_equal(opt["m"], mm)

    def test_snapshot_dir_written_during_churn(self, fault_spec,
                                               tmp_path):
        """With HVD_CKPT_DIR set the plane snapshots during training,
        and a from-disk restore_or_none after the run reassembles a
        committed step whose params equal the replayed update rule."""
        step, _t, _p, _o, _l, _m = self._run(
            fault_spec, "worker:preempt:rank=3:at_step=4:grace=30",
            extra={"HVD_CKPT_DIR": str(tmp_path),
                   "HVD_CKPT_INTERVAL": "2"})
        manifests = [n for n in os.listdir(str(tmp_path))
                     if n.startswith("manifest-")]
        assert manifests, os.listdir(str(tmp_path))
        target = {"params": {"w": np.zeros((4, 3), np.float32),
                             "b": np.zeros(3, np.float32)},
                  "opt_state": {"m": np.zeros((4, 3), np.float32),
                                "count": 0},
                  "step": 0, "trans": 0, "lastw": 0}
        got = ck.restore_or_none(str(tmp_path), target=target)
        assert got is not None
        assert 2 <= got["step"] <= step
        w, _ = _replay(got["step"])
        np.testing.assert_array_equal(got["params"]["w"], w)
