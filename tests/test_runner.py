"""Launcher unit tests, mirroring the reference's ``test/single/test_run.py``
(arg parsing, host parsing, assignment math, mocked command construction)
plus live KV-server and local end-to-end programmatic runs."""

import os
import socket
import sys
import textwrap

import pytest

from horovod_tpu.runner import (
    KVClient, KVServer, get_host_assignments, make_secret, parse_args,
    parse_hostfile, parse_hosts, run, worker_env,
)
from horovod_tpu.runner.hosts import HostParseError, HostSpec, total_slots
from horovod_tpu.runner.launch import _ssh_command, is_local_host


# --- host parsing ----------------------------------------------------------

def test_parse_hosts():
    specs = parse_hosts("h1:4,h2:4,h3")
    assert [(s.hostname, s.slots) for s in specs] == [
        ("h1", 4), ("h2", 4), ("h3", 1)]


def test_parse_hosts_invalid():
    with pytest.raises(HostParseError):
        parse_hosts("")
    with pytest.raises(HostParseError):
        parse_hosts("h1:x")
    with pytest.raises(HostParseError):
        parse_hosts("h1:0")


def test_parse_hostfile(tmp_path):
    f = tmp_path / "hostfile"
    f.write_text(textwrap.dedent("""\
        # training pod
        tpu-a slots=4
        tpu-b slots=2
        tpu-c
    """))
    specs = parse_hostfile(str(f))
    assert [(s.hostname, s.slots) for s in specs] == [
        ("tpu-a", 4), ("tpu-b", 2), ("tpu-c", 1)]
    assert total_slots(specs) == 7


# --- assignment math (reference hosts.py semantics) ------------------------

def test_host_assignments_basic():
    slots = get_host_assignments(parse_hosts("a:2,b:2"), 4)
    assert [(s.hostname, s.rank, s.local_rank, s.cross_rank) for s in slots] == [
        ("a", 0, 0, 0), ("a", 1, 1, 0), ("b", 2, 0, 1), ("b", 3, 1, 1)]
    assert all(s.size == 4 and s.local_size == 2 and s.cross_size == 2
               for s in slots)


def test_host_assignments_uneven():
    slots = get_host_assignments(parse_hosts("a:3,b:1"), 4)
    a2 = slots[2]
    assert (a2.hostname, a2.local_rank, a2.cross_size) == ("a", 2, 1)
    b0 = slots[3]
    assert (b0.hostname, b0.local_rank, b0.local_size, b0.cross_rank,
            b0.cross_size) == ("b", 0, 1, 1, 2)


def test_host_assignments_partial_fill():
    slots = get_host_assignments(parse_hosts("a:4,b:4"), 5)
    assert [s.hostname for s in slots] == ["a"] * 4 + ["b"]
    assert slots[4].local_size == 1


def test_host_assignments_overflow():
    with pytest.raises(ValueError, match="exceeds total available slots"):
        get_host_assignments(parse_hosts("a:2"), 3)


# --- CLI parsing -----------------------------------------------------------

def test_parse_args_basic():
    args = parse_args(["-np", "4", "-H", "h1:2,h2:2", "python", "train.py"])
    assert args.np == 4 and args.hosts == "h1:2,h2:2"
    assert args.command == ["python", "train.py"]


def test_parse_args_config_file(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(textwrap.dedent("""\
        np: 8
        hosts: "x:4,y:4"
        params:
          fusion-threshold-mb: 64
          cycle-time-ms: 2.5
        timeline:
          filename: /tmp/tl.json
        autotune:
          enabled: true
    """))
    args = parse_args(["--config-file", str(cfg), "cmd"])
    assert args.np == 8 and args.hosts == "x:4,y:4"
    assert args._config_env["HVD_FUSION_THRESHOLD"] == str(64 * 1024 * 1024)
    assert args._config_env["HVD_CYCLE_TIME"] == "2.5"
    assert args._config_env["HVD_TIMELINE"] == "/tmp/tl.json"
    assert args._config_env["HVD_AUTOTUNE"] == "1"


def test_parse_args_cli_overrides_config(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("np: 8\n")
    args = parse_args(["-np", "2", "--config-file", str(cfg), "cmd"])
    assert args.np == 2


# --- worker env / ssh command ----------------------------------------------

def test_worker_env_seeding():
    slot = get_host_assignments(parse_hosts("a:2,b:2"), 4)[2]
    env = worker_env(slot, coordinator_addr="10.0.0.1", coordinator_port=9778,
                     kv_addr="10.0.0.9", kv_port=8000, secret="s3cr3t")
    assert env["HVD_RANK"] == "2" and env["HVD_SIZE"] == "4"
    assert env["HVD_LOCAL_RANK"] == "0" and env["HVD_CROSS_RANK"] == "1"
    assert env["HVD_PROCESS_ID"] == "2" and env["HVD_NUM_PROCESSES"] == "4"
    assert env["HVD_COORDINATOR_ADDR"] == "10.0.0.1"
    assert env["HVD_SECRET_KEY"] == "s3cr3t"


def test_ssh_command_construction():
    cmd = _ssh_command("remote-host", ["python", "train.py"],
                       {"HVD_RANK": "1"}, ssh_port=2222,
                       identity_file="/id_rsa")
    assert cmd[0] == "ssh"
    assert "-p" in cmd and "2222" in cmd
    assert "-i" in cmd and "/id_rsa" in cmd
    assert cmd[-2] == "remote-host"
    assert "export HVD_RANK=1;" in cmd[-1]
    assert "python train.py" in cmd[-1]


def test_is_local_host():
    assert is_local_host("localhost")
    assert is_local_host("127.0.0.1")
    assert is_local_host(socket.gethostname())
    assert not is_local_host("surely-not-this-host.invalid")


# --- KV server/client ------------------------------------------------------

def test_kv_roundtrip():
    server = KVServer(secret=None)
    port = server.start()
    try:
        c = KVClient("127.0.0.1", port)
        assert c.get("scope/missing") is None
        c.put("scope/k1", b"v1")
        c.put("scope/k2", b"v2")
        assert c.get("scope/k1") == b"v1"
        assert c.keys("scope") == ["scope/k1", "scope/k2"]
        c.delete("scope/k1")
        assert c.get("scope/k1") is None
        assert c.wait("scope/k2", timeout=1.0) == b"v2"
        with pytest.raises(TimeoutError):
            c.wait("scope/never", timeout=0.3)
    finally:
        server.stop()


def test_kv_signature_rejected():
    secret = make_secret()
    server = KVServer(secret=secret)
    port = server.start()
    try:
        good = KVClient("127.0.0.1", port, secret=secret)
        good.put("s/k", b"payload")
        assert good.get("s/k") == b"payload"
        bad = KVClient("127.0.0.1", port, secret="wrong")
        import urllib.error
        with pytest.raises(urllib.error.HTTPError):
            bad.put("s/evil", b"x")
    finally:
        server.stop()


# --- end-to-end local programmatic run -------------------------------------

# Worker processes can't import this test module; ship the functions by value.
import cloudpickle  # noqa: E402
cloudpickle.register_pickle_by_value(sys.modules[__name__])


def _worker_fn(scale):
    # No jax here: validates launcher plumbing (env seeding, fn shipping,
    # result collection) without paying distributed-XLA startup per test.
    rank = int(os.environ["HVD_RANK"])
    size = int(os.environ["HVD_SIZE"])
    return {"rank": rank, "size": size, "value": rank * scale,
            "coord": os.environ["HVD_COORDINATOR_ADDR"]}


def test_programmatic_run_local():
    results = run(_worker_fn, args=(10,), np=2,
                  env={"JAX_PLATFORMS": "cpu"})
    assert [r["rank"] for r in results] == [0, 1]
    assert all(r["size"] == 2 for r in results)
    assert [r["value"] for r in results] == [0, 10]


def _failing_fn():
    raise RuntimeError("worker exploded")


def test_programmatic_run_propagates_failure():
    with pytest.raises(RuntimeError, match="worker exploded"):
        run(_failing_fn, np=2, env={"JAX_PLATFORMS": "cpu"})


def test_hvdrun_cli_local(tmp_path):
    """Full hvdrun static launch of a trivial 2-rank command."""
    from horovod_tpu.runner.launch import run_commandline
    out = tmp_path / "out"
    script = tmp_path / "w.py"
    script.write_text(
        "import os\n"
        "print('rank', os.environ['HVD_RANK'], 'of', os.environ['HVD_SIZE'])\n")
    code = run_commandline(
        ["-np", "2", "--output-filename", str(out), "--",
         sys.executable, str(script)])
    assert code == 0
    assert "rank 0 of 2" in (out / "rank.0" / "stdout").read_text()
    assert "rank 1 of 2" in (out / "rank.1" / "stdout").read_text()


def test_hvdrun_cli_failure_exit_code(tmp_path):
    from horovod_tpu.runner.launch import run_commandline
    code = run_commandline(
        ["-np", "2", "--", sys.executable, "-c", "import sys; sys.exit(3)"])
    assert code == 3


def test_kv_gather_endpoint():
    """Server-side long-poll gather: one round trip collects a scope."""
    from horovod_tpu.runner.http_kv import KVClient, KVServer, make_secret
    import threading as _threading
    import time as _time

    secret = make_secret()
    server = KVServer(secret=secret)
    port = server.start()
    client = KVClient("127.0.0.1", port, secret=secret)
    try:
        client.put("g/0", b"a" * 10)
        client.put("g/2", b"c")

        def late_put():
            _time.sleep(0.2)
            client2 = KVClient("127.0.0.1", port, secret=secret)
            client2.put("g/1", b"bb")

        t = _threading.Thread(target=late_put)
        t.start()
        got = client.gather("g", 3, timeout=10)
        t.join()
        assert got == {"g/0": b"a" * 10, "g/1": b"bb", "g/2": b"c"}
        # timeout path
        import pytest as _pytest
        with _pytest.raises(TimeoutError):
            client.gather("nothing", 2, timeout=0.3)
    finally:
        server.stop()
