"""Hierarchical two-level (ICI/DCN) collectives.

Validates the reference-parity schedule (reduce-scatter over ICI → allreduce
over DCN → allgather over ICI, ``nccl_operations.cc:286-506``) numerically
against the flat path on an 8-device world reshaped 2×4.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import hierarchical


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


def _world():
    return hvd.size()


class TestHierarchicalMesh:
    def test_shape(self):
        m = hvd.hierarchical_mesh(ici_size=4)
        assert m.axis_names == (hierarchical.DCN_AXIS, hierarchical.ICI_AXIS)
        assert m.devices.shape == (_world() // 4, 4)

    def test_bad_ici_size(self):
        with pytest.raises(ValueError):
            hvd.hierarchical_mesh(ici_size=3)

    def test_default_ici_size_env_override(self, monkeypatch):
        monkeypatch.setenv("HVD_HIERARCHICAL_ICI_SIZE", "2")
        assert hierarchical.default_ici_size() == 2


class TestTracedHierarchicalAllreduce:
    @pytest.mark.parametrize("shape", [(16,), (5,), (3, 7), (2, 3, 4)])
    def test_matches_flat_sum(self, rng, shape):
        n = _world()
        data = rng.normal(size=(n,) + shape).astype(np.float32)
        mesh = hvd.hierarchical_mesh(ici_size=4)
        da, ia = mesh.axis_names

        def inner(x):
            return hierarchical.hierarchical_allreduce_traced(
                x[0], ia, da, op=hvd.Sum)[None]

        fn = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=P((da, ia)), out_specs=P((da, ia)),
            check_vma=False))
        out = np.asarray(fn(data)[0])
        np.testing.assert_allclose(out, data.sum(axis=0), rtol=1e-5)

    def test_average(self, rng):
        n = _world()
        data = rng.normal(size=(n, 9)).astype(np.float32)
        mesh = hvd.hierarchical_mesh(ici_size=2)
        da, ia = mesh.axis_names

        def inner(x):
            return hierarchical.hierarchical_allreduce_traced(
                x[0], ia, da, op=hvd.Average)[None]

        fn = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=P((da, ia)), out_specs=P((da, ia)),
            check_vma=False))
        out = np.asarray(fn(data)[0])
        np.testing.assert_allclose(out, data.mean(axis=0), rtol=1e-5)

    def test_prescale_postscale(self, rng):
        n = _world()
        data = rng.normal(size=(n, 4)).astype(np.float32)
        mesh = hvd.hierarchical_mesh(ici_size=4)
        da, ia = mesh.axis_names

        def inner(x):
            return hierarchical.hierarchical_allreduce_traced(
                x[0], ia, da, op=hvd.Sum, prescale_factor=2.0,
                postscale_factor=0.5)[None]

        fn = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=P((da, ia)), out_specs=P((da, ia)),
            check_vma=False))
        out = np.asarray(fn(data)[0])
        np.testing.assert_allclose(out, data.sum(axis=0), rtol=1e-5)

    def test_rejects_min(self):
        mesh = hvd.hierarchical_mesh(ici_size=4)
        da, ia = mesh.axis_names
        with pytest.raises(ValueError, match="SUM/AVERAGE"):
            jax.shard_map(
                lambda x: hierarchical.hierarchical_allreduce_traced(
                    x[0], ia, da, op=hvd.Min)[None],
                mesh=mesh, in_specs=P((da, ia)), out_specs=P((da, ia)),
                check_vma=False)(np.zeros((_world(), 2), np.float32))


class TestTracedHierarchicalAllgather:
    def test_matches_concat_in_rank_order(self, rng):
        n = _world()
        data = rng.normal(size=(n, 2, 3)).astype(np.float32)
        mesh = hvd.hierarchical_mesh(ici_size=4)
        da, ia = mesh.axis_names

        def inner(x):
            return hierarchical.hierarchical_allgather_traced(x[0], ia, da)

        fn = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=P((da, ia)), out_specs=P(),
            check_vma=False))
        out = np.asarray(fn(data))
        np.testing.assert_allclose(out, data.reshape(n * 2, 3), rtol=1e-6)


class TestEagerHierarchical:
    def test_public_allreduce(self, rng):
        n = _world()
        vals = [rng.normal(size=(6, 2)).astype(np.float32) for _ in range(n)]
        out = hvd.hierarchical_allreduce(hvd.per_rank(vals), op=hvd.Sum,
                                         ici_size=4)
        np.testing.assert_allclose(np.asarray(out), np.sum(vals, axis=0),
                                   rtol=1e-5)

    def test_public_allreduce_average(self, rng):
        n = _world()
        vals = [rng.normal(size=(5,)).astype(np.float32) for _ in range(n)]
        out = hvd.hierarchical_allreduce(hvd.per_rank(vals), ici_size=2)
        np.testing.assert_allclose(np.asarray(out), np.mean(vals, axis=0),
                                   rtol=1e-5)

    def test_public_allgather(self, rng):
        n = _world()
        vals = [rng.normal(size=(2, 3)).astype(np.float32) for _ in range(n)]
        out = hvd.hierarchical_allgather(hvd.per_rank(vals), ici_size=4)
        np.testing.assert_allclose(np.asarray(out),
                                   np.concatenate(vals, axis=0), rtol=1e-6)

    def test_knob_routes_allreduce(self, rng, monkeypatch):
        monkeypatch.setenv("HVD_HIERARCHICAL_ALLREDUCE", "1")
        monkeypatch.setenv("HVD_HIERARCHICAL_ICI_SIZE", "4")
        n = _world()
        vals = [rng.normal(size=(7,)).astype(np.float32) for _ in range(n)]
        out = hvd.allreduce(hvd.per_rank(vals), op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(out), np.sum(vals, axis=0),
                                   rtol=1e-5)
        out = hvd.allreduce(hvd.per_rank(vals))  # AVERAGE
        np.testing.assert_allclose(np.asarray(out), np.mean(vals, axis=0),
                                   rtol=1e-5)

    def test_knob_routes_grouped_allreduce(self, rng, monkeypatch):
        monkeypatch.setenv("HVD_HIERARCHICAL_ALLREDUCE", "1")
        monkeypatch.setenv("HVD_HIERARCHICAL_ICI_SIZE", "2")
        n = _world()
        a = [rng.normal(size=(3,)).astype(np.float32) for _ in range(n)]
        b = [rng.normal(size=(2, 2)).astype(np.float32) for _ in range(n)]
        outs = hvd.grouped_allreduce([hvd.per_rank(a), hvd.per_rank(b)],
                                     op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(outs[0]), np.sum(a, axis=0),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(outs[1]), np.sum(b, axis=0),
                                   rtol=1e-5)

    def test_knob_routes_allgather(self, rng, monkeypatch):
        monkeypatch.setenv("HVD_HIERARCHICAL_ALLGATHER", "1")
        monkeypatch.setenv("HVD_HIERARCHICAL_ICI_SIZE", "4")
        n = _world()
        vals = [rng.normal(size=(2,)).astype(np.float32) for _ in range(n)]
        out = hvd.allgather(hvd.per_rank(vals))
        np.testing.assert_allclose(np.asarray(out),
                                   np.concatenate(vals, axis=0), rtol=1e-6)

    def test_knob_ignored_for_subset(self, rng, monkeypatch):
        monkeypatch.setenv("HVD_HIERARCHICAL_ALLREDUCE", "1")
        monkeypatch.setenv("HVD_HIERARCHICAL_ICI_SIZE", "4")
        ps = hvd.add_process_set([0, 1, 2])
        try:
            vals = [rng.normal(size=(3,)).astype(np.float32) for _ in range(3)]
            out = hvd.allreduce(hvd.per_rank(vals, ps), op=hvd.Sum,
                                process_set=ps)
            np.testing.assert_allclose(np.asarray(out), np.sum(vals, axis=0),
                                       rtol=1e-5)
        finally:
            hvd.remove_process_set(ps)

    def test_min_max_fall_back_to_flat(self, rng, monkeypatch):
        monkeypatch.setenv("HVD_HIERARCHICAL_ALLREDUCE", "1")
        monkeypatch.setenv("HVD_HIERARCHICAL_ICI_SIZE", "4")
        n = _world()
        vals = [rng.normal(size=(4,)).astype(np.float32) for _ in range(n)]
        out = hvd.allreduce(hvd.per_rank(vals), op=hvd.Min)
        np.testing.assert_allclose(np.asarray(out), np.min(vals, axis=0),
                                   rtol=1e-6)

    def test_bf16(self, rng, monkeypatch):
        monkeypatch.setenv("HVD_HIERARCHICAL_ALLREDUCE", "1")
        monkeypatch.setenv("HVD_HIERARCHICAL_ICI_SIZE", "4")
        n = _world()
        vals = [rng.normal(size=(8,)).astype(jnp.bfloat16) for _ in range(n)]
        out = hvd.allreduce(hvd.per_rank(vals), op=hvd.Sum)
        assert out.dtype == jnp.bfloat16
        expected = np.sum([np.asarray(v, np.float32) for v in vals], axis=0)
        np.testing.assert_allclose(np.asarray(out, np.float32), expected,
                                   rtol=0.05)
