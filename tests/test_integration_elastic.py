"""End-to-end elastic integration test: a real elastic hvdrun job on
localhost whose discovery script grows the world mid-run, forcing the
existing worker to re-rendezvous in-process (jax world teardown + rebuild)
and the new worker to join and receive synced state.

The analog of the reference's ``test/integration/test_elastic_torch.py``
driven by ``elastic_common.py`` (scripted discovery whose output changes
as the job runs)."""

import json
import os
import subprocess
import sys
import textwrap

from backend_markers import skip_if_cpu_backend

# The spawn variants stay marked for real-hardware runs; the loopback
# twins (the in-process driver in tests/test_loopback_world.py TestChaos/
# TestElastic, and the `hvdrun --loopback --min-np` CLI test below) run
# the same recovery protocol in tier-1 on the CPU backend.


WORKER = textwrap.dedent("""\
    import json
    import os
    import sys
    import time

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import horovod_tpu as hvd

    TRIGGER = sys.argv[1]
    OUTFILE = sys.argv[2]
    TOTAL_STEPS = 60
    MAX_STEPS = 400  # bail-out when the resize never happens
    GROW_AT_STEP = 2

    hvd.init()
    state = hvd.elastic.JaxState(step=0, sizes=[])

    @hvd.elastic.run
    def train(state):
        # Run at least TOTAL_STEPS and until the grown world was observed,
        # so a slow discovery poll on a loaded machine cannot flake the test.
        while state.step < TOTAL_STEPS or \\
                (2 not in state.sizes and state.step < MAX_STEPS):
            # world size via a real collective: sum of ones over all chips
            out = hvd.allreduce(jnp.ones(2), op=hvd.Sum)
            world = int(float(out.reshape(-1)[0]))
            state.sizes = state.sizes + [world]
            state.step += 1
            if state.step == GROW_AT_STEP and hvd.rank() == 0:
                open(TRIGGER, "w").close()  # discovery now reports 2 slots
            time.sleep(0.2)
            state.commit()
        return state.sizes

    sizes = train(state)
    if hvd.rank() == 0:
        with open(OUTFILE, "w") as f:
            json.dump(sizes, f)
    print("ELASTIC-DONE", hvd.rank(), sizes, flush=True)
""")

DISCOVERY = textwrap.dedent("""\
    #!/bin/sh
    if [ -f {trigger} ]; then
        echo localhost:2
    else
        echo localhost:1
    fi
""")


@skip_if_cpu_backend
def test_elastic_grow_world(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    trigger = tmp_path / "trigger"
    outfile = tmp_path / "sizes.json"
    discovery = tmp_path / "discover.sh"
    discovery.write_text(DISCOVERY.format(trigger=trigger))
    discovery.chmod(0o755)

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "1", "--min-np", "1", "--max-np", "2",
         "--host-discovery-script", str(discovery),
         "--start-timeout", "120",
         "--", sys.executable, str(worker), str(trigger), str(outfile)],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert outfile.exists(), proc.stdout
    sizes = json.load(open(outfile))
    # Started at world=1 (1 process x 1 chip), grew to world=2 after the
    # trigger; the committed step counter must not have gone backwards.
    assert len(sizes) >= 60
    assert sizes[0] == 1
    assert sizes[-1] == 2, sizes
    assert sorted(set(sizes)) == [1, 2]
    assert len(sizes) < 400, "world never grew; job hit the bail-out cap"


CRASH_WORKER = textwrap.dedent("""\
    import json
    import os
    import sys
    import time

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import horovod_tpu as hvd

    CRASH_MARK = sys.argv[1]
    OUTFILE = sys.argv[2]

    hvd.init()
    state = hvd.elastic.JaxState(step=0, sizes=[])

    @hvd.elastic.run
    def train(state):
        while state.step < 40:
            out = hvd.allreduce(jnp.ones(1), op=hvd.Sum)
            world = int(float(out.reshape(-1)[0]))
            state.sizes = state.sizes + [world]
            state.step += 1
            # The second worker kills itself once, mid-run: the survivor
            # must restore committed state and continue at world=1.
            if state.step == 10 and os.environ.get("HVD_RANK") == "1" \\
                    and not os.path.exists(CRASH_MARK):
                open(CRASH_MARK, "w").close()
                os._exit(1)
            time.sleep(0.15)
            state.commit()
        return state.sizes

    sizes = train(state)
    if hvd.rank() == 0:
        with open(OUTFILE, "w") as f:
            json.dump(sizes, f)
    print("SURVIVOR-DONE", hvd.rank(), len(sizes), flush=True)
""")

CRASH_DISCOVERY = textwrap.dedent("""\
    #!/bin/sh
    echo localhost:1
    echo 127.0.0.1:1
""")


@skip_if_cpu_backend
def test_elastic_worker_crash_recovery(tmp_path):
    """A worker dies mid-run; the survivor restores its last commit,
    re-rendezvouses into a shrunken world, and finishes — the analog of the
    reference's elastic fault-injection tests (``elastic_common.py``).
    The two workers use distinct hostnames (localhost / 127.0.0.1) so
    blacklisting the crashed worker's host leaves the survivor's host
    available."""
    worker = tmp_path / "worker.py"
    worker.write_text(CRASH_WORKER)
    crash_mark = tmp_path / "crash.mark"
    outfile = tmp_path / "sizes.json"
    discovery = tmp_path / "discover.sh"
    discovery.write_text(CRASH_DISCOVERY)
    discovery.chmod(0o755)

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", "2", "--min-np", "1", "--max-np", "2",
         "--host-discovery-script", str(discovery),
         "--start-timeout", "120",
         "--", sys.executable, str(worker), str(crash_mark), str(outfile)],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert outfile.exists(), proc.stdout
    sizes = json.load(open(outfile))
    # Job ran to completion: all 40 committed steps, starting at world=2
    # and ending at world=1 after the crash.
    assert len(sizes) >= 40
    assert sizes[0] == 2
    assert sizes[-1] == 1, sizes
    assert sorted(set(sizes)) == [1, 2]


LOOPBACK_WORKER = textwrap.dedent("""\
    import json
    import sys
    import time

    import jax.numpy as jnp
    import numpy as np
    import horovod_tpu as hvd

    TRIGGER = sys.argv[1]
    OUTFILE = sys.argv[2]

    hvd.init()
    state = hvd.elastic.JaxState(step=0, sizes=[])

    @hvd.elastic.run
    def train(state):
        while state.step < 15 or \\
                (2 not in state.sizes and state.step < 300):
            out = hvd.allreduce(jnp.ones(2), op=hvd.Sum)
            world = int(float(np.asarray(out).reshape(-1)[0]))
            state.sizes = state.sizes + [world]
            state.step += 1
            if state.step == 2 and hvd.rank() == 0:
                open(TRIGGER, "w").close()
            time.sleep(0.05)
            state.commit()
        return state.sizes

    sizes = train(state)
    if hvd.rank() == 0:
        with open(OUTFILE, "w") as f:
            json.dump(sizes, f)
    print("ELASTIC-DONE", hvd.rank(), len(sizes), flush=True)
""")


def test_elastic_grow_world_loopback(tmp_path):
    """The loopback CLI twin of test_elastic_grow_world: `hvdrun
    --loopback --min-np/--max-np` drives the REAL elastic driver over
    rank threads — the world grows 1 -> 2 mid-run on the CPU backend
    where the spawn variant must skip (docs/loopback.md)."""
    worker = tmp_path / "worker.py"
    worker.write_text(LOOPBACK_WORKER)
    trigger = tmp_path / "trigger"
    outfile = tmp_path / "sizes.json"
    discovery = tmp_path / "discover.sh"
    discovery.write_text(DISCOVERY.format(trigger=trigger))
    discovery.chmod(0o755)

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "--loopback",
         "-np", "1", "--min-np", "1", "--max-np", "2",
         "--host-discovery-script", str(discovery),
         "--start-timeout", "120",
         "--", sys.executable, str(worker), str(trigger), str(outfile)],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, \
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert outfile.exists(), proc.stdout
    sizes = json.load(open(outfile))
    assert sizes[0] == 1
    assert sizes[-1] == 2, sizes
    assert sorted(set(sizes)) == [1, 2]
    assert len(sizes) < 300, "world never grew; job hit the bail-out cap"
