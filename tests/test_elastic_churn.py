"""Elastic churn as a measured scenario (ISSUE 14; docs/elastic.md).

Scripted membership change through the ``HVD_FAULT_SPEC`` grammar
(``worker:add/remove/preempt``), warm re-form (shape-keyed dispatch-plan
shelves + coordinator ResponseCache re-arm), recovery SLOs, and the
typed ResponseCacheJoinError for the pre-join-latch serving race.
"""

import os
import pathlib
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu import _native
from horovod_tpu.dynamic import REQ_ALLREDUCE, REQ_JOIN, NativeEngine
from horovod_tpu.exceptions import ResponseCacheJoinError
from horovod_tpu.utils import envs
from horovod_tpu.utils import faults as _faults

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable")

FAST_HEALTH = {"HVD_HEALTH_INTERVAL": "0.2", "HVD_HEALTH_TIMEOUT": "2",
               "HVD_RESPONSE_CACHE": "1"}


@pytest.fixture
def fault_spec():
    """Install an HVD_FAULT_SPEC for the test and always clear it."""
    import os

    def install(spec):
        os.environ["HVD_FAULT_SPEC"] = spec
        _faults.refresh()

    yield install
    import os
    os.environ.pop("HVD_FAULT_SPEC", None)
    _faults.refresh()
    _faults.clear_membership_handler()


# ---------------------------------------------------------------------------
# grammar
# ---------------------------------------------------------------------------

class TestChurnGrammar:
    def test_membership_actions_parse(self):
        rules = _faults.parse_spec(
            "worker:add:at_step=3:count=2;"
            "worker:remove:rank=1:at_step=5;"
            "worker:preempt:rank=2:at_step=7:grace=12.5")
        add, rem, pre = rules
        assert (add.action, add.count, add.times) == ("add", 2, 1)
        assert (rem.action, rem.rank, rem.times) == ("remove", 1, 1)
        assert (pre.action, pre.grace_s) == ("preempt", 12.5)

    def test_membership_only_at_worker_site(self):
        with pytest.raises(_faults.FaultSpecError,
                           match="only legal at the 'worker' site"):
            _faults.parse_spec("kv.put:add:count=1")

    def test_bad_count_and_grace_rejected(self):
        with pytest.raises(_faults.FaultSpecError, match="count"):
            _faults.parse_spec("worker:add:count=0")
        with pytest.raises(_faults.FaultSpecError, match="grace"):
            _faults.parse_spec("worker:preempt:grace=-1")

    def test_at_round_parses_on_any_action(self):
        (r,) = _faults.parse_spec("worker:crash:rank=0:at_round=2")
        assert r.at_round == 2

    def test_at_round_filter_matches_elastic_round(self, fault_spec,
                                                   monkeypatch):
        """A rule keyed on at_round fires only in that elastic round —
        the deterministic way to target re-form boundaries (ISSUE 14
        satellite: at_step counts commits, which reset meaning across
        worlds; at_round does not)."""
        fired = []
        fault_spec("worker:remove:at_round=3")
        _faults.set_membership_handler(
            lambda action, rule: fired.append(action))
        monkeypatch.setenv("HVD_ELASTIC_ROUND", "2")
        _faults.inject("worker", rank=0, step=1)
        assert fired == []
        monkeypatch.setenv("HVD_ELASTIC_ROUND", "3")
        _faults.inject("worker", rank=0, step=2)
        assert fired == ["remove"]
        # membership actions default times=1: the schedule fires once
        _faults.inject("worker", rank=0, step=3)
        assert fired == ["remove"]

    def test_membership_without_handler_noops(self, fault_spec):
        fault_spec("worker:add:count=1")
        _faults.clear_membership_handler()
        _faults.inject("worker", rank=0, step=1)  # must not raise

    def test_has_membership_rules(self, fault_spec):
        fault_spec("kv.put:error:p=0.5")
        assert not _faults.has_membership_rules()
        fault_spec("kv.put:error:p=0.5;worker:preempt:rank=0:at_step=2")
        assert _faults.has_membership_rules()


# ---------------------------------------------------------------------------
# scripted churn end to end (loopback elastic)
# ---------------------------------------------------------------------------

def _train_body(box, total_steps, probe_name="w", sleep_s=0.03,
                collect_stats=False, until_transitions=0):
    # With ``until_transitions`` set, ``total_steps`` is a MINIMUM and
    # the body runs until that many world transitions have been
    # OBSERVED (hard-capped at 4x) — a fixed step budget races the
    # discovery/notify latency of the last scheduled event on a loaded
    # box (the ISSUE-15 scale tests hit exactly this). The transition
    # count lives on committed state and derives from the broadcast
    # world value, so every rank exits at the same commit.
    cap = total_steps * (4 if until_transitions else 1)

    def body():
        hvd.init()
        state = hvd.elastic.JaxState(step=0, log=[], trans=0, lastw=0)

        @hvd.elastic.run
        def train(state):
            from horovod_tpu import metrics as _metrics
            from horovod_tpu.ops import dispatch_cache
            while state.step < cap and not (
                    until_transitions and state.step >= total_steps
                    and state.trans >= until_transitions):
                out = hvd.allreduce(jnp.arange(4.0) + 1.0, op=hvd.Sum,
                                    name=probe_name)
                world = int(float(np.asarray(out).reshape(-1)[0]))
                if state.lastw and world != state.lastw:
                    state.trans += 1
                state.lastw = world
                if hvd.rank() == 0:
                    row = (state.step, world,
                           float(np.asarray(out).reshape(-1)[1]))
                    if collect_stats:
                        st = dispatch_cache.stats()
                        row = row + (st["warm_reuses"], int(
                            _metrics.ELASTIC_STEPS_LOST.value()))
                    state.log = state.log + [row]
                state.step += 1
                time.sleep(sleep_s)
                state.commit()
            return state.log

        log = train(state)
        if hvd.rank() == 0:
            box["log"] = log
        return 0

    return body


class TestScriptedChurn:
    def test_grow_2_to_4_numerics_parity(self, fault_spec):
        """Mid-training scale-up 2->4: after the re-form every logged
        allreduce equals exactly what an uninterrupted world-4 run
        computes, and committed steps never replay."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        fault_spec("worker:add:rank=0:at_step=2:count=2")
        disco = FixedHosts({"g2a": 1, "g2b": 1})
        box = {}
        results, ok = elastic_run(
            _train_body(box, 60), np=2, min_np=2, max_np=4,
            discovery=disco, timeout=90, extra_env=FAST_HEALTH)
        assert ok, results.error_message
        log = box["log"]
        worlds = [w for (_s, w, _p) in log]
        assert worlds[0] == 2 and worlds[-1] == 4, worlds
        assert sorted(set(worlds)) == [2, 4], worlds
        # numerics parity vs an uninterrupted run at the final world:
        # element 1 of sum(arange(4)+1) over `world` identical
        # contributions is exactly 2*world at every step
        for step, world, p1 in log:
            assert p1 == pytest.approx(2.0 * world), (step, world, p1)
        steps = [s for (s, _w, _p) in log]
        assert steps == sorted(set(steps)), "committed steps replayed"

    def test_shrink_4_to_2_numerics_parity(self, fault_spec):
        """Mid-training scale-down 4->2 via two scheduled removals."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        fault_spec("worker:remove:rank=3:at_step=2;"
                   "worker:remove:rank=2:at_step=14")
        disco = FixedHosts({f"s4{i}": 1 for i in range(4)})
        box = {}
        results, ok = elastic_run(
            _train_body(box, 40), np=4, min_np=2, max_np=4,
            discovery=disco, timeout=120, extra_env=FAST_HEALTH)
        assert ok, results.error_message
        log = box["log"]
        worlds = [w for (_s, w, _p) in log]
        assert worlds[0] == 4 and worlds[-1] == 2, worlds
        assert set(worlds) >= {4, 2}, worlds
        for step, world, p1 in log:
            assert p1 == pytest.approx(2.0 * world), (step, world, p1)

    def test_warm_reform_reuses_plans(self, fault_spec):
        """A resize back to a previously-seen shape must graft shelved
        dispatch plans: `dispatch_cache_stats()["warm_reuses"]` > 0
        after the second re-form (ISSUE 14 acceptance)."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        # the add is ROUND-keyed (fires inside the post-shrink round),
        # not step-keyed: on a loaded box a step-keyed add could land in
        # the same discovery window as the preempt's host removal and
        # merge into one 3->3 re-form that never exposes the 2-world
        # shape this test is about — and the body runs until both
        # transitions are observed rather than a fixed step budget
        # (the pre-existing flake this ordering race caused)
        fault_spec("worker:preempt:rank=2:at_round=1:at_step=4:grace=30;"
                   "worker:add:rank=0:at_round=2:after=5")
        disco = FixedHosts({"w3a": 1, "w3b": 1, "w3c": 1})
        box = {}
        results, ok = elastic_run(
            _train_body(box, 30, collect_stats=True,
                        until_transitions=2), np=3, min_np=2,
            max_np=3, discovery=disco, timeout=120, extra_env=FAST_HEALTH)
        assert ok, results.error_message
        log = box["log"]
        worlds = [w for row in log for w in (row[1],)]
        assert 2 in worlds and worlds[-1] == 3, worlds
        # the grow back to world=3 re-forms into a shape both survivors
        # shelved at the shrink: the first post-re-form plan build must
        # graft a shelved compiled stage
        assert log[-1][3] > 0, f"no warm plan reuse: {log[-1]}"

    def test_preempt_loses_zero_steps_crash_loses_at_most_one(
            self, fault_spec):
        """The ISSUE 14 SLO pair: a graceful preemption (drain + grace +
        slot-lost exit) rolls back nothing, while an abrupt kill loses
        at most the one in-flight step (commit-per-step)."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        # the crash is keyed on the ROUND, not a step count: under a
        # loaded box the preempt's re-form can take arbitrarily many
        # step-times, and a step-keyed crash racing it merges the two
        # transitions — at_round=2:after=5 fires deterministically on
        # rank 1's 6th commit INSIDE the post-preempt world
        fault_spec("worker:preempt:rank=2:at_step=4:grace=30;"
                   "worker:crash:rank=1:at_round=2:after=5")
        disco = FixedHosts({"pz0": 1, "pz1": 1, "pz2": 1})
        box = {}
        # min_np=1: after the crash only one host remains un-blacklisted,
        # and the job must finish there rather than wait for slots
        results, ok = elastic_run(
            _train_body(box, 40, collect_stats=True), np=3, min_np=1,
            max_np=3, discovery=disco, timeout=120, extra_env=FAST_HEALTH)
        assert ok, results.error_message
        log = box["log"]
        worlds = [row[1] for row in log]
        assert worlds[0] == 3 and worlds[-1] == 1, worlds
        # per-transition steps-lost deltas off the registry counter
        lost_at = {}
        for i in range(1, len(log)):
            if log[i][1] != log[i - 1][1]:
                lost_at[(log[i - 1][1], log[i][1])] = \
                    log[i][4] - log[i - 1][4]
        # preempt: 3 -> 2 with zero rolled-back steps; crash: 2 -> re-form
        # (2, with the dead host replaced or 2->2 restore) loses <= 1.
        assert lost_at, log
        assert (3, 2) in lost_at, (lost_at, worlds)  # preempt re-formed
        assert lost_at[(3, 2)] == 0, (lost_at, log)
        total_lost = log[-1][4]
        assert total_lost <= 1, (total_lost, lost_at)
        # committed steps never replay
        steps = [row[0] for row in log]
        assert steps == sorted(set(steps)), "committed steps replayed"


# ---------------------------------------------------------------------------
# driver-side grace + stale-report hygiene
# ---------------------------------------------------------------------------

class TestDriverChurnPlumbing:
    def test_fixed_hosts_mutators(self):
        from horovod_tpu.elastic.discovery import FixedHosts
        fh = FixedHosts({"a": 1})
        fh.add_hosts({"b": 2})
        assert fh.find_available_hosts_and_slots() == {"a": 1, "b": 2}
        assert fh.remove_host("a") is True
        assert fh.remove_host("a") is False
        assert fh.find_available_hosts_and_slots() == {"b": 2}

    def test_scripted_churn_handler(self, monkeypatch):
        from horovod_tpu.elastic.discovery import FixedHosts, ScriptedChurn
        fh = FixedHosts({"h0": 1})
        events = []
        churn = ScriptedChurn(fh, events=events)
        (add,) = _faults.parse_spec("worker:add:count=2")
        churn("add", add)
        hosts = fh.find_available_hosts_and_slots()
        assert hosts == {"h0": 1, "churn0": 1, "churn1": 1}
        monkeypatch.setenv("HVD_HOSTNAME", "churn0")

        class _Driver:
            grace = None

            def set_stale_grace(self, host, s):
                _Driver.grace = (host, s)

        churn.attach_driver(_Driver())
        (pre,) = _faults.parse_spec("worker:preempt:grace=7")
        churn("preempt", pre)
        assert _Driver.grace == ("churn0", 7.0)
        assert "churn0" not in fh.find_available_hosts_and_slots()
        assert [e[1] for e in events] == ["add", "preempt"]

    def test_stale_round_peer_report_ignored(self):
        """A peer-failure report resolved against a superseded round's
        rank numbering must not blacklist the innocent successor that
        inherited the rank number (the scripted-churn misattribution)."""
        import pickle

        from horovod_tpu.elastic import driver as drv

        class _KV(dict):
            def put(self, k, v):
                self[k] = v

            def get(self, k):
                return dict.get(self, k)

        recorded = []

        class _Registry:
            def record_failure(self, host, slot):
                recorded.append((host, slot))

        d = drv.ElasticDriver.__new__(drv.ElasticDriver)
        d._rendezvous = drv.ElasticRendezvous(_KV())
        d._rendezvous._round = 2
        d._worker_registry = _Registry()
        d._result_threads = []
        # round 1 had rank 2 on oldhost; round 2 reassigned rank 2 to
        # newhost (the replacement)
        d._rendezvous.kv.put(
            drv.ROUND_SPEC_KEY.format(1),
            pickle.dumps({"round": 1, "slots": [
                {"hostname": "oldhost", "rank": 2, "size": 3,
                 "local_rank": 0, "local_size": 1, "cross_rank": 2,
                 "cross_size": 3}]}))
        d._rank_assignments = {2: drv.slot_from_dict(
            {"hostname": "newhost", "rank": 2, "size": 3,
             "local_rank": 0, "local_size": 1, "cross_rank": 2,
             "cross_size": 3})}
        d.record_peer_failure(2, "silence", round_id=1)
        assert recorded == []  # stale report: hostnames differ -> ignored
        # a CURRENT-round report still records
        d.record_peer_failure(2, "silence", round_id=2)
        for t in d._result_threads:
            t.join(5)
        assert recorded == [("newhost", 0)]

    def test_resume_after_shutdown_noops(self):
        from horovod_tpu.elastic import driver as drv
        d = drv.ElasticDriver.__new__(drv.ElasticDriver)
        d._shutdown = threading.Event()
        d._shutdown.set()
        d.resume()  # must not raise / touch worker machinery


# ---------------------------------------------------------------------------
# ResponseCache: warm shelf mechanics + join-race typed error
# ---------------------------------------------------------------------------

class TestResponseCacheWarm:
    def _entry(self, name="t", world=2):
        from horovod_tpu.dynamic import Response
        req = {"name": name, "request_type": REQ_ALLREDUCE, "dtype": 0,
               "element_size": 4, "shape": (4,)}
        resp = Response(type=REQ_ALLREDUCE, tensor_names=[name])
        return req, resp

    def test_warm_restore_confirm_and_serve_gate(self):
        from horovod_tpu.negotiation.response_cache import ResponseCache
        rc = ResponseCache(8)
        req, resp = self._entry()
        rc.note_response(req, resp)
        exported = rc.export_entries()
        assert len(exported) == 0  # unconfirmed entries don't shelve
        resp.from_cache = True
        rc.note_response(req, resp)
        exported = rc.export_entries()
        assert len(exported) == 1

        rc2 = ResponseCache(8)
        assert rc2.restore_warm(exported) == 1
        assert rc2.warm_count() == 1
        # warm entries are present but NOT serveable pre-confirmation
        assert rc2.lookup_confirmed(req) is None
        assert rc2.confirm_warm() == 1
        assert rc2.warm_count() == 0
        assert rc2.lookup_confirmed(req) is not None

    def test_warm_digest_agreement_and_empty_marker(self):
        from horovod_tpu.negotiation.response_cache import ResponseCache
        req, resp = self._entry()
        resp.from_cache = True
        a, b, fresh = ResponseCache(8), ResponseCache(8), ResponseCache(8)
        a.note_response(req, resp)
        b.note_response(req, resp)
        a2, b2 = ResponseCache(8), ResponseCache(8)
        a2.restore_warm(a.export_entries())
        b2.restore_warm(b.export_entries())
        assert a2.warm_digest() == b2.warm_digest()
        assert fresh.warm_digest() == b"\x00" * 8  # the fresh-member veto
        assert a2.warm_digest() != fresh.warm_digest()
        assert b2.drop_warm() == 1
        assert b2.warm_count() == 0

    def test_shelf_lru_and_take(self):
        from horovod_tpu.negotiation import response_cache as rcm
        rcm.clear_shelf()
        try:
            rcm.shelve(("s", "global", 2, 0), [("n", ("sig",), None)])
            assert rcm.take_shelved(("s", "global", 2, 0)) is not None
            assert rcm.take_shelved(("s", "global", 2, 0)) is None
        finally:
            rcm.clear_shelf()


class _BarrierWorld:
    """In-memory lockstep exchange for N in-process DynamicServices
    (the test_negotiation fixture, re-used for the join-race test)."""

    def __init__(self, n):
        self.n = n
        self.cond = threading.Condition()
        self.frames: dict = {}
        self.closed = False

    def exchange(self, rank, cycle, req, bits, timeout):
        with self.cond:
            fr = self.frames.setdefault(cycle, {})
            fr[rank] = (req, bits)
            self.cond.notify_all()
            end = time.monotonic() + min(timeout, 30.0)
            while len(fr) < self.n:
                if self.closed:
                    raise RuntimeError("barrier world closed")
                if time.monotonic() > end:
                    raise TimeoutError(f"cycle {cycle} incomplete")
                self.cond.wait(0.2)
            self.frames.pop(cycle - 2, None)
            return ([fr[r][0] for r in range(self.n)],
                    [fr[r][1] for r in range(self.n)])

    def close(self):
        with self.cond:
            self.closed = True
            self.cond.notify_all()


class _BarrierTransport:
    def __init__(self, world, rank):
        self.world_mem = world
        self.world_size = world.n
        self.rank = rank

    def exchange(self, cycle, req, bits, timeout):
        return self.world_mem.exchange(self.rank, cycle, req, bits, timeout)


class TestResponseCacheJoinRace:
    def _services(self, monkeypatch, n=2):
        from horovod_tpu.engine_service import DynamicService
        monkeypatch.setenv("HVD_RESPONSE_CACHE", "1")
        world = _BarrierWorld(n)
        svcs = [DynamicService(NativeEngine(world_size=n, rank=r),
                               _BarrierTransport(world, r))
                for r in range(n)]
        return world, svcs

    def _negotiate_all(self, svcs, name):
        results = [None] * len(svcs)
        errors = []

        def one(i):
            try:
                results[i] = svcs[i].negotiate(name, REQ_ALLREDUCE,
                                               shape=(4,), timeout=30)
            except Exception as e:
                errors.append(e)

        ts = [threading.Thread(target=one, args=(i,), daemon=True)
              for i in range(len(svcs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(40)
        assert not errors, errors
        return results

    def test_pre_join_serve_raises_typed_error(self, monkeypatch):
        """Rank 0 serves a batch locally from its confirmed coordinator
        cache in the same window rank 1's JOIN goes to the wire: the
        cycle that first observes the JOIN must fail rank 0's service
        with ResponseCacheJoinError NAMING rank 1 — not leave the
        locally-served, never-scheduled collective to burn the exchange
        deadline (ROADMAP protocol follow-on (a))."""
        world, svcs = self._services(monkeypatch)
        try:
            # steady state: confirm + begin serving locally
            for _ in range(12):
                self._negotiate_all(svcs, "g")
                if all(s.response_cache_stats()["confirmed"] >= 1
                       for s in svcs):
                    break
            assert all(s.response_cache_stats()["confirmed"] >= 1
                       for s in svcs)
            self._negotiate_all(svcs, "g")  # served locally everywhere

            # rank 1 joins while rank 0 serves the same window locally
            join_exc = []

            def joiner():
                try:
                    svcs[1].join("j.join", timeout=20)
                except Exception as e:  # the abort fails the join too
                    join_exc.append(e)

            jt = threading.Thread(target=joiner, daemon=True)
            jt.start()
            # rank 0's local serve needs no peer: it returns immediately
            t0 = time.monotonic()
            ticket = svcs[0].negotiate_many_submit([dict(
                name="g", request_type=REQ_ALLREDUCE, dtype=0,
                element_size=4, shape=(4,), root_rank=-1, group_id=-1,
                splits=(), reduce_op=-1, prescale=1.0, postscale=1.0,
                splits_crc=0)])
            assert ticket.served, "serve did not happen pre-join"
            svcs[0].negotiate_many_wait(ticket, timeout=30)
            # rank 0's next REAL negotiation observes the failure fast
            with pytest.raises(ResponseCacheJoinError) as ei:
                for _ in range(40):
                    svcs[0].negotiate(f"after.{_}", REQ_ALLREDUCE,
                                      shape=(4,), timeout=30)
                    time.sleep(0.05)
            assert time.monotonic() - t0 < 20.0
            assert "rank 1" in str(ei.value)
            assert ei.value.joining_rank == 1
            jt.join(10)
        finally:
            world.close()
            for s in svcs:
                s.stop()

    def test_join_without_serves_latches_quietly(self, monkeypatch):
        """A JOIN observed with no pre-join local serves just latches —
        no typed error, the normal join semantics."""
        world, svcs = self._services(monkeypatch)
        try:
            self._negotiate_all(svcs, "q")  # real rounds only, no serving
            results = [None, None]

            def joiner(i):
                results[i] = svcs[i].join(f"q.join.{i}", timeout=30)

            ts = [threading.Thread(target=joiner, args=(i,), daemon=True)
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(40)
            assert results[0] is not None and results[1] is not None
            for s in svcs:
                assert s._failure is None
        finally:
            world.close()
            for s in svcs:
                s.stop()


# ---------------------------------------------------------------------------
# request-frame parsing (the join-race scanner's wire twin)
# ---------------------------------------------------------------------------

class TestParseRequests:
    def test_roundtrip_via_native_pop(self):
        from horovod_tpu.dynamic import parse_requests
        eng = NativeEngine(world_size=2, rank=1)
        eng.enqueue("a", REQ_ALLREDUCE, dtype=1, element_size=4,
                    shape=(3, 2), reduce_op=0)
        eng.enqueue("b.join", REQ_JOIN)
        reqs = parse_requests(eng.pop_requests())
        assert [(r["rank"], r["request_type"], r["name"]) for r in reqs] \
            == [(1, REQ_ALLREDUCE, "a"), (1, REQ_JOIN, "b.join")]

    def test_empty(self):
        from horovod_tpu.dynamic import parse_requests
        assert parse_requests(b"") == []


# ---------------------------------------------------------------------------
# churn at scale (ISSUE 15: ROADMAP elastic follow-ons (a)/(d))
# ---------------------------------------------------------------------------

_REPO = str(pathlib.Path(__file__).resolve().parents[1])

# One full churn cycle at world N in a fresh interpreter: preempt
# N -> N-1 (cold: no shelf for either shape yet), scripted add back to
# N (the survivors re-form into the shape they shelved at the preempt —
# plan grafts; the fresh replacement's empty digest vetoes the response
# re-arm, by design), then preempt N -> N-1 again (every survivor
# shelved shape N-1 at the grow's teardown: plans graft AND the warm
# digest round re-arms local serving). Past world 4 this exercises the
# shelf sizing, the hierarchical beat/negotiation path (auto-on above
# one leader group), and — with CHURN_CAPTURE=1 — the svc StepPlan
# graft the ROADMAP flagged as untested past world 4.
_SCALE_SCRIPT = r"""
import os, json, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp
import horovod_tpu as hvd
from horovod_tpu import metrics as _metrics
from horovod_tpu.elastic.discovery import FixedHosts
from horovod_tpu.loopback import elastic_run
from horovod_tpu.utils import faults

N = int(os.environ["CHURN_WORLD"])
CAPTURE = os.environ.get("CHURN_CAPTURE", "0") == "1"
E1, EK = 4, 5
# The bodies run until the full shrink->grow->shrink cycle has been
# OBSERVED (the discovery poll + notify poll put ~8 commit-times of
# latency between an event firing and its re-form landing at this
# pacing — a fixed step budget either races the last transition or
# pads every run), with a hard cap so a wedged schedule still fails
# fast. The transition count lives on committed state and derives from
# the broadcast world value, so every rank exits the loop at the same
# commit (rank-symmetric by construction).
MIN_STEPS, HARD_CAP = 30, 140

os.environ["HVD_FAULT_SPEC"] = (
    f"worker:preempt:rank={N-1}:at_round=1:at_step={E1}:grace=60;"
    f"worker:add:rank=0:at_round=2:after={EK};"
    f"worker:preempt:rank={N-1}:at_round=3:after=3:grace=60")
faults.refresh()

extra = {"HVD_RESPONSE_CACHE": "1", "HVD_HEALTH_INTERVAL": "0.3",
         "HVD_HEALTH_TIMEOUT": "8"}
if CAPTURE:
    extra["HVD_STEP_CAPTURE"] = "1"

disco = FixedHosts({f"h{i}": 1 for i in range(N)})
box = {}


def warm_counts():
    out = {"plan": 0, "step": 0, "response": 0}
    for li, v in _metrics.ELASTIC_WARM_REUSE.series().items():
        k = dict(li).get("kind")
        if k in out:
            out[k] = int(v)
    return out


def body():
    hvd.init()
    state = hvd.elastic.JaxState(step=0, log=[], trans=0, lastw=0)

    @hvd.elastic.run
    def train(state):
        while state.step < HARD_CAP and not (
                state.step >= MIN_STEPS and state.trans >= 3):
            if CAPTURE:
                hvd.step_marker()
            # async pair: the fusion/negotiated stream (and, with
            # capture on, the svc StepPlan the warm graft must carry
            # across the re-form)
            h1 = hvd.allreduce_async(jnp.arange(4.0) + 1.0, op=hvd.Sum,
                                     name="wa")
            h2 = hvd.allreduce_async(jnp.ones(2), op=hvd.Sum, name="wb")
            p1 = float(np.asarray(hvd.synchronize(h1)).reshape(-1)[1])
            world = int(float(np.asarray(
                hvd.synchronize(h2)).reshape(-1)[0]))
            # sync call: the eager plan-cache path whose compiled
            # execute stage the shape-keyed shelf grafts (the async
            # stream composes per-negotiation and has no eager plan)
            ws = hvd.allreduce(jnp.arange(4.0) + 1.0, op=hvd.Sum,
                               name="ws")
            assert float(np.asarray(ws).reshape(-1)[1]) == p1
            if state.lastw and world != state.lastw:
                state.trans += 1
            state.lastw = world
            if hvd.rank() == 0:
                w = warm_counts()
                state.log = state.log + [(
                    state.step, world, p1, w["plan"], w["step"],
                    w["response"],
                    int(_metrics.ELASTIC_STEPS_LOST.value()))]
            state.step += 1
            time.sleep(0.05)
            state.commit()
        return state.log

    log = train(state)
    if hvd.rank() == 0:
        box["log"] = log
    return 0


results, ok = elastic_run(body, np=N, min_np=N - 1, max_np=N,
                          discovery=disco, extra_env=extra)
assert ok, results.error_message
log = box["log"]
worlds = [row[1] for row in log]
assert worlds[0] == N and worlds[-1] == N - 1, worlds
assert sorted(set(worlds)) == [N - 1, N], worlds
# the full cycle: shrink -> grow -> shrink
transitions = [(worlds[i - 1], worlds[i]) for i in range(1, len(worlds))
               if worlds[i] != worlds[i - 1]]
assert transitions == [(N, N - 1), (N - 1, N), (N, N - 1)], transitions
# numerics parity vs an uninterrupted run at each step's world
for row in log:
    assert row[2] == (2.0 * row[1]), row
# committed steps never replay; graceful churn loses zero
steps = [row[0] for row in log]
assert steps == sorted(set(steps)), "committed steps replayed"
assert log[-1][6] == 0, f"graceful churn lost steps: {log[-1]}"
final = {"plan": log[-1][3], "step": log[-1][4], "response": log[-1][5]}
assert final["plan"] > 0, f"no warm plan graft at world {N}: {final}"
assert final["response"] > 0, \
    f"warm digest never re-armed local serving at world {N}: {final}"
if CAPTURE:
    assert final["step"] > 0, \
        f"svc StepPlan never grafted across the re-form: {final}"
print("CHURN_SCALE_OK " + json.dumps({"world": N, "warm": final,
                                      "rows": len(log)}))
"""


def _run_churn_world(world: int, capture: bool, timeout: float) -> str:
    env = dict(os.environ)
    env.pop("HVD_FAULT_SPEC", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={world}"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["CHURN_WORLD"] = str(world)
    env["CHURN_CAPTURE"] = "1" if capture else "0"
    proc = subprocess.run([sys.executable, "-c", _SCALE_SCRIPT],
                          cwd=_REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)
    assert proc.returncode == 0, \
        f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-4000:]}"
    return proc.stdout


class TestChurnAtScale:
    def test_world8_churn_smoke(self):
        """Tier-1 smoke: the full preempt->add->preempt warm cycle at
        world=8 — twice the world the PR-14 suite exercises — with the
        warm digest exchange and shape shelf asserted live."""
        out = _run_churn_world(8, capture=False, timeout=600)
        assert "CHURN_SCALE_OK" in out, out

    @pytest.mark.slow
    def test_world16_churn_capture_full(self):
        """ISSUE 15 acceptance (ROADMAP elastic follow-ons (a)/(d)):
        the full churn cycle at world=16 on the auto-engaged
        hierarchical control plane with step capture on — warm digest
        re-arm, shelf sizing, and the svc StepPlan graft all past
        world 4."""
        out = _run_churn_world(16, capture=True, timeout=1200)
        assert "CHURN_SCALE_OK" in out, out


# ---------------------------------------------------------------------------
# dispatch-cache shelf unit coverage
# ---------------------------------------------------------------------------

class TestDispatchShelf:
    def test_restorable_filter(self):
        from horovod_tpu.ops import dispatch_cache as dc
        plan = dc.DispatchPlan("l", "A", 1, None, lambda t: t)
        assert dc._restorable(("allreduce", "n", ("r",), None, "g", 1),
                              plan)
        assert dc._restorable(("allreduce", "n", ("r",), None, 0, 1),
                              plan)  # the registered GLOBAL set (id 0)
        assert dc._restorable(("allreduce", "n", ("r",), None, (0, 1), 1),
                              plan)  # self-describing rank tuple
        assert not dc._restorable(
            ("allreduce", "n", ("r",), None, 3, 1), plan)  # other ids
        assert not dc._restorable(("k",), dc.UNPLANNABLE)

    def test_stats_expose_warm_fields(self):
        from horovod_tpu.ops import dispatch_cache as dc
        st = dc.stats()
        assert "warm_pool" in st and "warm_reuses" in st
