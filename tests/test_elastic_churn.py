"""Elastic churn as a measured scenario (ISSUE 14; docs/elastic.md).

Scripted membership change through the ``HVD_FAULT_SPEC`` grammar
(``worker:add/remove/preempt``), warm re-form (shape-keyed dispatch-plan
shelves + coordinator ResponseCache re-arm), recovery SLOs, and the
typed ResponseCacheJoinError for the pre-join-latch serving race.
"""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu import _native
from horovod_tpu.dynamic import REQ_ALLREDUCE, REQ_JOIN, NativeEngine
from horovod_tpu.exceptions import ResponseCacheJoinError
from horovod_tpu.utils import envs
from horovod_tpu.utils import faults as _faults

pytestmark = pytest.mark.skipif(
    not _native.available(), reason="native engine unavailable")

FAST_HEALTH = {"HVD_HEALTH_INTERVAL": "0.2", "HVD_HEALTH_TIMEOUT": "2",
               "HVD_RESPONSE_CACHE": "1"}


@pytest.fixture
def fault_spec():
    """Install an HVD_FAULT_SPEC for the test and always clear it."""
    import os

    def install(spec):
        os.environ["HVD_FAULT_SPEC"] = spec
        _faults.refresh()

    yield install
    import os
    os.environ.pop("HVD_FAULT_SPEC", None)
    _faults.refresh()
    _faults.clear_membership_handler()


# ---------------------------------------------------------------------------
# grammar
# ---------------------------------------------------------------------------

class TestChurnGrammar:
    def test_membership_actions_parse(self):
        rules = _faults.parse_spec(
            "worker:add:at_step=3:count=2;"
            "worker:remove:rank=1:at_step=5;"
            "worker:preempt:rank=2:at_step=7:grace=12.5")
        add, rem, pre = rules
        assert (add.action, add.count, add.times) == ("add", 2, 1)
        assert (rem.action, rem.rank, rem.times) == ("remove", 1, 1)
        assert (pre.action, pre.grace_s) == ("preempt", 12.5)

    def test_membership_only_at_worker_site(self):
        with pytest.raises(_faults.FaultSpecError,
                           match="only legal at the 'worker' site"):
            _faults.parse_spec("kv.put:add:count=1")

    def test_bad_count_and_grace_rejected(self):
        with pytest.raises(_faults.FaultSpecError, match="count"):
            _faults.parse_spec("worker:add:count=0")
        with pytest.raises(_faults.FaultSpecError, match="grace"):
            _faults.parse_spec("worker:preempt:grace=-1")

    def test_at_round_parses_on_any_action(self):
        (r,) = _faults.parse_spec("worker:crash:rank=0:at_round=2")
        assert r.at_round == 2

    def test_at_round_filter_matches_elastic_round(self, fault_spec,
                                                   monkeypatch):
        """A rule keyed on at_round fires only in that elastic round —
        the deterministic way to target re-form boundaries (ISSUE 14
        satellite: at_step counts commits, which reset meaning across
        worlds; at_round does not)."""
        fired = []
        fault_spec("worker:remove:at_round=3")
        _faults.set_membership_handler(
            lambda action, rule: fired.append(action))
        monkeypatch.setenv("HVD_ELASTIC_ROUND", "2")
        _faults.inject("worker", rank=0, step=1)
        assert fired == []
        monkeypatch.setenv("HVD_ELASTIC_ROUND", "3")
        _faults.inject("worker", rank=0, step=2)
        assert fired == ["remove"]
        # membership actions default times=1: the schedule fires once
        _faults.inject("worker", rank=0, step=3)
        assert fired == ["remove"]

    def test_membership_without_handler_noops(self, fault_spec):
        fault_spec("worker:add:count=1")
        _faults.clear_membership_handler()
        _faults.inject("worker", rank=0, step=1)  # must not raise

    def test_has_membership_rules(self, fault_spec):
        fault_spec("kv.put:error:p=0.5")
        assert not _faults.has_membership_rules()
        fault_spec("kv.put:error:p=0.5;worker:preempt:rank=0:at_step=2")
        assert _faults.has_membership_rules()


# ---------------------------------------------------------------------------
# scripted churn end to end (loopback elastic)
# ---------------------------------------------------------------------------

def _train_body(box, total_steps, probe_name="w", sleep_s=0.03,
                collect_stats=False):
    def body():
        hvd.init()
        state = hvd.elastic.JaxState(step=0, log=[])

        @hvd.elastic.run
        def train(state):
            from horovod_tpu import metrics as _metrics
            from horovod_tpu.ops import dispatch_cache
            while state.step < total_steps:
                out = hvd.allreduce(jnp.arange(4.0) + 1.0, op=hvd.Sum,
                                    name=probe_name)
                world = int(float(np.asarray(out).reshape(-1)[0]))
                if hvd.rank() == 0:
                    row = (state.step, world,
                           float(np.asarray(out).reshape(-1)[1]))
                    if collect_stats:
                        st = dispatch_cache.stats()
                        row = row + (st["warm_reuses"], int(
                            _metrics.ELASTIC_STEPS_LOST.value()))
                    state.log = state.log + [row]
                state.step += 1
                time.sleep(sleep_s)
                state.commit()
            return state.log

        log = train(state)
        if hvd.rank() == 0:
            box["log"] = log
        return 0

    return body


class TestScriptedChurn:
    def test_grow_2_to_4_numerics_parity(self, fault_spec):
        """Mid-training scale-up 2->4: after the re-form every logged
        allreduce equals exactly what an uninterrupted world-4 run
        computes, and committed steps never replay."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        fault_spec("worker:add:rank=0:at_step=2:count=2")
        disco = FixedHosts({"g2a": 1, "g2b": 1})
        box = {}
        results, ok = elastic_run(
            _train_body(box, 60), np=2, min_np=2, max_np=4,
            discovery=disco, timeout=90, extra_env=FAST_HEALTH)
        assert ok, results.error_message
        log = box["log"]
        worlds = [w for (_s, w, _p) in log]
        assert worlds[0] == 2 and worlds[-1] == 4, worlds
        assert sorted(set(worlds)) == [2, 4], worlds
        # numerics parity vs an uninterrupted run at the final world:
        # element 1 of sum(arange(4)+1) over `world` identical
        # contributions is exactly 2*world at every step
        for step, world, p1 in log:
            assert p1 == pytest.approx(2.0 * world), (step, world, p1)
        steps = [s for (s, _w, _p) in log]
        assert steps == sorted(set(steps)), "committed steps replayed"

    def test_shrink_4_to_2_numerics_parity(self, fault_spec):
        """Mid-training scale-down 4->2 via two scheduled removals."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        fault_spec("worker:remove:rank=3:at_step=2;"
                   "worker:remove:rank=2:at_step=14")
        disco = FixedHosts({f"s4{i}": 1 for i in range(4)})
        box = {}
        results, ok = elastic_run(
            _train_body(box, 40), np=4, min_np=2, max_np=4,
            discovery=disco, timeout=120, extra_env=FAST_HEALTH)
        assert ok, results.error_message
        log = box["log"]
        worlds = [w for (_s, w, _p) in log]
        assert worlds[0] == 4 and worlds[-1] == 2, worlds
        assert set(worlds) >= {4, 2}, worlds
        for step, world, p1 in log:
            assert p1 == pytest.approx(2.0 * world), (step, world, p1)

    def test_warm_reform_reuses_plans(self, fault_spec):
        """A resize back to a previously-seen shape must graft shelved
        dispatch plans: `dispatch_cache_stats()["warm_reuses"]` > 0
        after the second re-form (ISSUE 14 acceptance)."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        fault_spec("worker:preempt:rank=2:at_step=4:grace=30;"
                   "worker:add:rank=0:at_step=20:count=1")
        disco = FixedHosts({"w3a": 1, "w3b": 1, "w3c": 1})
        box = {}
        results, ok = elastic_run(
            _train_body(box, 60, collect_stats=True), np=3, min_np=2,
            max_np=3, discovery=disco, timeout=120, extra_env=FAST_HEALTH)
        assert ok, results.error_message
        log = box["log"]
        worlds = [w for row in log for w in (row[1],)]
        assert 2 in worlds and worlds[-1] == 3, worlds
        # the grow back to world=3 re-forms into a shape both survivors
        # shelved at the shrink: the first post-re-form plan build must
        # graft a shelved compiled stage
        assert log[-1][3] > 0, f"no warm plan reuse: {log[-1]}"

    def test_preempt_loses_zero_steps_crash_loses_at_most_one(
            self, fault_spec):
        """The ISSUE 14 SLO pair: a graceful preemption (drain + grace +
        slot-lost exit) rolls back nothing, while an abrupt kill loses
        at most the one in-flight step (commit-per-step)."""
        from horovod_tpu.elastic.discovery import FixedHosts
        from horovod_tpu.loopback import elastic_run

        # the crash is keyed on the ROUND, not a step count: under a
        # loaded box the preempt's re-form can take arbitrarily many
        # step-times, and a step-keyed crash racing it merges the two
        # transitions — at_round=2:after=5 fires deterministically on
        # rank 1's 6th commit INSIDE the post-preempt world
        fault_spec("worker:preempt:rank=2:at_step=4:grace=30;"
                   "worker:crash:rank=1:at_round=2:after=5")
        disco = FixedHosts({"pz0": 1, "pz1": 1, "pz2": 1})
        box = {}
        # min_np=1: after the crash only one host remains un-blacklisted,
        # and the job must finish there rather than wait for slots
        results, ok = elastic_run(
            _train_body(box, 40, collect_stats=True), np=3, min_np=1,
            max_np=3, discovery=disco, timeout=120, extra_env=FAST_HEALTH)
        assert ok, results.error_message
        log = box["log"]
        worlds = [row[1] for row in log]
        assert worlds[0] == 3 and worlds[-1] == 1, worlds
        # per-transition steps-lost deltas off the registry counter
        lost_at = {}
        for i in range(1, len(log)):
            if log[i][1] != log[i - 1][1]:
                lost_at[(log[i - 1][1], log[i][1])] = \
                    log[i][4] - log[i - 1][4]
        # preempt: 3 -> 2 with zero rolled-back steps; crash: 2 -> re-form
        # (2, with the dead host replaced or 2->2 restore) loses <= 1.
        assert lost_at, log
        assert (3, 2) in lost_at, (lost_at, worlds)  # preempt re-formed
        assert lost_at[(3, 2)] == 0, (lost_at, log)
        total_lost = log[-1][4]
        assert total_lost <= 1, (total_lost, lost_at)
        # committed steps never replay
        steps = [row[0] for row in log]
        assert steps == sorted(set(steps)), "committed steps replayed"


# ---------------------------------------------------------------------------
# driver-side grace + stale-report hygiene
# ---------------------------------------------------------------------------

class TestDriverChurnPlumbing:
    def test_fixed_hosts_mutators(self):
        from horovod_tpu.elastic.discovery import FixedHosts
        fh = FixedHosts({"a": 1})
        fh.add_hosts({"b": 2})
        assert fh.find_available_hosts_and_slots() == {"a": 1, "b": 2}
        assert fh.remove_host("a") is True
        assert fh.remove_host("a") is False
        assert fh.find_available_hosts_and_slots() == {"b": 2}

    def test_scripted_churn_handler(self, monkeypatch):
        from horovod_tpu.elastic.discovery import FixedHosts, ScriptedChurn
        fh = FixedHosts({"h0": 1})
        events = []
        churn = ScriptedChurn(fh, events=events)
        (add,) = _faults.parse_spec("worker:add:count=2")
        churn("add", add)
        hosts = fh.find_available_hosts_and_slots()
        assert hosts == {"h0": 1, "churn0": 1, "churn1": 1}
        monkeypatch.setenv("HVD_HOSTNAME", "churn0")

        class _Driver:
            grace = None

            def set_stale_grace(self, host, s):
                _Driver.grace = (host, s)

        churn.attach_driver(_Driver())
        (pre,) = _faults.parse_spec("worker:preempt:grace=7")
        churn("preempt", pre)
        assert _Driver.grace == ("churn0", 7.0)
        assert "churn0" not in fh.find_available_hosts_and_slots()
        assert [e[1] for e in events] == ["add", "preempt"]

    def test_stale_round_peer_report_ignored(self):
        """A peer-failure report resolved against a superseded round's
        rank numbering must not blacklist the innocent successor that
        inherited the rank number (the scripted-churn misattribution)."""
        import pickle

        from horovod_tpu.elastic import driver as drv

        class _KV(dict):
            def put(self, k, v):
                self[k] = v

            def get(self, k):
                return dict.get(self, k)

        recorded = []

        class _Registry:
            def record_failure(self, host, slot):
                recorded.append((host, slot))

        d = drv.ElasticDriver.__new__(drv.ElasticDriver)
        d._rendezvous = drv.ElasticRendezvous(_KV())
        d._rendezvous._round = 2
        d._worker_registry = _Registry()
        d._result_threads = []
        # round 1 had rank 2 on oldhost; round 2 reassigned rank 2 to
        # newhost (the replacement)
        d._rendezvous.kv.put(
            drv.ROUND_SPEC_KEY.format(1),
            pickle.dumps({"round": 1, "slots": [
                {"hostname": "oldhost", "rank": 2, "size": 3,
                 "local_rank": 0, "local_size": 1, "cross_rank": 2,
                 "cross_size": 3}]}))
        d._rank_assignments = {2: drv.slot_from_dict(
            {"hostname": "newhost", "rank": 2, "size": 3,
             "local_rank": 0, "local_size": 1, "cross_rank": 2,
             "cross_size": 3})}
        d.record_peer_failure(2, "silence", round_id=1)
        assert recorded == []  # stale report: hostnames differ -> ignored
        # a CURRENT-round report still records
        d.record_peer_failure(2, "silence", round_id=2)
        for t in d._result_threads:
            t.join(5)
        assert recorded == [("newhost", 0)]

    def test_resume_after_shutdown_noops(self):
        from horovod_tpu.elastic import driver as drv
        d = drv.ElasticDriver.__new__(drv.ElasticDriver)
        d._shutdown = threading.Event()
        d._shutdown.set()
        d.resume()  # must not raise / touch worker machinery


# ---------------------------------------------------------------------------
# ResponseCache: warm shelf mechanics + join-race typed error
# ---------------------------------------------------------------------------

class TestResponseCacheWarm:
    def _entry(self, name="t", world=2):
        from horovod_tpu.dynamic import Response
        req = {"name": name, "request_type": REQ_ALLREDUCE, "dtype": 0,
               "element_size": 4, "shape": (4,)}
        resp = Response(type=REQ_ALLREDUCE, tensor_names=[name])
        return req, resp

    def test_warm_restore_confirm_and_serve_gate(self):
        from horovod_tpu.negotiation.response_cache import ResponseCache
        rc = ResponseCache(8)
        req, resp = self._entry()
        rc.note_response(req, resp)
        exported = rc.export_entries()
        assert len(exported) == 0  # unconfirmed entries don't shelve
        resp.from_cache = True
        rc.note_response(req, resp)
        exported = rc.export_entries()
        assert len(exported) == 1

        rc2 = ResponseCache(8)
        assert rc2.restore_warm(exported) == 1
        assert rc2.warm_count() == 1
        # warm entries are present but NOT serveable pre-confirmation
        assert rc2.lookup_confirmed(req) is None
        assert rc2.confirm_warm() == 1
        assert rc2.warm_count() == 0
        assert rc2.lookup_confirmed(req) is not None

    def test_warm_digest_agreement_and_empty_marker(self):
        from horovod_tpu.negotiation.response_cache import ResponseCache
        req, resp = self._entry()
        resp.from_cache = True
        a, b, fresh = ResponseCache(8), ResponseCache(8), ResponseCache(8)
        a.note_response(req, resp)
        b.note_response(req, resp)
        a2, b2 = ResponseCache(8), ResponseCache(8)
        a2.restore_warm(a.export_entries())
        b2.restore_warm(b.export_entries())
        assert a2.warm_digest() == b2.warm_digest()
        assert fresh.warm_digest() == b"\x00" * 8  # the fresh-member veto
        assert a2.warm_digest() != fresh.warm_digest()
        assert b2.drop_warm() == 1
        assert b2.warm_count() == 0

    def test_shelf_lru_and_take(self):
        from horovod_tpu.negotiation import response_cache as rcm
        rcm.clear_shelf()
        try:
            rcm.shelve(("s", "global", 2, 0), [("n", ("sig",), None)])
            assert rcm.take_shelved(("s", "global", 2, 0)) is not None
            assert rcm.take_shelved(("s", "global", 2, 0)) is None
        finally:
            rcm.clear_shelf()


class _BarrierWorld:
    """In-memory lockstep exchange for N in-process DynamicServices
    (the test_negotiation fixture, re-used for the join-race test)."""

    def __init__(self, n):
        self.n = n
        self.cond = threading.Condition()
        self.frames: dict = {}
        self.closed = False

    def exchange(self, rank, cycle, req, bits, timeout):
        with self.cond:
            fr = self.frames.setdefault(cycle, {})
            fr[rank] = (req, bits)
            self.cond.notify_all()
            end = time.monotonic() + min(timeout, 30.0)
            while len(fr) < self.n:
                if self.closed:
                    raise RuntimeError("barrier world closed")
                if time.monotonic() > end:
                    raise TimeoutError(f"cycle {cycle} incomplete")
                self.cond.wait(0.2)
            self.frames.pop(cycle - 2, None)
            return ([fr[r][0] for r in range(self.n)],
                    [fr[r][1] for r in range(self.n)])

    def close(self):
        with self.cond:
            self.closed = True
            self.cond.notify_all()


class _BarrierTransport:
    def __init__(self, world, rank):
        self.world_mem = world
        self.world_size = world.n
        self.rank = rank

    def exchange(self, cycle, req, bits, timeout):
        return self.world_mem.exchange(self.rank, cycle, req, bits, timeout)


class TestResponseCacheJoinRace:
    def _services(self, monkeypatch, n=2):
        from horovod_tpu.engine_service import DynamicService
        monkeypatch.setenv("HVD_RESPONSE_CACHE", "1")
        world = _BarrierWorld(n)
        svcs = [DynamicService(NativeEngine(world_size=n, rank=r),
                               _BarrierTransport(world, r))
                for r in range(n)]
        return world, svcs

    def _negotiate_all(self, svcs, name):
        results = [None] * len(svcs)
        errors = []

        def one(i):
            try:
                results[i] = svcs[i].negotiate(name, REQ_ALLREDUCE,
                                               shape=(4,), timeout=30)
            except Exception as e:
                errors.append(e)

        ts = [threading.Thread(target=one, args=(i,), daemon=True)
              for i in range(len(svcs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(40)
        assert not errors, errors
        return results

    def test_pre_join_serve_raises_typed_error(self, monkeypatch):
        """Rank 0 serves a batch locally from its confirmed coordinator
        cache in the same window rank 1's JOIN goes to the wire: the
        cycle that first observes the JOIN must fail rank 0's service
        with ResponseCacheJoinError NAMING rank 1 — not leave the
        locally-served, never-scheduled collective to burn the exchange
        deadline (ROADMAP protocol follow-on (a))."""
        world, svcs = self._services(monkeypatch)
        try:
            # steady state: confirm + begin serving locally
            for _ in range(12):
                self._negotiate_all(svcs, "g")
                if all(s.response_cache_stats()["confirmed"] >= 1
                       for s in svcs):
                    break
            assert all(s.response_cache_stats()["confirmed"] >= 1
                       for s in svcs)
            self._negotiate_all(svcs, "g")  # served locally everywhere

            # rank 1 joins while rank 0 serves the same window locally
            join_exc = []

            def joiner():
                try:
                    svcs[1].join("j.join", timeout=20)
                except Exception as e:  # the abort fails the join too
                    join_exc.append(e)

            jt = threading.Thread(target=joiner, daemon=True)
            jt.start()
            # rank 0's local serve needs no peer: it returns immediately
            t0 = time.monotonic()
            ticket = svcs[0].negotiate_many_submit([dict(
                name="g", request_type=REQ_ALLREDUCE, dtype=0,
                element_size=4, shape=(4,), root_rank=-1, group_id=-1,
                splits=(), reduce_op=-1, prescale=1.0, postscale=1.0,
                splits_crc=0)])
            assert ticket.served, "serve did not happen pre-join"
            svcs[0].negotiate_many_wait(ticket, timeout=30)
            # rank 0's next REAL negotiation observes the failure fast
            with pytest.raises(ResponseCacheJoinError) as ei:
                for _ in range(40):
                    svcs[0].negotiate(f"after.{_}", REQ_ALLREDUCE,
                                      shape=(4,), timeout=30)
                    time.sleep(0.05)
            assert time.monotonic() - t0 < 20.0
            assert "rank 1" in str(ei.value)
            assert ei.value.joining_rank == 1
            jt.join(10)
        finally:
            world.close()
            for s in svcs:
                s.stop()

    def test_join_without_serves_latches_quietly(self, monkeypatch):
        """A JOIN observed with no pre-join local serves just latches —
        no typed error, the normal join semantics."""
        world, svcs = self._services(monkeypatch)
        try:
            self._negotiate_all(svcs, "q")  # real rounds only, no serving
            results = [None, None]

            def joiner(i):
                results[i] = svcs[i].join(f"q.join.{i}", timeout=30)

            ts = [threading.Thread(target=joiner, args=(i,), daemon=True)
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(40)
            assert results[0] is not None and results[1] is not None
            for s in svcs:
                assert s._failure is None
        finally:
            world.close()
            for s in svcs:
                s.stop()


# ---------------------------------------------------------------------------
# request-frame parsing (the join-race scanner's wire twin)
# ---------------------------------------------------------------------------

class TestParseRequests:
    def test_roundtrip_via_native_pop(self):
        from horovod_tpu.dynamic import parse_requests
        eng = NativeEngine(world_size=2, rank=1)
        eng.enqueue("a", REQ_ALLREDUCE, dtype=1, element_size=4,
                    shape=(3, 2), reduce_op=0)
        eng.enqueue("b.join", REQ_JOIN)
        reqs = parse_requests(eng.pop_requests())
        assert [(r["rank"], r["request_type"], r["name"]) for r in reqs] \
            == [(1, REQ_ALLREDUCE, "a"), (1, REQ_JOIN, "b.join")]

    def test_empty(self):
        from horovod_tpu.dynamic import parse_requests
        assert parse_requests(b"") == []


# ---------------------------------------------------------------------------
# dispatch-cache shelf unit coverage
# ---------------------------------------------------------------------------

class TestDispatchShelf:
    def test_restorable_filter(self):
        from horovod_tpu.ops import dispatch_cache as dc
        plan = dc.DispatchPlan("l", "A", 1, None, lambda t: t)
        assert dc._restorable(("allreduce", "n", ("r",), None, "g", 1),
                              plan)
        assert dc._restorable(("allreduce", "n", ("r",), None, 0, 1),
                              plan)  # the registered GLOBAL set (id 0)
        assert dc._restorable(("allreduce", "n", ("r",), None, (0, 1), 1),
                              plan)  # self-describing rank tuple
        assert not dc._restorable(
            ("allreduce", "n", ("r",), None, 3, 1), plan)  # other ids
        assert not dc._restorable(("k",), dc.UNPLANNABLE)

    def test_stats_expose_warm_fields(self):
        from horovod_tpu.ops import dispatch_cache as dc
        st = dc.stats()
        assert "warm_pool" in st and "warm_reuses" in st
