"""Composed-parallelism mesh layer (ISSUE 17 tentpole): ONE hierarchical
``dcn x ici_dp (x model axes)`` mesh shared by every schedule, with the
engine's gradient collectives reduced two-level over the DATA axes only.

Numerics conventions (measured on this XLA CPU backend): flat ``psum`` is
a sequential left fold in rank order, so regrouping it two-level is a
~1-ulp change on generic floats. The bit-parity gates therefore run in
the EXACTNESS DOMAIN — integer-valued float32 contributions and
power-of-two divisors, where every correct reduction order is exact and
any wrong-axis/double-count/padding/scale bug still breaks equality —
and trajectory parity vs pure DP is tight float32 allclose.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import parallel
from horovod_tpu.ops import hierarchical
from horovod_tpu.parallel import mesh as composed

N = 8


# ------------------------------------------------------------- layout unit

class TestLayout:
    def test_parse_axes(self):
        assert composed.parse_axes("") == ()
        assert composed.parse_axes("seq:2") == (("seq", 2),)
        assert composed.parse_axes(" expert:4 , stage:2 ") == (
            ("expert", 4), ("stage", 2))

    @pytest.mark.parametrize("spec", ["seq", "seq:", "seq:two", ":4"])
    def test_parse_axes_malformed_is_typed(self, spec):
        with pytest.raises(parallel.MeshLayoutError):
            composed.parse_axes(spec)

    def test_layout_carves_model_axes_from_the_island(self):
        lay = parallel.layout((("seq", 2),), ici_size=4, world=8)
        assert lay.shape == (2, 2, 2)
        assert lay.axis_names == ("dcn", "ici_dp", "seq")
        assert lay.data_axes == ("dcn", "ici_dp")
        assert lay.model_axis_names == ("seq",)
        assert lay.axis_size("seq") == 2 and lay.size == 8
        assert lay.batch_spec("seq") == P(("dcn", "ici_dp"), "seq")

    def test_layout_rejects_bad_carve_and_bad_island(self):
        with pytest.raises(parallel.MeshLayoutError):
            parallel.layout((("seq", 3),), ici_size=4, world=8)
        with pytest.raises(parallel.MeshLayoutError):
            parallel.layout((), ici_size=3, world=8)

    def test_layout_rejects_data_axis_collision_and_dup_names(self):
        with pytest.raises(parallel.MeshLayoutError):
            parallel.MeshLayout(dcn=2, ici_dp=2,
                                model_axes=(("ici_dp", 2),))
        with pytest.raises(parallel.MeshLayoutError):
            parallel.MeshLayout(dcn=2, ici_dp=1,
                                model_axes=(("m", 2), ("m", 2)))

    def test_default_layout_reads_the_knob(self, monkeypatch):
        monkeypatch.setenv("HVD_MESH_AXES", "seq:2")
        monkeypatch.setenv("HVD_HIERARCHICAL_ICI_SIZE", "4")
        lay = parallel.default_layout(world=8)
        assert lay.key() == (2, 2, ("seq", 2))
        assert composed.layout_signature() == (8, 2, 2, ("seq", 2))

    def test_layout_signature_never_raises(self, monkeypatch):
        monkeypatch.setenv("HVD_MESH_AXES", "seq:5")  # 5 can't divide 8
        sig = composed.layout_signature()
        assert sig[1] == "unrealizable" and "seq:5" in sig[2]


# ------------------------------------------------------------- shared mesh

class TestSharedMesh:
    def test_axis_product_mismatch_is_typed(self):
        with pytest.raises(parallel.MeshLayoutError):
            parallel.mesh_for_axes(("dcn", "ici_dp"), (3, 2))

    def test_composed_mesh_shape_and_device_order(self):
        lay = parallel.layout((("seq", 2),), ici_size=4, world=8)
        m = parallel.composed_mesh(lay)
        assert m.axis_names == ("dcn", "ici_dp", "seq")
        # dcn-major reshape of the rank-ordered device list: coords
        # (d, i, s) hold global rank ((d*2)+i)*2+s
        flat = list(np.asarray(m.devices).ravel())
        assert flat == list(hvd.devices())

    def test_hierarchical_mesh_routes_through_the_shared_cache(self):
        # satellite 2: the eager 2-D hierarchical mesh and the composed
        # layer resolve through ONE generation-keyed cache, so device
        # order cannot diverge after an elastic re-form
        m1 = hvd.hierarchical_mesh(ici_size=4)
        m2 = parallel.mesh_for_axes(
            (hierarchical.DCN_AXIS, hierarchical.ICI_AXIS), (2, 4))
        assert m1 is m2
        assert hvd.hierarchical_mesh(ici_size=4) is m1

    def test_stale_generation_entries_are_evicted(self):
        from horovod_tpu import runtime

        live = parallel.mesh_for_axes(("dcn", "ici_dp"), (2, 4))
        stale = (("dcn", "ici_dp"), (2, 4), -1)  # impossible generation
        composed._mesh_cache[stale] = live
        parallel.mesh_for_axes(("gen_probe",), (8,))  # any miss evicts
        assert stale not in composed._mesh_cache
        assert (("dcn", "ici_dp"), (2, 4),
                runtime.generation()) in composed._mesh_cache


# ----------------------------------------------- sync bit-parity (exact)

def _sync_bit_parity(model_axis):
    """Composed sync (pmean over the model axis + two-level over the data
    axes) vs pure-DP flat pmean over one 8-wide axis, in the exactness
    domain — must agree BIT FOR BIT. Includes an odd length (33) so the
    two-level pad-to-ici_dp path is exercised."""
    lay = parallel.layout(((model_axis, 2),), ici_size=4, world=8)
    mesh_c = parallel.composed_mesh(lay)
    mesh_f = parallel.mesh_for_axes(("data",), (N,))
    shapes = [(33,), (4, 5)]

    def contrib(r):
        return [jnp.arange(np.prod(s), dtype=jnp.float32).reshape(s) * 3.0
                + r * 7.0 for s in shapes]

    def composed_fn():
        d, i = lax.axis_index("dcn"), lax.axis_index("ici_dp")
        m = lax.axis_index(model_axis)
        r = ((d * lay.ici_dp) + i) * 2 + m
        xs = [lax.pmean(x, model_axis) for x in contrib(r)]
        return parallel.sync_gradients(xs, lay, op=hvd.ReduceOp.AVERAGE)

    def flat_fn():
        return [lax.pmean(x, "data")
                for x in contrib(lax.axis_index("data"))]

    got = jax.jit(jax.shard_map(composed_fn, mesh=mesh_c, in_specs=(),
                                out_specs=P(), check_vma=False))()
    want = jax.jit(jax.shard_map(flat_fn, mesh=mesh_f, in_specs=(),
                                 out_specs=P(), check_vma=False))()
    for a, b in zip(got, want):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dpsp_sync_bit_identical_to_pure_dp():
    _sync_bit_parity("seq")


def test_dpep_sync_bit_identical_to_pure_dp():
    _sync_bit_parity("expert")


def test_sync_gradients_adasum_rides_dcn_and_rejects_scales():
    lay = parallel.layout((), ici_size=4, world=8)
    mesh = parallel.composed_mesh(lay)
    data = np.arange(N * 6, dtype=np.float32).reshape(N, 6)

    def fn(x):
        return parallel.sync_gradients([x[0]], lay,
                                       op=hvd.ReduceOp.ADASUM)[0][None]

    out = np.asarray(jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=P(("dcn", "ici_dp")),
        out_specs=P(("dcn", "ici_dp")), check_vma=False))(data))
    # all ranks agree and the result is finite (Adasum's magnitude is
    # direction-dependent, not a plain mean)
    assert np.isfinite(out).all()
    for r in range(1, N):
        np.testing.assert_array_equal(out[0], out[r])
    with pytest.raises(ValueError):
        parallel.sync_gradients([jnp.ones(3)], lay,
                                op=hvd.ReduceOp.ADASUM, prescale_factor=2.0)


def test_resolve_data_axes_rejects_junk():
    assert composed.resolve_data_axes(("a", "b")) == ("a", "b")
    with pytest.raises(parallel.MeshLayoutError):
        composed.resolve_data_axes("dcn")


# -------------------------------------- grouped two-level vs flat (world=8)

def test_two_level_grouped_allreduce_matches_flat_exactly(monkeypatch):
    """Eager grouped_allreduce, two-level (ICI-then-DCN) vs flat at
    world=8: bitwise on integer-valued float32, ~1-ulp on gaussian."""
    rng = np.random.default_rng(5)
    ints = [np.float32(rng.integers(-400, 400, size=s))
            for s in [(33,), (8, 3), (64,)]]
    gauss = [np.float32(rng.standard_normal(s)) for s in [(33,), (8, 3)]]

    def run(two_level, tensors):
        if two_level:
            monkeypatch.setenv("HVD_HIERARCHICAL_ALLREDUCE", "1")
            monkeypatch.setenv("HVD_HIERARCHICAL_ICI_SIZE", "4")
        else:
            monkeypatch.delenv("HVD_HIERARCHICAL_ALLREDUCE", raising=False)
        per = [hvd.per_rank([x * 1.0 + r for r in range(N)])
               for x in tensors]
        return [np.asarray(t)
                for t in hvd.grouped_allreduce(per, op=hvd.ReduceOp.SUM)]

    for a, b in zip(run(False, ints), run(True, ints)):
        np.testing.assert_array_equal(a, b)  # exactness domain: bitwise
    for a, b in zip(run(False, gauss), run(True, gauss)):
        np.testing.assert_allclose(a, b, rtol=1e-6)


# --------------------------------------------- composed TransformerLM step

def _lm(attn_mode="full", moe=0, **over):
    from horovod_tpu.models import TransformerConfig, TransformerLM
    base = dict(vocab_size=32, num_layers=1, num_heads=2, d_model=16,
                d_ff=32, max_seq_len=8, dtype=jnp.float32)
    base.update(over)
    if moe:
        cfg = TransformerConfig(**base, moe_experts=moe, moe_axis="expert")
    elif attn_mode != "full":
        cfg = TransformerConfig(**base, attn_mode=attn_mode, seq_axis="seq")
    else:
        cfg = TransformerConfig(**base)
    return TransformerLM(cfg), cfg


def _composed_lm_steps(lane, tokens, targets, steps=3):
    """Run `steps` SGD steps of one lane from a fixed init; returns
    (losses, final embed table). Lanes: dp (flat 8-wide mesh), dpsp
    (dcn=2 x ici_dp=2 x seq=2, ulysses, DistributedOptimizer mesh_spec),
    dpep (dcn=2 x ici_dp=2 x expert=2, MoE FFN), dpep_flat (data=4 x
    expert=2, flat data sync — the dpep control)."""
    moe = lane in ("dpep", "dpep_flat")
    if lane == "dp":
        model, cfg = _lm()
        mesh = parallel.mesh_for_axes(("data",), (N,))
        tx = hvd.DistributedOptimizer(optax.sgd(0.1), axis_name="data")
        tok_spec, model_axis = P("data"), None
    elif lane == "dpsp":
        model, cfg = _lm(attn_mode="ulysses")
        lay = parallel.layout((("seq", 2),), ici_size=4, world=8)
        mesh = parallel.composed_mesh(lay)
        tx = hvd.DistributedOptimizer(optax.sgd(0.1), mesh_spec=lay)
        tok_spec, model_axis = lay.batch_spec("seq"), "seq"
    elif lane == "dpep":
        model, cfg = _lm(moe=2)
        lay = parallel.layout((("expert", 2),), ici_size=4, world=8)
        mesh = parallel.composed_mesh(lay)
        tx = hvd.DistributedOptimizer(optax.sgd(0.1), mesh_spec=lay)
        tok_spec, model_axis = lay.batch_spec(), "expert"
    else:
        model, cfg = _lm(moe=2)
        mesh = parallel.mesh_for_axes(("data", "expert"), (4, 2))
        tx = hvd.DistributedOptimizer(optax.sgd(0.1), axis_name="data")
        tok_spec, model_axis = P("data"), "expert"
    axes = mesh.axis_names

    def loss_fn(p, t, tgt):
        if moe:
            logits, inter = model.apply({"params": p}, t,
                                        mutable=["intermediates"])
            aux = sum(jnp.sum(a) for a in
                      jax.tree_util.tree_leaves(inter["intermediates"]))
        else:
            logits, aux = model.apply({"params": p}, t), 0.0
        ce = -jnp.mean(jnp.take_along_axis(
            jax.nn.log_softmax(logits), tgt[..., None], -1))
        return ce + 0.01 * aux

    def train_step(p, o, t, tgt):
        loss, g = jax.value_and_grad(loss_fn)(p, t, tgt)
        if model_axis is not None:
            g = jax.tree.map(lambda x: lax.pmean(x, model_axis), g)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, lax.pmean(loss, axes)

    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh, in_specs=(P(), P(), tok_spec, tok_spec),
        out_specs=(P(), P(), P()), check_vma=False))

    # init with attn_mode=full: never routes, same param tree per family
    init_model, _ = _lm(moe=2) if moe else _lm()
    params = init_model.init(jax.random.PRNGKey(0),
                             jnp.asarray(tokens[:1]))["params"]
    opt = tx.init(params)
    t = jax.device_put(tokens, NamedSharding(mesh, tok_spec))
    tgt = jax.device_put(targets, NamedSharding(mesh, tok_spec))
    losses = []
    for _ in range(steps):
        params, opt, loss = step(params, opt, t, tgt)
        losses.append(float(np.ravel(np.asarray(loss))[0]))
    return losses, np.asarray(params["embed"]["embedding"])


@pytest.fixture()
def lm_batch():
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 32, size=(8, 8))
    return tokens, np.roll(tokens, -1, axis=1)  # global roll: a local
    # roll would wrap within a sequence SHARD in the dpsp lane


def test_dpsp_trains_like_pure_dp(lm_batch):
    """DP x SP composed step (ulysses over seq, two-level data sync via
    the DistributedOptimizer mesh_spec path) tracks the pure-DP
    trajectory at float32 ulp scale."""
    tokens, targets = lm_batch
    dp_losses, dp_emb = _composed_lm_steps("dp", tokens, targets)
    sp_losses, sp_emb = _composed_lm_steps("dpsp", tokens, targets)
    np.testing.assert_allclose(sp_losses, dp_losses, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(sp_emb, dp_emb, rtol=1e-3, atol=1e-5)
    assert dp_losses[-1] < dp_losses[0]  # it actually trains


def test_dpep_trains_like_flat_data_sync(lm_batch):
    """DP x EP composed step vs its flat-data-sync control: identical
    compute, only the data-axis sync schedule differs."""
    tokens, targets = lm_batch
    f_losses, f_emb = _composed_lm_steps("dpep_flat", tokens, targets)
    c_losses, c_emb = _composed_lm_steps("dpep", tokens, targets)
    np.testing.assert_allclose(c_losses, f_losses, rtol=5e-5, atol=1e-7)
    np.testing.assert_allclose(c_emb, f_emb, rtol=1e-3, atol=1e-5)


# -------------------------------------------------- step capture (eager)

def test_composed_eager_step_records_and_replays(hvd, monkeypatch):
    """A composed eager step — the two-level ICI+DCN stream under a step
    marker with the mesh-axes knob set — records once and REPLAYS with no
    steady-state fallback; flipping HVD_MESH_AXES re-records under the
    new layout key instead of wrongly replaying the old plan."""
    import horovod_tpu.ops.fusion_cycle as fusion_cycle
    from horovod_tpu.ops import dispatch_cache

    monkeypatch.setenv("HVD_CYCLE_TIME", "2000")
    monkeypatch.setenv("HVD_PENDING_CYCLE_TIME", "2000")
    monkeypatch.setenv("HVD_STEP_CAPTURE", "1")
    monkeypatch.setenv("HVD_HIERARCHICAL_ALLREDUCE", "1")
    monkeypatch.setenv("HVD_HIERARCHICAL_ICI_SIZE", "4")
    monkeypatch.setenv("HVD_MESH_AXES", "seq:2")
    fusion_cycle.reset()
    dispatch_cache.reset()
    try:
        def one_step(mult):
            with hvd.step_marker():
                handles = []
                for i, shp in enumerate([(48,), (33,)]):
                    t = hvd.per_rank([jnp.full(shp, (r + 1) * mult * (i + 1),
                                               jnp.float32)
                                      for r in range(N)])
                    h = hvd.allreduce_async(t, op=hvd.Sum)
                    h.flush()
                    handles.append(h)
                return [np.asarray(h.synchronize()) for h in handles]

        first = one_step(1.0)
        for k in range(2, 5):
            out = one_step(float(k))  # replays the sealed program
            for a, b in zip(out, first):
                np.testing.assert_allclose(a, b * k, rtol=1e-6)
        st = hvd.fusion_stats()["capture"]
        assert st["recorded_steps"] == 1
        assert st["replayed_steps"] == 3
        assert st["fallbacks"] == 0

        # layout flip: the step key folds envs.mesh_axes(), so the same
        # stream under a new layout re-records (no false replay, no
        # fallback)
        monkeypatch.setenv("HVD_MESH_AXES", "expert:2")
        one_step(1.0)
        one_step(2.0)
        st = hvd.fusion_stats()["capture"]
        assert st["recorded_steps"] == 2
        assert st["replayed_steps"] == 4
        assert st["fallbacks"] == 0
    finally:
        fusion_cycle.reset()
        dispatch_cache.reset()


# ------------------------------------------------ gspmd cache composition

def test_cached_step_accepts_composed_mesh_shardings(hvd):
    """hvd.cached_step with composed-mesh shardings: recreated closures
    share ONE program (the signature fingerprints the full mesh), and
    moving the same arrays to a different layout is a miss, not a stale
    hit."""
    from horovod_tpu.ops import dispatch_cache, gspmd_cache

    dispatch_cache.reset()
    gspmd_cache.reset_stats()
    try:
        lay = parallel.layout((("seq", 2),), ici_size=4, world=8)
        mesh_c = parallel.composed_mesh(lay)
        mesh_f = parallel.mesh_for_axes(("data",), (N,))

        def make_step():
            def train_step(params, x):
                return jax.tree.map(lambda p: p - 0.1 * x.mean(), params)
            return train_step

        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        x_c = jax.device_put(jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
                             NamedSharding(mesh_c, lay.batch_spec()))
        s1 = hvd.cached_step(make_step())
        out1 = s1(params, x_c)
        assert dispatch_cache.stats()["gspmd_builds"] == 1
        s2 = hvd.cached_step(make_step())  # fresh closure, same content
        out2 = s2(params, x_c)
        assert dispatch_cache.stats()["gspmd_builds"] == 1
        assert dispatch_cache.stats()["hits_by_source"].get("gspmd", 0) == 1
        np.testing.assert_array_equal(np.asarray(out1["w"]),
                                      np.asarray(out2["w"]))

        x_f = jax.device_put(np.asarray(x_c),
                             NamedSharding(mesh_f, P("data")))
        s2(params, x_f)  # layout drift -> second program, coexisting
        assert dispatch_cache.stats()["gspmd_builds"] == 2
    finally:
        dispatch_cache.reset()
        gspmd_cache.reset_stats()
