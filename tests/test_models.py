"""Model + graft-entry smoke tests on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np


def test_resnet18_forward(hvd):
    from horovod_tpu.models import ResNet18
    model = ResNet18(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


def test_transformer_forward(hvd):
    from horovod_tpu.models import TransformerConfig, TransformerLM
    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                            d_model=32, d_ff=64, max_seq_len=16)
    model = TransformerLM(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    out = model.apply(params, tokens)
    assert out.shape == (2, 8, 64)


def test_graft_dryrun_multichip(hvd):
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)
