"""Model + graft-entry smoke tests on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np


def test_resnet18_forward(hvd):
    from horovod_tpu.models import ResNet18
    model = ResNet18(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


def test_transformer_forward(hvd):
    from horovod_tpu.models import TransformerConfig, TransformerLM
    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                            d_model=32, d_ff=64, max_seq_len=16)
    model = TransformerLM(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    out = model.apply(params, tokens)
    assert out.shape == (2, 8, 64)


def test_graft_dryrun_multichip(hvd):
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


def test_vgg16_forward_and_grad(hvd):
    from horovod_tpu.models import VGG16
    model = VGG16(num_classes=10, dtype=jnp.float32, classifier_width=64)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32

    def loss(p):
        return jnp.mean(model.apply(p, x, train=False) ** 2)
    g = jax.grad(loss)(variables)
    assert jax.tree_util.tree_all(
        jax.tree.map(lambda t: bool(jnp.all(jnp.isfinite(t))), g))


def test_inception_v3_forward(hvd):
    from horovod_tpu.models import InceptionV3
    model = InceptionV3(num_classes=10, dtype=jnp.float32)
    # 75x75 is the smallest valid input (stem reductions); keeps CPU fast
    x = jnp.zeros((1, 75, 75, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 10)
    # batch-norm state exists and updates under train=True
    out2, mutated = model.apply(variables, x, train=True,
                                mutable=["batch_stats"],
                                rngs={"dropout": jax.random.PRNGKey(1)})
    assert "batch_stats" in mutated
