"""Elastic Ray executor tests with an in-process stub of the Ray API
(Ray is not installed here; the reference's elastic_v2 tests run against
local Ray). The stub runs "actors" as threads and lets the test mutate the
cluster state, so the full elastic driver path is exercised: discovery
from cluster state, a node dying mid-round, a replacement joining, and
state re-sync through the KV — without jax world rebuilds (those are
covered end-to-end by tests/test_integration_elastic.py)."""

import pickle
import threading
import time
import types

import pytest

import horovod_tpu.ray.elastic as ray_elastic
from horovod_tpu.elastic.driver import (
    ROUND_KEY,
    ROUND_SPEC_KEY,
    done_key,
    ready_key,
)
from horovod_tpu.ray.elastic import ElasticRayExecutor, RayHostDiscovery


class _Cluster:
    """Mutable fake Ray cluster state."""

    def __init__(self, hosts):
        self.lock = threading.Lock()
        self.hosts = dict(hosts)  # ip -> cpus (0 = dead)

    def nodes(self):
        with self.lock:
            return [{"NodeManagerAddress": ip,
                     "Alive": cpus > 0,
                     "Resources": {"CPU": float(cpus)}}
                    for ip, cpus in self.hosts.items()]

    def kill(self, ip):
        with self.lock:
            self.hosts[ip] = 0

    def add(self, ip, cpus=1):
        with self.lock:
            self.hosts[ip] = cpus


class _Future:
    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error = None

    def resolve(self, value=None, error=None):
        self.value, self.error = value, error
        self.event.set()


class _ActorMethod:
    def __init__(self, bound):
        self._bound = bound

    def remote(self, *args, **kwargs):
        fut = _Future()

        def run():
            try:
                fut.resolve(value=self._bound(*args, **kwargs))
            except BaseException as e:
                fut.resolve(error=e)

        threading.Thread(target=run, daemon=True).start()
        return fut


class _ActorHandle:
    def __init__(self, instance):
        self._instance = instance

    def __getattr__(self, name):
        return _ActorMethod(getattr(self._instance, name))


class _RemoteCls:
    def __init__(self, cls):
        self._cls = cls

    def options(self, **kwargs):
        self.opts = kwargs
        return self

    def remote(self, *args, **kwargs):
        return _ActorHandle(self._cls(*args, **kwargs))


def _make_stub_ray(cluster):
    ray = types.ModuleType("ray")
    ray.is_initialized = lambda: True
    ray.init = lambda *a, **k: None
    ray.remote = lambda cls: _RemoteCls(cls)
    ray.nodes = cluster.nodes
    ray.kill = lambda actor: None

    def ray_wait(refs, timeout=None):
        (ref,) = refs
        # Event.wait matches real Ray's semantics directly: None blocks
        # forever, 0 is a non-blocking poll
        ok = ref.event.wait(timeout)
        return ([ref], []) if ok else ([], [ref])

    def ray_get(ref):
        ref.event.wait()
        if ref.error is not None:
            raise ref.error
        return ref.value

    ray.wait = ray_wait
    ray.get = ray_get
    return ray


def test_ray_host_discovery_parses_cluster_state():
    cluster = _Cluster({"10.0.0.1": 4, "10.0.0.2": 2, "10.0.0.3": 0})
    disc = RayHostDiscovery(_make_stub_ray(cluster), cpus_per_worker=2)
    assert disc.find_available_hosts_and_slots() == {
        "10.0.0.1": 2, "10.0.0.2": 1}
    # custom resources bound the slot count too
    cluster2 = _Cluster({"10.0.0.1": 8})
    ray2 = _make_stub_ray(cluster2)
    disc2 = RayHostDiscovery(ray2, cpus_per_worker=1,
                             resources_per_worker={"TPU": 1})
    assert disc2.find_available_hosts_and_slots() == {}  # no TPU resource


def test_elastic_ray_node_death_and_replacement(monkeypatch):
    """The headline scenario (reference elastic_v2.py): a worker's node
    dies mid-round; the driver blacklists it, discovery reports a
    replacement, a new round starts, the surviving worker re-registers
    in-process and the replacement picks up synced state through the KV."""
    cluster = _Cluster({"10.0.0.1": 1, "10.0.0.2": 1})
    ray = _make_stub_ray(cluster)
    monkeypatch.setitem(__import__("sys").modules, "ray", ray)

    # stub worker class: passes the seeded env dict straight to fn so the
    # in-process threads don't race on a shared os.environ
    def stub_cls_factory(_ray):
        class _W:
            def execute(self, env, fn, args, kwargs):
                try:
                    return ("ok", fn(env, *args, **(kwargs or {})))
                except SystemExit as e:
                    return ("exit", int(e.code or 0))

        return _W

    monkeypatch.setattr(ray_elastic, "_make_elastic_worker_cls",
                        stub_cls_factory)

    from horovod_tpu.runner.http_kv import KVClient

    def worker_fn(env):
        kv = KVClient(env["HVD_KV_ADDR"], int(env["HVD_KV_PORT"]),
                      secret=env["HVD_SECRET_KEY"])
        host = env["HVD_HOSTNAME"]
        slot = int(env["HVD_LOCAL_RANK"])
        rnd = int(env["HVD_ELASTIC_ROUND"])
        kv.put(ready_key(rnd, host, slot), b"1")

        if host == "10.0.0.2":
            # this node dies: cluster state flips AND the actor errors,
            # and a replacement node appears for discovery to find
            cluster.kill("10.0.0.2")
            cluster.add("10.0.0.3")
            raise RuntimeError("node lost")

        if rnd == 1:
            # survivor: wait for the driver to publish the next round,
            # re-register in-process (the subprocess analog of
            # WorkerRendezvous.reset), and publish state for newcomers
            deadline = time.monotonic() + 60
            while True:
                raw = kv.get(ROUND_KEY)
                if raw is not None and int(raw) > 1:
                    new_round = int(raw)
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError("no new round")
                time.sleep(0.1)
            spec = pickle.loads(kv.get(ROUND_SPEC_KEY.format(new_round)))
            assert any(s["hostname"] == "10.0.0.3" for s in spec["slots"])
            kv.put("test/state", b"step=7")
            kv.put(ready_key(new_round, host, slot), b"1")
        else:
            # replacement worker: joins the new round and syncs state
            deadline = time.monotonic() + 60
            while kv.get("test/state") is None:
                if time.monotonic() > deadline:
                    raise TimeoutError("state never synced")
                time.sleep(0.1)
            assert kv.get("test/state") == b"step=7"

        kv.put(done_key(host, slot), b"1")
        return f"{host}/{slot}"

    ex = ElasticRayExecutor(min_workers=2, elastic_timeout=60)
    ex.start()
    try:
        results = ex.run(worker_fn)
    finally:
        ex.shutdown()
    # survivor and replacement finished; the dead node's worker did not
    assert sorted(results) == ["10.0.0.1/0", "10.0.0.3/0"]


def test_elastic_ray_requires_start():
    ex = ElasticRayExecutor(min_workers=1)
    with pytest.raises(RuntimeError, match="start"):
        ex.run(lambda env: None)


def test_module_imports_without_ray(monkeypatch):
    monkeypatch.setitem(__import__("sys").modules, "ray", None)
    ex = ElasticRayExecutor(min_workers=1)
    with pytest.raises((ImportError, RuntimeError)):
        ex.start()
