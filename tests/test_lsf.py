"""LSF / jsrun launch parity (reference ``runner/js_run.py:1-151``,
``runner/util/lsf.py:1-103``): allocation detection, host derivation from
LSF env, the ``hvdrun --launcher`` escape hatch, and in-task JSM rank
detection."""

import pytest

from horovod_tpu.runner import launch, lsf
from horovod_tpu.runner.hosts import HostSpec


def _clear_lsf_env(monkeypatch):
    for var in ("LSB_JOBID", "LSB_DJOB_RANKFILE", "LSB_MCPU_HOSTS",
                "LSB_HOSTS", "JSM_NAMESPACE_RANK", "JSM_NAMESPACE_SIZE",
                "SLURM_PROCID", "SLURM_NTASKS", "OMPI_COMM_WORLD_RANK",
                "OMPI_COMM_WORLD_SIZE", "PMI_RANK", "PMI_SIZE"):
        monkeypatch.delenv(var, raising=False)


def test_using_lsf(monkeypatch):
    _clear_lsf_env(monkeypatch)
    assert not lsf.using_lsf()
    monkeypatch.setenv("LSB_JOBID", "12345")
    assert lsf.using_lsf()


def test_host_specs_from_rankfile(monkeypatch, tmp_path):
    _clear_lsf_env(monkeypatch)
    rankfile = tmp_path / "rankfile"
    rankfile.write_text("nodeA\nnodeA\nnodeB\nnodeB\nnodeA\n")
    monkeypatch.setenv("LSB_JOBID", "1")
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rankfile))
    specs = lsf.lsf_host_specs()
    # first-appearance order: rank 0 must land on the first rankfile host
    assert specs == [HostSpec("nodeA", 3), HostSpec("nodeB", 2)]


def test_host_specs_from_mcpu_hosts(monkeypatch):
    _clear_lsf_env(monkeypatch)
    monkeypatch.setenv("LSB_JOBID", "1")
    monkeypatch.setenv("LSB_MCPU_HOSTS", "nodeA 4 nodeB 2")
    assert lsf.lsf_host_specs() == [HostSpec("nodeA", 4), HostSpec("nodeB", 2)]


def test_host_specs_from_lsb_hosts(monkeypatch):
    _clear_lsf_env(monkeypatch)
    monkeypatch.setenv("LSB_JOBID", "1")
    monkeypatch.setenv("LSB_HOSTS", "nodeB nodeB nodeA")
    assert lsf.lsf_host_specs() == [HostSpec("nodeB", 2), HostSpec("nodeA", 1)]


def test_host_specs_without_lsf_info_raises(monkeypatch):
    _clear_lsf_env(monkeypatch)
    monkeypatch.setenv("LSB_JOBID", "1")
    with pytest.raises(RuntimeError, match="pass -H/--hostfile"):
        lsf.lsf_host_specs()


def test_rankfile_beats_mcpu_hosts(monkeypatch, tmp_path):
    """LSB_DJOB_RANKFILE is per-slot truth; it wins over the summary var."""
    _clear_lsf_env(monkeypatch)
    rankfile = tmp_path / "rankfile"
    rankfile.write_text("nodeX\nnodeX\n")
    monkeypatch.setenv("LSB_JOBID", "1")
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rankfile))
    monkeypatch.setenv("LSB_MCPU_HOSTS", "nodeY 8")
    assert lsf.lsf_host_specs() == [HostSpec("nodeX", 2)]


def test_resolve_hosts_uses_lsf_allocation(monkeypatch, tmp_path):
    """hvdrun inside an LSF allocation with no -H/--hostfile derives hosts
    from the allocation (reference launch.py via LSFUtils)."""
    _clear_lsf_env(monkeypatch)
    rankfile = tmp_path / "rankfile"
    rankfile.write_text("nodeA\nnodeB\n")
    monkeypatch.setenv("LSB_JOBID", "1")
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rankfile))
    args = launch.parse_args(["--", "python", "train.py"])
    assert launch._resolve_hosts(args) == [HostSpec("nodeA", 1),
                                           HostSpec("nodeB", 1)]


def test_launcher_local_ignores_lsf(monkeypatch):
    _clear_lsf_env(monkeypatch)
    monkeypatch.setenv("LSB_JOBID", "1")
    monkeypatch.setenv("LSB_MCPU_HOSTS", "nodeA 4")
    args = launch.parse_args(
        ["--launcher", "local", "-np", "2", "--", "python", "train.py"])
    assert launch._resolve_hosts(args) == [HostSpec("localhost", 2)]


def test_launcher_lsf_requires_allocation(monkeypatch):
    _clear_lsf_env(monkeypatch)
    args = launch.parse_args(
        ["--launcher", "lsf", "--", "python", "train.py"])
    with pytest.raises(RuntimeError, match="no LSF allocation"):
        launch._resolve_hosts(args)


def test_explicit_hosts_beat_lsf(monkeypatch):
    _clear_lsf_env(monkeypatch)
    monkeypatch.setenv("LSB_JOBID", "1")
    monkeypatch.setenv("LSB_MCPU_HOSTS", "nodeZ 8")
    args = launch.parse_args(["-H", "me:2", "--", "python", "train.py"])
    assert launch._resolve_hosts(args) == [HostSpec("me", 2)]


def test_cluster_world_hint_jsm(monkeypatch):
    """jsrun tasks advertise JSM_NAMESPACE_SIZE/RANK; the batch-level var
    alone (no rank) must not trigger a join — same contract as srun."""
    from horovod_tpu import runtime as rt
    _clear_lsf_env(monkeypatch)
    assert rt._cluster_world_hint() == 1
    monkeypatch.setenv("JSM_NAMESPACE_SIZE", "4")
    assert rt._cluster_world_hint() == 1  # no rank var: not inside a task
    monkeypatch.setenv("JSM_NAMESPACE_RANK", "2")
    assert rt._cluster_world_hint() == 4


def test_jsm_init_kwargs(monkeypatch, tmp_path):
    from horovod_tpu import runtime as rt
    _clear_lsf_env(monkeypatch)
    assert rt._jsm_init_kwargs() == {}
    rankfile = tmp_path / "rankfile"
    # Summit layout: the launch (batch) node leads the rankfile but jsrun
    # never places a rank there — the coordinator must land on the first
    # COMPUTE node or every rank hangs dialing a host with no rank 0.
    rankfile.write_text("batch2\nworker1\nworker2\n")
    monkeypatch.setenv("LSB_JOBID", "1")
    monkeypatch.setenv("LSB_DJOB_RANKFILE", str(rankfile))
    monkeypatch.setenv("JSM_NAMESPACE_SIZE", "2")
    monkeypatch.setenv("JSM_NAMESPACE_RANK", "1")
    kw = rt._jsm_init_kwargs()
    assert kw["coordinator_address"].startswith("worker1:")
    assert kw["num_processes"] == 2 and kw["process_id"] == 1
    # SLURM rank var present too: defer to jax's own detector
    monkeypatch.setenv("SLURM_PROCID", "1")
    assert rt._jsm_init_kwargs() == {}


def test_launch_nodes_filtered_only_when_compute_hosts_remain(monkeypatch):
    _clear_lsf_env(monkeypatch)
    monkeypatch.setenv("LSB_JOBID", "1")
    # batch node leads with 1 slot (Summit convention): filtered out
    monkeypatch.setenv("LSB_MCPU_HOSTS", "batch1 1 c35n04 42 c35n05 42")
    assert lsf.lsf_host_specs() == [HostSpec("c35n04", 42),
                                    HostSpec("c35n05", 42)]
    # single-host job ON a batch-named node: nothing else left, keep it
    monkeypatch.setenv("LSB_MCPU_HOSTS", "batch1 4")
    assert lsf.lsf_host_specs() == [HostSpec("batch1", 4)]


def test_launcher_auto_falls_back_to_localhost(monkeypatch):
    """LSB_JOBID set but no usable host env: --launcher auto must degrade
    to the localhost default instead of crashing (--launcher lsf raises)."""
    _clear_lsf_env(monkeypatch)
    monkeypatch.setenv("LSB_JOBID", "1")
    args = launch.parse_args(["-np", "2", "--", "python", "train.py"])
    assert launch._resolve_hosts(args) == [HostSpec("localhost", 2)]
    args = launch.parse_args(
        ["--launcher", "lsf", "-np", "2", "--", "python", "train.py"])
    with pytest.raises(RuntimeError, match="pass -H/--hostfile"):
        launch._resolve_hosts(args)
