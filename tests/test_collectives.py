"""Collective op tests, modeled on the reference's op×dtype×mode matrix
(``test/parallel/test_tensorflow.py`` / ``test_torch.py`` — allreduce
sum/average/min/max, allgather, broadcast, alltoall, grouped ops, barrier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P


N = 8


def _rank_values(shape=(4,), dtype=jnp.float32, mult=1.0):
    """values[i] = (i+1) * mult * ones(shape)"""
    return [jnp.full(shape, (i + 1) * mult, dtype=dtype) for i in range(N)]


# ---------------------------------------------------------------- eager mode

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.int32])
def test_allreduce_sum_eager(hvd, dtype):
    vals = _rank_values(dtype=dtype)
    out = hvd.allreduce(hvd.per_rank(vals), op=hvd.Sum)
    expected = sum(range(1, N + 1))
    np.testing.assert_allclose(np.asarray(out, np.float64),
                               np.full((4,), expected), rtol=1e-2)


def test_allreduce_average_eager(hvd):
    vals = _rank_values()
    out = hvd.allreduce(hvd.per_rank(vals), op=hvd.Average)
    np.testing.assert_allclose(np.asarray(out), np.full((4,), 4.5), rtol=1e-6)


def test_allreduce_default_is_average(hvd):
    vals = _rank_values()
    out = hvd.allreduce(hvd.per_rank(vals))
    np.testing.assert_allclose(np.asarray(out), np.full((4,), 4.5), rtol=1e-6)


def test_allreduce_min_max_product(hvd):
    vals = _rank_values()
    out_min = hvd.allreduce(hvd.per_rank(vals), op=hvd.Min)
    out_max = hvd.allreduce(hvd.per_rank(vals), op=hvd.Max)
    out_prod = hvd.allreduce(hvd.per_rank(vals), op=hvd.Product)
    np.testing.assert_allclose(np.asarray(out_min), np.full((4,), 1.0))
    np.testing.assert_allclose(np.asarray(out_max), np.full((4,), 8.0))
    np.testing.assert_allclose(np.asarray(out_prod),
                               np.full((4,), float(np.prod(range(1, 9)))))


def test_allreduce_prescale_postscale(hvd):
    vals = _rank_values()
    out = hvd.allreduce(hvd.per_rank(vals), op=hvd.Sum,
                        prescale_factor=2.0, postscale_factor=0.5)
    np.testing.assert_allclose(np.asarray(out), np.full((4,), 36.0))


def test_allreduce_average_int_raises(hvd):
    with pytest.raises(TypeError):
        hvd.allreduce(hvd.per_rank(_rank_values(dtype=jnp.int32)), op=hvd.Average)


def test_allreduce_replicated_input(hvd):
    # plain array = same contribution from every rank
    out = hvd.allreduce(jnp.ones((3,)), op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out), np.full((3,), 8.0))


def test_grouped_allreduce_eager(hvd):
    t1 = _rank_values((4,))
    t2 = _rank_values((2, 3), mult=10.0)
    t3 = [jnp.full((5,), i + 1, jnp.int32) for i in range(N)]
    outs = hvd.grouped_allreduce(
        [hvd.per_rank(t1), hvd.per_rank(t2), hvd.per_rank(t3)], op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(outs[0]), np.full((4,), 36.0))
    np.testing.assert_allclose(np.asarray(outs[1]), np.full((2, 3), 360.0))
    np.testing.assert_array_equal(np.asarray(outs[2]), np.full((5,), 36, np.int32))
    assert outs[2].dtype == jnp.int32


def test_allgather_eager(hvd):
    vals = [jnp.full((2, 3), i, jnp.float32) for i in range(N)]
    out = hvd.allgather(hvd.per_rank(vals))
    assert out.shape == (16, 3)
    for i in range(N):
        np.testing.assert_allclose(np.asarray(out[2 * i:2 * i + 2]), i)


def test_allgather_scalars(hvd):
    out = hvd.allgather(hvd.per_rank([jnp.float32(i) for i in range(N)]))
    np.testing.assert_allclose(np.asarray(out), np.arange(N, dtype=np.float32))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.int32])
def test_allgather_ragged(hvd, dtype):
    """Per-rank different first dims (the reference's allgatherv contract,
    collective_operations.h:143-178): output concatenates each rank's
    valid rows in rank order."""
    d0s = [(i % 3) + 1 for i in range(N)]  # 1,2,3,1,2,3,...
    vals = [jnp.full((d0s[i], 3), i, dtype) for i in range(N)]
    bundle = hvd.per_rank(vals)
    assert bundle.dim0s == tuple(d0s)
    out = hvd.allgather(bundle)
    assert out.shape == (sum(d0s), 3)
    assert out.dtype == jnp.dtype(dtype)
    off = 0
    for i in range(N):
        np.testing.assert_allclose(
            np.asarray(out[off:off + d0s[i]], np.float64), i)
        off += d0s[i]


def test_allgather_ragged_zero_rows(hvd):
    """A rank may contribute zero rows (the joined-rank contribution)."""
    d0s = [2, 0, 1] + [1] * (N - 3)
    vals = [jnp.full((d0s[i], 2), float(i + 1)) for i in range(N)]
    out = hvd.allgather(hvd.per_rank(vals))
    assert out.shape == (sum(d0s), 2)
    np.testing.assert_allclose(np.asarray(out[:2]), 1.0)
    np.testing.assert_allclose(np.asarray(out[2:3]), 3.0)  # rank 1 skipped


def test_per_rank_ragged_trailing_dims_must_match(hvd):
    with pytest.raises(ValueError, match="except the first"):
        hvd.per_rank([jnp.ones((2, 3))] * (N - 1) + [jnp.ones((2, 4))])


def test_ragged_bundle_rejected_by_uniform_ops(hvd):
    """Ragged per_rank bundles must not slip zero padding into ops with
    uniform-shape contracts (code-review r4): allreduce, broadcast,
    reducescatter and even alltoall all reject them loudly."""
    ragged = hvd.per_rank([jnp.ones((1 + (i % 2), 2)) for i in range(N)])
    for op in (lambda: hvd.allreduce(ragged, op=hvd.Sum),
               lambda: hvd.broadcast(ragged, 0),
               lambda: hvd.reducescatter(ragged),
               lambda: hvd.alltoall(ragged)):
        with pytest.raises(ValueError, match="ragged"):
            op()


def test_alltoall_uneven_ragged_per_rank(hvd):
    """Uneven alltoall accepts a ragged per_rank bundle: row sums are
    validated against each rank's OWN first dim (ADVICE r3 #2)."""
    d0s = [(i % 2) + 1 for i in range(N)]  # 1,2,1,2,...
    vals = [jnp.arange(d0s[i] * 2, dtype=jnp.float32).reshape(d0s[i], 2)
            + 10 * i for i in range(N)]
    # rank i sends its single first row to rank 0, rest nowhere
    smat = np.zeros((N, N), np.int64)
    smat[:, 0] = 1
    outs, recv = hvd.alltoall(hvd.per_rank(vals), splits=smat)
    assert outs[0].shape == (N, 2)
    for i in range(N):
        np.testing.assert_allclose(np.asarray(outs[0][i]),
                                   np.asarray(vals[i][0]))
    # row sums beyond a rank's real rows must raise with the rank named
    bad = np.zeros((N, N), np.int64)
    bad[0, :2] = (1, 1)  # rank 0 only has 1 row
    with pytest.raises(ValueError, match="rank 0's first dimension"):
        hvd.alltoall(hvd.per_rank(vals), splits=bad)


def test_broadcast_eager(hvd):
    vals = _rank_values()
    for root in (0, 3, 7):
        out = hvd.broadcast(hvd.per_rank(vals), root)
        np.testing.assert_allclose(np.asarray(out), np.full((4,), root + 1.0))


def test_broadcast_bool(hvd):
    vals = [jnp.full((3,), i % 2 == 0) for i in range(N)]
    out = hvd.broadcast(hvd.per_rank(vals), 1)
    assert out.dtype == jnp.bool_
    np.testing.assert_array_equal(np.asarray(out), np.zeros((3,), bool))


def test_alltoall_eager(hvd):
    # rank i sends row j*1 chunk valued i*10+j to rank j
    vals = [jnp.arange(N, dtype=jnp.float32) + 10 * i for i in range(N)]
    out = hvd.alltoall(hvd.per_rank(vals))
    assert isinstance(out, hvd.PerRank)
    recv = np.asarray(out.array)
    for j in range(N):
        np.testing.assert_allclose(recv[j], np.array([10 * i + j for i in range(N)]))


def test_reducescatter_eager(hvd):
    vals = [jnp.arange(16, dtype=jnp.float32) * (i + 1) for i in range(N)]
    out = hvd.reducescatter(hvd.per_rank(vals), op=hvd.Sum)
    recv = np.asarray(out.array)
    total = np.arange(16, dtype=np.float32) * 36.0
    np.testing.assert_allclose(recv.reshape(-1), total)


def test_barrier_and_join(hvd):
    hvd.barrier()
    assert hvd.join() == hvd.size() - 1


def test_async_handles(hvd):
    h = hvd.allreduce_async(hvd.per_rank(_rank_values()), op=hvd.Sum)
    out = hvd.synchronize(h)
    assert hvd.poll(h)
    np.testing.assert_allclose(np.asarray(out), np.full((4,), 36.0))


# ---------------------------------------------------------------- traced mode

def _shard_mapped(hvd, fn, x, out_specs=P("hvd")):
    return jax.jit(jax.shard_map(
        fn, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=out_specs,
        check_vma=False))(x)


def test_allreduce_traced(hvd):
    x = jnp.arange(1.0, 9.0).reshape(N, 1)

    def step(v):
        return hvd.allreduce(v, op=hvd.Sum)

    out = _shard_mapped(hvd, step, x)
    np.testing.assert_allclose(np.asarray(out).ravel(), np.full(N, 36.0))


def test_allreduce_average_traced(hvd):
    x = jnp.arange(1.0, 9.0).reshape(N, 1)
    out = _shard_mapped(hvd, lambda v: hvd.allreduce(v, op=hvd.Average), x)
    np.testing.assert_allclose(np.asarray(out).ravel(), np.full(N, 4.5))


def test_allgather_traced(hvd):
    x = jnp.arange(8.0).reshape(N, 1)
    out = _shard_mapped(hvd, lambda v: hvd.allgather(v), x)
    # each rank gathers all 8 values -> global result is (8*8, 1) stacked
    assert out.shape == (64, 1)
    np.testing.assert_allclose(np.asarray(out[:8]).ravel(), np.arange(8.0))


def test_broadcast_traced(hvd):
    x = jnp.arange(1.0, 9.0).reshape(N, 1)
    out = _shard_mapped(hvd, lambda v: hvd.broadcast(v, 2), x)
    np.testing.assert_allclose(np.asarray(out).ravel(), np.full(N, 3.0))


def test_grouped_allreduce_traced(hvd):
    x = jnp.arange(1.0, 9.0).reshape(N, 1)

    def step(v):
        a, b = hvd.grouped_allreduce([v, v * 2], op=hvd.Sum)
        return a + b

    out = _shard_mapped(hvd, step, x)
    np.testing.assert_allclose(np.asarray(out).ravel(), np.full(N, 108.0))


def test_traced_inside_user_axis_name(hvd):
    # user meshes with their own axis names work via axis_name=
    import numpy as onp
    from jax.sharding import Mesh
    mesh = Mesh(onp.array(jax.devices()), ("dp",))
    x = jnp.arange(1.0, 9.0).reshape(N, 1)
    fn = jax.jit(jax.shard_map(
        lambda v: hvd.allreduce(v, op=hvd.Sum, axis_name="dp"),
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False))
    np.testing.assert_allclose(np.asarray(fn(x)).ravel(), np.full(N, 36.0))


def test_allreduce_average_over_subaxis(hvd):
    """AVERAGE must divide by the bound axis size, not the world size
    (regression: dp-axis average on a (dp, tp) mesh)."""
    import numpy as onp
    from jax.sharding import Mesh
    mesh = Mesh(onp.array(jax.devices()).reshape(4, 2), ("dp", "tp"))
    x = jnp.arange(16.0).reshape(8, 2)  # x[m, j] = 2m + j
    fn = jax.jit(jax.shard_map(
        lambda v: hvd.allreduce(v, op=hvd.Average, axis_name="dp"),
        mesh=mesh, in_specs=P("dp", "tp"), out_specs=P("dp", "tp"),
        check_vma=False))
    out = np.asarray(fn(x))
    # mean over the 4 dp shards of each (2, 1) block; world size is 8 —
    # dividing by 8 (the old bug) would halve these values
    np.testing.assert_allclose(out, np.tile([[6.0, 7.0], [8.0, 9.0]], (4, 1)))


def test_gspmd_passthrough_min_raises(hvd):
    with pytest.raises(RuntimeError):
        jax.jit(lambda v: hvd.allreduce(v, op=hvd.Min))(jnp.ones(2))


def test_grouped_allreduce_async(hvd):
    t1 = _rank_values((4,))
    t2 = _rank_values((2,), mult=10.0)
    h = hvd.grouped_allreduce_async(
        [hvd.per_rank(t1), hvd.per_rank(t2)], op=hvd.Sum)
    outs = hvd.synchronize(h)
    np.testing.assert_allclose(np.asarray(outs[0]), np.full((4,), 36.0))
    np.testing.assert_allclose(np.asarray(outs[1]), np.full((2,), 360.0))


def test_sparse_allreduce_async(hvd):
    from horovod_tpu.ops.sparse import SparseRows, sparse_allreduce_async
    rows = SparseRows(indices=jnp.asarray([0, 2]),
                      values=jnp.ones((2, 3)), num_rows=4)
    h = sparse_allreduce_async(rows, op=hvd.Sum)
    out = hvd.synchronize(h)
    dense = np.asarray(hvd.rows_to_dense(out))
    np.testing.assert_allclose(dense[0], N * 1.0)
    np.testing.assert_allclose(dense[1], 0.0)


@pytest.mark.parametrize("op_name", ["Average", "Sum", "Max"])
def test_grouped_allreduce_traced_fusion_exact(hvd, monkeypatch, op_name):
    """The traced fusion buffer (pack same-dtype leaves, ONE collective
    per HVD_TRACED_FUSION_THRESHOLD-bounded chunk) must be numerically
    identical to per-leaf collectives, across chunk boundaries, mixed
    shapes and dtypes, and every elementwise reduce op."""
    op = getattr(hvd, op_name)
    rng = np.random.default_rng(5)
    # mixed shapes/dtypes; threshold 64 bytes forces multiple f32 chunks
    leaves = [
        jnp.asarray(rng.standard_normal((N, 3)), jnp.float32),
        jnp.asarray(rng.standard_normal((N,)), jnp.float32),
        jnp.asarray(rng.standard_normal((N, 2, 2)), jnp.float32),
        # a genuinely distinct dtype group (x64 is off, float64 would
        # silently truncate to float32 and never split the groups)
        jnp.asarray(rng.standard_normal((N, 5)), jnp.bfloat16),
    ]
    monkeypatch.setenv("HVD_TRACED_FUSION_THRESHOLD", "64")

    def step(*vs):
        return tuple(hvd.grouped_allreduce(list(vs), op=op))

    mesh, axis = hvd.mesh(), hvd.axis_name()
    fn = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(axis),) * len(leaves),
        out_specs=(P(axis),) * len(leaves), check_vma=False))
    fused = [np.asarray(o) for o in fn(*leaves)]

    monkeypatch.setenv("HVD_TRACED_FUSION_THRESHOLD", "0")  # per-leaf
    fn2 = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(axis),) * len(leaves),
        out_specs=(P(axis),) * len(leaves), check_vma=False))
    unfused = [np.asarray(o) for o in fn2(*leaves)]
    for f, u in zip(fused, unfused):
        np.testing.assert_allclose(f, u, rtol=1e-6)
